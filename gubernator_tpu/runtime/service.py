"""The service instance: per-request routing over the cluster.

This is the analog of the reference's V1Instance (gubernator.go:46-824) — the
"brain" that decides, for every rate-limit check, whether to answer from the
local device engine, serve a GLOBAL key from replicated cache, or forward to
the owning peer.  One deliberate TPU-first difference: where the reference
dispatches each request to a worker goroutine individually
(gubernator.go:222-300), this service partitions a client batch ONCE and
applies all locally-owned checks in a single device step — the request fan
becomes vector lanes, not goroutines.

Routing per request (gubernator.go:222-300):
  - validation errors answer inline (handled by the packer);
  - owner == us      -> local device batch;
  - GLOBAL, not ours -> local device batch with the use_cached lane flag
                        (stale-but-fast read, gubernator.go:420-460) + hit
                        queued to the global manager; metadata["owner"] set;
  - otherwise        -> forwarded to the owner through the batching peer
                        client with <=5 retries on ownership change
                        (gubernator.go:327-416).

The GlobalManager re-implements global.go:33-254 on asyncio: an async-hits
loop aggregating (key -> summed hits) flushed to owners every
`global_sync_wait`, and a broadcast loop pushing owner-authoritative statuses
to every peer with the GLOBAL flag cleared to avoid loops (global.go:214-215).

The MultiRegionManager implements the cross-region tier the reference leaves
stubbed (multiregion.go:96-98 "Does nothing for now"): hits aggregate per key
and flush to the key's owner in every OTHER region with the MULTI_REGION flag
cleared (same loop-prevention trick as GLOBAL broadcasts), giving each region
an eventually-consistent view of cross-region hit pressure over DCN.
"""
from __future__ import annotations

import asyncio
import fnmatch
import logging
import random
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from gubernator_tpu.core import clock as clock_mod
from gubernator_tpu.core.config import Config, MAX_BATCH_SIZE
from gubernator_tpu.core.interval import GregorianError, gregorian_expiration
from gubernator_tpu.core.types import (
    Behavior,
    HealthCheckResp,
    LeaseGrant,
    PeerInfo,
    RateLimitReq,
    RateLimitResp,
    Status,
    UpdatePeerGlobal,
    has_behavior,
)
from gubernator_tpu.net.peer_client import (
    PeerClient,
    PeerNotReadyError,
    provably_unsent,
)
from gubernator_tpu.net.replicated_hash import (
    HASH_FUNCTIONS,
    PoolEmptyError,
    RegionPicker,
    ReplicatedConsistentHash,
)
from gubernator_tpu.runtime import tracing
from gubernator_tpu.runtime.backend import DeviceBackend

log = logging.getLogger("gubernator_tpu.service")

HEALTHY = "healthy"
UNHEALTHY = "unhealthy"

ASYNC_RETRIES = 5  # forwarded-request ownership-change retries (gubernator.go:350)

# The shadow slot's key suffix: a degraded local_shadow check serves
# from `<unique_key>` + this suffix, so shadow admission state never
# collides with the real key's authoritative or cached rows.
SHADOW_SUFFIX = ".degraded-shadow"

_EMPTY_I64 = np.empty(0, dtype=np.int64)


def forward_backoff_s(
    attempt: int, cap_s: float, rng: random.Random
) -> float:
    """Backoff before ownership-retry `attempt` (1-based) of the
    forwarded-request loop: equal-jittered exponential —
    uniform over [base/2, base] with base = 10ms * 2^(attempt-1) —
    capped at `cap_s` (the batch timeout, so the retry loop's total
    added latency stays within one RPC budget).  Jitter decorrelates
    the retry stampede a dying owner otherwise sees from every
    forwarder at once (the coordination failure arXiv:1909.08969
    measures).  Pure function of (attempt, cap, rng) so tests pin the
    schedule with a seeded rng."""
    base = min(0.01 * (2 ** max(attempt - 1, 0)), cap_s)
    lo = base / 2.0
    return min(lo + rng.random() * (base - lo), cap_s)


class ApiError(Exception):
    """Service-level error with a gRPC status-code name."""

    def __init__(self, code: str, message: str) -> None:
        super().__init__(message)
        self.code = code


class Service:
    """The per-node service instance."""

    def __init__(
        self,
        cfg: Optional[Config] = None,
        backend: Optional[DeviceBackend] = None,
        clock: Optional[clock_mod.Clock] = None,
        peer_credentials=None,
        metrics=None,
    ) -> None:
        from gubernator_tpu.runtime.metrics import Metrics

        self.cfg = cfg or Config()
        self.clock = clock or clock_mod.default_clock()
        self.metrics = metrics or Metrics()
        if backend is not None:
            self.backend = backend
        elif self.cfg.device.num_shards > 1:
            # Multi-chip: shard the table over the device mesh (full
            # Store/Loader SPI, same as the single-device backend).
            from gubernator_tpu.parallel.sharded import MeshBackend

            self.backend = MeshBackend(
                self.cfg.device,
                clock=self.clock,
                metrics=self.metrics,
                store=self.cfg.store,
                track_keys=(self.cfg.loader is not None),
            )
        else:
            self.backend = DeviceBackend(
                self.cfg.device,
                clock=self.clock,
                store=self.cfg.store,
                track_keys=(self.cfg.loader is not None),
                metrics=self.metrics,
            )
        self._inflight_checks = 0
        self._peer_credentials = peer_credentials
        # Chaos binding (testing/chaos.py): set by the daemon after its
        # listen address is known, handed to every PeerClient built
        # afterwards.  None in production.
        self.chaos = None
        # Degraded-mode ownership fallback (docs/resilience.md).
        self._rng = random.Random()
        self.degraded_served = 0
        # owner addr -> {shadow hash_key: the RESET_REMAINING req that
        # drops the shadow slot once the owner heals}.
        self._shadow: Dict[str, Dict[str, RateLimitReq]] = {}
        self._shadow_tasks: set = set()
        # Cached label child: the hot path must not pay a labels() dict
        # lookup per call (reference funcTimeMetric, gubernator.go:118).
        self._fd_get_rate_limits = self.metrics.func_duration.labels(
            "V1Instance.GetRateLimits"
        )

        def picker_hash(name: str, which: str):
            # Named error over a bare KeyError (config.go:403-425
            # validates the same knob).
            try:
                return HASH_FUNCTIONS[name]
            except KeyError:
                raise ValueError(
                    f"invalid {which} picker hash {name!r}; choose one "
                    f"of {sorted(HASH_FUNCTIONS)}"
                ) from None

        hash_fn = picker_hash(self.cfg.local_picker_hash, "local")
        self.local_picker: ReplicatedConsistentHash[PeerClient] = (
            ReplicatedConsistentHash(hash_fn)
        )
        self.region_picker: RegionPicker[PeerClient] = RegionPicker(
            ReplicatedConsistentHash(
                picker_hash(self.cfg.region_picker_hash, "region")
            )
        )
        self._peer_lock = asyncio.Lock()
        # Single-thread executor serializes blocking device work off the loop
        # (the whole-table single-writer discipline, workers.go:19-37).
        self._dev_executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="tpu-step"
        )
        self._local_batcher = LocalBatcher(self)
        # Approximate tier for configured limit names (runtime/sketch_backend).
        # A names-less config still instantiates when dynamic spillover
        # is armed — membership then grows at runtime (spill_name).
        self.sketch_backend = None
        if self.cfg.sketch is not None and (
            self.cfg.sketch.names
            or self.cfg.sketch.spill_inserts is not None
            or self.cfg.sketch.spill_transients is not None
        ):
            from gubernator_tpu.runtime.sketch_backend import SketchBackend

            self.sketch_backend = SketchBackend(
                self.cfg.sketch, clock=self.clock
            )
            # Every actual spill — policy-driven or operator-called —
            # hits the Prometheus counter.
            self.sketch_backend.on_spill = self.metrics.sketch_spillover.inc
        # Hot-key survival plane (runtime/hotkey.py; docs/hotkeys.md):
        # detection over the traffic this node routes.  Promotion is
        # gated on MEASURED owner pressure, so without a flight
        # recorder (or with every owner healthy) the tracker is inert.
        self.hotkeys = None
        if self.cfg.hotkey.enabled:
            from gubernator_tpu.runtime.hotkey import HotKeyTracker

            self.hotkeys = HotKeyTracker(
                self.cfg.hotkey, metrics=self.metrics
            )
            self.hotkeys.pressure_fn = self._owner_pressure_of
            self.hotkeys.on_demote = self._on_hot_demote
        # Guberberg tier manager (runtime/coldtier.py; docs/tiering.md):
        # the daemon arms it when GUBER_TIER_ENABLED; note_traffic feeds
        # its promote-on-access path.
        self.tier = None
        # fp -> RESET_REMAINING req that drops the local mirror slot
        # when its key demotes (the shadow-drop discipline).
        self._mirror_resets: Dict[int, RateLimitReq] = {}
        # (built_monotonic, tracker version, int64 fps) cache for the
        # fast lane's active-mirror mask.
        self._mirror_fps_cache = None
        self.mirror_served = 0
        self.shed_served = 0
        # Gubstat per-tenant admission ledger (runtime/gubstat.py;
        # docs/observability.md): fed at the LOCAL serve choke points
        # only (_check_local tail, fast-lane _finish_process, the shed
        # path) so a cluster-wide sum never double-counts a hit.
        self.tenants = None
        if self.cfg.stats.enabled:
            from gubernator_tpu.runtime.gubstat import TenantAccounting

            self.tenants = TenantAccounting(self.cfg.stats.top_k)
        # Client-side admission leases (runtime/lease.py; docs/leases.md):
        # the owner-side grant/reconcile plane for the Lease/Reconcile
        # peer RPCs.  None when disabled — every grant then refuses.
        self.leases = None
        if self.cfg.lease.enabled:
            from gubernator_tpu.runtime.lease import LeaseManager

            self.leases = LeaseManager(
                self, self.cfg.lease, metrics=self.metrics
            )
        self._lease_sweep_task: Optional[asyncio.Task] = None
        # Elastic membership (runtime/reshard.py; docs/resharding.md):
        # a remap streams moved rows old owner -> new owner instead of
        # orphaning them.  None when disabled — a remap then degrades
        # to the legacy counter reset.
        self.reshard = None
        if self.cfg.reshard.enabled:
            from gubernator_tpu.runtime.reshard import ReshardManager

            self.reshard = ReshardManager(
                self, self.cfg.reshard, metrics=self.metrics
            )
        # The ring as it stood before the latest remap — the inbound
        # handoff's covered-key test (reshard.inbound_covering).
        self._prev_picker = None
        self._reshard_watch_task: Optional[asyncio.Task] = None
        # Planet-scale regions (runtime/multiregion.py;
        # docs/multiregion.md): remote-homed keys serve from a bounded
        # `.region-carve` slot and reconcile over the WAN lane.  None
        # when disabled — every key is then home here.
        self.regions = None
        if self.cfg.region.enabled:
            from gubernator_tpu.runtime.multiregion import RegionManager

            self.regions = RegionManager(
                self, self.cfg.region, metrics=self.metrics
            )
        self.global_mgr = GlobalManager(self)
        self.multi_region_mgr = MultiRegionManager(self)
        # On a mesh backend, GLOBAL keys owned by THIS node serve from the
        # collective engine's replicated cache and sync over ICI
        # (all_to_all hits -> owner, all_gather broadcast) instead of the
        # RPC loops — wired at construction like the reference's
        # globalManager (gubernator.go:137, global.go:63-64).  The RPC
        # GlobalManager still handles keys owned by OTHER nodes.
        self.global_engine = None
        self._collective_loop: Optional[CollectiveGlobalLoop] = None
        from gubernator_tpu.parallel.sharded import MeshBackend

        if isinstance(self.backend, MeshBackend):
            from gubernator_tpu.parallel.global_sync import GlobalEngine

            self.global_engine = GlobalEngine(
                self.backend,
                batch_limit=self.cfg.behaviors.global_batch_limit,
            )
            self.global_engine.on_synced = self._engine_synced
            self._collective_loop = CollectiveGlobalLoop(
                self, self.global_engine
            )
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._closed = False
        self._started = False
        if self.cfg.loader is not None:
            n = self.backend.load_items(self.cfg.loader.load())
            log.info("loader restored %d items", n)

    async def start(self) -> None:
        """Start the background replication loops; requires a running event
        loop (the analog of NewV1Instance spawning the manager goroutines,
        gubernator.go:137-138)."""
        if self._started:
            return
        self._started = True
        self._loop = asyncio.get_running_loop()
        self.global_mgr.start()
        self.multi_region_mgr.start()
        if self.regions is not None:
            self.regions.start()
        if self._collective_loop is not None:
            self._collective_loop.start()
        if self.leases is not None:
            self._lease_sweep_task = asyncio.ensure_future(
                self._lease_sweep_loop()
            )
        if self.reshard is not None:
            self._reshard_watch_task = asyncio.ensure_future(
                self._reshard_watch_loop()
            )
        # Warm the jitted device step so the first client request doesn't
        # pay XLA compilation (20-40s cold) inside an RPC deadline.
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(self._dev_executor, self.backend.warmup)
        if self.global_engine is not None:
            await loop.run_in_executor(
                self._dev_executor, self.global_engine.warmup
            )
        if self.sketch_backend is not None:
            await loop.run_in_executor(
                self._dev_executor, self.sketch_backend.warmup
            )

    # ------------------------------------------------------------------
    # peer management
    # ------------------------------------------------------------------
    async def set_peers(self, peer_info: Sequence[PeerInfo]) -> None:
        """Atomically swap in a new peer set and drain removed peers
        (gubernator.go:634-717).  The lock spans the whole rebuild so
        concurrent discovery updates (fire-and-forget on_update tasks)
        serialize instead of interleaving across awaits; readers run on
        the same loop and see either the old or the new picker."""
        async with self._peer_lock:
            local = self.local_picker.new()
            region = self.region_picker.new()
            for info in peer_info:
                if info.data_center != self.cfg.data_center:
                    peer = self.region_picker.get_by_address(
                        info.grpc_address
                    )
                    if peer is None:
                        peer = self._new_peer(info)
                    region.add(peer, info.data_center)
                else:
                    peer = self.local_picker.get_by_address(
                        info.grpc_address
                    )
                    if peer is None:
                        peer = self._new_peer(info)
                    else:
                        peer.peer_info = info  # refresh is_owner flag
                    local.add(peer)

            old_local, old_region = self.local_picker, self.region_picker
            self.local_picker, self.region_picker = local, region
            self._prev_picker = old_local

        # Live resharding (docs/resharding.md): the remap may have
        # moved arcs this node owned — stream their rows to the new
        # owners instead of orphaning them.  Spawned (the delta needs a
        # device fetch); routing already follows the NEW ring, and the
        # handoff protocol bounds the window's double admission.
        if self.reshard is not None and old_local.size() > 0:
            self.reshard.on_remap(old_local, local)
        # Derived-slot invalidation: a demoted owner must not keep
        # honoring lease renewals against a stale carve slot, and a
        # node that just BECAME a hot key's owner must not keep a
        # mirror allowance for it.
        if self.leases is not None and old_local.size() > 0:
            self.leases.on_remap()
        self._invalidate_unowned_mirrors()
        if self.regions is not None:
            self.regions.on_remap()

        shutdown: List[PeerClient] = []
        for peer in old_local.peers():
            if local.get_by_address(peer.info().grpc_address) is None:
                shutdown.append(peer)
        for picker in old_region.pickers().values():
            for peer in picker.peers():
                if region.get_by_address(peer.info().grpc_address) is None:
                    shutdown.append(peer)
        if shutdown:
            await asyncio.gather(
                *(p.shutdown() for p in shutdown), return_exceptions=True
            )
            log.debug(
                "peers shutdown: %s",
                [p.info().grpc_address for p in shutdown],
            )

    def _new_peer(self, info: PeerInfo) -> PeerClient:
        peer = PeerClient(
            info,
            behavior=self.cfg.behaviors,
            channel_credentials=self._peer_credentials,
            metrics=self.metrics,
            circuit=self.cfg.circuit,
            chaos=self.chaos,
            pressure_ttl_s=self.cfg.hotkey.pressure_ttl_s,
        )
        # Heal detection for the degraded-mode fallback: ANY successful
        # RPC to the peer (object path, compiled raw lane, GLOBAL
        # flush/broadcast) drops its shadow admission state.
        addr = info.grpc_address
        peer.on_rpc_success = lambda: self._drop_shadow(addr)
        return peer

    def get_peer(self, key: str) -> PeerClient:
        """Owning peer for a hash key (gubernator.go:719-731)."""
        return self.local_picker.get(key)

    def peer_list(self) -> List[PeerClient]:
        return self.local_picker.peers()

    def _owns_key(self, key: str) -> bool:
        """Does THIS node own `key` under the current ring?  An empty
        pool owns everything (single-node mode)."""
        if self.local_picker.size() == 0:
            return True
        try:
            return self.get_peer(key).info().is_owner
        except PoolEmptyError:
            return True

    # ------------------------------------------------------------------
    # elastic membership (runtime/reshard.py; docs/resharding.md)
    # ------------------------------------------------------------------
    def _derived_slot_keys(self) -> List[str]:
        """Hash-key strings of every derived slot this node knows about
        (each ends with its reserved suffix class — lease carve,
        hot-mirror, degraded shadow, handoff shadow)."""
        keys: List[str] = []
        if self.leases is not None:
            from gubernator_tpu.runtime.lease import LEASE_SUFFIX

            with self.leases._lock:
                keys.extend(
                    k + LEASE_SUFFIX for k in self.leases._keys
                )
        keys.extend(
            r.hash_key() for r in self._mirror_resets.values()
        )
        for pending in self._shadow.values():
            keys.extend(pending.keys())
        if self.reshard is not None:
            from gubernator_tpu.runtime.reshard import HANDOFF_SUFFIX

            with self.reshard._lock:
                for ib in self.reshard._inbound.values():
                    keys.extend(
                        k + HANDOFF_SUFFIX for k in ib.shadow
                    )
        if self.regions is not None:
            keys.extend(self.regions.carve_slot_keys())
        return keys

    def derived_slot_fps(self) -> np.ndarray:
        """int64 fingerprints of the derived slots this node can
        invalidate locally — lease carve slots, hot-mirror allowances,
        degraded shadows, handoff shadows.  The reshard plane excludes
        them from migration: derived state re-homes by re-creation at
        its new home (leases re-grant through the ring, mirrors
        re-promote, shadows re-carve), never by copy."""
        from gubernator_tpu.core.hashing import key_hash64

        keys = self._derived_slot_keys()
        if not keys:
            return _EMPTY_I64
        return np.array(
            [np.uint64(key_hash64(k)).view(np.int64) for k in keys],
            dtype=np.int64,
        )

    def derived_slot_fps_by_plane(self) -> Dict[str, np.ndarray]:
        """The same enumeration grouped by reserved suffix class (the
        ops/state.SHADOW_PLANES census order) — the gubstat sampler's
        input: each plane's fingerprints probe the live table so the
        carve-slot population is observable per class."""
        from gubernator_tpu.core.hashing import key_hash64
        from gubernator_tpu.ops.state import SHADOW_PLANES

        grouped: Dict[str, List[int]] = {p: [] for p in SHADOW_PLANES}
        for k in self._derived_slot_keys():
            for p in SHADOW_PLANES:
                if k.endswith(p):
                    grouped[p].append(
                        int(np.uint64(key_hash64(k)).view(np.int64))
                    )
                    break
        return {
            p: np.array(v, dtype=np.int64) if v else _EMPTY_I64
            for p, v in grouped.items()
        }

    def _invalidate_unowned_mirrors(self) -> None:
        """A remap can make this node the OWNER of a key it was
        mirroring — drop the stale mirror allowance so no widened
        admission state survives the ownership change."""
        from gubernator_tpu.runtime.hotkey import MIRROR_SUFFIX

        fps = [
            fp for fp, r in self._mirror_resets.items()
            if r.unique_key.endswith(MIRROR_SUFFIX)
            and self._owns_key(
                r.name + "_" + r.unique_key[: -len(MIRROR_SUFFIX)]
            )
        ]
        if fps:
            self._on_hot_demote(fps)

    async def _reshard_watch_loop(self) -> None:
        """Watchdog cadence for the reshard plane: self-cutover inbound
        handoffs whose old owner went silent, expire released outbound
        records past the stale-router linger."""
        interval = max(self.cfg.reshard.timeout_s / 4.0, 0.05)
        while True:
            await asyncio.sleep(interval)
            try:
                await self.reshard.check_timeouts()
            except Exception as e:  # noqa: BLE001 — keep the cadence
                log.warning("reshard watchdog failed: %s", e)

    async def handoff(
        self, from_addr: str, epoch: int, phase: str, total_rows: int
    ) -> Tuple[bool, str]:
        """Peer-facing Handoff receive (docs/resharding.md)."""
        if self.reshard is None:
            return False, "resharding disabled"
        return await self.reshard.on_handoff(
            from_addr, epoch, phase, total_rows
        )

    async def migrate(
        self, from_addr: str, epoch: int, rows, final: bool
    ) -> Tuple[int, int]:
        """Peer-facing Migrate receive: inject one chunk of packed rows
        for an active inbound handoff."""
        if self.reshard is None:
            raise ApiError(
                "FAILED_PRECONDITION", "resharding disabled"
            )
        try:
            return await self.reshard.on_migrate(
                from_addr, epoch, rows, final
            )
        except KeyError as e:
            raise ApiError("FAILED_PRECONDITION", str(e)) from None

    async def drain_for_shutdown(self) -> int:
        """Graceful scale-down: migrate every owned row to the ring
        without this node (the autoscaler's SIGTERM/preStop drain),
        then keep forwarding stale-routed checks until close.  Returns
        rows shipped; 0 when resharding is disabled or single-node."""
        if self.reshard is None:
            return 0
        return await self.reshard.drain_all()

    def _strip_sketch_global(
        self, reqs: Sequence[RateLimitReq]
    ) -> Sequence[RateLimitReq]:
        """Sketch-tier names don't compose with GLOBAL replication (the
        sketch is not broadcast); strip the flag so such requests route
        plainly to the key's owner and are counted ONCE there instead of
        locally-plus-forwarded (double counting).  Applied on both the
        client routing path and the peer RPC (zero-copy forwards splice
        the client's original bytes, so the owner re-strips)."""
        if self.sketch_backend is None:
            return reqs
        from dataclasses import replace as dc_replace

        return [
            dc_replace(
                r,
                behavior=Behavior(int(r.behavior) & ~int(Behavior.GLOBAL)),
            )
            if (
                has_behavior(r.behavior, Behavior.GLOBAL)
                and self.sketch_backend.handles(r)
            )
            else r
            for r in reqs
        ]

    # ------------------------------------------------------------------
    # hot-key survival plane (runtime/hotkey.py; docs/hotkeys.md)
    # ------------------------------------------------------------------
    def note_traffic(
        self, key_hashes: np.ndarray, hits: np.ndarray
    ) -> None:
        """Feed the hot-key detector one batch of routed traffic.
        Called once per batch by whichever path actually serves it (the
        compiled lane's check_raw or the object path), so a fast-lane
        fallback never observes the same requests twice."""
        hk = self.hotkeys
        if hk is not None and len(key_hashes):
            hk.observe(key_hashes, hits)
        tier = self.tier
        if tier is not None and len(key_hashes):
            # Promote-on-access (docs/tiering.md): a served key that is
            # cold-resident schedules a FIFO host-job inject; THIS
            # batch was already answered from whatever the device had.
            tier.note_access(key_hashes, hits)

    def _peer_by_fp(self, fp: int) -> Optional[PeerClient]:
        """Owning peer for a device fingerprint — xx rings only, where
        the ring hash IS the XXH64 key fingerprint (the fast router's
        own premise, replicated_hash.ring_arrays).  None on fnv interop
        rings or an empty pool."""
        from gubernator_tpu.net.replicated_hash import xx_64

        pick = self.local_picker
        if pick.size() == 0 or pick.hash_fn is not xx_64:
            return None
        ring, ring_idx, peers = pick.ring_arrays()
        if not len(ring):
            return None
        i = int(np.searchsorted(
            ring, np.int64(fp).astype(np.uint64), side="left"
        ))
        if i == len(ring):
            i = 0
        # ring_idx is the picker's host-side numpy cache, never a
        # device array.
        idx = int(ring_idx[i])  # gubguard: ok=host-sync
        return peers[idx]

    def _owner_pressure_of(self, fp: int) -> float:
        """Owner SLO-pressure ratio for a key fingerprint — the
        multiplier in the hot-key promotion score.  Keys we own use our
        own flight recorder's sustained-breach state; keys a peer owns
        use the ratio that peer advertised on RPC trailing metadata
        (0 once its TTL lapsed).  On fnv interop rings (no fp->owner
        mapping) the strongest signal anywhere applies — conservative:
        it can only promote more, and mirror membership is still
        checked per key at serve time."""
        fr = getattr(self.metrics, "flightrec", None)
        own = (
            fr.pressure_ratio()
            if fr is not None and fr.pressure_active() else 0.0
        )
        peer = self._peer_by_fp(fp)
        if peer is not None:
            if peer.info().is_owner:
                return own
            return peer.pressure_ratio()
        peers = self.local_picker.peers()
        if not peers:
            return own
        return max(
            [own]
            + [
                p.pressure_ratio() for p in peers
                if not p.info().is_owner
            ]
        )

    def _is_mirror_hashed(self, h: int) -> bool:
        """True when this node is one of the key's next-arc mirror
        replicas (owner excluded) for ring hash `h`."""
        try:
            cand = self.local_picker.get_n_hashed(
                h, 1 + self.cfg.hotkey.mirrors
            )
        except PoolEmptyError:
            return False
        return any(p.info().is_owner for p in cand[1:])

    def _mirror_eligible(
        self, req: RateLimitReq, key: str, peer: PeerClient
    ) -> bool:
        """Should this forwarded check serve from a local mirror
        allowance instead?  All four gates must hold: widening enabled,
        the owner currently advertising pressure, the key promoted into
        the hot-set, and this node among the key's next-arc replicas.
        Sketch-tier names never mirror (the CMS tier is already
        cardinality-safe and counts once at the owner)."""
        hk = self.hotkeys
        hkc = self.cfg.hotkey
        if hk is None or hkc.mirrors <= 0:
            return False
        if not peer.pressure_active():
            return False
        if (
            self.sketch_backend is not None
            and self.sketch_backend.handles(req)
        ):
            return False
        from gubernator_tpu.core.hashing import key_hash64
        from gubernator_tpu.runtime.hotkey import fp64

        if not hk.is_hot(fp64(key_hash64(key))):
            return False
        return self._is_mirror_hashed(
            self.local_picker.hash_fn(key.encode())
        )

    def active_mirror_fps(self) -> np.ndarray:
        """int64 fingerprints this node is actively mirroring right now
        (hot AND owner pressured AND we are a next-arc replica) — the
        compiled lane's pull-out mask.  Cached per tracker version with
        a short TTL so pressure transitions land within ~a window.
        Empty on fnv interop rings (the object path still mirrors
        there; only the columnar mask needs the fp->owner mapping)."""
        hk = self.hotkeys
        if hk is None or self.cfg.hotkey.mirrors <= 0:
            return _EMPTY_I64
        hot = hk.hot_arr
        if not len(hot):
            return _EMPTY_I64
        now = time.monotonic()
        cached = self._mirror_fps_cache
        if (
            cached is not None
            and cached[1] == hk.version
            and now - cached[0] < 0.25
        ):
            return cached[2]
        active = [
            int(fp) for fp in hot if self._fp_actively_mirrored(int(fp))
        ]
        arr = (
            np.array(active, dtype=np.int64) if active else _EMPTY_I64
        )
        self._mirror_fps_cache = (now, hk.version, arr)
        return arr

    def _fp_actively_mirrored(self, fp: int) -> bool:
        peer = self._peer_by_fp(fp)
        if peer is None or peer.info().is_owner:
            return False
        if not peer.pressure_active():
            return False
        return self._is_mirror_hashed(int(np.int64(fp).astype(np.uint64)))

    async def _mirror_serve(
        self, req: RateLimitReq, peer: PeerClient
    ) -> RateLimitResp:
        """Serve a hot key from this mirror's LOCAL allowance while its
        owner is under measured SLO pressure.

        The admission algebra is local_shadow's with pressure (not
        death) as the gate: the check rewrites onto
        `<unique_key>.hot-mirror` — its own slot in the local table —
        at `fraction x limit`, so each of the `mirrors` next-arc
        replicas admits at most fraction x limit per window and
        cluster-wide admission for the key stays within
        limit x (1 + mirrors x fraction).  The ORIGINAL hits reconcile
        to the owner through the GLOBAL async-hit machinery
        (aggregated, provably-unsent-gated — at most once), so the
        authoritative row converges on the true total."""
        from dataclasses import replace as dc_replace

        from gubernator_tpu.core.hashing import key_hash64
        from gubernator_tpu.runtime.hotkey import MIRROR_SUFFIX, fp64

        owner = peer.info().grpc_address
        hkc = self.cfg.hotkey
        self.mirror_served += 1
        self.metrics.hotkey_mirror_served.inc()
        self.metrics.getratelimit_counter.labels("local").inc()
        if req.limit <= 0:
            # Deny-all keys stay deny-all on mirrors (the local_shadow
            # rule): the max(1, ...) floor keeps small positive limits
            # serviceable, never fails-open an explicit zero.
            return RateLimitResp(
                status=Status.OVER_LIMIT,
                limit=req.limit,
                remaining=0,
                reset_time=self._resolve_reset_ms(req),
                metadata={"hotkey": "mirror", "owner": owner},
            )
        mirror_limit = max(1, int(req.limit * hkc.fraction))
        mirror = dc_replace(
            req,
            unique_key=req.unique_key + MIRROR_SUFFIX,
            limit=mirror_limit,
            burst=min(req.burst, mirror_limit) if req.burst else 0,
            behavior=Behavior(
                int(req.behavior)
                & ~int(Behavior.GLOBAL)
                & ~int(Behavior.MULTI_REGION)
            ),
        )
        resps = await self._check_local([mirror])
        resp = resps[0]
        if not resp.error:
            md = dict(resp.metadata) if resp.metadata else {}
            md["hotkey"] = "mirror"
            md["owner"] = owner
            resp.metadata = md
            fp = fp64(key_hash64(req.hash_key()))
            if self.hotkeys is not None:
                self.hotkeys.note_name(fp, req.hash_key())
            # Reconcile the ORIGINAL hits toward the owner (async,
            # aggregated per key — global.go:87-95's queue).
            if req.hits:
                self.global_mgr.queue_hit(dc_replace(req))
            # Remember how to drop this mirror slot when the key
            # demotes: zero-hit RESET_REMAINING removes a token row
            # outright and re-fills a leaky one (the shadow-drop
            # mechanics, _drop_shadow).
            self._mirror_resets[fp] = dc_replace(
                mirror,
                hits=0,
                behavior=Behavior(
                    int(mirror.behavior) | int(Behavior.RESET_REMAINING)
                ),
            )
        return resp

    def _on_hot_demote(self, fps: List[int]) -> None:
        """Tracker callback (outside its lock, any thread): the keys
        collapsed out of the hot-set — drop their local mirror slots so
        no stale mirror admission state survives the widening."""
        resets = [
            self._mirror_resets.pop(fp)
            for fp in fps
            if fp in self._mirror_resets
        ]
        if not resets:
            return
        loop = self._loop
        if loop is None or loop.is_closed():
            return

        def submit() -> None:
            t = asyncio.ensure_future(self._reset_mirrors(resets))
            self._shadow_tasks.add(t)
            t.add_done_callback(self._shadow_tasks.discard)

        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is loop:
            submit()
        else:
            loop.call_soon_threadsafe(submit)

    async def _reset_mirrors(self, resets: List[RateLimitReq]) -> None:
        try:
            await self._check_local(resets)
            fr = getattr(self.metrics, "flightrec", None)
            if fr is not None:
                fr.record("hotkey_mirror_drop", keys=len(resets))
        except Exception as e:  # noqa: BLE001 — slots expire anyway
            log.warning("mirror reset after demotion failed: %s", e)

    # ------------------------------------------------------------------
    # SLO-driven adaptive shedding (docs/hotkeys.md)
    # ------------------------------------------------------------------
    def shed_level(self) -> int:
        """Current shed escalation level.  0 = no shedding.  Level L
        sheds requests whose priority class index is < L, where classes
        are the `shed_priorities` globs in lowest-priority-first order.
        Arms only once this node's own p99 breach run has persisted
        `shed_cooldown_s` (the flight recorder's sustained-breach
        clock), escalating one class per further cooldown — and never
        sheds names matching no glob."""
        hkc = self.cfg.hotkey
        if not hkc.enabled or not hkc.shed_priorities:
            return 0
        fr = getattr(self.metrics, "flightrec", None)
        if fr is None:
            return 0
        sustained = fr.pressure_sustained_s()
        if sustained < hkc.shed_cooldown_s:
            return 0
        return min(
            1 + int((sustained - hkc.shed_cooldown_s)
                    // hkc.shed_cooldown_s),
            len(hkc.shed_priorities),
        )

    def shed_priority(self, name: str) -> int:
        """Priority class of a limit name: the index of the first
        matching glob (0 sheds first); names matching none rank past
        every class and are never shed."""
        for i, pat in enumerate(self.cfg.hotkey.shed_priorities):
            if fnmatch.fnmatch(name, pat):
                return i
        return len(self.cfg.hotkey.shed_priorities)

    def _shed_response(self, req: RateLimitReq) -> RateLimitResp:
        """DROP with retry-after rather than queueing: an overloaded
        node must not stack deferred work it cannot serve
        (arXiv:2510.04516's requester-side admission argument)."""
        self.shed_served += 1
        self.metrics.peer_shed_total.labels(
            peerAddr="local", reason="pressure"
        ).inc()
        if self.tenants is not None:
            self.tenants.record_shed(req.name, int(req.hits or 0))
        retry_ms = int(self.cfg.hotkey.shed_cooldown_s * 1000)
        now_ms = int(self.clock.now_ns() // 1_000_000)
        return RateLimitResp(
            status=Status.OVER_LIMIT,
            limit=req.limit,
            remaining=0,
            reset_time=now_ms + retry_ms,
            metadata={
                "shed": "pressure",
                "retry_after_ms": str(retry_ms),
            },
        )

    # ------------------------------------------------------------------
    # client API
    # ------------------------------------------------------------------
    async def get_rate_limits(
        self, reqs: Sequence[RateLimitReq]
    ) -> List[RateLimitResp]:
        """The hot path (gubernator.go:194-310)."""
        if len(reqs) > MAX_BATCH_SIZE:
            self.metrics.note_check_error("Request too large")
            raise ApiError(
                "OUT_OF_RANGE",
                "Requests.RateLimits list too large; max size is '%d'"
                % MAX_BATCH_SIZE,
            )
        self._inflight_checks += 1
        self.metrics.concurrent_checks.observe(self._inflight_checks)
        start = time.monotonic()
        try:
            with tracing.span(
                "V1Instance.GetRateLimits", num_items=len(reqs)
            ):
                return await self._get_rate_limits(reqs)
        finally:
            self._inflight_checks -= 1
            self._fd_get_rate_limits.observe(time.monotonic() - start)

    async def _get_rate_limits(
        self, reqs: Sequence[RateLimitReq]
    ) -> List[RateLimitResp]:
        n = len(reqs)
        responses: List[Optional[RateLimitResp]] = [None] * n

        local_idx: List[int] = []
        local_cached: List[bool] = []
        local_owner_meta: List[Optional[str]] = []
        forwards: List[Tuple[int, PeerClient, RateLimitReq, str]] = []
        mirrors: List[Tuple[int, PeerClient, RateLimitReq]] = []
        covered: List[Tuple[int, RateLimitReq, str, object]] = []
        region_serves: List[Tuple[int, RateLimitReq, str, str]] = []

        reqs = self._strip_sketch_global(reqs)

        if self.hotkeys is not None or self.tier is not None:
            valid = [r for r in reqs if r.unique_key and r.name]
            if valid:
                from gubernator_tpu.core.hashing import bulk_key_hash64

                self.note_traffic(
                    bulk_key_hash64([r.hash_key() for r in valid]),
                    np.array([r.hits for r in valid], dtype=np.int64),
                )
        shed = self.shed_level()

        engine_idx: List[int] = []

        single_node = self.local_picker.size() == 0
        for i, req in enumerate(reqs):
            # Client-side validation BEFORE routing (gubernator.go:228-237):
            # an invalid request answers inline — it is never forwarded (no
            # owner metadata on its error) and never queues GLOBAL updates
            # or MULTI_REGION hits.  The peer RPC keeps the owner-side
            # packer validation with QueueUpdate-before-algorithm semantics.
            if not req.unique_key:
                self.metrics.note_check_error("Invalid request")
                responses[i] = RateLimitResp(
                    error="field 'unique_key' cannot be empty"
                )
                continue
            if not req.name:
                self.metrics.note_check_error("Invalid request")
                responses[i] = RateLimitResp(
                    error="field 'namespace' cannot be empty"
                )
                continue
            if shed and self.shed_priority(req.name) < shed:
                # SLO-driven shedding (docs/hotkeys.md): the breach run
                # outlasted the cooldown — drop low-priority traffic
                # BEFORE any routing, device work, or replication
                # queueing (a shed request must leave no state behind).
                responses[i] = self._shed_response(req)
                continue
            key = req.hash_key()
            is_global = has_behavior(req.behavior, Behavior.GLOBAL)
            # Region routing (docs/multiregion.md): a key whose HOME
            # region is elsewhere serves from the bounded local
            # `.region-carve` slot at the in-region owner — never a
            # WAN round-trip on the request path.  GLOBAL and legacy
            # MULTI_REGION traffic keep their own replication lanes.
            region_home: Optional[str] = None
            if (
                self.regions is not None
                and not is_global
                and not has_behavior(req.behavior, Behavior.MULTI_REGION)
            ):
                region_home = self.regions.remote_home(key)
            if single_node:
                if region_home is not None:
                    region_serves.append((i, req, key, region_home))
                    continue
                if is_global and self.global_engine is not None:
                    self.metrics.getratelimit_counter.labels("global").inc()
                    engine_idx.append(i)
                    if has_behavior(req.behavior, Behavior.MULTI_REGION):
                        # The engine path bypasses _check_local's owner-side
                        # queueing — keep cross-region replication alive.
                        self.multi_region_mgr.queue_hits(req)
                else:
                    local_idx.append(i)
                    local_cached.append(False)
                    local_owner_meta.append(None)
                continue
            try:
                peer = self.get_peer(key)
            except PoolEmptyError as e:
                responses[i] = RateLimitResp(
                    error=f"Error in GetPeer, looking up peer that owns "
                    f"rate limit '{key}': {e}"
                )
                continue
            if peer.info().is_owner:
                if region_home is not None:
                    # In-region owner of a remote-homed key: the one
                    # node in this region that carves for it (one
                    # carve per region, not one per node — the bound
                    # counts regions).
                    region_serves.append((i, req, key, region_home))
                    continue
                rs = self.reshard
                if rs is not None and rs.active() and not is_global:
                    # Live resharding (docs/resharding.md): a key whose
                    # arc is mid-handoff must not be served from this
                    # node's (absent or not-yet-authoritative) row.
                    ib = rs.inbound_covering(key)
                    if ib is not None:
                        # We are the NEW owner and the handoff is still
                        # in flight: forward back / bounded shadow.
                        covered.append((i, req, key, ib))
                        continue
                    tgt = rs.reroute_target(key)
                    if tgt is not None:
                        # We are a draining OLD owner whose rows are
                        # gone: forwards-or-serves says forward.
                        tp = self.local_picker.get_by_address(tgt)
                        if tp is not None:
                            forwards.append((i, tp, req, key))
                            continue
                if is_global and self.global_engine is not None:
                    # This node's mesh owns the key: replicated serving +
                    # ICI-collective sync instead of the RPC loops.
                    self.metrics.getratelimit_counter.labels("global").inc()
                    engine_idx.append(i)
                    if has_behavior(req.behavior, Behavior.MULTI_REGION):
                        self.multi_region_mgr.queue_hits(req)
                    continue
                self.metrics.getratelimit_counter.labels("local").inc()
                local_idx.append(i)
                local_cached.append(False)
                local_owner_meta.append(None)
            elif has_behavior(req.behavior, Behavior.GLOBAL):
                self.metrics.getratelimit_counter.labels("global").inc()
                # Serve locally from replicated cache; queue the hit for the
                # owner (gubernator.go:272-283, 420-460).
                local_idx.append(i)
                local_cached.append(True)
                local_owner_meta.append(peer.info().grpc_address)
                self.global_mgr.queue_hit(req)
            elif region_home is None and self._mirror_eligible(req, key, peer):
                # Hot-key widening (docs/hotkeys.md): the owner is
                # measurably pressured and this node is one of the
                # key's next-arc mirrors — serve from the local
                # allowance instead of piling onto the owner.
                mirrors.append((i, peer, req))
            else:
                forwards.append((i, peer, req, key))

        tasks = [
            asyncio.ensure_future(self._forward(peer, req, key))
            for (_, peer, req, key) in forwards
        ]
        mirror_tasks = [
            asyncio.ensure_future(self._mirror_serve(req, peer))
            for (_, peer, req) in mirrors
        ]
        covered_tasks = [
            asyncio.ensure_future(
                self.reshard.serve_covered(req, key, ib)
            )
            for (_, req, key, ib) in covered
        ]
        region_tasks = [
            asyncio.ensure_future(self.regions.serve(req, key, home))
            for (_, req, key, home) in region_serves
        ]

        try:
            if local_idx:
                local_resps = await self._check_local(
                    [reqs[i] for i in local_idx], local_cached
                )
                for j, i in enumerate(local_idx):
                    resp = local_resps[j]
                    if local_owner_meta[j] is not None and not resp.error:
                        resp.metadata = {"owner": local_owner_meta[j]}
                    responses[i] = resp
            if engine_idx:
                eng_reqs = [reqs[i] for i in engine_idx]
                loop = asyncio.get_running_loop()
                eng_resps = await loop.run_in_executor(
                    self._dev_executor,
                    lambda: self.global_engine.check(eng_reqs),
                )
                for j, i in enumerate(engine_idx):
                    responses[i] = eng_resps[j]
                if self._collective_loop is not None:
                    self._collective_loop.notify()
        finally:
            # Always await in-flight forwards — a local-check failure must
            # not orphan tasks whose hits were already applied on peers.
            if tasks:
                results = await asyncio.gather(*tasks, return_exceptions=True)
                for (i, _, _, key), resp in zip(forwards, results):
                    if isinstance(resp, BaseException):
                        responses[i] = RateLimitResp(
                            error=f"Error while fetching rate limit "
                            f"'{key}' from peer: {resp}"
                        )
                    else:
                        responses[i] = resp
            if mirror_tasks:
                results = await asyncio.gather(
                    *mirror_tasks, return_exceptions=True
                )
                for (i, _, req), resp in zip(mirrors, results):
                    if isinstance(resp, BaseException):
                        responses[i] = RateLimitResp(
                            error=f"Error serving hot-key mirror for "
                            f"'{req.hash_key()}': {resp}"
                        )
                    else:
                        responses[i] = resp
            if covered_tasks:
                results = await asyncio.gather(
                    *covered_tasks, return_exceptions=True
                )
                for (i, _, key, _ib), resp in zip(covered, results):
                    if isinstance(resp, BaseException):
                        responses[i] = RateLimitResp(
                            error=f"Error serving resharding key "
                            f"'{key}': {resp}"
                        )
                    else:
                        responses[i] = resp
            if region_tasks:
                results = await asyncio.gather(
                    *region_tasks, return_exceptions=True
                )
                for (i, _, key, _home), resp in zip(region_serves, results):
                    if isinstance(resp, BaseException):
                        responses[i] = RateLimitResp(
                            error=f"Error serving region carve for "
                            f"'{key}': {resp}"
                        )
                    else:
                        responses[i] = resp

        return [r if r is not None else RateLimitResp() for r in responses]

    async def _check_local(
        self,
        reqs: Sequence[RateLimitReq],
        use_cached: Optional[Sequence[bool]] = None,
    ) -> List[RateLimitResp]:
        """Apply checks on the local device engine; queue GLOBAL owner
        updates and MULTI_REGION hits (getRateLimit, gubernator.go:600-631).

        Concurrent callers COALESCE: their requests merge into one device
        step through the local batcher instead of serializing one step per
        RPC — the device analog of the reference's many-workers
        concurrency, and the main p99 lever under concurrent small calls.
        """
        for r, cached in zip(
            reqs, use_cached or [False] * len(reqs)
        ):
            if cached:
                continue  # non-owner read path — not authoritative
            if has_behavior(r.behavior, Behavior.GLOBAL):
                self.global_mgr.queue_update(r)
            if has_behavior(r.behavior, Behavior.MULTI_REGION):
                self.multi_region_mgr.queue_hits(r)
        loop = asyncio.get_running_loop()
        if self.sketch_backend is not None:
            # Split off approximate-tier names; merge answers back in order.
            sk_idx = [
                i for i, r in enumerate(reqs)
                if self.sketch_backend.handles(r)
            ]
            if sk_idx:
                sk_set = set(sk_idx)
                ex_idx = [i for i in range(len(reqs)) if i not in sk_set]
                sk_resps = await loop.run_in_executor(
                    self._dev_executor,
                    lambda: self.sketch_backend.check(
                        [reqs[i] for i in sk_idx]
                    ),
                )
                ex_resps = (
                    await self._local_batcher.check(
                        [reqs[i] for i in ex_idx],
                        [
                            use_cached[i] if use_cached else False
                            for i in ex_idx
                        ],
                    )
                    if ex_idx
                    else []
                )
                out: List[Optional[RateLimitResp]] = [None] * len(reqs)
                for j, i in enumerate(sk_idx):
                    out[i] = sk_resps[j]
                for j, i in enumerate(ex_idx):
                    out[i] = ex_resps[j]
                self._touch_global_captures(
                    [reqs[i] for i in ex_idx],
                    [use_cached[i] for i in ex_idx] if use_cached else None,
                )
                if self.tenants is not None:
                    self.tenants.record_checks(reqs, out)
                return out  # type: ignore[return-value]
        resps = await self._local_batcher.check(reqs, use_cached)
        self._touch_global_captures(reqs, use_cached)
        # Gubstat: every LOCAL device serve — direct and every shadow
        # plane (mirror / lease / degraded / handoff reqs all ride
        # through here with their suffixed unique_key) — tallies into
        # the per-tenant ledger exactly once, at this choke point.
        if self.tenants is not None:
            self.tenants.record_checks(reqs, resps)
        return resps

    def _touch_global_captures(
        self,
        reqs: Sequence[RateLimitReq],
        use_cached: Optional[Sequence[bool]] = None,
    ) -> None:
        """Object-path mutations must degrade any stale captured GLOBAL
        broadcast rows for the touched keys (GlobalManager.touch_hashes).
        No-op unless captures are pending."""
        if not self.global_mgr._pending_h or not reqs:
            return
        from gubernator_tpu.core.hashing import bulk_key_hash64

        keys = [
            r.hash_key()
            for r, cached in zip(
                reqs, use_cached or [False] * len(reqs)
            )
            if not cached
        ]
        if keys:
            self.global_mgr.touch_hashes(bulk_key_hash64(keys))

    async def _forward(
        self, peer: PeerClient, req: RateLimitReq, key: str
    ) -> RateLimitResp:
        """Forward to the owning peer; on NotReady re-resolve the owner (it
        may now be us) up to 5 times (asyncRequests, gubernator.go:327-416).
        When the owner's breaker is open, or the retry loop exhausts, the
        configured GUBER_DEGRADED_MODE policy decides the answer
        (docs/resilience.md).
        """
        attempts = 0
        last_err: Optional[Exception] = None
        cap_s = self.cfg.behaviors.batch_timeout_s
        degraded = self.cfg.degraded_mode != "error"
        while True:
            if attempts > ASYNC_RETRIES:
                return await self._degraded_response(req, key, peer, last_err)
            if attempts != 0 and peer.info().is_owner:
                resps = await self._check_local([req])
                return resps[0]
            if degraded and peer.circuit_open():
                # The owner is known-dead (breaker open, backoff running):
                # re-resolving the ring would hand back the same peer, so
                # serve the degraded policy without burning the retry loop.
                return await self._degraded_response(
                    req, key, peer,
                    last_err or PeerNotReadyError(
                        f"circuit open for {peer.info().grpc_address}"
                    ),
                )
            try:
                self.metrics.getratelimit_counter.labels("forward").inc()
                resp = await peer.get_peer_rate_limit(req)
                # The reference replaces metadata wholesale with the owner
                # annotation (gubernator.go:281,406), but its responses
                # never carry other metadata, so merging is observably
                # identical there — and it preserves the sketch tier's
                # "tier" tag (no reference analog) across forwards.
                md = dict(resp.metadata) if resp.metadata else {}
                md["owner"] = peer.info().grpc_address
                resp.metadata = md
                # (Shadow drop on heal rides peer.on_rpc_success — it
                # fires for this success and every other RPC path.)
                return resp
            except PeerNotReadyError as e:
                last_err = e
                attempts += 1
                self.metrics.asyncrequest_retries.labels(req.name).inc()
                if attempts > ASYNC_RETRIES:
                    continue  # exhausted — no pointless final backoff
                # Back off before re-resolving: immediate retries against a
                # dying peer all complete before any discovery update can
                # land (the reference retries after the peer's reconnect
                # backoff).  Equal-jittered exponential (10ms.. doubling,
                # capped at the batch timeout) keeps total added latency
                # within one RPC budget while decorrelating the retry
                # stampede across forwarders.
                await asyncio.sleep(
                    forward_backoff_s(attempts, cap_s, self._rng)
                )
                try:
                    peer = self.get_peer(key)
                except PoolEmptyError as pe:
                    return RateLimitResp(
                        error="Error finding peer that owns rate limit "
                        f"'{key}': {pe}"
                    )
            except Exception as e:  # noqa: BLE001
                return RateLimitResp(
                    error=f"Error while fetching rate limit '{key}' "
                    f"from peer: {e}"
                )

    def _resolve_reset_ms(self, req: RateLimitReq) -> int:
        """reset_time for a synthesized (degraded / mirror-denied)
        answer.  req.duration under DURATION_IS_GREGORIAN is a
        calendar-interval id (0-5), NOT milliseconds — resolve it
        through the same expansion the algorithm layer uses, or omit
        reset_time when the id is invalid (the authoritative path would
        error on it anyway)."""
        now_ms = int(self.clock.now_ns() // 1_000_000)
        if has_behavior(req.behavior, Behavior.DURATION_IS_GREGORIAN):
            try:
                return gregorian_expiration(
                    self.clock.now(), int(req.duration)
                )
            except GregorianError:
                return 0
        return now_ms + max(int(req.duration), 0)

    # ------------------------------------------------------------------
    # degraded-mode ownership fallback (docs/resilience.md)
    # ------------------------------------------------------------------
    async def _degraded_response(
        self,
        req: RateLimitReq,
        key: str,
        peer: PeerClient,
        last_err: Optional[Exception],
    ) -> RateLimitResp:
        """The answer while the owner is gone, per GUBER_DEGRADED_MODE:

        error        the legacy strict contract — an error response, the
                     client decides (reference gubernator.go:358-366);
        fail_closed  deny: OVER_LIMIT, remaining=0 (an outage admits
                     nothing extra, at the price of rejecting legitimate
                     traffic);
        fail_open    admit: UNDER_LIMIT at the full limit (availability
                     over enforcement — unbounded over-admission while
                     degraded);
        local_shadow serve from a LOCAL shadow slot in the device table
                     at `shadow_fraction` of the limit: each non-owner
                     admits at most fraction*limit per window, bounding
                     cluster-wide over-admission to peers * fraction *
                     limit while keeping per-client fairness.  Shadow
                     state is reset when the owner heals.

        All degraded answers tag `metadata["degraded"]` so clients and
        tests can distinguish them from authoritative decisions."""
        mode = self.cfg.degraded_mode
        if mode == "error":
            return RateLimitResp(
                error="GetPeer() keeps returning peers that are not "
                f"connected for '{key}': {last_err}"
            )
        owner = peer.info().grpc_address
        self.degraded_served += 1
        self.metrics.degraded_total.labels(mode=mode).inc()
        fr = getattr(self.metrics, "flightrec", None)
        if fr is not None:
            fr.record("degraded", mode=mode, key=key, owner=owner)
        reset_ms = self._resolve_reset_ms(req)
        if mode == "fail_closed":
            return RateLimitResp(
                status=Status.OVER_LIMIT,
                limit=req.limit,
                remaining=0,
                reset_time=reset_ms,
                metadata={"degraded": mode, "owner": owner},
            )
        if mode == "fail_open":
            return RateLimitResp(
                status=Status.UNDER_LIMIT,
                limit=req.limit,
                remaining=max(req.limit - req.hits, 0),
                reset_time=reset_ms,
                metadata={"degraded": mode, "owner": owner},
            )
        # local_shadow
        if req.limit <= 0:
            # A deny-all key must stay deny-all while degraded: the
            # max(1, ...) floor below exists to keep a small positive
            # limit serviceable, not to fail-open an explicit zero.
            return RateLimitResp(
                status=Status.OVER_LIMIT,
                limit=req.limit,
                remaining=0,
                reset_time=reset_ms,
                metadata={"degraded": mode, "owner": owner},
            )
        from dataclasses import replace as dc_replace

        shadow_limit = max(1, int(req.limit * self.cfg.shadow_fraction))
        shadow = dc_replace(
            req,
            unique_key=req.unique_key + SHADOW_SUFFIX,
            limit=shadow_limit,
            burst=min(req.burst, shadow_limit) if req.burst else 0,
            behavior=Behavior(
                int(req.behavior)
                & ~int(Behavior.GLOBAL)
                & ~int(Behavior.MULTI_REGION)
            ),
        )
        resps = await self._check_local([shadow])
        resp = resps[0]
        if not resp.error:
            md = dict(resp.metadata) if resp.metadata else {}
            md["degraded"] = mode
            md["owner"] = owner
            resp.metadata = md
            # Remember how to drop this shadow slot on heal: a zero-hit
            # RESET_REMAINING removes a token-bucket row outright
            # (algorithms.go:78-90) and re-fills a leaky one — either
            # way no stale shadow admission state survives the owner
            # becoming authoritative again.
            self._shadow.setdefault(owner, {})[shadow.hash_key()] = (
                dc_replace(
                    shadow,
                    hits=0,
                    behavior=Behavior(
                        int(shadow.behavior)
                        | int(Behavior.RESET_REMAINING)
                    ),
                )
            )
        return resp

    def _drop_shadow(self, addr: str) -> None:
        """The owner healed: reset its shadow slots (fire-and-forget —
        the healed forward that triggered this must not wait on it)."""
        pending = self._shadow.pop(addr, None)
        if not pending:
            return
        resets = list(pending.values())

        async def reset() -> None:
            try:
                await self._check_local(resets)
                fr = getattr(self.metrics, "flightrec", None)
                if fr is not None:
                    fr.record("shadow_drop", owner=addr, keys=len(resets))
            except Exception as e:  # noqa: BLE001 — slots expire anyway
                log.warning(
                    "shadow reset after owner %s healed failed: %s",
                    addr, e,
                )

        t = asyncio.ensure_future(reset())
        self._shadow_tasks.add(t)
        t.add_done_callback(self._shadow_tasks.discard)

    # ------------------------------------------------------------------
    # client-side admission leases (runtime/lease.py; docs/leases.md)
    # ------------------------------------------------------------------
    def spawn_task(self, coro) -> None:
        """Fire-and-forget a coroutine on the service loop, tracked so
        shutdown can await it (the shadow-task discipline)."""
        t = asyncio.ensure_future(coro)
        self._shadow_tasks.add(t)
        t.add_done_callback(self._shadow_tasks.discard)

    async def _lease_sweep_loop(self) -> None:
        """Periodic grant-expiry sweep: lapsed holders are revoked and a
        key's carve slot drops once its last holder is gone, so the
        owner re-collects un-burned allowance without waiting for a
        reconcile that may never come (a dead holder)."""
        interval = max(self.cfg.lease.ttl_ms / 2000.0, 0.05)
        while True:
            await asyncio.sleep(interval)
            try:
                await self.leases.sweep_apply()
            except Exception as e:  # noqa: BLE001 — keep the cadence
                log.warning("lease sweep failed: %s", e)

    def _split_by_owner(self, keys: Sequence[str]):
        """(owned indices, {addr: (peer, indices)}) for a key list —
        the lease/reconcile ownership split.  A pool-empty or
        single-node picker owns everything locally."""
        owned: List[int] = []
        by_peer: Dict[str, Tuple[PeerClient, List[int]]] = {}
        single = self.local_picker.size() == 0
        for i, key in enumerate(keys):
            if single:
                owned.append(i)
                continue
            try:
                peer = self.get_peer(key)
            except PoolEmptyError:
                owned.append(i)
                continue
            if peer.info().is_owner:
                owned.append(i)
            else:
                addr = peer.info().grpc_address
                by_peer.setdefault(addr, (peer, []))[1].append(i)
        return owned, by_peer

    async def lease(
        self, client_id: str, reqs: Sequence[RateLimitReq]
    ) -> List[LeaseGrant]:
        """Grant leases for the keys this node owns; forward the rest
        to their owners (the edge-daemon proxy role — a LeasedClient
        talks to ONE daemon and the ring routes its grants).  Grants
        come back in request order; an unreachable owner refuses
        rather than errors, so the client degrades to per-call checks
        transparently."""
        if self.leases is None:
            return [
                LeaseGrant(
                    key=r.hash_key(), limit=r.limit,
                    refusal="leases disabled",
                )
                for r in reqs
            ]
        out: List[Optional[LeaseGrant]] = [None] * len(reqs)
        owned, by_peer = self._split_by_owner(
            [r.hash_key() for r in reqs]
        )
        if owned:
            grants = await self.leases.grant(
                client_id, [reqs[i] for i in owned]
            )
            for i, g in zip(owned, grants):
                out[i] = g

        async def forward(peer: PeerClient, idx: List[int]) -> None:
            try:
                grants = await peer.lease(
                    client_id, [reqs[i] for i in idx]
                )
                for i, g in zip(idx, grants):
                    out[i] = g
            except Exception as e:  # noqa: BLE001 — refuse, degrade
                for i in idx:
                    out[i] = LeaseGrant(
                        key=reqs[i].hash_key(), limit=reqs[i].limit,
                        refusal=f"owner unreachable: {e}",
                    )

        if by_peer:
            await asyncio.gather(
                *(forward(p, idx) for p, idx in by_peer.values())
            )
        return [
            g if g is not None else LeaseGrant(refusal="not routed")
            for g in out
        ]

    async def reconcile(
        self, client_id: str, items: Sequence
    ) -> List[LeaseGrant]:
        """Apply burned-hit reconciliation for the keys this node owns;
        forward the rest to their owners.  One grant per item in item
        order (allowance 0 unless the item asked to renew)."""
        if self.leases is None:
            return [
                LeaseGrant(
                    key=it.request.hash_key(), limit=it.request.limit,
                    refusal="leases disabled",
                )
                for it in items
            ]
        from dataclasses import replace as dc_replace

        out: List[Optional[LeaseGrant]] = [None] * len(items)
        owned, by_peer = self._split_by_owner(
            [it.request.hash_key() for it in items]
        )
        if owned:
            grants = await self.leases.reconcile(
                client_id, [items[i] for i in owned]
            )
            for i, g in zip(owned, grants):
                out[i] = g

        # Non-owned burned hits ride GlobalManager.queue_hit — the
        # at-most-once aggregation whose flush re-queues on provably-
        # unsent failures, so a holder's burn survives an owner
        # partition and converges after heal (a direct forward would
        # have to drop it on any failure).  Only the release/renew
        # bookkeeping forwards to the owner's LeaseManager, with hits
        # zeroed so they cannot double-apply.
        for _peer, idx in by_peer.values():
            for i in idx:
                if items[i].request.hits > 0:
                    self.global_mgr.queue_hit(
                        dc_replace(items[i].request)
                    )

        async def forward(peer: PeerClient, idx: List[int]) -> None:
            if not any(
                items[i].release or items[i].renew for i in idx
            ):
                # Burn-only items already rode queue_hit — nothing
                # for the owner's LeaseManager to learn.
                for i in idx:
                    out[i] = LeaseGrant(
                        key=items[i].request.hash_key(),
                        limit=items[i].request.limit,
                    )
                return
            stripped = [
                dc_replace(
                    items[i],
                    request=dc_replace(items[i].request, hits=0),
                )
                for i in idx
            ]
            try:
                grants = await peer.reconcile(client_id, stripped)
                for i, g in zip(idx, grants):
                    out[i] = g
            except Exception as e:  # noqa: BLE001
                # Renewals refuse (the client degrades); a lost release
                # is re-collected by the owner's TTL sweep.
                for i in idx:
                    out[i] = LeaseGrant(
                        key=items[i].request.hash_key(),
                        limit=items[i].request.limit,
                        refusal=f"owner unreachable: {e}",
                    )

        if by_peer:
            await asyncio.gather(
                *(forward(p, idx) for p, idx in by_peer.values())
            )
        return [
            g if g is not None else LeaseGrant(refusal="not routed")
            for g in out
        ]

    # ------------------------------------------------------------------
    # peer-facing API (server side)
    # ------------------------------------------------------------------
    async def get_peer_rate_limits(
        self, reqs: Sequence[RateLimitReq]
    ) -> List[RateLimitResp]:
        """Owner side of a forwarded batch: apply ALL requests in one device
        step (replacing the reference's goroutine fan-out,
        gubernator.go:482-543) preserving request order."""
        if len(reqs) > MAX_BATCH_SIZE:
            raise ApiError(
                "OUT_OF_RANGE",
                "'PeerRequest.rate_limits' list too large; max size is '%d'"
                % MAX_BATCH_SIZE,
            )
        # Forwarders normally strip GLOBAL from sketch-tier names before
        # sending, but zero-copy forwards (the compiled lane) splice the
        # client's original bytes — re-strip here so a GLOBAL+sketch
        # request never queues an exact-table broadcast for a sketch key.
        reqs = self._strip_sketch_global(reqs)
        if self.hotkeys is not None or self.tier is not None:
            # Owner-side detection: forwarded traffic is exactly the
            # load a pressured owner needs to see per key.
            valid = [r for r in reqs if r.unique_key and r.name]
            if valid:
                from gubernator_tpu.core.hashing import bulk_key_hash64

                self.note_traffic(
                    bulk_key_hash64([r.hash_key() for r in valid]),
                    np.array([r.hits for r in valid], dtype=np.int64),
                )
        special: Dict[int, object] = {}
        if self.regions is not None:
            # Region routing (docs/multiregion.md): a forwarded check
            # for a remote-homed key lands here because this node is
            # the key's in-region owner — serve the bounded
            # `.region-carve` slot, never the raw row at full limit.
            # The WAN reconcile lane arrives at the HOME region's
            # owner, where remote_home() is None, and applies below.
            for i, r in enumerate(reqs):
                if not r.unique_key or not r.name:
                    continue
                if has_behavior(r.behavior, Behavior.GLOBAL):
                    continue
                if has_behavior(r.behavior, Behavior.MULTI_REGION):
                    continue
                key = r.hash_key()
                home = self.regions.remote_home(key)
                if home is not None:
                    special[i] = ("region", key, home)
        rs = self.reshard
        if rs is not None and rs.active():
            # Live resharding (docs/resharding.md): forwarded checks
            # for mid-handoff keys must not apply on this node's table.
            # Covered inbound keys (we are the new owner, handoff in
            # flight) forward back / serve the bounded shadow; rerouted
            # outbound keys (our rows are gone — post-TRANSFER or a
            # draining leaver) forward to the new owner.  Everything
            # else applies locally as usual.  (Remote-homed keys keep
            # their region dispatch: the carve slot is a derived slot
            # and migrates with the arc.)
            for i, r in enumerate(reqs):
                if i in special:
                    continue
                if not r.unique_key or not r.name:
                    continue
                if has_behavior(r.behavior, Behavior.GLOBAL):
                    continue
                key = r.hash_key()
                ib = rs.inbound_covering(key)
                if ib is not None:
                    special[i] = ("covered", key, ib)
                    continue
                tgt = rs.reroute_target(key)
                if tgt is not None:
                    tp = self.local_picker.get_by_address(tgt)
                    if tp is not None:
                        special[i] = ("reroute", key, tp)
        if special:
            async def _serve_special(spec, r):
                kind, key, arg = spec
                if kind == "region":
                    return await self.regions.serve(r, key, arg)
                if kind == "covered":
                    return await rs.serve_covered(r, key, arg)
                return await self._forward(arg, r, key)

            kept = [
                r for i, r in enumerate(reqs) if i not in special
            ]
            inner_task = asyncio.gather(*(
                _serve_special(special[i], reqs[i])
                for i in sorted(special)
            ), return_exceptions=True)
            inner = (
                await self._check_local(kept) if kept else []
            )
            spec_resps = dict(zip(sorted(special), await inner_task))
            it = iter(inner)
            out: List[RateLimitResp] = []
            for i, r in enumerate(reqs):
                if i in special:
                    resp = spec_resps[i]
                    if isinstance(resp, BaseException):
                        resp = RateLimitResp(
                            error="Error serving forwarded key "
                            f"'{r.hash_key()}': {resp}"
                        )
                    out.append(resp)
                else:
                    out.append(next(it))
            return out
        shed = self.shed_level()
        if shed:
            # Owner-side shedding of forwarded traffic — the relief
            # valve that actually unloads a pressured owner.
            shed_idx = {
                i for i, r in enumerate(reqs)
                if r.name and self.shed_priority(r.name) < shed
            }
            if shed_idx:
                kept = [
                    r for i, r in enumerate(reqs) if i not in shed_idx
                ]
                inner = await self._check_local(kept) if kept else []
                it = iter(inner)
                return [
                    self._shed_response(r) if i in shed_idx else next(it)
                    for i, r in enumerate(reqs)
                ]
        return await self._check_local(reqs)

    async def update_peer_globals(
        self, globals_: Sequence[UpdatePeerGlobal]
    ) -> None:
        """Receive owner-authoritative GLOBAL statuses into the local cache
        (gubernator.go:464-479)."""
        rows = [
            (
                g.key,
                int(g.algorithm),
                int(g.status.limit),
                int(g.status.remaining),
                int(g.status.status),
                int(g.status.reset_time),
            )
            for g in globals_
            if g.status is not None
        ]
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(
            self._dev_executor, lambda: self.backend.apply_cached_rows(rows)
        )

    # ------------------------------------------------------------------
    # health / lifecycle
    # ------------------------------------------------------------------
    async def health_check(self) -> HealthCheckResp:
        """Report peer connectivity from the rolling per-peer error windows
        (gubernator.go:546-598)."""
        errs: List[str] = []
        local_peers = self.local_picker.peers()
        for peer in local_peers:
            for msg in peer.last_errors():
                errs.append(
                    f"Error returned from local peer.GetLastErr: {msg}"
                )
        region_peers = self.region_picker.peers()
        for peer in region_peers:
            for msg in peer.last_errors():
                errs.append(
                    f"Error returned from region peer.GetLastErr: {msg}"
                )
        # Circuit plane: an open/half-open breaker is a live statement
        # that a peer is being shed — surface it even after the error
        # window has pruned the failures that tripped it.
        for peer in local_peers + region_peers:
            state = peer.circuit_state_name()
            if state in ("open", "half_open"):
                snap = peer.circuit_snapshot()
                errs.append(
                    f"Circuit {state} for peer "
                    f"{peer.info().grpc_address} (trips="
                    f"{snap.get('trips', 0)}, reopens in "
                    f"{snap.get('open_remaining_s', 0.0):g}s)"
                )
        h = HealthCheckResp(
            status=HEALTHY, peer_count=len(local_peers) + len(region_peers)
        )
        if errs:
            h.status = UNHEALTHY
            h.message = "|".join(errs)
        # Pressure plane (docs/hotkeys.md): an overloaded-but-ALIVE
        # peer — clean error window, breaker closed, SLO advertised
        # breached — must not read as fully healthy.  Advisory lines
        # only: the peer IS serving, so status stays driven by
        # connectivity (flipping it would invite LB churn on exactly
        # the node that needs its traffic spread, not removed).
        pressure_lines = []
        for peer in local_peers + region_peers:
            ratio = peer.pressure_ratio()
            if ratio >= 1.0:
                pressure_lines.append(
                    f"Pressure on peer {peer.info().grpc_address}: "
                    f"advertised p99 at {ratio:.2f}x its SLO target"
                )
        lvl = self.shed_level()
        if lvl:
            pressure_lines.append(
                f"Pressure shedding active on this node (level {lvl} "
                f"of {len(self.cfg.hotkey.shed_priorities)})"
            )
        # Migration-state lines (docs/resharding.md): in-flight
        # handoffs are advisory — the node IS serving, just with
        # covered keys routed through the handoff protocol.
        if self.reshard is not None and self.reshard.active():
            pressure_lines.extend(self.reshard.health_lines())
        if pressure_lines:
            extra = "|".join(pressure_lines)
            h.message = f"{h.message}|{extra}" if h.message else extra
        # SLO telemetry rides along (runtime/flightrec.py): the rolling
        # p99 vs the configured target, so degraded-mode decisions can
        # key off measured tail latency (status itself stays driven by
        # peer connectivity, like the reference).
        fr = getattr(self.metrics, "flightrec", None)
        if fr is not None and fr.breaches:
            slo = (
                f"SLO: {fr.breaches} p99 breach(es) of "
                f"{fr.slo_p99_ms:g}ms target; rolling "
                f"p99={fr.last_p99_ms:.3f}ms"
            )
            h.message = f"{h.message}|{slo}" if h.message else slo
        return h

    def _engine_synced(self, pending) -> None:
        """Bridge collective syncs to the RPC tier: after the engine applies
        a window's hits on the auth table, broadcast the (now authoritative)
        statuses to cross-NODE peers via the RPC GlobalManager.  Runs on a
        device-executor thread, so hop to the loop for the asyncio queues."""
        if self.local_picker.size() <= 1:
            return  # single node — every peer already saw the all_gather
        loop = self._loop
        if loop is None or loop.is_closed():
            return

        def queue_all() -> None:
            for p in pending.values():
                self.global_mgr.queue_update(p.req)

        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is loop:
            queue_all()
        else:
            loop.call_soon_threadsafe(queue_all)

    async def close(self) -> None:
        """Flush managers, run the Loader save, shut down peers
        (gubernator.go:159-189)."""
        if self._closed:
            return
        self._closed = True
        if self._lease_sweep_task is not None:
            self._lease_sweep_task.cancel()
            await asyncio.gather(
                self._lease_sweep_task, return_exceptions=True
            )
            self._lease_sweep_task = None
        if self._reshard_watch_task is not None:
            self._reshard_watch_task.cancel()
            await asyncio.gather(
                self._reshard_watch_task, return_exceptions=True
            )
            self._reshard_watch_task = None
        if self._collective_loop is not None:
            await self._collective_loop.close()
        await self.global_mgr.close()
        await self.multi_region_mgr.close()
        if self.regions is not None:
            await self.regions.close()
        await self._local_batcher.close()
        if self.cfg.loader is not None:
            loop = asyncio.get_running_loop()
            items = await loop.run_in_executor(
                self._dev_executor, self.backend.live_items
            )
            self.cfg.loader.save(iter(items))
        peers = set(self.local_picker.peers()) | set(
            self.region_picker.peers()
        )
        if peers:
            await asyncio.gather(
                *(p.shutdown() for p in peers), return_exceptions=True
            )
        self._dev_executor.shutdown(wait=True)


class LocalBatcher:
    """Coalesces concurrent local checks into shared device steps.

    No artificial wait window (unlike the network peer batcher, there is no
    RPC to amortize): a drain loop takes EVERYTHING queued the moment the
    device is free and runs it as one step.  Under load the step rate is
    device-bound while arrival concurrency rides along as extra lanes —
    latency stays ~2 steps instead of `concurrency` steps.
    """

    def __init__(self, service: Service, max_coalesce: int = 8192) -> None:
        self.s = service
        self.max_coalesce = max_coalesce
        self._queue: asyncio.Queue = asyncio.Queue()
        self._task: Optional[asyncio.Task] = None
        # Device steps this batcher ran (round-trip accounting).
        self.steps = 0

    async def check(
        self,
        reqs: Sequence[RateLimitReq],
        use_cached: Optional[Sequence[bool]] = None,
    ) -> List[RateLimitResp]:
        if self._task is None:
            self._task = asyncio.ensure_future(self._run())
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        await self._queue.put((list(reqs), use_cached, fut))
        return await fut

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            entries = [await self._queue.get()]
            total = len(entries[0][0])
            while total < self.max_coalesce:
                try:
                    e = self._queue.get_nowait()
                except asyncio.QueueEmpty:
                    break
                entries.append(e)
                total += len(e[0])

            merged: List[RateLimitReq] = []
            merged_cached: List[bool] = []
            for reqs, cached, _ in entries:
                merged.extend(reqs)
                merged_cached.extend(
                    cached if cached is not None else [False] * len(reqs)
                )
            self.steps += 1
            try:
                resps = await loop.run_in_executor(
                    self.s._dev_executor,
                    lambda: self.s.backend.check(merged, merged_cached),
                )
            except Exception as e:  # noqa: BLE001
                for _, _, fut in entries:
                    if not fut.done():
                        fut.set_exception(e)
                continue
            off = 0
            for reqs, _, fut in entries:
                if not fut.done():
                    fut.set_result(resps[off:off + len(reqs)])
                off += len(reqs)

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            await asyncio.gather(self._task, return_exceptions=True)
            self._task = None


async def window_flush_loop(event, sync_wait_s, take, flush) -> None:
    """The shared batching heartbeat (interval.go:29-72's one-shot ticker):
    the first queued item sets `event`, opening a `sync_wait_s` window;
    when it closes, `take()`'s batch (if any) goes to `flush`.  A flush
    failure is logged and the cadence survives (the flushers do their own
    per-chunk error handling; this guard is the backstop)."""
    while True:
        await event.wait()
        await asyncio.sleep(sync_wait_s)
        event.clear()
        batch = take()
        if batch:
            try:
                await flush(batch)
            except Exception as e:  # noqa: BLE001 — keep the cadence
                log.error("window flush failed: %s", e)


class CollectiveGlobalLoop:
    """Drives GlobalEngine.sync on the global_sync_wait cadence — the
    collective analog of the reference's runAsyncHits + runBroadcasts
    timers (global.go:63-64, 96-119): the first queued hit opens a sync
    window; everything queued within it syncs in one all_to_all/all_gather
    step.  (The batch-limit trigger lives in GlobalEngine.check itself.)
    """

    def __init__(self, service: Service, engine) -> None:
        self.s = service
        self.engine = engine
        self.sync_wait_s = service.cfg.behaviors.global_sync_wait_s
        self._event = asyncio.Event()
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.ensure_future(
                window_flush_loop(
                    self._event, self.sync_wait_s,
                    lambda: self.engine.pending, self._flush,
                )
            )

    def notify(self) -> None:
        """Hits were queued on the engine — open/extend a sync window."""
        self._event.set()

    async def _flush(self, _pending) -> None:
        loop = asyncio.get_running_loop()
        start = time.monotonic()
        n = await loop.run_in_executor(
            self.s._dev_executor, self.engine.sync
        )
        if n:
            self.s.metrics.async_durations.observe(time.monotonic() - start)

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            await asyncio.gather(self._task, return_exceptions=True)
            self._task = None
        # Final flush so queued hits survive a graceful shutdown.
        if self.engine.pending:
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(
                self.s._dev_executor, self.engine.sync
            )


class GlobalManager:
    """Async GLOBAL replication loops (global.go:33-254)."""

    def __init__(self, service: Service) -> None:
        self.s = service
        cfg = service.cfg.behaviors
        self.sync_wait_s = cfg.global_sync_wait_s
        self.batch_limit = cfg.global_batch_limit
        self.timeout_s = cfg.global_timeout_s
        self._hits: Dict[str, RateLimitReq] = {}
        # key -> (req, captured status | None).  A captured status is the
        # post-step stored state from the drain that queued it — broadcast
        # directly, no zero-hit re-read needed.  None falls back to the
        # re-read (object path, engine bridge).
        self._updates: Dict[
            str, Tuple[RateLimitReq, Optional[RateLimitResp]]
        ] = {}
        # Device-fingerprint hash -> key, for entries holding a captured
        # status; lets mutation paths degrade a capture that went stale
        # (touch_hashes) without decoding keys.
        self._pending_h: Dict[int, str] = {}
        self._pending_arr: Optional[np.ndarray] = None
        self._hits_event = asyncio.Event()
        self._updates_event = asyncio.Event()
        self._tasks: List[asyncio.Task] = []
        # Observability counters (scraped by tests for eventual-consistency
        # assertions, functional_test.go:843-867).
        self.async_sends = 0
        self.broadcasts = 0
        # Round-trip accounting: zero-hit broadcast re-read batches/keys
        # (each batch is one LocalBatcher device step).
        self.reread_batches = 0
        self.reread_keys = 0

    def start(self) -> None:
        if self._tasks:
            return
        self._tasks = [
            asyncio.ensure_future(self._run_async_hits()),
            asyncio.ensure_future(self._run_broadcasts()),
        ]

    def queue_hit(self, r: RateLimitReq) -> None:
        """Aggregate a non-owner hit (summing same-key hits,
        global.go:87-95)."""
        key = r.hash_key()
        cur = self._hits.get(key)
        if cur is not None:
            cur.hits += r.hits
        else:
            from dataclasses import replace as dc_replace

            self._hits[key] = dc_replace(r)
        self._hits_event.set()

    def queue_update(
        self, r: RateLimitReq, status: Optional[RateLimitResp] = None
    ) -> None:
        """Record an owner-side status change to broadcast
        (global.go:167-191; last write per key wins).

        `status` is the drain's own post-step stored state for the key —
        when supplied, the broadcast uses it directly instead of running
        the zero-hit re-read of global.go:205-250 (equivalent by
        construction: a GLOBAL-cleared hits=0 read of a bucket row
        reports exactly the post-step stored status/remaining/reset; see
        ops.step.Resp.stored_status).  Callers that cannot capture pass
        None and keep the re-read."""
        key = r.hash_key()
        self._updates[key] = (r, status)
        if status is not None:
            from gubernator_tpu.core.hashing import key_hash64

            h = int(np.uint64(key_hash64(key)).view(np.int64))
            if self._pending_h.get(h) != key:
                self._pending_h[h] = key
                self._pending_arr = None
        self._updates_event.set()

    def touch_hashes(self, hashes: np.ndarray) -> None:
        """Degrade captured updates whose key a later drain mutated
        WITHOUT re-queueing (a non-GLOBAL request on the same key): the
        broadcast must not ship the stale capture, so the entry falls
        back to the zero-hit re-read — which sees the post-mutation
        state, exactly like the reference's flush-time read.  Called by
        every machinery mutation path with the drained int64 fingerprint
        column; near-free while no captures are pending.

        Concurrent-drain caveat: with overlapped drains a capture can be
        queued after the touch of a later-completing drain and survive
        one window stale — bounded by GLOBAL's eventual consistency (the
        reference's own broadcast value is stale by its flush+network
        delay)."""
        if not self._pending_h:
            return
        if self._pending_arr is None:
            self._pending_arr = np.fromiter(
                self._pending_h.keys(), dtype=np.int64,
                count=len(self._pending_h),
            )
        hit = np.isin(self._pending_arr, hashes)
        if not hit.any():
            return
        for h in self._pending_arr[hit]:
            key = self._pending_h.pop(int(h), None)
            if key is None:
                continue
            cur = self._updates.get(key)
            if cur is not None and cur[1] is not None:
                self._updates[key] = (cur[0], None)
        self._pending_arr = None

    def _take_hits(self) -> Dict[str, RateLimitReq]:
        hits, self._hits = self._hits, {}
        return hits

    def _take_updates(
        self,
    ) -> Dict[str, Tuple[RateLimitReq, Optional[RateLimitResp]]]:
        updates, self._updates = self._updates, {}
        self._pending_h.clear()
        self._pending_arr = None
        return updates

    async def _run_async_hits(self) -> None:
        # The first queued hit opens a sync_wait window; everything queued
        # within it flushes together (interval semantics, global.go:96-119),
        # split into batch_limit-sized RPCs by _send_hits.
        await window_flush_loop(
            self._hits_event, self.sync_wait_s,
            self._take_hits, self._send_hits,
        )

    async def _send_hits(self, hits: Dict[str, RateLimitReq]) -> None:
        """Group aggregated hits by owning peer and flush
        (global.go:124-164)."""
        by_peer: Dict[str, Tuple[PeerClient, List[RateLimitReq]]] = {}
        for key, r in hits.items():
            try:
                peer = self.s.get_peer(key)
            except PoolEmptyError:
                continue
            addr = peer.info().grpc_address
            by_peer.setdefault(addr, (peer, []))[1].append(r)
        start = time.monotonic()

        async def flush_one(peer: PeerClient, batch: List[RateLimitReq]):
            # One RPC per batch_limit-sized slice (the owner rejects
            # batches over MAX_BATCH_SIZE, gubernator.go:486-490).
            for lo in range(0, len(batch), self.batch_limit):
                chunk = batch[lo:lo + self.batch_limit]
                try:
                    await asyncio.wait_for(
                        peer.get_peer_rate_limits_batch(chunk),
                        timeout=self.timeout_s,
                    )
                    self.async_sends += 1
                except Exception as e:  # noqa: BLE001
                    if provably_unsent(e, peer):
                        # Shutdown / queue-full / connect-refused provably
                        # precede any delivery, so re-queueing cannot double
                        # count; a transiently unreachable owner keeps the
                        # window's hits (aggregation bounds the backlog by
                        # unique keys).
                        log.warning(
                            "re-queueing global hits for '%s': %s",
                            peer.info().grpc_address, e,
                        )
                        for r in chunk:
                            self.queue_hit(r)
                    else:
                        # Timeout or mid-RPC failure: the owner MAY have
                        # applied the batch already — re-sending would
                        # double count.  Drop, like the reference
                        # (global.go:152-162); the next live hit re-syncs.
                        log.error(
                            "dropping global hits for '%s': %s",
                            peer.info().grpc_address, e,
                        )

        # Fan out per peer — one slow peer must not delay the others.
        # The flush is a trace ROOT (sampled per the configured root
        # sampler): it aggregates many requests' queued hits, so there
        # is no single request context to continue — but the peer RPCs
        # under it still carry w3c traceparent, connecting the flush to
        # the owner daemons' server spans.
        with tracing.span(
            "global.flush_hits", parent=None,
            peers=len(by_peer), keys=len(hits),
        ):
            await asyncio.gather(
                *(flush_one(p, b) for p, b in by_peer.values())
            )
        self.s.metrics.async_durations.observe(time.monotonic() - start)

    async def _run_broadcasts(self) -> None:
        await window_flush_loop(
            self._updates_event, self.sync_wait_s,
            self._take_updates, self._broadcast_peers,
        )

    async def _read_statuses(self, reads) -> List[RateLimitResp]:
        """Zero-hit status re-read for the broadcast, on the OBJECT path.

        Deliberately NOT routed through the compiled lane: re-read lanes
        share keys with in-flight client GLOBAL merges, and a key whose
        occurrences mix use_cached (client reads) with uncached (the
        re-read) loses host-cascade eligibility — an A/B on the r4 rig
        measured global_4peer collapsing 20k -> 5k checks/s with re-reads
        merged into the lane, versus ~1/3 of cluster cycles saved.  The
        LocalBatcher still coalesces concurrent re-read batches."""
        return await self.s._check_local(reads)

    async def _broadcast_peers(
        self,
        updates: Dict[str, Tuple[RateLimitReq, Optional[RateLimitResp]]],
    ) -> None:
        """Push each updated status to every non-owner peer
        (global.go:205-250).  Entries whose drain captured the post-step
        stored state broadcast it directly; the rest re-read it (hits=0,
        GLOBAL cleared to avoid re-queueing) on the object path."""
        from dataclasses import replace as dc_replace

        globals_: List[UpdatePeerGlobal] = []
        to_read: List[RateLimitReq] = []
        for key, (r, captured) in updates.items():
            if captured is None:
                to_read.append(r)
            elif not captured.error:
                # An errored capture (validation / Gregorian) broadcasts
                # nothing — the re-read would fail the same way and be
                # skipped below.
                globals_.append(
                    UpdatePeerGlobal(
                        key=key, status=captured, algorithm=r.algorithm
                    )
                )
        if to_read:
            # Clear GLOBAL (avoid re-queueing a broadcast,
            # global.go:214-215) AND MULTI_REGION (a zero-hit status read
            # must not wake the cross-region sender).
            reads = [
                dc_replace(
                    r,
                    hits=0,
                    behavior=Behavior(
                        int(r.behavior)
                        & ~int(Behavior.GLOBAL)
                        & ~int(Behavior.MULTI_REGION)
                    ),
                )
                for r in to_read
            ]
            self.reread_batches += 1
            self.reread_keys += len(reads)
            try:
                statuses = await self._read_statuses(reads)
            except Exception as e:  # noqa: BLE001
                # The captured entries need no read — still ship them.
                log.error("while broadcasting update to peers: %s", e)
                statuses = []
            for r, status in zip(reads, statuses):
                if status.error:
                    continue
                globals_.append(
                    UpdatePeerGlobal(
                        key=r.hash_key(), status=status,
                        algorithm=r.algorithm,
                    )
                )
        if not globals_:
            return
        start = time.monotonic()

        async def push_one(peer: PeerClient) -> bool:
            try:
                # Chunk to respect the receiver's 1MB message cap.
                for lo in range(0, len(globals_), self.batch_limit):
                    await asyncio.wait_for(
                        peer.update_peer_globals(
                            globals_[lo:lo + self.batch_limit]
                        ),
                        timeout=self.timeout_s,
                    )
                return True
            except PeerNotReadyError:
                return False
            except Exception as e:  # noqa: BLE001
                log.error(
                    "while broadcasting global updates to '%s': %s",
                    peer.info().grpc_address, e,
                )
                return False

        with tracing.span(
            "global.broadcast", parent=None, updates=len(globals_)
        ):
            results = await asyncio.gather(
                *(
                    push_one(p)
                    for p in self.s.peer_list()
                    if not p.info().is_owner
                )
            )
        sent = any(results)
        if sent:
            self.broadcasts += 1
            self.s.metrics.broadcast_durations.observe(
                time.monotonic() - start
            )

    async def close(self) -> None:
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks = []
        # Drain-on-close: flush queued hits and broadcast queued updates
        # (best effort) — a graceful multi-node shutdown must not strand the
        # last window's statuses, especially those the collective engine's
        # final sync just queued for cross-node broadcast.
        hits = self._take_hits()
        if hits:
            await self._send_hits(hits)
        updates = self._take_updates()
        if updates:
            await self._broadcast_peers(updates)


class MultiRegionManager:
    """Cross-region (DCN-tier) hit replication.

    The reference ships only the skeleton — queue + interval loop with a
    no-op sender (multiregion.go:23-102).  Here the sender works: aggregated
    hits flush to the key's owner in every OTHER region, with MULTI_REGION
    cleared on the forwarded copy so receiving regions apply the hits locally
    instead of re-forwarding (the GLOBAL broadcast loop-prevention pattern,
    global.go:214-215).  Every region therefore converges on the sum of all
    regions' hits per key.
    """

    def __init__(self, service: Service) -> None:
        self.s = service
        cfg = service.cfg.behaviors
        self.sync_wait_s = cfg.multi_region_sync_wait_s
        self.batch_limit = cfg.multi_region_batch_limit
        self.timeout_s = cfg.multi_region_timeout_s
        self._hits: Dict[str, RateLimitReq] = {}
        self._event = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        self.region_sends = 0

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.ensure_future(self._run())

    def queue_hits(self, r: RateLimitReq) -> None:
        key = r.hash_key()
        cur = self._hits.get(key)
        if cur is not None:
            cur.hits += r.hits
        else:
            from dataclasses import replace as dc_replace

            self._hits[key] = dc_replace(r)
        self._event.set()

    def _take_hits(self) -> Dict[str, RateLimitReq]:
        hits, self._hits = self._hits, {}
        return hits

    async def _run(self) -> None:
        await window_flush_loop(
            self._event, self.sync_wait_s, self._take_hits, self._send_hits
        )

    async def _send_hits(self, hits: Dict[str, RateLimitReq]) -> None:
        from dataclasses import replace as dc_replace

        by_peer: Dict[str, Tuple[PeerClient, List[RateLimitReq]]] = {}
        for key, r in hits.items():
            fwd = dc_replace(
                r,
                behavior=Behavior(
                    int(r.behavior) & ~int(Behavior.MULTI_REGION)
                ),
            )
            for peer in self.s.region_picker.get_clients(key):
                addr = peer.info().grpc_address
                by_peer.setdefault(addr, (peer, []))[1].append(fwd)
        async def flush_one(peer: PeerClient, batch: List[RateLimitReq]):
            for lo in range(0, len(batch), self.batch_limit):
                chunk = batch[lo:lo + self.batch_limit]
                attempts = 0
                while True:
                    try:
                        await asyncio.wait_for(
                            peer.get_peer_rate_limits_batch(chunk),
                            timeout=self.timeout_s,
                        )
                        self.region_sends += 1
                        break
                    except Exception as e:  # noqa: BLE001
                        # Retry in place (with the peer that failed): a
                        # GLOBAL-style re-queue would double-count the
                        # regions that already received this window's fan.
                        attempts += 1
                        if attempts > 3:
                            log.error(
                                "dropping multi-region hits for '%s': %s",
                                peer.info().grpc_address, e,
                            )
                            break
                        # Floor the backoff at 200ms*attempt: a restarted
                        # peer's gRPC channel needs ~1s to reconnect, and
                        # sync_wait-paced retries (500µs default) would all
                        # fail inside that window and drop the hits.
                        await asyncio.sleep(
                            max(0.2 * attempts, self.sync_wait_s)
                        )

        await asyncio.gather(
            *(flush_one(p, b) for p, b in by_peer.values())
        )

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            await asyncio.gather(self._task, return_exceptions=True)
