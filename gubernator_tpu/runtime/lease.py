"""Client-side admission leases — the owner side (docs/leases.md).

At millions of users the cheapest RPC is the one never sent
(arXiv:2510.04516): a key's owner grants a holder (a LeasedClient or an
edge daemon) a bounded LOCAL allowance it may burn with zero RPCs,
decoupling admission from state publication (arXiv:2602.11741) exactly
the way the GLOBAL owner/broadcast machinery already does server-side.

The admission algebra is the hot-mirror / local_shadow carve, with the
OWNER holding the slot: every grant for a key burns `allowance =
fraction x limit` hits against a `<unique_key>.lease-grant` shadow slot
whose limit is `max_holders x allowance` per window, so the total
allowance outstanding per window can never exceed
`max_holders x fraction x limit` — and cluster-wide admission for the
key is bounded by `limit x (1 + max_holders x fraction)` even if every
holder partitions away with a full, unreconciled grant.  Burned hits
reconcile asynchronously (Reconcile RPC -> GlobalManager.queue_hit's
at-most-once aggregation; a peer-less single node applies directly), so
the authoritative row converges on the true total; grants are refused
outright while the owner is shedding under SLO pressure
(docs/hotkeys.md — a pressured owner must shed work, not delegate
more admission); and the carve slot is dropped via a zero-hit
RESET_REMAINING check once the last holder releases, reconciles away,
or expires — the shadow-drop discipline, so no stale lease admission
state outlives its holders.

Threading: `_lock` guards only the holder dict (never held across an
await or any device work); registered in the gubguard lock-order
ranking (tools/gubguard/lockorder.py) alongside hotkey._lock — taken
holding nothing, takes nothing while held.

Protocol spec: tools/gubproof/specs/lease.json — every write to a
holder record or the key table must map to a declared lifecycle edge
(grant -> renew -> reconcile -> release/expire), and the explorer
reproduces the `limit x (1 + max_holders x fraction)` bound exactly.
"""
from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, replace as dc_replace
from typing import Dict, List, Optional, Tuple

from gubernator_tpu.core.config import LeaseConfig
from gubernator_tpu.core.types import (
    Behavior,
    LeaseGrant,
    RateLimitReq,
    ReconcileItem,
    Status,
    has_behavior,
)

log = logging.getLogger("gubernator_tpu.lease")

# The carve slot's key suffix: lease allowance state lives in
# `<unique_key>` + this suffix, its own slot in the device table, so it
# never collides with the real key's authoritative or cached rows (the
# SHADOW_SUFFIX / MIRROR_SUFFIX convention).
LEASE_SUFFIX = ".lease-grant"

# Behaviors a lease cannot carry: GLOBAL/MULTI_REGION keys already have
# their own replication planes (and a broadcast would race the carve),
# RESET_REMAINING is a mutation rather than an admission, and Gregorian
# windows reset on calendar boundaries the holder cannot see.  Shared
# with the client SDK (client.LeasedClient) so both sides agree on what
# degrades to per-call checks.
NON_LEASABLE = (
    Behavior.GLOBAL
    | Behavior.MULTI_REGION
    | Behavior.RESET_REMAINING
    | Behavior.DURATION_IS_GREGORIAN
)


@dataclass
class _Holder:
    allowance: int
    expires_ms: int  # unix ms; 0 = placeholder being granted


class _KeyState:
    __slots__ = ("holders", "slot_reset")

    def __init__(self) -> None:
        self.holders: Dict[str, _Holder] = {}
        # Zero-hit RESET_REMAINING req that drops the carve slot once
        # the last holder is gone (filled on first successful grant).
        self.slot_reset: Optional[RateLimitReq] = None


class LeaseManager:
    """Per-node lease grant/reconcile state (owner side)."""

    def __init__(self, service, cfg: LeaseConfig, metrics=None) -> None:
        self.s = service
        self.cfg = cfg
        self.metrics = metrics
        self._lock = threading.Lock()
        self._keys: Dict[str, _KeyState] = {}
        # Observability mirrors (scraped by tests and /debug/vars).
        self.grants = 0
        self.refusals = 0
        self.reconciled_hits = 0
        self.revocations = 0

    # ------------------------------------------------------------------
    # helpers
    # ------------------------------------------------------------------
    def _now_ms(self) -> int:
        return int(self.s.clock.now_ns() // 1_000_000)

    def allowance_of(self, limit: int) -> int:
        """One holder's allowance for a limit — the carve unit."""
        return max(1, int(limit * self.cfg.fraction))

    def _leasable_limit(self, req: RateLimitReq) -> int:
        """The budget a grant may carve from.  A key homed in another
        REGION is itself served from this region's bounded
        `.region-carve` slot (docs/multiregion.md), so the lease
        fraction nests inside the region fraction — carving from the
        full limit here would hand holders budget this region never
        owned."""
        rm = getattr(self.s, "regions", None)
        if rm is not None and rm.remote_home(req.hash_key()) is not None:
            return max(1, int(req.limit * rm.fraction))
        return req.limit

    def refusal_for(self, req: RateLimitReq) -> str:
        """Why this limit cannot be leased; empty = leasable."""
        if not req.unique_key:
            return "field 'unique_key' cannot be empty"
        if not req.name:
            return "field 'namespace' cannot be empty"
        if req.limit <= 0:
            return "deny-all limit is not leasable"
        if int(req.behavior) & int(NON_LEASABLE):
            return "non-leasable behavior"
        if not self.s._owns_key(req.hash_key()):
            # A remap can demote this node between the routing split
            # and the grant (or a renewal can land on a demoted owner
            # directly): granting against the stale carve slot here
            # would be UNBOUNDED over-admission — the slot's budget
            # no longer backs the authoritative row, which lives (and
            # is fully spendable) at the new owner.
            return "not the owner of this key"
        sb = self.s.sketch_backend
        if sb is not None and sb.handles(req):
            return "sketch-tier names are not leasable"
        return ""

    def active_holders(self) -> int:
        """Total unexpired holders across keys (the active-grants
        gauge)."""
        now = self._now_ms()
        with self._lock:
            return sum(
                1
                for ks in self._keys.values()
                for h in ks.holders.values()
                if h.expires_ms == 0 or h.expires_ms > now
            )

    def _note_grant(self, outcome: str) -> None:
        if outcome == "granted":
            self.grants += 1
        else:
            self.refusals += 1
        if self.metrics is not None:
            self.metrics.lease_grants.labels(outcome=outcome).inc()

    def _note_revocation(self, reason: str, n: int = 1) -> None:
        self.revocations += n
        if self.metrics is not None:
            self.metrics.lease_revocations.labels(reason=reason).inc(n)

    def _refresh_gauge(self) -> None:
        if self.metrics is not None:
            self.metrics.lease_active_grants.set(self.active_holders())

    # ------------------------------------------------------------------
    # grant
    # ------------------------------------------------------------------
    async def grant(
        self, client_id: str, reqs: List[RateLimitReq]
    ) -> List[LeaseGrant]:
        """Grant (or refuse) a lease per request, in request order.

        The holder-count gate runs under the lock with a placeholder
        holder reserved BEFORE the device carve, so concurrent grant
        RPCs cannot overshoot max_holders between check and fill; the
        carve slot's own limit caps total outstanding allowance per
        window regardless."""
        now = self._now_ms()
        out: List[LeaseGrant] = []
        shedding = self.s.shed_level() > 0
        carve_reqs: List[RateLimitReq] = []
        carve_idx: List[int] = []
        reserved: List[Tuple[str, str]] = []  # (hash_key, client_id)
        for req in reqs:
            key = req.hash_key()
            g = LeaseGrant(key=key, limit=req.limit)
            refusal = self.refusal_for(req)
            if refusal:
                g.refusal = refusal
                self._note_grant("refused_behavior")
                out.append(g)
                continue
            if shedding:
                # A pressured owner sheds work; handing out MORE local
                # admission while breaching its SLO would hide exactly
                # the traffic it needs shed (docs/hotkeys.md).
                g.refusal = "owner shedding under pressure"
                self._note_grant("refused_pressure")
                out.append(g)
                continue
            with self._lock:
                ks = self._keys.setdefault(key, _KeyState())
                self._sweep_key_locked(ks, now)
                holder = ks.holders.get(client_id)
                if holder is None and (
                    len(ks.holders) >= self.cfg.max_holders
                ):
                    g.refusal = (
                        "max concurrent holders "
                        f"({self.cfg.max_holders}) reached"
                    )
                    self._note_grant("refused_holders")
                    out.append(g)
                    continue
                if holder is None:
                    # Reserve the holder slot before the await below.
                    ks.holders[client_id] = _Holder(0, 0)
                    reserved.append((key, client_id))
            carve_idx.append(len(out))
            carve_reqs.append(req)
            out.append(g)

        if not carve_reqs:
            self._refresh_gauge()
            return out

        allowances = [
            self.allowance_of(self._leasable_limit(r)) for r in carve_reqs
        ]
        slots = [
            dc_replace(
                r,
                unique_key=r.unique_key + LEASE_SUFFIX,
                hits=a,
                limit=a * self.cfg.max_holders,
                burst=0,
                behavior=Behavior.BATCHING,
            )
            for r, a in zip(carve_reqs, allowances)
        ]
        try:
            resps = await self.s._check_local(slots)
        except Exception as e:  # noqa: BLE001 — refuse, don't 500
            log.warning("lease carve failed: %s", e)
            resps = None
        expires = now + self.cfg.ttl_ms
        for j, i in enumerate(carve_idx):
            req, a, g = carve_reqs[j], allowances[j], out[i]
            key = g.key
            resp = resps[j] if resps is not None else None
            if resp is None or resp.error:
                g.refusal = (
                    f"carve failed: {resp.error}" if resp is not None
                    else "carve failed: device error"
                )
                self._note_grant("refused_error")
                self._unreserve(key, client_id, reserved)
                continue
            if resp.status != Status.UNDER_LIMIT:
                # The window's allowance budget (max_holders x
                # allowance) is spent — refuse until the slot refills.
                g.refusal = "allowance exhausted for this window"
                g.reset_time = resp.reset_time
                self._note_grant("refused_exhausted")
                self._unreserve(key, client_id, reserved)
                continue
            g.allowance = a
            g.expires_at = expires
            g.reset_time = resp.reset_time
            with self._lock:
                ks = self._keys.setdefault(key, _KeyState())
                ks.holders[client_id] = _Holder(a, expires)
                if ks.slot_reset is None:
                    ks.slot_reset = dc_replace(
                        slots[j],
                        hits=0,
                        behavior=Behavior.RESET_REMAINING,
                    )
            self._note_grant("granted")
        self._refresh_gauge()
        return out

    def _unreserve(
        self, key: str, client_id: str,
        reserved: List[Tuple[str, str]],
    ) -> None:
        """Drop a placeholder holder reserved for a grant that was then
        refused (keeps the count gate honest)."""
        if (key, client_id) not in reserved:
            return
        with self._lock:
            ks = self._keys.get(key)
            if ks is None:
                return
            h = ks.holders.get(client_id)
            if h is not None and h.expires_ms == 0 and h.allowance == 0:
                del ks.holders[client_id]
            if not ks.holders and ks.slot_reset is None:
                self._keys.pop(key, None)

    # ------------------------------------------------------------------
    # reconcile
    # ------------------------------------------------------------------
    async def reconcile(
        self, client_id: str, items: List[ReconcileItem]
    ) -> List[LeaseGrant]:
        """Apply burned hits (at-most-once), handle releases, and
        piggyback renewals; one grant per item in item order (allowance
        0 unless the item asked to renew)."""
        now = self._now_ms()
        out: List[LeaseGrant] = []
        burned: List[RateLimitReq] = []
        drops: List[RateLimitReq] = []
        renew_items: List[Tuple[int, RateLimitReq]] = []
        for it in items:
            req = it.request
            key = req.hash_key()
            g = LeaseGrant(key=key, limit=req.limit)
            out.append(g)
            if req.hits > 0:
                burned.append(dc_replace(req))
                self.reconciled_hits += req.hits
                if self.metrics is not None:
                    self.metrics.lease_reconciled_hits.inc(req.hits)
            if it.release:
                with self._lock:
                    ks = self._keys.get(key)
                    if ks is not None and ks.holders.pop(
                        client_id, None
                    ) is not None:
                        self._note_revocation("release")
                        if not ks.holders and ks.slot_reset is not None:
                            drops.append(ks.slot_reset)
                            self._keys.pop(key, None)
                g.refusal = "released"
            elif it.renew:
                renew_items.append((len(out) - 1, dc_replace(req, hits=0)))

        if burned:
            self._apply_burned(burned)
        if drops:
            await self._drop_slots(drops, reason="release")
        if renew_items:
            grants = await self.grant(
                client_id, [r for _, r in renew_items]
            )
            for (i, _), g in zip(renew_items, grants):
                out[i] = g
        self._refresh_gauge()
        return out

    def _apply_burned(self, burned: List[RateLimitReq]) -> None:
        """Converge the authoritative rows on the holders' local burn.

        With peers configured, the hits ride GlobalManager.queue_hit —
        the existing at-most-once aggregation (summed per key, flushed
        on the GLOBAL cadence, provably-unsent-gated re-queueing) whose
        flush lands on the key's owner wherever it is.  A peer-less
        single node applies directly through the local check path (the
        flush would have nowhere to route)."""
        rm = getattr(self.s, "regions", None)
        if rm is not None:
            # Remote-homed burns belong to the region reconcile lane:
            # the WAN flush routes them to the key's HOME region with
            # the same at-most-once discipline (a queue_hit flush
            # would land them on an in-region peer that is not truth).
            rest: List[RateLimitReq] = []
            for r in burned:
                home = rm.remote_home(r.hash_key())
                if home is not None:
                    rm.queue_burn(home, dc_replace(r))
                else:
                    rest.append(r)
            burned = rest
            if not burned:
                return
        if self.s.local_picker.size() == 0:
            reads = [
                dc_replace(
                    r,
                    behavior=Behavior(
                        int(r.behavior)
                        & ~int(Behavior.GLOBAL)
                        & ~int(Behavior.MULTI_REGION)
                    ),
                )
                for r in burned
            ]

            async def apply() -> None:
                try:
                    await self.s._check_local(reads)
                except Exception as e:  # noqa: BLE001
                    log.warning("lease burn apply failed: %s", e)

            self.s.spawn_task(apply())
            return
        for r in burned:
            self.s.global_mgr.queue_hit(r)

    async def _drop_slots(
        self, resets: List[RateLimitReq], reason: str
    ) -> None:
        """Drop carve slots whose last holder is gone: a zero-hit
        RESET_REMAINING removes a token row outright and re-fills a
        leaky one (the shadow-drop mechanics), so the un-burned
        allowance returns to the owner."""
        try:
            await self.s._check_local(resets)
            fr = getattr(self.s.metrics, "flightrec", None)
            if fr is not None:
                fr.record(
                    "lease_slot_drop", keys=len(resets), reason=reason
                )
        except Exception as e:  # noqa: BLE001 — slots expire anyway
            log.warning("lease slot drop (%s) failed: %s", reason, e)

    # ------------------------------------------------------------------
    # remap invalidation (runtime/reshard.py; docs/resharding.md)
    # ------------------------------------------------------------------
    def on_remap(self) -> None:
        """The ring changed: spawn the unowned-grant sweep (fire-and-
        forget on the service loop — set_peers must not await device
        work)."""
        self.s.spawn_task(self.drop_unowned())

    async def drop_unowned(self) -> int:
        """Revoke holder records and drop carve slots for keys this
        node no longer owns.  A demoted owner keeping them would keep
        honoring renewals against a stale carve slot — over-admission
        no algebra bounds, because the new owner grants its own full
        budget in parallel.  Holders renew through the ring and land on
        the new owner (their un-burned allowance stays within the lease
        bound and their burns reconcile there via queue_hit)."""
        drops: List[RateLimitReq] = []
        revoked = 0
        with self._lock:
            for key in list(self._keys):
                if self.s._owns_key(key):
                    continue
                ks = self._keys.pop(key)
                revoked += len(ks.holders)
                if ks.slot_reset is not None:
                    drops.append(ks.slot_reset)
        if revoked:
            self._note_revocation("remap", revoked)
        if drops:
            await self._drop_slots(drops, reason="remap")
        self._refresh_gauge()
        return revoked

    async def drop_rehomed(self, region: str) -> int:
        """Revoke holder records and drop carve slots for keys homed
        in `region` — the region-cutover analog of drop_unowned
        (docs/multiregion.md).  A healed home region re-asserts
        authority over its keys; grants carved here from the region
        fraction must not keep renewing against it, so holders
        re-acquire and their next grant sizes against the live
        topology."""
        rm = getattr(self.s, "regions", None)
        if rm is None:
            return 0
        drops: List[RateLimitReq] = []
        revoked = 0
        with self._lock:
            for key in list(self._keys):
                if rm.home_region(key) != region:
                    continue
                ks = self._keys.pop(key)
                revoked += len(ks.holders)
                if ks.slot_reset is not None:
                    drops.append(ks.slot_reset)
        if revoked:
            self._note_revocation("rehome", revoked)
        if drops:
            await self._drop_slots(drops, reason="rehome")
        self._refresh_gauge()
        return revoked

    # ------------------------------------------------------------------
    # expiry
    # ------------------------------------------------------------------
    def _sweep_key_locked(self, ks: _KeyState, now: int) -> int:
        expired = [
            cid
            for cid, h in ks.holders.items()
            if h.expires_ms and h.expires_ms <= now
        ]
        for cid in expired:
            del ks.holders[cid]
        return len(expired)

    def sweep(self) -> List[RateLimitReq]:
        """Expire overdue holders; returns the slot-reset requests for
        keys whose last holder just lapsed (the caller applies them on
        the device — sync state walk only here, no device work under
        the lock)."""
        now = self._now_ms()
        drops: List[RateLimitReq] = []
        expired = 0
        with self._lock:
            for key in list(self._keys):
                ks = self._keys[key]
                expired += self._sweep_key_locked(ks, now)
                if not ks.holders:
                    if ks.slot_reset is not None:
                        drops.append(ks.slot_reset)
                    self._keys.pop(key, None)
        if expired:
            self._note_revocation("expiry", expired)
        self._refresh_gauge()
        return drops

    async def sweep_apply(self) -> int:
        """One expiry pass including the device-side slot drops — the
        periodic task body (and the deterministic test entrypoint)."""
        drops = self.sweep()
        if drops:
            await self._drop_slots(drops, reason="expiry")
        return len(drops)

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------
    def debug_vars(self) -> dict:
        now = self._now_ms()
        with self._lock:
            keys = {
                key: {
                    cid: max(h.expires_ms - now, 0)
                    for cid, h in ks.holders.items()
                }
                for key, ks in self._keys.items()
            }
        return {
            "grants": self.grants,
            "refusals": self.refusals,
            "reconciled_hits": self.reconciled_hits,
            "revocations": self.revocations,
            "keys": keys,
            "config": {
                "fraction": self.cfg.fraction,
                "ttl_ms": self.cfg.ttl_ms,
                "max_holders": self.cfg.max_holders,
            },
        }
