"""Approximate-tier backend: serves selected limit names from the CMS.

Wiring for ops/sketch.py at the service level: limits whose `name` is in
`SketchTierConfig.names` (e.g. per-IP abuse limits with unbounded
cardinality) are answered from the sliding-window count-min sketch instead
of exact slots.  Memory is O(depth*width) regardless of key count — the
100M-key tier (BASELINE.json) — at the cost of bounded over-limiting of
hot-colliding keys (never under-limiting).

Semantics differences from the exact tier, by design:
- `remaining` is an estimate (limit - estimated_count, floored at 0);
- duration selects the sliding window only at tier-config granularity
  (`window_ms`), not per request — callers pick the tier per limit name;
- hits are always counted, even over limit (abusers stay measured).
"""
from __future__ import annotations

import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

from gubernator_tpu.core import clock as clock_mod
from gubernator_tpu.core.config import SketchTierConfig
from gubernator_tpu.core.types import RateLimitReq, RateLimitResp, Status


class SketchBackend:
    """CMS limiter over fixed-shape device batches."""

    def __init__(
        self,
        cfg: SketchTierConfig,
        clock: Optional[clock_mod.Clock] = None,
    ) -> None:
        from gubernator_tpu.ops.sketch import init_sketch, make_cms_step

        self.cfg = cfg
        self.clock = clock or clock_mod.default_clock()
        self.state = init_sketch(
            depth=cfg.depth, width=cfg.width, window_ms=cfg.window_ms
        )
        self._step = make_cms_step(use_pallas=cfg.use_pallas)
        self._lock = threading.Lock()
        self.batch = cfg.batch_size

    def handles(self, req: RateLimitReq) -> bool:
        return req.name in self.cfg.names

    def check_cols(
        self,
        key_hash: np.ndarray,
        hits: np.ndarray,
        limits: np.ndarray,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Columnar check for the compiled fast lane: int64 fingerprint /
        hits / limit arrays in, (status, remaining, reset_time) int64
        arrays out.  Same decision semantics as check() without
        per-request objects; validation happens upstream (the wire
        parser's err column excludes errored lanes)."""
        n = len(key_hash)
        status = np.zeros(n, dtype=np.int64)
        remaining = np.zeros(n, dtype=np.int64)
        reset = np.zeros(n, dtype=np.int64)
        now = self.clock.millisecond_now()
        window_ms = self.cfg.window_ms
        for lo in range(0, n, self.batch):
            hi = min(lo + self.batch, n)
            pad = self.batch - (hi - lo)
            kh = np.concatenate(
                [key_hash[lo:hi], np.zeros(pad, dtype=np.int64)]
            )
            hc = np.concatenate(
                [hits[lo:hi], np.zeros(pad, dtype=np.int64)]
            ).astype(np.int32)
            lc = np.concatenate(
                [limits[lo:hi], np.zeros(pad, dtype=np.int64)]
            ).astype(np.int32)
            with self._lock:
                self.state, over, est = self._step(
                    self.state, kh, hc, lc, np.int64(now)
                )
            over = np.asarray(over)[: hi - lo]
            est = np.asarray(est)[: hi - lo].astype(np.int64)
            win_start = int(np.asarray(self.state.window_start))
            status[lo:hi] = over.astype(np.int64)  # 1 = OVER_LIMIT
            remaining[lo:hi] = np.maximum(
                0, limits[lo:hi] - est - np.maximum(hits[lo:hi], 0)
            )
            reset[lo:hi] = win_start + window_ms
        return status, remaining, reset

    def check(self, reqs: Sequence[RateLimitReq]) -> List[RateLimitResp]:
        from gubernator_tpu import native

        # Same validation contract as the exact packer
        # (gubernator.go:228-237): errored requests get an error response
        # and never touch the sketch (an empty unique_key would otherwise
        # collide every such client on one shared bucket).
        errors: dict = {}
        valid: List[RateLimitReq] = []
        for i, r in enumerate(reqs):
            if not r.unique_key:
                errors[i] = "field 'unique_key' cannot be empty"
            elif not r.name:
                errors[i] = "field 'namespace' cannot be empty"
            else:
                valid.append(r)
        if errors:
            inner = self.check(valid) if valid else []
            out_all: List[RateLimitResp] = []
            it = iter(inner)
            for i in range(len(reqs)):
                if i in errors:
                    out_all.append(RateLimitResp(error=errors[i]))
                else:
                    out_all.append(next(it))
            return out_all

        n = len(reqs)
        now = self.clock.millisecond_now()
        hashes_all = native.hash_keys([r.hash_key() for r in reqs])
        out: List[RateLimitResp] = []
        window_ms = self.cfg.window_ms
        for lo in range(0, n, self.batch):
            chunk = reqs[lo:lo + self.batch]
            pad = self.batch - len(chunk)
            kh = np.concatenate(
                [hashes_all[lo:lo + self.batch],
                 np.zeros(pad, dtype=np.int64)]
            )
            hits = np.array(
                [r.hits for r in chunk] + [0] * pad, dtype=np.int32
            )
            limits = np.array(
                [r.limit for r in chunk] + [0] * pad, dtype=np.int32
            )
            with self._lock:
                self.state, over, est = self._step(
                    self.state, kh, hits, limits, np.int64(now)
                )
            over = np.asarray(over)
            est = np.asarray(est)
            win_start = int(np.asarray(self.state.window_start))
            reset = win_start + window_ms
            for j, r in enumerate(chunk):
                e = int(est[j])
                out.append(
                    RateLimitResp(
                        status=(
                            Status.OVER_LIMIT if over[j]
                            else Status.UNDER_LIMIT
                        ),
                        limit=r.limit,
                        remaining=max(0, r.limit - e - max(r.hits, 0)),
                        reset_time=reset,
                        metadata={"tier": "sketch"},
                    )
                )
        return out
