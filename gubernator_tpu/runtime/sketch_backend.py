"""Approximate-tier backend: serves selected limit names from the CMS.

Wiring for ops/sketch.py at the service level: limits whose `name` is in
`SketchTierConfig.names` (e.g. per-IP abuse limits with unbounded
cardinality) are answered from the sliding-window count-min sketch instead
of exact slots.  Memory is O(depth*width) regardless of key count — the
100M-key tier (BASELINE.json) — at the cost of bounded over-limiting of
hot-colliding keys (never under-limiting).

Dispatch discipline (the exact lane's, runtime/fastpath.py): a whole
merge — any size — is ONE device dispatch (chunks ride a lax.scan on
device), issued under the lock with the response sync OUTSIDE it, so
concurrent merges pipeline against each other's device round-trips
instead of serializing blocking reads.  `window_start` is mirrored on
host with the same rotation arithmetic the kernel applies, so building
`reset_time` costs no device read-back.

Semantics differences from the exact tier, by design:
- `remaining` is an estimate (limit - estimated_count, floored at 0);
- duration selects the sliding window only at tier-config granularity
  (`window_ms`), not per request — callers pick the tier per limit name;
- hits are always counted, even over limit (abusers stay measured).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from gubernator_tpu.core import clock as clock_mod
from gubernator_tpu.core.config import SketchTierConfig
from gubernator_tpu.core.types import RateLimitReq, RateLimitResp, Status


class HostCMS:
    """The CMS tier's estimator (ops/sketch.py) re-expressed in numpy
    for HOST-side frequency tracking — the hot-key detector's sketch
    (runtime/hotkey.py).

    Same contract as the device tier: per-row multiply-shift universal
    hashing over the int64 key fingerprints, min over `depth` rows,
    never underestimates.  Window semantics are the caller's: the
    tracker tumbles windows with the same boundary arithmetic the
    device kernel's rotation uses (`SketchBackend._advance_window`) and calls
    `clear()` at each boundary.  Memory is O(depth x width) regardless
    of key cardinality, so a zipfian storm cannot grow host state."""

    # Fixed odd multipliers (splitmix64-style constants) — one per row,
    # so the rows are independent hash functions of the SAME
    # fingerprint the device table and the ring router already use.
    _MULTS = (
        0x9E3779B97F4A7C15,
        0xBF58476D1CE4E5B9,
        0x94D049BB133111EB,
        0xD6E8FEB86659FD93,
        0xA0761D6478BD642F,
        0xE7037ED1A0B428DB,
    )

    def __init__(self, depth: int = 4, width: int = 4096) -> None:
        if width & (width - 1) or width <= 0:
            raise ValueError(f"HostCMS width must be a power of two, "
                             f"got {width}")
        if not 1 <= depth <= len(self._MULTS):
            raise ValueError(
                f"HostCMS depth must be 1..{len(self._MULTS)}, "
                f"got {depth}"
            )
        self.depth = depth
        self.width = width
        self._shift = np.uint64(64 - int(width).bit_length() + 1)
        self._mults = [np.uint64(m) for m in self._MULTS[:depth]]
        self.table = np.zeros((depth, width), dtype=np.int64)

    def _row_idx(self, u: np.ndarray, d: int) -> np.ndarray:
        # Multiply-shift: top log2(width) bits of (u * odd_const).
        with np.errstate(over="ignore"):
            return ((u * self._mults[d]) >> self._shift).astype(np.int64)

    def update(self, key_hashes: np.ndarray, weights: np.ndarray) -> None:
        """Add `weights[i]` to fingerprint `key_hashes[i]` (vectorized;
        duplicate fingerprints in one call accumulate)."""
        u = key_hashes.view(np.uint64)
        w = weights.astype(np.int64, copy=False)
        for d in range(self.depth):
            np.add.at(self.table[d], self._row_idx(u, d), w)

    def estimate(self, key_hashes: np.ndarray) -> np.ndarray:
        """Min-over-rows point estimates; >= the true count, always."""
        u = key_hashes.view(np.uint64)
        est = self.table[0][self._row_idx(u, 0)]
        for d in range(1, self.depth):
            est = np.minimum(est, self.table[d][self._row_idx(u, d)])
        return est

    def estimate_one(self, key_hash: int) -> int:
        return int(self.estimate(np.array([key_hash], dtype=np.int64))[0])

    def clear(self) -> None:
        self.table[:] = 0


def make_multi_step(impl):
    """Jitted scan over k chunks: ONE dispatch per merge, chunks applied
    in order on device (each sees the previous chunk's adds, the same
    sequencing the per-chunk host loop had).  Returns
    (state', packed int32[k, 2, B]) — over/est stacked so the whole
    response is one transfer.  Module-level factory so the gubtrace
    kernel registry (tools/gubtrace/registry.py) verifies the same
    computation the backend dispatches."""
    import jax
    import jax.numpy as jnp

    def multi(state, kh, hits, lim, now):
        def body(st, xs):
            khr, hr, lr = xs
            st, over, est = impl(st, khr, hr, lr, now)
            return st, jnp.stack([over.astype(jnp.int32), est])

        st, packed = jax.lax.scan(body, state, (kh, hits, lim))
        return st, packed

    return jax.jit(multi, donate_argnums=(0,))


class SketchBackend:
    """CMS limiter over fixed-shape device batches."""

    def __init__(
        self,
        cfg: SketchTierConfig,
        clock: Optional[clock_mod.Clock] = None,
    ) -> None:
        from gubernator_tpu.ops.sketch import (
            cms_step_scatter_impl,
            init_sketch,
        )

        self.cfg = cfg
        self.clock = clock or clock_mod.default_clock()
        self.state = init_sketch(
            depth=cfg.depth, width=cfg.width, window_ms=cfg.window_ms
        )
        if cfg.use_pallas:
            from gubernator_tpu.ops.pallas.cms_kernel import (
                cms_step_pallas_impl,
            )

            self._impl = cms_step_pallas_impl
        else:
            self._impl = cms_step_scatter_impl
        self._lock = threading.Lock()
        self._compile_lock = threading.Lock()
        self.batch = cfg.batch_size
        # Dynamic spillover state (cfg.spill_inserts/spill_transients):
        # names the exact tier degraded here at runtime, plus the
        # per-name-hash pressure state feeding the policy.  Guarded by
        # _spill_lock — the fast-lane pool reports pressure from its
        # worker threads while the service path reads membership.
        # Pressure per name is (hll_registers uint8[64], transients):
        # cardinality comes from a HyperLogLog over the insert lanes'
        # 64-bit key fingerprints, NOT a raw insert count — a long-lived
        # healthy name whose keys expire and re-insert must never look
        # like a cardinality bomb (the estimate converges on DISTINCT
        # keys; ~±13% at 64 registers, plenty for an order-of-magnitude
        # threshold).
        self._spill_lock = threading.Lock()
        self._dyn_names: set = set()
        self._dyn_hashes: Optional[np.ndarray] = np.empty(
            0, dtype=np.int64
        )
        self._pressure: Dict[int, list] = {}  # h -> [hll_regs, transients]
        self.spillovers = 0  # metric mirror (sketch_spillover_total)
        # Optional hook fired once per actual spill (the Service wires
        # the Prometheus counter here so operator-initiated spill_name
        # calls count too).
        self.on_spill = None
        # Bumped per spill so routing caches (fastpath._sketch_hashes)
        # rebuild their combined hash array only on membership change.
        self.membership_version = 0
        # Host mirror of state.window_start (ms), advanced with the same
        # arithmetic as the kernel's rotation (ops/sketch.py _rotate) —
        # reset_time needs no device read-back.
        self._win_start = 0
        # k (chunk count) -> jitted multi-chunk step; k is rounded up to
        # a power of two so merge-size jitter costs O(log) compiles.
        self._multi: Dict[int, object] = {}

    def handles(self, req: RateLimitReq) -> bool:
        return req.name in self.cfg.names or req.name in self._dyn_names

    @property
    def spill_enabled(self) -> bool:
        return (
            self.cfg.spill_inserts is not None
            or self.cfg.spill_transients is not None
        )

    def dynamic_hashes(self) -> np.ndarray:
        """XXH64 name fingerprints of runtime-spilled names (appended to
        the configured set by the fast lane's routing)."""
        return self._dyn_hashes

    def spill_name(self, name: str) -> bool:
        """Route `name` to the sketch tier from now on (runtime degrade;
        operators may call this directly).  Existing exact rows for the
        name are orphaned and expire naturally — answers for the name
        become approximate (metadata tier=sketch), never lost.  Returns
        False when the name was already sketch-tier (no-op)."""
        from gubernator_tpu import native

        with self._spill_lock:
            if name in self._dyn_names or name in self.cfg.names:
                return False
            self._dyn_names.add(name)
            self._dyn_hashes = np.concatenate(
                [self._dyn_hashes, native.hash_keys([name])]
            )
            self.spillovers += 1
            self.membership_version += 1
            hook = self.on_spill
        import logging

        logging.getLogger("gubernator_tpu.sketch").warning(
            "exact-tier pressure: limit name %r degraded to the "
            "count-min-sketch tier (approximate answers)", name,
        )
        if hook is not None:
            hook()
        return True

    # Pressure-map size bound: one entry (64-byte HLL + a counter) per
    # distinct limit NAME hash.  A name sweep must not grow host memory
    # without bound, so past the cap the entries furthest from any
    # threshold are dropped — they re-accumulate if the pressure was
    # real.
    _PRESSURE_CAP = 16_384
    _HLL_M = 64  # registers; standard error ~1.04/sqrt(m) ≈ 13%

    @staticmethod
    def _hll_estimate(regs: np.ndarray) -> float:
        m = len(regs)
        est = (0.709 * m * m) / float(
            np.sum(np.exp2(-regs.astype(np.float64)))
        )
        if est <= 2.5 * m:
            zeros = int((regs == 0).sum())
            if zeros:
                est = m * np.log(m / zeros)  # small-range correction
        return est

    def note_exact_pressure_batch(self, items, decode_names) -> int:
        """Accumulate one drain's exact-tier pressure and spill names
        whose thresholds cross.  `items` is a list of
        (name_hash, insert_key_hashes int64[], transients_count);
        `decode_names(name_hash)` lazily yields the name string (only
        called for crossing names).  One lock hold covers the whole
        drain.  Returns the number of names actually spilled (dedup
        inside spill_name)."""
        ins_thr = self.cfg.spill_inserts
        tra_thr = self.cfg.spill_transients
        m = self._HLL_M
        crossed: List[int] = []
        with self._spill_lock:
            for name_hash, ins_keys, transients in items:
                p = self._pressure.get(name_hash)
                if p is None:
                    p = [np.zeros(m, dtype=np.uint8), 0]
                    self._pressure[name_hash] = p
                if len(ins_keys):
                    # HLL update: register = LOW 6 bits of the key
                    # fingerprint (robust to any bias in the high bits),
                    # rank = leading-zeros+1 of the remaining 58 bits.
                    u = ins_keys.view(np.uint64)
                    reg = (u & np.uint64(m - 1)).astype(np.int64)
                    bits = (u >> np.uint64(6)) << np.uint64(6)
                    rank = np.ones(len(u), dtype=np.uint8)
                    for shift in (32, 16, 8, 4, 2, 1):
                        hi = bits >> np.uint64(64 - shift)
                        z = hi == 0
                        rank = np.where(
                            z, rank + np.uint8(shift), rank
                        ).astype(np.uint8)
                        bits = np.where(z, bits << np.uint64(shift), bits)
                    np.maximum.at(p[0], reg, rank)
                p[1] += int(transients)
                over = (
                    ins_thr is not None
                    and self._hll_estimate(p[0]) >= ins_thr
                ) or (tra_thr is not None and p[1] >= tra_thr)
                if over:
                    # The name leaves the exact tier — state done.
                    self._pressure.pop(name_hash, None)
                    crossed.append(name_hash)
            if len(self._pressure) > self._PRESSURE_CAP:
                # Rank by normalized distance to the NEAREST threshold
                # (a raw register-vs-count comparison would let junk
                # transients evict a near-threshold cardinality bomb's
                # HLL state under a concurrent name sweep).
                def closeness(p) -> float:
                    c = 0.0
                    if ins_thr is not None:
                        c = max(c, self._hll_estimate(p[0]) / ins_thr)
                    if tra_thr is not None:
                        c = max(c, p[1] / tra_thr)
                    return c

                keep = sorted(
                    self._pressure.items(),
                    key=lambda kv: closeness(kv[1]),
                    reverse=True,
                )[: self._PRESSURE_CAP // 2]
                self._pressure = dict(keep)
        spilled = 0
        for nh in crossed:
            if self.spill_name(decode_names(nh)):
                spilled += 1
        return spilled

    def warmup(self) -> None:
        """Compile the merge step at every chunk count a coalesced drain
        can plausibly reach (service warmup, like the sibling backends).
        Chunk counts are powers of two, so this is O(log) executables —
        a lazy compile inside a serving window instead costs seconds of
        tail latency (measured ~2.7s p99 spikes when k=16 first
        appeared mid-benchmark); beyond 32 chunks compiles stay lazy
        (drains that big imply the device is the bottleneck anyway)."""
        for k in (1, 2, 4, 8, 16, 32):
            self._multi_step(k)

    def _advance_window(self, now_ms: int) -> None:
        """The kernel's rotation arithmetic on the host mirror (called
        under the lock, with the same `now` the dispatch uses)."""
        w = self.cfg.window_ms
        elapsed = now_ms - self._win_start
        if elapsed >= w:
            self._win_start = now_ms - (elapsed % w)

    def _multi_step(self, k: int):
        """Jitted scan over k chunks: ONE dispatch per merge, chunks
        applied in order on device (each sees the previous chunk's adds,
        the same sequencing the per-chunk host loop had).  Returns
        (state', packed int32[k, 2, B]) — over/est stacked so the whole
        response is one transfer.

        The first merge at a new k compiles OUTSIDE the dispatch lock
        (against a throwaway state), so concurrent merges never stall on
        an XLA compile — callers fetch the step before taking _lock."""
        fn = self._multi.get(k)
        if fn is not None:
            return fn
        with self._compile_lock:
            fn = self._multi.get(k)
            if fn is not None:
                return fn
            from gubernator_tpu.ops.sketch import init_sketch

            fn = make_multi_step(self._impl)
            warm_state = init_sketch(
                depth=self.cfg.depth, width=self.cfg.width,
                window_ms=self.cfg.window_ms,
            )
            z64 = np.zeros((k, self.batch), dtype=np.int64)
            z32 = np.zeros((k, self.batch), dtype=np.int32)
            st, packed = fn(warm_state, z64, z32, z32, np.int64(0))
            np.asarray(packed)  # block until the compile finishes
            self._multi[k] = fn
        return fn

    def check_cols(
        self,
        key_hash: np.ndarray,
        hits: np.ndarray,
        limits: np.ndarray,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Columnar check (the fast lane and check()'s core): int64
        fingerprint / hits / limit arrays in, (status, remaining,
        reset_time) int64 arrays out.  Validation happens upstream (the
        wire parser's err column / check()'s request validation)."""
        return self.check_cols_begin(key_hash, hits, limits)()

    def check_cols_begin(
        self,
        key_hash: np.ndarray,
        hits: np.ndarray,
        limits: np.ndarray,
    ):
        """Dispatch stage of check_cols: clamp/pad/chunk and issue the
        ONE device dispatch under the lock, then return a zero-arg fetch
        closure producing (status, remaining, reset_time).  The closure
        syncs this merge's own output buffer (only the state is
        donated), so the pipelined fast lane runs it on its fetch stage
        while the next merge dispatches."""
        n = len(key_hash)
        # Sketch cells are int32; clamp limits/hits into range ONCE so
        # the device decision and the host-side `remaining` agree (an
        # unclamped int64 limit would wrap in the int32 cast below and
        # flip the decision while `remaining` reported billions left).
        # A window limit beyond 2^31-1 is outside the tier's design
        # envelope anyway — the clamp only changes such configs.
        i32max = np.int64(2**31 - 1)
        limits = np.clip(limits, -i32max, i32max)
        hits = np.clip(hits, -i32max, i32max)
        B = self.batch
        k = 1
        while k * B < n:
            k <<= 1
        pad = k * B - n
        kh = np.concatenate(
            [key_hash, np.zeros(pad, dtype=np.int64)]
        ).reshape(k, B)
        hc = np.concatenate(
            [hits, np.zeros(pad, dtype=np.int64)]
        ).astype(np.int32).reshape(k, B)
        lc = np.concatenate(
            [limits, np.zeros(pad, dtype=np.int64)]
        ).astype(np.int32).reshape(k, B)
        step = self._multi_step(k)  # compiles outside the dispatch lock
        with self._lock:
            now = self.clock.millisecond_now()
            self._advance_window(int(now))
            reset_val = self._win_start + self.cfg.window_ms
            self.state, packed = step(self.state, kh, hc, lc, np.int64(now))

        def fetch() -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
            # Response sync OUTSIDE the lock: `packed` is this call's own
            # output buffer (only the state is donated), so later
            # dispatches can't touch it — merges pipeline like the exact
            # lane.
            out = np.asarray(packed)
            over = out[:, 0, :].reshape(-1)[:n]
            est = out[:, 1, :].reshape(-1)[:n].astype(np.int64)
            status = over.astype(np.int64)
            remaining = np.maximum(0, limits - est - np.maximum(hits, 0))
            reset = np.full(n, reset_val, dtype=np.int64)
            return status, remaining, reset

        return fetch

    def check(self, reqs: Sequence[RateLimitReq]) -> List[RateLimitResp]:
        from gubernator_tpu import native

        # Same validation contract as the exact packer
        # (gubernator.go:228-237): errored requests get an error response
        # and never touch the sketch (an empty unique_key would otherwise
        # collide every such client on one shared bucket).
        errors: dict = {}
        valid: List[RateLimitReq] = []
        for i, r in enumerate(reqs):
            if not r.unique_key:
                errors[i] = "field 'unique_key' cannot be empty"
            elif not r.name:
                errors[i] = "field 'namespace' cannot be empty"
            else:
                valid.append(r)
        if errors:
            inner = self.check(valid) if valid else []
            out_all: List[RateLimitResp] = []
            it = iter(inner)
            for i in range(len(reqs)):
                if i in errors:
                    out_all.append(RateLimitResp(error=errors[i]))
                else:
                    out_all.append(next(it))
            return out_all

        n = len(reqs)
        if n == 0:
            return []
        kh = native.hash_keys([r.hash_key() for r in reqs])
        hits = np.array([r.hits for r in reqs], dtype=np.int64)
        limits = np.array([r.limit for r in reqs], dtype=np.int64)
        status, remaining, reset = self.check_cols(kh, hits, limits)
        return [
            RateLimitResp(
                status=(
                    Status.OVER_LIMIT if status[j]
                    else Status.UNDER_LIMIT
                ),
                limit=int(limits[j]),
                remaining=int(remaining[j]),
                reset_time=int(reset[j]),
                metadata={"tier": "sketch"},
            )
            for j in range(n)
        ]
