"""Orbax-backed table checkpointing — TPU-native bulk persistence.

The Store/Loader SPI (runtime/store.py) persists CacheItems one at a time,
which round-trips every row through host python.  For large tables the
natural TPU path is to checkpoint the device arrays themselves: orbax
serializes the SlotTable pytree (plus the fingerprint->key map when key
strings must survive) straight from device buffers.

This powers two features the reference delegates to implementors
(store.go:69-78, README.md:165-181):
- fast restart warm-up: restore the whole table before serving;
- periodic snapshots: a background loop checkpointing every N seconds
  (crash recovery with bounded staleness — the acceptable-loss contract,
  architecture.md:5-11, with a much smaller loss window).

An `OrbaxLoader` adapter also plugs the checkpoint store into the standard
Loader slot of Config for code written against the SPI.
"""
from __future__ import annotations

import asyncio
import json
import logging
import os
import shutil
from typing import Iterable, Iterator, List, Optional

import numpy as np

from gubernator_tpu.core.types import CacheItem
from gubernator_tpu.ops.state import table_to_host
from gubernator_tpu.runtime.backend import DeviceBackend
from gubernator_tpu.runtime.store import Loader

log = logging.getLogger("gubernator_tpu.checkpoint")


class TableCheckpointer:
    """Save/restore a DeviceBackend's slot table with orbax."""

    def __init__(self, directory: str) -> None:
        import orbax.checkpoint as ocp

        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._ckptr = ocp.PyTreeCheckpointer()

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step:012d}")

    def _complete_steps(self) -> List[int]:
        """Steps with a fully written checkpoint.  Orbax temp dirs from a
        crash mid-save ('step_N.orbax-checkpoint-tmp-...') and any other
        non-integer suffixes are ignored, not fatal."""
        steps = []
        for d in os.listdir(self.directory):
            if not d.startswith("step_"):
                continue
            suffix = d[len("step_"):]
            if suffix.isdigit():
                steps.append(int(suffix))
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self._complete_steps()
        return steps[-1] if steps else None

    def save(
        self,
        backend,  # DeviceBackend or MeshBackend
        step: int,
        keep: int = 3,
        sketch=None,  # SketchBackend — include the CMS state
        coldtier=None,  # ColdTier — include the demoted-row store
    ) -> str:
        """Checkpoint the table (and keymap when tracked; the sketch
        tier's CMS state when passed — long-window abuse counters should
        survive a restart; and the cold tier's resident rows when passed
        — restart at 100M keys must not cold-start the cold tier);
        prunes old steps beyond `keep`."""
        # Copy to host while holding the lock: the step functions donate the
        # table buffers, so a concurrent check() would delete the captured
        # device arrays mid-serialization ("Array has been deleted").
        with backend._lock:
            payload = {"table": dict(table_to_host(backend.table))}
            keymap = (
                dict(backend._keymap) if backend._keymap is not None else None
            )
        if sketch is not None:
            with sketch._lock:
                st = sketch.state
                payload["sketch"] = {
                    "cur": np.asarray(st.cur),
                    "prev": np.asarray(st.prev),
                    "window_start": np.asarray(st.window_start),
                    "window_ms": np.asarray(st.window_ms),
                }
        if coldtier is not None:
            # snapshot() compacts under coldtier._lock — the columnar
            # MigratedRows layout, geometry-independent on restore.
            payload["coldtier"] = dict(coldtier.snapshot())
        path = self._step_dir(step)
        self._ckptr.save(path, payload, force=True)
        if keymap is not None:
            with open(os.path.join(path, "keymap.json"), "w") as f:
                json.dump({str(k): v for k, v in keymap.items()}, f)
        self._prune(keep)
        log.info("checkpointed table to %s", path)
        return path

    def restore(self, backend, step: Optional[int] = None,
                sketch=None, coldtier=None) -> int:
        """Restore the table in place; returns the restored step.  Works
        for DeviceBackend and MeshBackend alike — `_install_table` handles
        placement (sharded over the mesh for the latter; orbax stores the
        host copy either way).  With `sketch`, restores the CMS state too
        (a checkpoint without sketch state leaves the live sketch
        untouched); the host window mirror follows the restored
        window_start, and the next check's rotation handles any elapsed
        downtime exactly like elapsed uptime.  With `coldtier`, the
        demoted-row store is re-inserted row by row (capacity may have
        changed; overflow rows are dropped and counted)."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoints under {self.directory}"
                )
        path = self._step_dir(step)
        payload = self._ckptr.restore(path)
        arrays = {
            f: np.asarray(v) for f, v in payload["table"].items()
        }
        backend._install_table(arrays)
        if sketch is not None and "sketch" in payload:
            import jax.numpy as jnp

            from gubernator_tpu.ops.sketch import SketchState

            sk = payload["sketch"]
            cur = np.asarray(sk["cur"])
            if cur.shape != (sketch.cfg.depth, sketch.cfg.width):
                # A resized sketch hashes keys to different cells — old
                # counts are meaningless under the new geometry.  Start
                # fresh rather than installing garbage.
                log.warning(
                    "checkpointed sketch geometry %s != configured "
                    "(%d, %d); skipping sketch restore",
                    cur.shape, sketch.cfg.depth, sketch.cfg.width,
                )
            else:
                # The CURRENT config owns window_ms (the host mirror and
                # reset_time already use it); installing the checkpoint's
                # value would desync device rotation from the host
                # mirror after a window reconfiguration.
                with sketch._lock:
                    sketch.state = SketchState(
                        cur=jnp.asarray(cur),
                        prev=jnp.asarray(np.asarray(sk["prev"])),
                        window_start=jnp.asarray(
                            np.asarray(sk["window_start"])
                        ),
                        window_ms=jnp.asarray(
                            np.int64(sketch.cfg.window_ms)
                        ),
                    )
                    sketch._win_start = int(
                        np.asarray(sk["window_start"])
                    )
        if coldtier is not None and "coldtier" in payload:
            rows = {
                f: np.asarray(v)
                for f, v in payload["coldtier"].items()
            }
            n = coldtier.restore(rows)
            log.info("restored %d cold-tier rows", n)
        km_path = os.path.join(path, "keymap.json")
        if os.path.exists(km_path) and backend._keymap is not None:
            with open(km_path) as f:
                backend._keymap.update(
                    {int(k): v for k, v in json.load(f).items()}
                )
        log.info("restored table from %s", path)
        return step

    def _prune(self, keep: int) -> None:
        """Drop all but the newest `keep` checkpoints (keep <= 0 keeps
        only the newest one — the just-written snapshot)."""
        steps = self._complete_steps()
        cut = max(keep, 1)
        for s in steps[:-cut]:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)


class PeriodicCheckpointLoop:
    """Background snapshot loop (bounded-staleness crash recovery)."""

    def __init__(
        self,
        backend: DeviceBackend,
        directory: str,
        interval_s: float = 30.0,
        keep: int = 3,
        sketch=None,  # SketchBackend — snapshot the CMS state too
        coldtier=None,  # ColdTier — snapshot the demoted rows too
    ) -> None:
        self.ckptr = TableCheckpointer(directory)
        self.backend = backend
        self.sketch = sketch
        self.coldtier = coldtier
        self.interval_s = interval_s
        self.keep = keep
        self._task: Optional[asyncio.Task] = None
        self._step = (self.ckptr.latest_step() or 0) + 1

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.ensure_future(self._run())

    async def stop(self, final_save: bool = True) -> None:
        if self._task is not None:
            self._task.cancel()
            await asyncio.gather(self._task, return_exceptions=True)
            self._task = None
        if final_save:
            await self._save_once()

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.interval_s)
            await self._save_once()

    async def _save_once(self) -> None:
        loop = asyncio.get_running_loop()
        step = self._step
        self._step += 1
        try:
            await loop.run_in_executor(
                None,
                lambda: self.ckptr.save(
                    self.backend, step, self.keep, sketch=self.sketch,
                    coldtier=self.coldtier,
                ),
            )
        except Exception as e:  # noqa: BLE001
            log.error("periodic checkpoint failed: %s", e)


class OrbaxLoader(Loader):
    """Loader SPI adapter over TableCheckpointer.

    `load()` yields nothing itself — restore happens at table granularity
    via `attach()`; `save()` likewise checkpoints the whole table.  Use
    when code is wired for the Loader slot but orbax speed is wanted.
    """

    def __init__(self, directory: str) -> None:
        self.ckptr = TableCheckpointer(directory)
        self._backend: Optional[DeviceBackend] = None
        self._sketch = None
        self._coldtier = None

    def attach(self, backend: DeviceBackend, sketch=None,
               coldtier=None) -> None:
        self._backend = backend
        self._sketch = sketch
        self._coldtier = coldtier
        try:
            self.ckptr.restore(backend, sketch=sketch,
                               coldtier=coldtier)
        except FileNotFoundError:
            pass

    def load(self) -> Iterable[CacheItem]:
        return []

    def save(self, items: Iterator[CacheItem]) -> None:
        if self._backend is not None:
            step = (self.ckptr.latest_step() or 0) + 1
            self.ckptr.save(self._backend, step, sketch=self._sketch,
                            coldtier=self._coldtier)
