"""Elastic membership: live slot migration on the peers wire.

A peer join/leave remaps the consistent hash.  Without migration every
moved arc's device-resident counters are orphaned — the new owner
starts every limit fresh (a mass limit reset at "millions of users"
scale) while the old owner still holds rows it must never serve again.
This module makes ownership handoff the correctness-critical moment it
is (arXiv:2602.11741): the OLD owner of every moved arc drives a
per-destination state machine

    PREPARE -> DRAIN -> TRANSFER -> CUTOVER -> RELEASE

streaming packed table rows (the ops/state row serialization the
checkpoint plane DMAs) to the new owner over the new `Migrate` RPC,
with the `Handoff` RPC as the control-plane handshake.

Bounded double admission.  Routing flips to the new ring the moment
set_peers lands, so during the handoff window the two owners must agree
on who admits (retrying through ambiguity is how double-admission
compounds — the arXiv:1909.08969 caution already applied to hedging and
retry policy here):

  * PREPARE: the new owner FORWARDS covered checks back to the
    still-authoritative old owner (single authority — zero double
    admission while it is reachable);
  * TRANSFER (announced BEFORE the old owner's atomic extract+clear):
    the new owner serves covered keys from a bounded local
    `<unique_key>.handoff-shadow` carve at `handoff_fraction x limit`
    — each moved key's window admission is bounded by
    `limit x (1 + handoff_fraction)` (the local_shadow / hot-mirror /
    lease algebra with a remap as the gate); the old owner, its rows
    extracted-and-cleared in one donated kernel, forwards any
    stale-routed check to the new owner (forwards-or-serves: serve
    while authoritative, forward after);
  * CUTOVER: shadow burns are applied to the freshly injected
    authoritative rows (counters conserved, never inflated — applying
    hits can only lower remaining) and the shadow slots drop via
    zero-hit RESET_REMAINING;
  * crash mid-TRANSFER: the new owner's watchdog self-cutovers after
    `timeout_s` of silence — rows that never arrived start fresh
    (conservative reset, ≤ limit, never inflated) and rows that did
    arrive keep their exact state (Migrate injects only where the key
    is absent, so replayed or late chunks can never clobber newer
    state).

Derived slots are invalidated at the remap, not migrated: the old
owner's LeaseManager drops grants and carve slots for keys it no
longer owns (`LeaseManager.drop_unowned` — holders renew through the
ring and land on the new owner), mirror allowances for keys this node
now owns are reset, and handoff shadows drop at cutover.

Threading: `_lock` guards only the handoff dicts and counters — never
held across an await or any device work (registered in the gubguard
lock ranking next to lease._lock).  Device work rides the service's
single-thread device executor like every other table mutation.

Protocol spec: tools/gubproof/specs/reshard.json — every `phase` write
below must map to a declared edge, and the explorer closes the full
handoff x fault space at small scope (including the reshard+lease
composition), reproducing the admission bounds above exactly.
"""
from __future__ import annotations

import asyncio
import logging
import threading
import time
from dataclasses import dataclass, field, replace as dc_replace
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from gubernator_tpu.core.config import ReshardConfig
from gubernator_tpu.core.types import (
    Behavior,
    RateLimitReq,
    Status,
)
from gubernator_tpu.runtime import tracing

log = logging.getLogger("gubernator_tpu.reshard")

# The handoff shadow's key suffix (the SHADOW/MIRROR/LEASE convention):
# a covered key served during the window burns a
# `<unique_key>` + this suffix slot in the NEW owner's table, never the
# real key's row.
HANDOFF_SUFFIX = ".handoff-shadow"

# Outbound phases, in order.
PREPARE = "prepare"
DRAIN = "drain"
TRANSFER = "transfer"
CUTOVER = "cutover"
RELEASED = "released"
ABORTED = "aborted"

_PHASE_GAUGE = {
    PREPARE: 1, DRAIN: 2, TRANSFER: 3, CUTOVER: 4, RELEASED: 5,
    ABORTED: 6,
}


def ring_owner_indices(fps: np.ndarray, picker) -> np.ndarray:
    """Peer index per int64 device fingerprint via the picker's cached
    ring arrays — valid on xx rings only, where the ring hash IS the
    XXH64 key fingerprint (the fast router's premise,
    replicated_hash.ring_arrays)."""
    ring, ring_idx, _peers = picker.ring_arrays()
    i = np.searchsorted(
        ring, fps.astype(np.int64).view(np.uint64), side="left"
    )
    i[i == len(ring)] = 0
    return ring_idx[i]


def compute_moved(
    fps: np.ndarray, old_picker: Any, new_picker: Any
) -> Dict[str, np.ndarray]:
    """The remap delta: of the int64 fingerprints `fps` resident on
    THIS node, which were owned by us under `old_picker` but belong to
    another peer under `new_picker`?  Returns {new_owner_addr: fps}.
    Pure function of the two rings (unit-testable without a daemon);
    empty when either ring is empty or we own nothing."""
    out: Dict[str, np.ndarray] = {}
    if not len(fps) or old_picker.size() == 0 or new_picker.size() == 0:
        return out
    old_idx = ring_owner_indices(fps, old_picker)
    old_peers = old_picker.ring_arrays()[2]
    was_mine = np.array(
        [p.info().is_owner for p in old_peers], dtype=bool
    )[old_idx]
    if not was_mine.any():
        return out
    new_idx = ring_owner_indices(fps, new_picker)
    new_peers = new_picker.ring_arrays()[2]
    still_mine = np.array(
        [p.info().is_owner for p in new_peers], dtype=bool
    )[new_idx]
    moved = was_mine & ~still_mine
    if not moved.any():
        return out
    addrs = np.array(
        [p.info().grpc_address for p in new_peers]
    )[new_idx[moved]]
    moved_fps = fps[moved]
    for addr in np.unique(addrs):
        out[str(addr)] = moved_fps[addrs == addr]
    return out


@dataclass
class _Outbound:
    """One old-owner -> new-owner handoff this node is driving."""

    to_addr: str
    epoch: int
    fp_set: set
    n_rows: int
    phase: str = PREPARE
    rows_sent: int = 0
    rows_lost: int = 0
    started_ms: int = 0
    released_ms: int = 0  # clock ms of cutover/abort (linger anchor)


@dataclass
class _Inbound:
    """One handoff this node is receiving."""

    from_addr: str
    epoch: int
    phase: str = PREPARE  # prepare | transfer
    deadline_ms: int = 0  # self-cutover watchdog
    started_ms: int = 0
    injected: int = 0
    skipped: int = 0
    total_rows: int = 0
    # hash_key -> (request template, admitted shadow hits) — applied to
    # the authoritative rows at cutover (counters conserved).
    shadow: Dict[str, Tuple[RateLimitReq, int]] = field(
        default_factory=dict
    )
    # Fingerprints already delivered in this handoff: the replay guard
    # for the merge-on-conflict inject (a re-delivered chunk must not
    # re-subtract consumption).
    seen_fps: set = field(default_factory=set)


class ReshardManager:
    """Per-node live-resharding state (both directions)."""

    def __init__(self, service, cfg: ReshardConfig, metrics=None) -> None:
        self.s = service
        self.cfg = cfg
        self.metrics = metrics
        self._lock = threading.Lock()
        self._outbound: Dict[str, _Outbound] = {}
        self._inbound: Dict[str, _Inbound] = {}
        self._epoch = 0
        self._active = False
        self._minus_me_cache = None
        self.draining = False
        # Test hook: when set, outbound handoffs wait here between the
        # TRANSFER announcement and the extract — lets a test hold the
        # handoff window open deterministically.  None in production.
        self.transfer_gate: Optional[asyncio.Event] = None
        # Observability mirrors (scraped by tests and /debug/vars).
        self.remaps = 0
        self.handoffs_started = 0
        self.handoffs_completed = 0
        self.handoffs_aborted = 0
        self.self_cutovers = 0
        self.rows_sent = 0
        self.rows_received = 0
        self.rows_skipped = 0
        self.rows_lost = 0
        self.shadow_served = 0
        self.forwarded_back = 0

    # ------------------------------------------------------------------
    # helpers
    # ------------------------------------------------------------------
    def _now_ms(self) -> int:
        return int(self.s.clock.now_ns() // 1_000_000)

    def active(self) -> bool:
        """True while ANY handoff is live on this node — the compiled
        lane's fallback gate (check_raw steps aside so the object
        path's covered-key routing applies)."""
        return self._active

    def _refresh_active_locked(self) -> None:
        self._active = bool(
            self._outbound or self._inbound or self.draining
        )

    def _me(self) -> str:
        """This node's advertised address per the current ring."""
        for p in self.s.local_picker.peers():
            if p.info().is_owner:
                return p.info().grpc_address
        return ""

    def _set_state_gauge(
        self, addr: str, direction: str, phase: Optional[str]
    ) -> None:
        m = self.metrics
        if m is None:
            return
        try:
            if phase is None:
                m.reshard_state.remove(addr, direction)
            else:
                m.reshard_state.labels(
                    peerAddr=addr, direction=direction
                ).set(_PHASE_GAUGE.get(phase, 0))
        except Exception:  # noqa: BLE001 — label may not exist yet
            pass

    def _count_rows(self, direction: str, n: int) -> None:
        if n and self.metrics is not None:
            self.metrics.reshard_rows.labels(direction=direction).inc(n)

    def _fp_of(self, key: str) -> int:
        from gubernator_tpu.core.hashing import key_hash64

        return int(np.uint64(key_hash64(key)).view(np.int64))

    # ------------------------------------------------------------------
    # remap detection (old-owner side)
    # ------------------------------------------------------------------
    def on_remap(self, old_picker, new_picker) -> None:
        """Service.set_peers computed a remap: find the rows this node
        owned under the OLD ring that belong to someone else under the
        NEW one and drive one handoff per destination.  Spawned as a
        task — the delta needs a device fetch."""
        from gubernator_tpu.net.replicated_hash import xx_64

        self.remaps += 1
        if not self.cfg.enabled:
            return
        if old_picker.size() == 0 or new_picker.size() == 0:
            return
        if (
            old_picker.hash_fn is not xx_64
            or new_picker.hash_fn is not xx_64
        ):
            # fnv interop rings: the device fingerprint is not the ring
            # hash, so the delta cannot be computed from the table.
            log.warning(
                "resharding disabled on non-xx picker hash: a remap "
                "orphans moved counters (the legacy reset behavior)"
            )
            return
        self.s.spawn_task(self._remap_task(old_picker, new_picker))

    async def _remap_task(self, old_picker, new_picker) -> None:
        loop = asyncio.get_running_loop()
        try:
            fps = await loop.run_in_executor(
                self.s._dev_executor, self._owned_bucket_fps
            )
        except RuntimeError:
            # The service closed between the remap and this task (the
            # device executor is gone) — nothing left to migrate.
            return
        moved = compute_moved(fps, old_picker, new_picker)
        if not moved:
            return
        n = int(sum(len(v) for v in moved.values()))
        log.info(
            "remap: %d row(s) moved across %d destination(s)",
            n, len(moved),
        )
        fr = getattr(self.s.metrics, "flightrec", None)
        if fr is not None:
            fr.record(
                "reshard_remap", rows=n, destinations=len(moved)
            )
        await asyncio.gather(*(
            self._run_handoff(addr, dest_fps)
            for addr, dest_fps in moved.items()
        ))

    def _owned_bucket_fps(self) -> np.ndarray:
        """Live KIND_BUCKET fingerprints resident on this node, minus
        the derived slots this node can invalidate locally (lease
        carves, mirror allowances, degraded/handoff shadows) — those
        re-home by re-creation at their new homes, never by copy."""
        from gubernator_tpu.ops.state import KIND_BUCKET

        keys, kinds, expires = self.s.backend.key_snapshot()
        now = self._now_ms()
        live = (keys != 0) & (expires > now) & (kinds == KIND_BUCKET)
        fps = keys[live]
        derived = self.s.derived_slot_fps()
        if len(derived):
            fps = fps[~np.isin(fps, derived)]
        return fps

    # ------------------------------------------------------------------
    # outbound state machine
    # ------------------------------------------------------------------
    async def _run_handoff(self, to_addr: str, fps: np.ndarray) -> None:
        peer = self.s.local_picker.get_by_address(to_addr)
        if peer is None:
            return
        me = self._me()
        with self._lock:
            self._epoch += 1
            epoch = self._epoch
            ob = _Outbound(
                to_addr=to_addr, epoch=epoch,
                fp_set={int(f) for f in fps}, n_rows=len(fps),
                started_ms=self._now_ms(),
            )
            self._outbound[to_addr] = ob
            self._refresh_active_locked()
            self.handoffs_started += 1
        self._set_state_gauge(to_addr, "outbound", PREPARE)
        t0 = time.monotonic()
        outcome = "aborted"
        try:
            with tracing.span(
                "reshard.handoff", parent=None,
                peer=to_addr, rows=len(fps), epoch=epoch,
            ):
                accepted, state = await self._handoff_rpc(
                    peer, me, epoch, PREPARE
                )
                if not accepted:
                    raise RuntimeError(
                        f"prepare rejected by {to_addr}: {state}"
                    )
                # DRAIN: a no-op barrier through the local batcher —
                # every batch queued before this point has applied, so
                # the extract below sees their effects.
                ob.phase = DRAIN
                self._set_state_gauge(to_addr, "outbound", DRAIN)
                await self.s._local_batcher.check([], None)
                # Announce TRANSFER first: from the receiver's ack
                # onward it serves covered keys from the bounded
                # shadow, so the extract+clear below can never strand a
                # check between two absent rows.
                accepted, state = await self._handoff_rpc(
                    peer, me, epoch, TRANSFER, total_rows=len(fps)
                )
                if not accepted:
                    raise RuntimeError(
                        f"transfer rejected by {to_addr}: {state}"
                    )
                ob.phase = TRANSFER
                self._set_state_gauge(to_addr, "outbound", TRANSFER)
                if self.transfer_gate is not None:
                    await self.transfer_gate.wait()
                await self._transfer_rows(peer, ob, me, fps)
                ob.phase = CUTOVER
                self._set_state_gauge(to_addr, "outbound", CUTOVER)
                accepted, _state = await self._handoff_rpc(
                    peer, me, epoch, CUTOVER, retries=5
                )
                if not accepted:
                    raise RuntimeError(f"cutover rejected by {to_addr}")
            outcome = "completed"
            self.handoffs_completed += 1
            window_s = time.monotonic() - t0
            if self.metrics is not None:
                self.metrics.reshard_window_duration.observe(window_s)
            fr = getattr(self.s.metrics, "flightrec", None)
            if fr is not None:
                fr.record(
                    "reshard_cutover", peer=to_addr, epoch=epoch,
                    rows=ob.rows_sent, lost=ob.rows_lost,
                    window_ms=round(window_s * 1e3, 3),
                )
            log.info(
                "handoff to %s complete: %d row(s) in %.1fms (%d lost)",
                to_addr, ob.rows_sent, window_s * 1e3, ob.rows_lost,
            )
        except Exception as e:  # noqa: BLE001 — degrade to legacy reset
            self.handoffs_aborted += 1
            log.warning(
                "handoff to %s aborted in %s: %s — moved counters for "
                "%d row(s) degrade to the legacy reset",
                to_addr, ob.phase, e, ob.n_rows - ob.rows_sent,
            )
        finally:
            with self._lock:
                ob.phase = RELEASED if outcome == "completed" else ABORTED
                ob.released_ms = self._now_ms()
            self._set_state_gauge(to_addr, "outbound", ob.phase)
            if self.metrics is not None:
                self.metrics.reshard_handoffs.labels(
                    direction="outbound", outcome=outcome
                ).inc()

    async def _handoff_rpc(
        self, peer, me: str, epoch: int, phase: str,
        total_rows: int = 0, retries: int = 2,
    ) -> Tuple[bool, str]:
        last: Optional[Exception] = None
        for attempt in range(retries + 1):
            try:
                return await peer.handoff(
                    me, epoch, phase, total_rows=total_rows
                )
            except Exception as e:  # noqa: BLE001
                last = e
                await asyncio.sleep(min(0.1 * (2 ** attempt), 1.0))
        raise RuntimeError(f"handoff({phase}) failed: {last}")

    async def _transfer_rows(
        self, peer, ob: _Outbound, me: str, fps: np.ndarray
    ) -> None:
        """Extract+clear moved rows chunk by chunk (each chunk one
        atomic donated kernel under backend._lock) and stream them to
        the new owner.  A chunk that cannot be delivered before the
        handoff deadline is LOST — the new owner's watchdog will
        self-cutover and those keys conservatively reset."""
        from gubernator_tpu.proto import peers_pb2

        loop = asyncio.get_running_loop()
        chunk = self.cfg.chunk_rows
        deadline = time.monotonic() + self.cfg.timeout_s
        backend = self.s.backend
        keymap = getattr(backend, "_keymap", None)
        n_chunks = max((len(fps) + chunk - 1) // chunk, 1)
        for ci in range(n_chunks):
            part = fps[ci * chunk:(ci + 1) * chunk]
            packed, rf = await loop.run_in_executor(
                self.s._dev_executor,
                lambda p=part: backend.migrate_extract_rows(p),
            )
            found = packed[0] != 0
            if not found.any() and ci + 1 < n_chunks:
                continue
            rows = peers_pb2.MigratedRows(
                key_hash=part[found].tolist(),
                algo=packed[2][found].tolist(),
                limit=packed[3][found].tolist(),
                duration=packed[4][found].tolist(),
                remaining=packed[5][found].tolist(),
                remaining_f=rf[found].tolist(),
                t0=packed[6][found].tolist(),
                status=packed[7][found].tolist(),
                burst=packed[8][found].tolist(),
                expire_at=packed[9][found].tolist(),
            )
            if keymap is not None:
                with backend._keymap_lock:
                    rows.keys.extend(
                        keymap.get(
                            int(np.int64(f).view(np.uint64)), ""
                        )
                        for f in part[found]
                    )
            n = len(rows.key_hash)
            final = ci + 1 >= n_chunks
            sent = False
            attempt = 0
            while time.monotonic() < deadline:
                try:
                    await peer.migrate(me, ob.epoch, rows, final=final)
                    sent = True
                    break
                except Exception as e:  # noqa: BLE001
                    attempt += 1
                    log.debug(
                        "migrate chunk to %s failed (attempt %d): %s",
                        ob.to_addr, attempt, e,
                    )
                    await asyncio.sleep(
                        min(0.05 * (2 ** min(attempt, 6)), 1.0)
                    )
            if sent:
                ob.rows_sent += n
                self.rows_sent += n
                self._count_rows("sent", n)
            else:
                ob.rows_lost += n
                self.rows_lost += n
                self._count_rows("lost", n)
                raise RuntimeError(
                    f"transfer deadline: {n} row(s) undeliverable to "
                    f"{ob.to_addr}"
                )

    def reroute_target(self, key: str) -> Optional[str]:
        """Where the old owner sends a check it must no longer serve:
        the destination of the handoff covering `key`, once its rows
        are gone (TRANSFER onward).  None = serve normally (we are
        still authoritative, or the key never moved)."""
        if not self._active:
            return None
        fp = self._fp_of(key)
        with self._lock:
            for ob in self._outbound.values():
                if ob.phase in (TRANSFER, CUTOVER, RELEASED) and (
                    fp in ob.fp_set
                ):
                    return ob.to_addr
        return None

    # ------------------------------------------------------------------
    # inbound (new-owner side)
    # ------------------------------------------------------------------
    async def on_handoff(
        self, from_addr: str, epoch: int, phase: str, total_rows: int
    ) -> Tuple[bool, str]:
        """The Handoff RPC receive path."""
        if not self.cfg.enabled:
            return False, "resharding disabled"
        now = self._now_ms()
        deadline = now + int(self.cfg.timeout_s * 1000)
        if phase == PREPARE:
            with self._lock:
                ib = self._inbound.get(from_addr)
                if ib is not None and ib.epoch > epoch:
                    return False, f"stale epoch {epoch} < {ib.epoch}"
                self._inbound[from_addr] = _Inbound(
                    from_addr=from_addr, epoch=epoch,
                    deadline_ms=deadline, started_ms=now,
                )
                self._refresh_active_locked()
            self._set_state_gauge(from_addr, "inbound", PREPARE)
            return True, PREPARE
        with self._lock:
            ib = self._inbound.get(from_addr)
            if ib is None or ib.epoch != epoch:
                stale = ib.epoch if ib is not None else None
                # An unmatched cutover is idempotent-accept: the sender
                # only needs to know it may release.
                if phase in (CUTOVER, "abort"):
                    return True, "no such handoff (already finalized)"
                return False, f"unknown handoff (have epoch {stale})"
            if phase == TRANSFER:
                ib.phase = TRANSFER
                ib.total_rows = int(total_rows)
                ib.deadline_ms = deadline
        if phase == TRANSFER:
            self._set_state_gauge(from_addr, "inbound", TRANSFER)
            return True, TRANSFER
        if phase == CUTOVER:
            await self._finalize_inbound(ib, outcome="completed")
            return True, CUTOVER
        if phase == "abort":
            await self._finalize_inbound(ib, outcome="aborted")
            return True, "aborted"
        return False, f"unknown phase {phase!r}"

    async def on_migrate(
        self, from_addr: str, epoch: int, rows, final: bool
    ) -> Tuple[int, int]:
        """The Migrate RPC receive path: inject one chunk of packed
        rows (only where the key is not already resident).  Raises
        KeyError for an unknown/stale handoff so the servicer maps it
        to FAILED_PRECONDITION."""
        with self._lock:
            ib = self._inbound.get(from_addr)
            if ib is None or ib.epoch != epoch:
                raise KeyError(
                    f"no active handoff from {from_addr} at epoch "
                    f"{epoch}"
                )
            ib.deadline_ms = self._now_ms() + int(
                self.cfg.timeout_s * 1000
            )
            # Replay guard: injection MERGES conflicting rows (the
            # receiver may have served a moved key before its row
            # arrived), so a re-delivered chunk — the sender retries on
            # any ambiguous failure — must not re-subtract.  Only
            # first-delivery fingerprints reach the device.
            fresh = [
                j for j, fp in enumerate(rows.key_hash)
                if fp not in ib.seen_fps
            ]
            ib.seen_fps.update(rows.key_hash)
        n = len(rows.key_hash)
        if n == 0:
            return 0, 0
        if not fresh:
            return 0, n
        cols = {
            "key_hash": np.array(rows.key_hash, dtype=np.int64)[fresh],
            "algo": np.array(rows.algo, dtype=np.int32)[fresh],
            "limit": np.array(rows.limit, dtype=np.int64)[fresh],
            "duration": np.array(rows.duration, dtype=np.int64)[fresh],
            "remaining": np.array(
                rows.remaining, dtype=np.int64
            )[fresh],
            "remaining_f": np.array(
                rows.remaining_f, dtype=np.float64
            )[fresh],
            "t0": np.array(rows.t0, dtype=np.int64)[fresh],
            "status": np.array(rows.status, dtype=np.int32)[fresh],
            "burst": np.array(rows.burst, dtype=np.int64)[fresh],
            "expire_at": np.array(
                rows.expire_at, dtype=np.int64
            )[fresh],
        }
        loop = asyncio.get_running_loop()
        injected, skipped = await loop.run_in_executor(
            self.s._dev_executor,
            lambda: self.s.backend.migrate_inject_rows(cols),
        )
        skipped += n - len(fresh)
        if rows.keys:
            keymap = getattr(self.s.backend, "_keymap", None)
            if keymap is not None:
                with self.s.backend._keymap_lock:
                    for fp, key in zip(rows.key_hash, rows.keys):
                        if key:
                            keymap[
                                int(np.int64(fp).view(np.uint64))
                            ] = key
        with self._lock:
            ib.injected += injected
            ib.skipped += skipped
        self.rows_received += injected
        self.rows_skipped += skipped
        self._count_rows("injected", injected)
        self._count_rows("skipped", skipped)
        return injected, skipped

    def _ring_without_me(self) -> Any:
        """The current ring minus this node — on a JOINER (which never
        saw the old ring) the owner of a moved key under this ring IS
        its old owner, because adding a peer's vnodes only reassigns
        arcs TO that peer.  Cached per picker swap."""
        pick = self.s.local_picker
        cached = self._minus_me_cache
        if cached is not None and cached[0] is pick:
            return cached[1]
        sub = pick.new()
        for p in pick.peers():
            if not p.info().is_owner:
                sub.add(p)
        self._minus_me_cache = (pick, sub)
        return sub

    def inbound_covering(self, key: str) -> Optional[_Inbound]:
        """The active inbound handoff covering `key`, if any.  The
        sending old owner is identified three ways, matching the three
        membership shapes a receiver can be in: the key's owner under
        the PREVIOUS ring (an existing daemon after a leave landed),
        under the CURRENT ring (a draining leaver still in the set),
        or under the current ring WITHOUT this node (a joiner, which
        never saw the old ring)."""
        if not self._inbound:
            return None
        owners = []
        prev = getattr(self.s, "_prev_picker", None)
        for picker in (
            prev, self.s.local_picker, self._ring_without_me()
        ):
            if picker is None or picker.size() == 0:
                continue
            try:
                owners.append(picker.get(key).info().grpc_address)
            except Exception:  # noqa: BLE001 — PoolEmptyError
                continue
        if not owners:
            return None
        with self._lock:
            for addr in owners:
                ib = self._inbound.get(addr)
                if ib is not None:
                    return ib
        return None

    async def serve_covered(
        self, req: RateLimitReq, key: str, ib: _Inbound
    ):
        """Serve a check for a covered key during the handoff window.

        PREPARE: forward back to the still-authoritative old owner
        (single authority — no double admission while reachable).
        TRANSFER, or PREPARE with the old owner unreachable: serve the
        bounded `.handoff-shadow` carve — this is the window's entire
        double-admission budget (handoff_fraction x limit)."""
        from gubernator_tpu.core.types import RateLimitResp

        with self._lock:
            live = self._inbound.get(ib.from_addr) is ib
        if not live:
            # CUTOVER landed between routing and serving: this node is
            # fully authoritative now — serve the real row.
            return (await self.s._check_local([req]))[0]
        if ib.phase == PREPARE:
            peer = self.s.local_picker.get_by_address(ib.from_addr)
            if peer is not None and not peer.info().is_owner:
                try:
                    with tracing.span(
                        "reshard.forward_back", require_parent=True,
                        peer=ib.from_addr,
                    ):
                        resp = await peer.get_peer_rate_limit(req)
                    self.forwarded_back += 1
                    md = dict(resp.metadata) if resp.metadata else {}
                    md["reshard"] = "forwarded"
                    md["owner"] = ib.from_addr
                    resp.metadata = md
                    return resp
                except Exception:  # noqa: BLE001 — degrade to shadow
                    pass
        self.shadow_served += 1
        if self.metrics is not None:
            self.metrics.reshard_shadow_served.inc()
        reset_ms = self.s._resolve_reset_ms(req)
        if req.limit <= 0:
            # Deny-all keys stay deny-all during a handoff (the
            # local_shadow rule).
            return RateLimitResp(
                status=Status.OVER_LIMIT, limit=req.limit, remaining=0,
                reset_time=reset_ms,
                metadata={"reshard": "handoff-shadow",
                          "owner": ib.from_addr},
            )
        frac_limit = max(1, int(req.limit * self.cfg.handoff_fraction))
        shadow = dc_replace(
            req,
            unique_key=req.unique_key + HANDOFF_SUFFIX,
            limit=frac_limit,
            burst=min(req.burst, frac_limit) if req.burst else 0,
            behavior=Behavior(
                int(req.behavior)
                & ~int(Behavior.GLOBAL)
                & ~int(Behavior.MULTI_REGION)
            ),
        )
        resps = await self.s._check_local([shadow])
        resp = resps[0]
        if not resp.error:
            md = dict(resp.metadata) if resp.metadata else {}
            md["reshard"] = "handoff-shadow"
            md["owner"] = ib.from_addr
            resp.metadata = md
            if req.hits and resp.status == Status.UNDER_LIMIT:
                # Conservation ledger: admitted shadow hits are applied
                # to the authoritative row at cutover.  If CUTOVER
                # finalized while this check's shadow step was in
                # flight, the ledger snapshot missed this burn (and the
                # step may have re-created the just-dropped slot) —
                # compensate directly: apply the hit to the now-
                # authoritative row and re-drop the shadow slot.
                late = False
                with self._lock:
                    if self._inbound.get(ib.from_addr) is ib:
                        cur = ib.shadow.get(key)
                        burned = (
                            cur[1] if cur is not None else 0
                        ) + int(req.hits)
                        ib.shadow[key] = (
                            dc_replace(req, hits=0), burned
                        )
                    else:
                        late = True
                if late:
                    self.s.spawn_task(self._late_burn(req))
        return resp

    async def _late_burn(self, req: RateLimitReq) -> None:
        """A shadow admission that raced CUTOVER: conserve it by
        applying the hits to the authoritative row and re-dropping the
        shadow slot the racing step may have re-created."""
        strip = Behavior(
            int(req.behavior)
            & ~int(Behavior.GLOBAL)
            & ~int(Behavior.MULTI_REGION)
        )
        frac_limit = max(1, int(req.limit * self.cfg.handoff_fraction))
        try:
            await self.s._check_local([
                dc_replace(req, behavior=strip),
                dc_replace(
                    req,
                    unique_key=req.unique_key + HANDOFF_SUFFIX,
                    limit=frac_limit,
                    burst=0,
                    hits=0,
                    behavior=Behavior(
                        int(strip) | int(Behavior.RESET_REMAINING)
                    ),
                ),
            ])
        except Exception as e:  # noqa: BLE001 — slots expire anyway
            log.warning("late shadow-burn reconcile failed: %s", e)

    async def _finalize_inbound(
        self, ib: _Inbound, outcome: str
    ) -> None:
        """CUTOVER: the new owner becomes authoritative.  Apply the
        window's shadow burns to the (now injected) authoritative rows
        — applying hits only ever LOWERS remaining, so conservation
        can never inflate admission — and drop the shadow slots."""
        with self._lock:
            cur = self._inbound.get(ib.from_addr)
            if cur is not ib:
                return  # already finalized
            del self._inbound[ib.from_addr]
            self._refresh_active_locked()
            shadow = dict(ib.shadow)
        self._set_state_gauge(ib.from_addr, "inbound", None)
        burns: List[RateLimitReq] = []
        drops: List[RateLimitReq] = []
        for _key, (tmpl, burned) in shadow.items():
            strip = Behavior(
                int(tmpl.behavior)
                & ~int(Behavior.GLOBAL)
                & ~int(Behavior.MULTI_REGION)
            )
            if burned > 0:
                burns.append(
                    dc_replace(tmpl, hits=burned, behavior=strip)
                )
            frac_limit = max(
                1, int(tmpl.limit * self.cfg.handoff_fraction)
            )
            drops.append(dc_replace(
                tmpl,
                unique_key=tmpl.unique_key + HANDOFF_SUFFIX,
                limit=frac_limit,
                burst=0,
                hits=0,
                behavior=Behavior(
                    int(strip) | int(Behavior.RESET_REMAINING)
                ),
            ))
        try:
            if burns:
                await self.s._check_local(burns)
            if drops:
                await self.s._check_local(drops)
        except Exception as e:  # noqa: BLE001 — slots expire anyway
            log.warning("handoff shadow reconcile failed: %s", e)
        if outcome == "self_cutover":
            self.self_cutovers += 1
        if self.metrics is not None:
            self.metrics.reshard_handoffs.labels(
                direction="inbound", outcome=outcome
            ).inc()
        fr = getattr(self.s.metrics, "flightrec", None)
        if fr is not None:
            fr.record(
                "reshard_cutover_inbound", peer=ib.from_addr,
                epoch=ib.epoch, outcome=outcome,
                injected=ib.injected, skipped=ib.skipped,
                shadow_keys=len(shadow),
            )
        log.info(
            "inbound handoff from %s finalized (%s): injected=%d "
            "skipped=%d shadow_keys=%d",
            ib.from_addr, outcome, ib.injected, ib.skipped, len(shadow),
        )

    # ------------------------------------------------------------------
    # watchdog + drain
    # ------------------------------------------------------------------
    async def check_timeouts(self) -> int:
        """One watchdog pass: self-cutover inbound handoffs whose old
        owner went silent (crash mid-TRANSFER — missing rows start
        fresh: conservative reset, never inflated), and forget released
        outbound records past the stale-router linger.  Returns the
        number of self-cutovers."""
        now = self._now_ms()
        overdue: List[_Inbound] = []
        with self._lock:
            for ib in self._inbound.values():
                if ib.deadline_ms and now >= ib.deadline_ms:
                    overdue.append(ib)
            linger = int(self.cfg.release_linger_s * 1000)
            done = [
                addr for addr, ob in self._outbound.items()
                if ob.phase in (RELEASED, ABORTED)
                and now - ob.released_ms >= linger
            ]
            for addr in done:
                del self._outbound[addr]
            self._refresh_active_locked()
        for addr in done:
            self._set_state_gauge(addr, "outbound", None)
        for ib in overdue:
            log.warning(
                "inbound handoff from %s timed out (%d/%s rows "
                "arrived) — self-cutover, missing rows reset",
                ib.from_addr, ib.injected,
                ib.total_rows or "?",
            )
            await self._finalize_inbound(ib, outcome="self_cutover")
        return len(overdue)

    async def drain_all(self) -> int:
        """Graceful scale-down (the autoscaler's SIGTERM/preStop hook):
        migrate EVERY row this node owns to its next owner — the ring
        without this node — then keep forwarding stale-routed traffic
        until the caller closes the daemon.  Returns rows shipped."""
        pick = self.s.local_picker
        if pick.size() <= 1:
            return 0
        without_me = pick.new()
        for p in pick.peers():
            if not p.info().is_owner:
                without_me.add(p)
        if without_me.size() == 0:
            return 0
        self.draining = True
        with self._lock:
            self._refresh_active_locked()
        loop = asyncio.get_running_loop()
        fps = await loop.run_in_executor(
            self.s._dev_executor, self._owned_bucket_fps
        )
        moved = compute_moved(fps, pick, without_me)
        sent_before = self.rows_sent
        if moved:
            await asyncio.gather(*(
                self._run_handoff(addr, dest_fps)
                for addr, dest_fps in moved.items()
            ))
        return self.rows_sent - sent_before

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------
    def debug_vars(self) -> dict:
        with self._lock:
            outbound = {
                addr: {
                    "phase": ob.phase, "epoch": ob.epoch,
                    "rows": ob.n_rows, "sent": ob.rows_sent,
                    "lost": ob.rows_lost,
                }
                for addr, ob in self._outbound.items()
            }
            inbound = {
                addr: {
                    "phase": ib.phase, "epoch": ib.epoch,
                    "injected": ib.injected, "skipped": ib.skipped,
                    "total_rows": ib.total_rows,
                    "shadow_keys": len(ib.shadow),
                }
                for addr, ib in self._inbound.items()
            }
        return {
            "active": self._active,
            "draining": self.draining,
            "remaps": self.remaps,
            "handoffs": {
                "started": self.handoffs_started,
                "completed": self.handoffs_completed,
                "aborted": self.handoffs_aborted,
                "self_cutovers": self.self_cutovers,
            },
            "rows": {
                "sent": self.rows_sent,
                "received": self.rows_received,
                "skipped": self.rows_skipped,
                "lost": self.rows_lost,
            },
            "shadow_served": self.shadow_served,
            "forwarded_back": self.forwarded_back,
            "outbound": outbound,
            "inbound": inbound,
            "config": {
                "handoff_fraction": self.cfg.handoff_fraction,
                "chunk_rows": self.cfg.chunk_rows,
                "timeout_s": self.cfg.timeout_s,
            },
        }

    def health_lines(self) -> List[str]:
        """Advisory HealthCheck lines while migrations are in flight
        (the daemon IS serving; status stays connectivity-driven)."""
        out: List[str] = []
        with self._lock:
            for addr, ob in self._outbound.items():
                if ob.phase not in (RELEASED, ABORTED):
                    out.append(
                        f"Resharding: handing off {ob.n_rows} row(s) "
                        f"to {addr} ({ob.phase})"
                    )
            for addr, ib in self._inbound.items():
                out.append(
                    f"Resharding: receiving from {addr} "
                    f"({ib.phase}, {ib.injected} injected)"
                )
        if self.draining:
            out.append("Resharding: node draining for shutdown")
        return out
