"""Persistence SPI: Store (write-through) and Loader (bulk load/save).

Mirrors the reference contracts (store.go:49-78): a `Store` sees every state
change and cache miss synchronously with request processing; a `Loader` bulk
restores the cache before serving and bulk saves it at shutdown.

The device re-expression works at BATCH granularity instead of per item
(there is no per-item hook point inside a jitted kernel):

- miss seeding: before a device step, one `probe_batch` gather finds the
  batch's missing keys; `Store.get` is consulted for those and hits are bulk
  upserted via `load_rows` (replacing the in-algorithm s.Get calls,
  algorithms.go:45-51);
- write-through: after the step, written rows are read back with one more
  `probe_batch` + row DMA and handed to `Store.on_change` (replacing the
  in-algorithm s.OnChange calls, algorithms.go:154-158);
- bulk load/save: `Loader.load()` yields CacheItems streamed to device in
  batch-size chunks; `save()` receives the live rows of the final table
  (workers.go:340-426, 467-530).

The backend keeps a fingerprint->key-string map only while a Store/Loader is
attached, so key strings can be reconstructed on save (device rows hold only
64-bit fingerprints).
"""
from __future__ import annotations

import threading
from typing import Dict, Iterable, Iterator, List, Optional

from gubernator_tpu.core.types import Algorithm, CacheItem, RateLimitReq


class Store:
    """Write-through persistence hooks (reference store.go:49-65).

    Implementations must tolerate batch-granular calls: `on_change` receives
    the post-step state of every persisted request in the batch.
    """

    def get(self, req: RateLimitReq) -> Optional[CacheItem]:
        """Called on cache miss; return the persisted item or None."""
        raise NotImplementedError

    def on_change(self, req: RateLimitReq, item: CacheItem) -> None:
        """Called after the request's state changed on device."""
        raise NotImplementedError

    def remove(self, key: str) -> None:
        """Called when an item is explicitly invalidated."""
        raise NotImplementedError


class Loader:
    """Bulk persistence (reference store.go:69-78)."""

    def load(self) -> Iterable[CacheItem]:
        """Yield items to preload before serving."""
        raise NotImplementedError

    def save(self, items: Iterator[CacheItem]) -> None:
        """Consume the live items at shutdown."""
        raise NotImplementedError


class MockStore(Store):
    """Dict-backed Store, mirroring the in-library mock (store.go:80-106)."""

    def __init__(self) -> None:
        self.called: Dict[str, int] = {"get": 0, "on_change": 0, "remove": 0}
        self.data: Dict[str, CacheItem] = {}
        self._lock = threading.Lock()

    def get(self, req: RateLimitReq) -> Optional[CacheItem]:
        with self._lock:
            self.called["get"] += 1
            return self.data.get(req.hash_key())

    def on_change(self, req: RateLimitReq, item: CacheItem) -> None:
        with self._lock:
            self.called["on_change"] += 1
            self.data[item.key] = item

    def remove(self, key: str) -> None:
        with self._lock:
            self.called["remove"] += 1
            self.data.pop(key, None)


class MockLoader(Loader):
    """List-backed Loader, mirroring store.go:108-150."""

    def __init__(self, items: Optional[List[CacheItem]] = None) -> None:
        self.called: Dict[str, int] = {"load": 0, "save": 0}
        self.contents: List[CacheItem] = list(items or [])

    def load(self) -> Iterable[CacheItem]:
        self.called["load"] += 1
        return list(self.contents)

    def save(self, items: Iterator[CacheItem]) -> None:
        self.called["save"] += 1
        self.contents = list(items)


def item_to_row_fields(item: CacheItem) -> dict:
    """CacheItem -> BucketRows field dict (minus key_hash)."""
    leaky = item.algorithm == Algorithm.LEAKY_BUCKET
    return dict(
        algo=int(item.algorithm),
        limit=int(item.limit),
        duration=int(item.duration),
        remaining=0 if leaky else int(item.remaining),
        remaining_f=float(item.remaining) if leaky else 0.0,
        t0=int(item.created_at),
        status=int(item.status),
        burst=int(item.burst) if item.burst else int(item.limit),
        expire_at=int(item.expire_at),
    )
