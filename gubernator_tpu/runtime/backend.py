"""Device backend: the intra-node engine behind the service instance.

Replaces the reference WorkerPool (workers.go:56-664).  Where the reference
shards the key space across NumCPU goroutine workers each owning a private
LRU, this backend owns ONE device-resident slot table and applies whole
batches in a single jitted step — intra-node parallelism comes from vector
lanes, not threads.  (The multi-chip version shards the same table over a
mesh axis; see gubernator_tpu.parallel.mesh.)

Synchronous by design: callers (the async batcher / service) serialize calls,
which preserves the reference's single-writer-per-shard discipline
(workers.go:19-37) at whole-table granularity.
"""
from __future__ import annotations

import functools
import threading
from typing import Dict, List, NamedTuple, Optional, Sequence

import jax
import numpy as np

import gubernator_tpu.ops  # noqa: F401  (enables x64)
from gubernator_tpu.core import clock as clock_mod
from gubernator_tpu.core.config import DeviceConfig
from gubernator_tpu.core.hashing import key_hash64
from gubernator_tpu.core.types import (
    CacheItem,
    RateLimitReq,
    RateLimitResp,
    Status,
)
from gubernator_tpu.ops.batch import DeviceBatch, pack_requests
from gubernator_tpu.ops.state import SlotTable, init_table, table_to_host
from gubernator_tpu.ops.step import DeviceBatchJ, apply_batch


class DeviceBackend:
    """Single-table rate-limit engine on one device (or CPU backend)."""

    def __init__(
        self,
        cfg: Optional[DeviceConfig] = None,
        clock: Optional[clock_mod.Clock] = None,
    ) -> None:
        self.cfg = cfg or DeviceConfig()
        self.clock = clock or clock_mod.default_clock()
        self._lock = threading.Lock()
        if self.cfg.platform is not None:
            self._device = jax.devices(self.cfg.platform)[0]
        else:
            self._device = jax.devices()[0]
        with jax.default_device(self._device):
            self.table: SlotTable = init_table(self.cfg.num_slots)
        self._step = functools.partial(apply_batch, ways=self.cfg.ways)
        # Running totals (metric parity: gubernator_over_limit_counter etc.)
        self.checks = 0
        self.over_limit = 0
        self.not_persisted = 0

    def _add_tally(self, tally: "Tally") -> None:
        with self._lock:
            self.checks += tally.checks
            self.over_limit += tally.over_limit
            self.not_persisted += tally.not_persisted

    # -- hot path --------------------------------------------------------
    def check(self, reqs: Sequence[RateLimitReq]) -> List[RateLimitResp]:
        """Apply a list of checks; returns responses in request order.

        The packer splits duplicate keys into sequential rounds so same-key
        requests observe each other's effects, like the reference's per-key
        worker serialization (workers.go:182-186).
        """
        packed = pack_requests(reqs, self.cfg.batch_size, self.clock)
        now = self.clock.millisecond_now()

        round_resps = []
        with self._lock:
            for db in packed.rounds:
                self.table, resp = self._step(
                    self.table, _to_device(db), np.int64(now)
                )
                round_resps.append(resp)
        # One sync at the end of all rounds.
        out, tally = unmarshal_responses(
            len(reqs), packed.errors, packed.positions,
            resp_rounds_to_host(round_resps),
        )
        self._add_tally(tally)
        return out

    # -- cache item access (GLOBAL path + persistence SPI) ---------------
    def get_cache_item(self, key: str) -> Optional[CacheItem]:
        """Point read of one key; reads only the key's bucket (`ways` slots),
        not the whole table."""
        ways = self.cfg.ways
        nb = self.cfg.num_slots // ways
        bucket = key_hash64(key) & (nb - 1)
        now = self.clock.millisecond_now()
        with self._lock:
            return probe_bucket(self.table, bucket * ways, ways, key, now)

    def snapshot(self) -> Dict[str, np.ndarray]:
        """Device->host DMA of the whole table (Loader save path,
        workers.go:467-530)."""
        with self._lock:
            return table_to_host(self.table)

    def occupancy(self) -> int:
        with self._lock:
            return int(np.asarray(self.table.occupancy()))


class Tally(NamedTuple):
    """Per-call metric increments (gubernator.go:59-113 counters)."""

    checks: int
    over_limit: int
    not_persisted: int


def resp_rounds_to_host(round_resps) -> List[Dict[str, np.ndarray]]:
    """DMA one list of device Resp rounds to host numpy dicts (single sync)."""
    return [
        {
            "status": np.asarray(r.status),
            "remaining": np.asarray(r.remaining),
            "reset_time": np.asarray(r.reset_time),
            "limit": np.asarray(r.limit),
            "persisted": np.asarray(r.persisted),
        }
        for r in round_resps
    ]


def unmarshal_responses(
    n_reqs: int,
    errors: Dict[int, str],
    positions: Sequence[tuple],
    round_host: List[Dict[str, np.ndarray]],
) -> tuple:
    """Build per-request RateLimitResp from packed positions.

    `positions[i]` is (round, *index) where *index indexes the response
    arrays directly — (lane,) for the single-table backend, (shard, lane)
    for the mesh backend.  Returns (responses, Tally).
    """
    out: List[RateLimitResp] = []
    checks = over = notp = 0
    for i in range(n_reqs):
        err = errors.get(i)
        if err is not None:
            out.append(RateLimitResp(error=err))
            continue
        rnd, *idx_l = positions[i]
        idx = tuple(idx_l)
        r = round_host[rnd]
        resp = RateLimitResp(
            status=Status(int(r["status"][idx])),
            limit=int(r["limit"][idx]),
            remaining=int(r["remaining"][idx]),
            reset_time=int(r["reset_time"][idx]),
        )
        out.append(resp)
        checks += 1
        if resp.status == Status.OVER_LIMIT:
            over += 1
        if not r["persisted"][idx]:
            notp += 1
    return out, Tally(checks, over, notp)


def probe_bucket(
    table: SlotTable, lo: int, ways: int, key: str, now: int
) -> Optional[CacheItem]:
    """Host-side point read of one bucket: DMA `ways` rows starting at `lo`
    and return the live item for `key`, if any (the WorkerPool.GetCacheItem
    analog, workers.go:614-646; expired rows read as misses like
    lrucache.go:115-127)."""
    rows = {
        f: np.asarray(getattr(table, f)[lo:lo + ways])
        for f in table._fields
    }
    h = int(np.uint64(key_hash64(key)).view(np.int64))
    for w in range(ways):
        if rows["key"][w] == h and rows["expire_at"][w] > now:
            return _row_to_item(rows, w, key)
    return None


def _to_device(db: DeviceBatch) -> DeviceBatchJ:
    return DeviceBatchJ(*[np.asarray(a) for a in db])


def _row_to_item(snap: Dict[str, np.ndarray], s: int, key: str) -> CacheItem:
    from gubernator_tpu.core.types import Algorithm

    algo = Algorithm(int(snap["algo"][s]))
    remaining: float
    if algo == Algorithm.LEAKY_BUCKET:
        remaining = float(snap["remaining_f"][s])
    else:
        remaining = int(snap["remaining"][s])
    return CacheItem(
        key=key,
        algorithm=algo,
        expire_at=int(snap["expire_at"][s]),
        limit=int(snap["limit"][s]),
        duration=int(snap["duration"][s]),
        remaining=remaining,
        created_at=int(snap["t0"][s]),
        status=Status(int(snap["status"][s])),
        burst=int(snap["burst"][s]),
    )
