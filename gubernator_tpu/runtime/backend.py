"""Device backend: the intra-node engine behind the service instance.

Replaces the reference WorkerPool (workers.go:56-664).  Where the reference
shards the key space across NumCPU goroutine workers each owning a private
LRU, this backend owns ONE device-resident slot table and applies whole
batches in a single jitted step — intra-node parallelism comes from vector
lanes, not threads.  (The multi-chip version shards the same table over a
mesh axis; see gubernator_tpu.parallel.mesh.)

Synchronous by design: callers (the async batcher / service) serialize calls,
which preserves the reference's single-writer-per-shard discipline
(workers.go:19-37) at whole-table granularity.
"""
from __future__ import annotations

import functools
import threading
import time
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import numpy as np

import gubernator_tpu.ops  # noqa: F401  (enables x64)
from gubernator_tpu.core import clock as clock_mod
from gubernator_tpu.core.config import DeviceConfig
from gubernator_tpu.core.hashing import key_hash64
from gubernator_tpu.core.types import (
    CacheItem,
    RateLimitReq,
    RateLimitResp,
    Status,
)
from gubernator_tpu.ops.batch import DeviceBatch, pack_requests
from gubernator_tpu.ops.state import SlotTable, init_table, table_to_host
from gubernator_tpu.ops.step import (
    BucketRows,
    CachedRows,
    apply_batch_packed_q,
    gather_rows,
    load_rows,
    probe_batch,
    store_cached_rows,
)


def pack_batch_q(db) -> np.ndarray:
    """Stack a [B] DeviceBatch into one int64[12, B] host array (single
    host->device transfer; bools/int32 widen)."""
    arrs = [np.asarray(a) for a in db]
    q = np.empty((len(arrs),) + arrs[0].shape, dtype=np.int64)
    for i, a in enumerate(arrs):
        q[i] = a
    return q


def resolve_tiers(cfg) -> tuple:
    """Sorted compiled batch tiers; batch_size is ALWAYS included so
    tier_of's fallback never truncates a full round."""
    tiers = cfg.batch_tiers or (128, cfg.batch_size)
    return tuple(sorted(
        {min(t, cfg.batch_size) for t in tiers} | {cfg.batch_size}
    ))


def tier_of(active: np.ndarray, tiers) -> int:
    """Smallest compiled batch tier that holds this round's active lanes
    (the packer fills lanes contiguously from 0 per shard, so the max
    per-shard count bounds the highest used lane).  `active` is [B] or
    [n_shards, B]."""
    occ = int(np.asarray(active).sum(-1).max())
    for t in tiers:
        if occ <= t:
            return t
    return tiers[-1]


def _h64s(hashes: Sequence[int]) -> np.ndarray:
    """Unsigned 64-bit key fingerprints -> the int64 view stored on device."""
    return np.array(hashes, dtype=np.uint64).view(np.int64)


class PersistenceHost:
    """Host-side Store/Loader plumbing shared by DeviceBackend and
    MeshBackend (the SPI semantics of store.go:49-78 / workers.go:340-530).

    Backends provide the device hooks:
    - `_found_mask(keys, hashes, now)` -> bool[len(keys)] residency probe
      (caller holds `_lock`; `hashes` are unsigned 64-bit ints);
    - `_bulk_upsert(rows, hashes, now)` upserts row-field dicts (caller
      holds `_lock`);
    - `read_items_bulk(keys)` -> {key: CacheItem} (takes its own lock);
    - `snapshot()` -> host arrays of the whole table.
    Plus the attributes `cfg`, `clock`, `store`, `_keymap`, `_lock`, `table`.
    """

    def _maybe_prune_keymap(self) -> None:
        """Bound the fingerprint->key map: the table holds at most num_slots
        live rows, so once the map is 4x that, drop fingerprints no longer
        resident (evicted/expired keys would otherwise accumulate forever).
        The rebuild holds `_keymap_lock` — the object path's executor
        thread, the fast-lane pool, and the engine lane all write the map
        concurrently, and an unlocked rebuild would either crash on a
        concurrent insert or silently drop it."""
        assert self._keymap is not None
        if len(self._keymap) <= max(4 * self.cfg.num_slots, 65_536):
            return
        with self._lock:
            resident = set(
                np.asarray(self.table.key).view(np.uint64).tolist()
            )
        with self._keymap_lock:
            self._keymap = {
                fp: k for fp, k in self._keymap.items() if fp in resident
            }

    def _seed_from_store(self, reqs, packed, now: int) -> None:
        """Consult Store.get for batch keys not resident on device and bulk
        upsert the hits (the batched analog of algorithms.go:45-51).
        Caller holds `_lock`."""
        uniq: Dict[str, RateLimitReq] = {}
        for i, r in enumerate(reqs):
            if i not in packed.errors:
                uniq.setdefault(r.hash_key(), r)
        keys = list(uniq.keys())
        if not keys:
            return
        hashes = [key_hash64(k) for k in keys]
        self._seed_missing(keys, hashes, [uniq[k] for k in keys], now)

    def _seed_missing(self, keys, hashes, reqs, now: int) -> None:
        """Object-path seeding: one residency probe over `hashes`
        (unsigned), then the shared Store-consult core.  Caller holds
        `_lock`."""
        found = self._found_mask(keys, hashes, now)
        self._store_seed_misses(hashes, reqs, found, now)

    def _store_seed_misses(self, hashes, reqs, found, now: int):
        """Store-consult core shared by the object path (probe-derived
        `found`) and the fast lane's cold-key repair (the step's own
        `found` column): Store.get for each miss, one bulk upsert of the
        live items (algorithms.go:45-51 batched).  Caller holds `_lock`.
        Returns the indices (into the input lists) that were seeded."""
        from gubernator_tpu.runtime.store import item_to_row_fields

        rows: List[dict] = []
        row_hashes: List[int] = []
        seeded: List[int] = []
        for i, (h, r, f) in enumerate(zip(hashes, reqs, found)):
            if f:
                continue
            item = self.store.get(r)
            if item is None or item.is_expired(now):
                continue
            rows.append(item_to_row_fields(item))
            row_hashes.append(h)
            seeded.append(i)
        if rows:
            self._bulk_upsert(rows, row_hashes, now)
        return seeded

    def _init_write_through(self) -> None:
        """Write-through delivery ordering + keymap-writer state (backend
        __init__)."""
        self._wt_seq = 0
        self._wt_next = 0
        self._wt_cond = threading.Condition()
        # Guards every _keymap mutation: the step executor, the fast-lane
        # pool, and the engine lane write it from different threads.
        self._keymap_lock = threading.Lock()

    def _wt_ticket(self) -> int:
        """Next write-through delivery ticket (caller holds `_lock`).
        Tickets order Store.on_change delivery across concurrent batches:
        captures are per-batch-consistent, but without ordering a slower
        thread could deliver an OLDER captured state after a newer one and
        the store would diverge from the table (the reference orders
        delivery by calling OnChange inside the per-key worker).  Every
        ticket MUST be redeemed via _deliver_write_through (even with an
        empty capture) or later deliveries stall."""
        seq = self._wt_seq
        self._wt_seq = seq + 1
        return seq

    def _capture_write_through(
        self, reqs, packed, use_cached=None
    ) -> List[Tuple[RateLimitReq, CacheItem]]:
        """Read back post-step rows for persisted requests while the caller
        STILL HOLDS `_lock` — a concurrent batch must not mutate a key
        between this batch's step and its Store.on_change read-back (the
        reference calls OnChange synchronously inside the algorithm,
        algorithms.go:154-158).

        Lanes served from GLOBAL broadcast cache (use_cached) are excluded —
        their rows are replicated responses, not authoritative bucket state
        (the reference only runs OnChange inside the owner's algorithm)."""
        seen: set = set()
        key_req: List[Tuple[str, RateLimitReq]] = []
        for i, r in enumerate(reqs):
            if i in packed.errors:
                continue
            if use_cached is not None and use_cached[i]:
                continue
            key = r.hash_key()
            if key in seen:
                continue
            seen.add(key)
            key_req.append((key, r))
        if not key_req:
            return []
        items = self._read_items_locked([k for k, _ in key_req])
        return [(r, items[k]) for k, r in key_req if k in items]

    def _deliver_write_through(self, captured, seq: int) -> None:
        """Hand captured post-step items to Store.on_change, in capture
        order (`seq` from `_wt_ticket`).  Runs OUTSIDE `_lock` — on_change
        is user code and must not be able to deadlock against backend
        entry points — but a FIFO ticket wait preserves step order, so a
        stale capture can never overwrite a newer one in the store."""
        cond = self._wt_cond
        with cond:
            while self._wt_next != seq:
                cond.wait()
        try:
            for r, item in captured:
                self.store.on_change(r, item)
        finally:
            with cond:
                self._wt_next += 1
                cond.notify_all()

    # -- live slot migration (runtime/reshard.py; docs/resharding.md) ----
    def key_snapshot(self):
        """(key int64[S], kind int32[S], expire_at int64[S]) host view —
        the reshard plane's remap-delta input (one fetch, no full-table
        DMA)."""
        with self._lock:
            t = self.table
            return (
                np.asarray(t.key), np.asarray(t.kind),
                np.asarray(t.expire_at),
            )

    def migrate_extract_rows(self, fps: np.ndarray):
        """Atomically gather-and-clear the rows for int64 fingerprints
        `fps`: returns (int64[10, n] in ops.step.GATHER_ROW_FIELDS
        order — packed[0] is the found mask — and float64[n]
        remaining_f).  Cleared rows read as empty to every probe from
        the moment the lock releases, so the old owner can never serve
        a migrated key from an orphaned slot.

        Generic path (MeshBackend): a row gather plus an expire_at=0
        re-upsert in ONE critical section — two dispatches, same
        atomicity, riding the registered sharded gather/load kernels."""
        from gubernator_tpu.ops.step import GATHER_ROW_FIELDS

        n = len(fps)
        now = self.clock.millisecond_now()
        with self._lock:
            token = self._gather_rows_dispatch(
                np.asarray(fps, dtype=np.int64), now
            )
            packed, rf = self._gather_rows_finish(token, n)
            found = packed[0] != 0
            if found.any():
                rows = [
                    {
                        "algo": int(packed[2][j]),
                        "limit": int(packed[3][j]),
                        "duration": int(packed[4][j]),
                        "remaining": int(packed[5][j]),
                        "remaining_f": float(rf[j]),
                        "t0": int(packed[6][j]),
                        "status": int(packed[7][j]),
                        "burst": int(packed[8][j]),
                        "expire_at": 0,  # the clear
                    }
                    for j in np.flatnonzero(found)
                ]
                hashes = [
                    int(np.int64(fps[j]).view(np.uint64))
                    for j in np.flatnonzero(found)
                ]
                self._bulk_upsert(rows, hashes, now)
        assert packed.shape[0] == len(GATHER_ROW_FIELDS)
        return packed, rf

    def migrate_inject_rows(self, cols: Dict[str, np.ndarray]):
        """Upsert migrated row columns (BucketRows field names) where
        the key is absent; MERGE where it is resident — subtract the
        migrated row's consumed budget from the resident row, clamped
        at 0 (counters conserved, never inflated; a receiver may have
        served a moved key before its row arrived).  Returns
        (injected, merged).  The reshard manager guards chunk replays
        per handoff epoch — a re-delivered chunk never reaches this.

        Generic path (MeshBackend): probe + upsert + a gather/re-upsert
        merge in one critical section over the registered sharded
        kernels."""
        n = len(cols["key_hash"])
        now = self.clock.millisecond_now()
        h64 = np.asarray(cols["key_hash"], dtype=np.int64)
        hashes_u = [int(np.int64(h).view(np.uint64)) for h in h64]
        with self._lock:
            found = np.asarray(
                self._found_mask([""] * n, hashes_u, now)
            )
            absent = ~found

            def row_at(j, remaining, remaining_f):
                return {
                    "algo": int(cols["algo"][j]),
                    "limit": int(cols["limit"][j]),
                    "duration": int(cols["duration"][j]),
                    "remaining": int(remaining),
                    "remaining_f": float(remaining_f),
                    "t0": int(cols["t0"][j]),
                    "status": int(cols["status"][j]),
                    "burst": int(cols["burst"][j]),
                    "expire_at": int(cols["expire_at"][j]),
                }

            if absent.any():
                idx = np.flatnonzero(absent)
                self._bulk_upsert(
                    [
                        row_at(
                            j, cols["remaining"][j],
                            cols["remaining_f"][j],
                        )
                        for j in idx
                    ],
                    [hashes_u[j] for j in idx], now,
                )
            if found.any():
                idx = np.flatnonzero(found)
                token = self._gather_rows_dispatch(h64[idx], now)
                packed, rf = self._gather_rows_finish(token, len(idx))
                rows = []
                hashes = []
                for k, j in enumerate(idx):
                    consumed_i = max(
                        int(cols["limit"][j])
                        - int(cols["remaining"][j]), 0,
                    )
                    consumed_f = max(
                        float(cols["limit"][j])
                        - float(cols["remaining_f"][j]), 0.0,
                    )
                    leaky = int(cols["algo"][j]) == 1
                    rows.append({
                        # The RESIDENT row's fields, with the migrated
                        # consumption folded in.
                        "algo": int(packed[2][k]),
                        "limit": int(packed[3][k]),
                        "duration": int(packed[4][k]),
                        "remaining": max(
                            int(packed[5][k])
                            - (0 if leaky else consumed_i), 0,
                        ),
                        "remaining_f": max(
                            float(rf[k])
                            - (consumed_f if leaky else 0.0), 0.0,
                        ),
                        "t0": int(packed[6][k]),
                        "status": int(packed[7][k]),
                        "burst": int(packed[8][k]),
                        "expire_at": int(packed[9][k]),
                    })
                    hashes.append(hashes_u[j])
                self._bulk_upsert(rows, hashes, now)
        injected = int(absent.sum())
        return injected, n - injected

    def load_items(self, items) -> int:
        """Bulk upsert CacheItems (Loader restore, workers.go:340-426)."""
        from gubernator_tpu.runtime.store import item_to_row_fields

        chunk = 4 * self.cfg.batch_size
        now = self.clock.millisecond_now()
        n = 0
        rows: List[dict] = []
        hashes: List[int] = []
        for item in items:
            h = key_hash64(item.key)
            if self._keymap is not None:
                with self._keymap_lock:
                    self._keymap[h] = item.key
            rows.append(item_to_row_fields(item))
            hashes.append(h)
            n += 1
            if len(rows) >= chunk:
                with self._lock:
                    self._bulk_upsert(rows, hashes, now)
                rows, hashes = [], []
        if rows:
            with self._lock:
                self._bulk_upsert(rows, hashes, now)
        return n

    def live_items(self) -> List[CacheItem]:
        """All live rows as CacheItems (Loader save, workers.go:467-530).
        Requires key tracking (a Store/Loader attached at construction)."""
        if self._keymap is None:
            raise RuntimeError(
                "live_items() needs key tracking; construct the backend "
                "with a store or track_keys=True"
            )
        from gubernator_tpu.ops.state import KIND_CACHED_RESP

        snap = self.snapshot()
        now = self.clock.millisecond_now()
        out: List[CacheItem] = []
        # KIND_CACHED_RESP rows are replicated GLOBAL broadcast responses,
        # not authoritative bucket state — saving them would resurrect them
        # as owner buckets on restore.
        live = np.flatnonzero(
            (snap["key"] != 0)
            & (snap["expire_at"] > now)
            & (snap["kind"] != KIND_CACHED_RESP)
        )
        for s in live:
            fp = int(np.int64(snap["key"][s]).view(np.uint64))
            key = self._keymap.get(fp)
            if key is None:
                continue
            out.append(_row_to_item(snap, s, key))
        return out


class DeviceBackend(PersistenceHost):
    """Single-table rate-limit engine on one device (or CPU backend)."""

    def __init__(
        self,
        cfg: Optional[DeviceConfig] = None,
        clock: Optional[clock_mod.Clock] = None,
        store: Optional["Store"] = None,
        track_keys: bool = False,
        metrics=None,
    ) -> None:
        self.metrics = metrics
        self.cfg = cfg or DeviceConfig()
        self.clock = clock or clock_mod.default_clock()
        self._lock = threading.Lock()
        self._init_write_through()
        if self.cfg.platform is not None:
            self._device = jax.devices(self.cfg.platform)[0]
        else:
            self._device = jax.devices()[0]
        with jax.default_device(self._device):
            self.table: SlotTable = init_table(self.cfg.num_slots)
        self._step_packed_q = functools.partial(
            apply_batch_packed_q, ways=self.cfg.ways
        )
        # Batch-shape tiers: a round with few active lanes rides a small
        # compiled shape instead of shipping the full [12, B] array — the
        # transfer (and on slow links, the E2E latency) scales with the
        # traffic, not the configured max batch.  batch_size is always a
        # tier so a full round can never be truncated.
        self._tiers = resolve_tiers(self.cfg)
        self._load_rows = functools.partial(load_rows, ways=self.cfg.ways)
        self._probe = functools.partial(probe_batch, ways=self.cfg.ways)
        # Module-level jits (apply_batch_packed/load_rows/probe_batch/
        # store_cached_rows) share one compile cache across backends — the
        # in-process cluster fixture runs many daemons per process and
        # per-instance jits would recompile per daemon.
        self._store_cached = functools.partial(
            store_cached_rows, ways=self.cfg.ways
        )
        self._gather_rows = functools.partial(
            gather_rows, ways=self.cfg.ways
        )
        self.store = store
        # Force the persistent serve kernel's interpret emulation
        # (tests/smokes on CPU; see persistent_serve_supported).
        self._persistent_interpret = False
        # fingerprint -> hash-key string, maintained when persistence needs
        # to reconstruct key strings from device rows (save path).
        self._keymap: Optional[Dict[int, str]] = (
            {} if (store is not None or track_keys) else None
        )
        # Running totals (metric parity: gubernator_over_limit_counter etc.)
        self.checks = 0
        self.over_limit = 0
        self.not_persisted = 0

    def _add_tally(self, tally: "Tally") -> None:
        with self._lock:
            self.checks += tally.checks
            self.over_limit += tally.over_limit
            self.not_persisted += tally.not_persisted
        m = self.metrics
        if m is not None:
            m.check_counter.inc(tally.checks)
            if tally.over_limit:
                m.over_limit_counter.inc(tally.over_limit)
            if tally.not_persisted:
                m.unexpired_evictions.inc(tally.not_persisted)
            m.cache_access_count.labels(type="hit").inc(tally.cache_hits)
            m.cache_access_count.labels(type="miss").inc(
                tally.checks - tally.cache_hits
            )

    # -- hot path --------------------------------------------------------
    def check(
        self,
        reqs: Sequence[RateLimitReq],
        use_cached: Optional[Sequence[bool]] = None,
    ) -> List[RateLimitResp]:
        """Apply a list of checks; returns responses in request order.

        The packer splits duplicate keys into sequential rounds so same-key
        requests observe each other's effects, like the reference's per-key
        worker serialization (workers.go:182-186).

        `use_cached[i]` marks request i to serve a live GLOBAL broadcast row
        verbatim (the non-owner read path, gubernator.go:434-447).
        """
        packed = pack_requests(
            reqs, self.cfg.batch_size, self.clock, use_cached
        )
        now = self.clock.millisecond_now()
        if self._keymap is not None:
            with self._keymap_lock:
                for i, r in enumerate(reqs):
                    if i not in packed.errors:
                        k = r.hash_key()
                        self._keymap[key_hash64(k)] = k
            self._maybe_prune_keymap()
        round_resps = []
        captured = None
        t_start = time.monotonic()
        with self._lock:
            if self.store is not None:
                self._seed_from_store(reqs, packed, now)
            from gubernator_tpu.runtime.tracing import device_step_annotation

            with device_step_annotation():
                for db in packed.rounds:
                    t = tier_of(db.active, self._tiers)
                    self.table, packed_resp = self._step_packed_q(
                        self.table, pack_batch_q(db)[:, :t], np.int64(now)
                    )
                    round_resps.append(packed_resp)
            if self.store is not None:
                # Read-back inside the lock: a concurrent batch must not
                # mutate a key between this batch's step and on_change.
                captured = self._capture_write_through(
                    reqs, packed, use_cached
                )
                wt_seq = self._wt_ticket()
        try:
            step_s = time.monotonic() - t_start
            if self.metrics is not None:
                self.metrics.device_step_duration.observe(step_s)
                self.metrics.pool_queue_length.observe(len(reqs))
            # One packed sync per round (one transfer instead of six).
            out, tally = unmarshal_responses(
                len(reqs), packed.errors, packed.positions,
                packed_rounds_to_host(round_resps),
            )
            self._add_tally(tally)
            fr = getattr(self.metrics, "flightrec", None)
            if fr is not None:
                fr.record_batch(
                    len(reqs), step_s * 1e3,
                    over_limit=tally.over_limit,
                    errors=len(packed.errors),
                )
        finally:
            # The ticket MUST be redeemed even if unmarshal fails, or
            # every later delivery wedges in cond.wait (the step itself
            # already happened, so delivering the capture is correct).
            if captured is not None:
                self._deliver_write_through(captured, wt_seq)
        return out

    def step_rounds(
        self, rounds: Sequence[DeviceBatch], add_tally: bool = True
    ) -> List[Dict[str, np.ndarray]]:
        """Columnar hot path: apply pre-packed [B] DeviceBatch rounds with
        no per-request Python anywhere (the compiled fast lane,
        runtime/fastpath.py).  Persistence hooks are NOT run here — a
        store-attached drain runs them itself around
        _dispatch_rounds_locked (fastpath._process: seed inside the lock,
        capture dispatched inside, delivered outside); this entry serves
        the storeless plain merge.  Returns host response dicts per round;
        with add_tally, tallies update vectorized (the fast lane passes
        False and counts per REQUEST — cascade occurrences share device
        lanes)."""
        return self.step_rounds_begin(rounds, add_tally)()

    def step_rounds_begin(
        self, rounds: Sequence[DeviceBatch], add_tally: bool = True
    ):
        """Pipelined step_rounds: dispatch the rounds under the lock and
        return a zero-arg fetch closure producing the host response
        dicts.  The dispatched responses are this call's own output
        buffers pinned to this table version (jax arrays are immutable),
        so the caller may run the closure on a fetch stage while the
        next merge dispatches — the two-stage drain discipline
        (fastpath._Coalescer)."""
        t_start = time.monotonic()
        with self._lock:
            round_resps = self._dispatch_rounds_locked(rounds)

        def fetch() -> List[Dict[str, np.ndarray]]:
            host = packed_rounds_to_host(round_resps)
            if add_tally:
                tally = tally_from_rounds(rounds, host)
                self._add_tally(tally)
                fr = getattr(self.metrics, "flightrec", None)
                if fr is not None:
                    fr.record_batch(
                        tally.checks, (time.monotonic() - t_start) * 1e3,
                        over_limit=tally.over_limit,
                    )
            return host

        return fetch

    def _dispatch_rounds_locked(self, rounds) -> list:
        """Dispatch pre-packed rounds; caller holds `_lock`.  Returns the
        device response handles WITHOUT syncing them — the fast lane's
        cascade section syncs inside the lock (its critical window spans
        the sync) while the plain path syncs after release."""
        now = np.int64(self.clock.millisecond_now())
        t_start = time.monotonic()
        round_resps = []
        for db in rounds:
            t = tier_of(db.active, self._tiers)
            self.table, packed_resp = self._step_packed_q(
                self.table, pack_batch_q(db)[:, :t], now
            )
            round_resps.append(packed_resp)
        if self.metrics is not None:
            self.metrics.device_step_duration.observe(
                time.monotonic() - t_start
            )
        return round_resps

    # -- ring drain discipline (runtime/ring.py) -------------------------
    def ring_supported(self) -> bool:
        """Single-table backends scan ops/ring.ring_step directly; the
        mesh backend serves the same protocol through its shard_map lift
        (parallel/sharded.make_mesh_ring_step) — both report True, and
        the RingBackend shapes its blocks via ring_q_shape()."""
        return True

    def ring_q_shape(self, tb: int) -> tuple:
        """Per-round request-slot shape at batch tier `tb`: [12, tb]
        (pack_batch_q row order).  The mesh backend returns the grid
        form [12, n_shards, tb]; the ring runner is layout-agnostic —
        it only stacks rounds along a leading slot axis."""
        return (12, tb)

    def ring_pack_round(self, db, tb: int) -> np.ndarray:
        """One [B] DeviceBatch -> its ring slot layout [12, tb]."""
        return pack_batch_q(db)[:, :tb]

    def ring_seq_init(self):
        """A fresh device-resident sequence word for a RingBackend."""
        import jax.numpy as jnp

        with jax.default_device(self._device):
            return jnp.zeros((), dtype=jnp.int64)

    def ring_step_dispatch(self, qs: np.ndarray, nows: np.ndarray, seq):
        """Dispatch one bounded ring iteration — `qs` int64[k, 12, B]
        stacked rounds applied in order by ops/ring.ring_step — under
        the lock (the same single-writer section as every other table
        mutation, so store write-through and the object path dispatch-
        order against ring steps).  Returns the un-synced device
        (responses, new seq word); the ring runner fetches them off the
        request path."""
        from gubernator_tpu.ops.ring import ring_step

        t_start = time.monotonic()
        with self._lock:
            self.table, resps, seq = ring_step(
                self.table, qs, nows, seq, ways=self.cfg.ways
            )
        if self.metrics is not None:
            self.metrics.device_step_duration.observe(
                time.monotonic() - t_start
            )
        return resps, seq

    def ring_mega_dispatch(self, qs: np.ndarray, nows: np.ndarray, seq):
        """Dispatch one MEGAROUND iteration — `qs` int64[r, s, 12, B]
        stacked ring rounds applied in order by ops/ring.mega_ring_step
        (ONE XLA entry for r*s rounds; docs/ring.md's
        dispatch-amortization tier) — under the lock.  Returns the
        un-synced device (responses[r, s, 9, B], new seq word); the
        ring runner flattens the (r, s) round axes back on the host."""
        from gubernator_tpu.ops.ring import mega_ring_step

        t_start = time.monotonic()
        with self._lock:
            self.table, resps, seq = mega_ring_step(
                self.table, qs, nows, seq, ways=self.cfg.ways
            )
        if self.metrics is not None:
            self.metrics.device_step_duration.observe(
                time.monotonic() - t_start
            )
        return resps, seq

    # -- persistent serve kernel (ops/pallas/serve_kernel.py) ------------
    def persistent_serve_supported(self):
        """(ok, reason) capability report for GUBER_SERVE_MODE=
        persistent: a real probe compile on this backend's platform
        (docs/ring.md's capability matrix), or the forced interpret
        mode tests/smokes use to exercise the persistent serving path
        on CPU.  The runtime falls back to megaround when not ok and
        surfaces the reason in /debug/vars."""
        if self._persistent_interpret:
            return True, (
                "interpret mode forced (CPU emulation; differential "
                "tests/smokes only — not a performance mode)"
            )
        from gubernator_tpu.ops.pallas.serve_kernel import (
            persistent_supported,
        )

        return persistent_supported(self._device.platform)

    def persistent_serve_dispatch(
        self, qs: np.ndarray, nows: np.ndarray, seq
    ):
        """Dispatch one persistent-kernel iteration — `qs`
        int64[k, 12, B] stacked rounds drained inside ONE Pallas launch
        — under the lock.  Same contract as ring_step_dispatch; the
        interpret form runs the un-jitted emulation (exact, slow — the
        differential path, never a deployment mode)."""
        from gubernator_tpu.ops.pallas.serve_kernel import (
            persistent_serve_step,
            persistent_serve_step_impl,
        )

        t_start = time.monotonic()
        with self._lock:
            if self._persistent_interpret:
                self.table, resps, seq = persistent_serve_step_impl(
                    self.table, qs, nows, seq, ways=self.cfg.ways,
                    interpret=True,
                )
            else:
                self.table, resps, seq = persistent_serve_step(
                    self.table, qs, nows, seq, ways=self.cfg.ways
                )
        if self.metrics is not None:
            self.metrics.device_step_duration.observe(
                time.monotonic() - t_start
            )
        return resps, seq

    def _probe_padded(self, hashes: np.ndarray, now: int) -> np.ndarray:
        """found-mask for a host hash vector, probing in fixed batch_size
        chunks so the jitted probe never sees a new shape (the fixed-shape
        rule, core/config.py DeviceConfig).  All chunks dispatch before the
        first fetch — one round-trip of latency however many chunks."""
        B = self.cfg.batch_size
        devs = []
        for lo in range(0, len(hashes), B):
            chunk = hashes[lo:lo + B]
            padded = np.zeros(B, dtype=np.int64)
            padded[: len(chunk)] = chunk
            devs.append(self._probe(self.table, padded, np.int64(now))[0])
        out = np.zeros(len(hashes), dtype=bool)
        for i, d in enumerate(fetch_ravel(devs)):
            lo = i * B
            out[lo:lo + B] = d[: len(hashes) - lo]
        return out

    def _gather_rows_dispatch(self, h64: np.ndarray, now: int):
        """Dispatch columnar row gathers for int64 fingerprints (lock
        held).  Returns an opaque token for `_gather_rows_finish`: the
        dispatched reads are pinned to this table version (jax arrays are
        immutable), so the caller may release the lock before fetching."""
        B = self.cfg.batch_size
        token = []
        for lo in range(0, len(h64), B):
            chunk = h64[lo:lo + B]
            padded = np.zeros(B, dtype=np.int64)
            padded[: len(chunk)] = chunk
            token.append(
                self._gather_rows(self.table, padded, np.int64(now))
            )
        return token

    def _gather_rows_int_arrays(self, token) -> list:
        """The token's int64 device buffers — exposed so a caller can fold
        them into ONE fetch_ravel round-trip with its response buffers."""
        return [d for d, _rf in token]

    def _gather_rows_rf_arrays(self, token) -> list:
        """The token's float64 remaining_f buffers (needed only when a
        leaky row may have been captured — token rows read remaining from
        the int columns)."""
        return [rf for _d, rf in token]

    def _gather_rows_build(self, token, m: int, int_hosts,
                           rf_hosts=None):
        """Assemble (int64[10, m] GATHER_ROW_FIELDS columns, float64[m]
        remaining_f) from pre-fetched host chunks.  rf_hosts=None means
        the caller proved no leaky row was captured (zeros)."""
        from gubernator_tpu.ops.step import GATHER_ROW_FIELDS

        if not token:
            return (
                np.zeros((len(GATHER_ROW_FIELDS), 0), dtype=np.int64),
                np.zeros(0),
            )
        packed = np.concatenate(int_hosts, axis=1)[:, :m]
        rf = (
            np.concatenate(rf_hosts)[:m] if rf_hosts is not None
            else np.zeros(m)
        )
        return packed, rf

    def _gather_rows_finish(self, token, m: int):
        """Fetch + assemble in two packed round-trips (ints, rf)."""
        return self._gather_rows_build(
            token, m,
            fetch_ravel(self._gather_rows_int_arrays(token)),
            fetch_ravel(self._gather_rows_rf_arrays(token)),
        )

    def migrate_extract_rows(self, fps: np.ndarray):
        """Fused single-device form of the generic gather-and-clear:
        each chunk is ONE donated ops/state.migrate_extract dispatch,
        so extraction and clearing are a per-row atomicity fact (the
        gubtrace-registered kernel), not a two-step protocol."""
        from gubernator_tpu.ops.state import migrate_extract

        B = self.cfg.batch_size
        now = np.int64(self.clock.millisecond_now())
        packed_devs = []
        rf_devs = []
        with self._lock:
            for lo in range(0, len(fps), B):
                chunk = np.asarray(fps[lo:lo + B], dtype=np.int64)
                padded = np.zeros(B, dtype=np.int64)
                padded[: len(chunk)] = chunk
                self.table, packed, rf = migrate_extract(
                    self.table, padded, now, ways=self.cfg.ways
                )
                packed_devs.append(packed)
                rf_devs.append(rf)
        if not packed_devs:
            return np.zeros((10, 0), dtype=np.int64), np.zeros(0)
        ints = fetch_ravel(packed_devs)
        rfs = fetch_ravel(rf_devs)
        n = len(fps)
        return (
            np.concatenate(ints, axis=1)[:, :n],
            np.concatenate(rfs)[:n],
        )

    def migrate_inject_rows(self, cols: Dict[str, np.ndarray]):
        """Fused single-device inject-if-absent (ops/state
        .migrate_inject): one donated dispatch per chunk; returns
        (injected, skipped)."""
        from gubernator_tpu.ops.state import migrate_inject
        from gubernator_tpu.ops.step import BucketRows

        B = self.cfg.batch_size
        now = np.int64(self.clock.millisecond_now())
        n = len(cols["key_hash"])
        resident_devs = []
        actives = []
        with self._lock:
            for lo in range(0, len(cols["key_hash"]), B):
                hi = min(lo + B, n)
                pad = B - (hi - lo)

                def col(f, dt):
                    return np.concatenate([
                        np.asarray(cols[f][lo:hi], dtype=dt),
                        np.zeros(pad, dtype=dt),
                    ])

                rows = BucketRows(
                    key_hash=col("key_hash", np.int64),
                    algo=col("algo", np.int32),
                    limit=col("limit", np.int64),
                    duration=col("duration", np.int64),
                    remaining=col("remaining", np.int64),
                    remaining_f=col("remaining_f", np.float64),
                    t0=col("t0", np.int64),
                    status=col("status", np.int32),
                    burst=col("burst", np.int64),
                    expire_at=col("expire_at", np.int64),
                )
                self.table, resident = migrate_inject(
                    self.table, rows, now, ways=self.cfg.ways
                )
                resident_devs.append(resident)
                actives.append(np.asarray(rows.key_hash) != 0)
        if not resident_devs:
            return 0, 0
        injected = skipped = 0
        for res, act in zip(fetch_ravel(resident_devs), actives):
            res = np.asarray(res)
            injected += int((act & ~res).sum())
            skipped += int((act & res).sum())
        return injected, skipped

    def warmup(self) -> None:
        """Compile the hot-path executables with a synthetic batch that
        bypasses the Store/Loader hooks and the keymap — no persistence
        side effects (a real check() would leak the synthetic key into an
        attached store)."""
        now = np.int64(self.clock.millisecond_now())
        packed = pack_requests(
            [RateLimitReq(name="__warmup__", unique_key="w", hits=0,
                          limit=1, duration=1)],
            self.cfg.batch_size,
            self.clock,
        )
        with self._lock:
            # Compile the packed step at EVERY batch tier — check()'s
            # actual hot path — so no client request ever pays a cold XLA
            # compile.
            for t in self._tiers:
                self.table, resp = self._step_packed_q(
                    self.table,
                    np.zeros((12, t), dtype=np.int64),
                    now,
                )
            for db in packed.rounds:
                t = tier_of(db.active, self._tiers)
                self.table, resp = self._step_packed_q(
                    self.table, pack_batch_q(db)[:, :t], now
                )
            # Fixed-shape probe + row-gather executables (store seeding /
            # write-through capture / bulk reads).
            self._probe(
                self.table,
                np.zeros(self.cfg.batch_size, dtype=np.int64),
                now,
            )
            self._gather_rows(
                self.table,
                np.zeros(self.cfg.batch_size, dtype=np.int64),
                now,
            )
            # Broadcast-receive executable (UpdatePeerGlobals path) — a
            # first compile inside a peer's RPC deadline would time out.
            B = self.cfg.batch_size
            self.table = self._store_cached(
                self.table,
                CachedRows(
                    key_hash=np.zeros(B, dtype=np.int64),
                    algo=np.zeros(B, dtype=np.int32),
                    limit=np.zeros(B, dtype=np.int64),
                    remaining=np.zeros(B, dtype=np.int64),
                    status=np.zeros(B, dtype=np.int32),
                    reset_time=np.zeros(B, dtype=np.int64),
                ),
                now,
            )
            # Gubstat census executable at the sampler's minimum shadow
            # pad tier (runtime/gubstat.py pads to powers of two from
            # 8) — the periodic sample should never pay a cold compile.
            from gubernator_tpu.ops.state import table_stats

            table_stats(
                self.table, np.zeros((4, 8), dtype=np.int64), now,
                ways=self.cfg.ways,
            )
        jax.block_until_ready(resp)

    # -- persistence device hooks (PersistenceHost) ----------------------
    def _found_mask(self, keys, hashes, now: int) -> np.ndarray:
        return self._probe_padded(_h64s(hashes), now)

    def _bulk_upsert(
        self, rows: List[dict], hashes: List[int], now: int
    ) -> None:
        """Chunked load_rows over the fixed batch shape (lock held)."""
        B = self.cfg.batch_size
        h64 = _h64s(hashes)
        for lo in range(0, len(rows), B):
            chunk = rows[lo:lo + B]
            pad = B - len(chunk)
            br = BucketRows(
                key_hash=np.concatenate([
                    h64[lo:lo + B], np.zeros(pad, dtype=np.int64)
                ]),
                **{
                    f: np.array(
                        [c[f] for c in chunk] + [0] * pad,
                        dtype=np.float64 if f == "remaining_f" else (
                            np.int32 if f in ("algo", "status") else np.int64
                        ),
                    )
                    for f in (
                        "algo", "limit", "duration", "remaining",
                        "remaining_f", "t0", "status", "burst", "expire_at",
                    )
                },
            )
            self.table = self._load_rows(self.table, br, np.int64(now))

    def read_items_bulk(
        self, keys: Sequence[str], include_cached: bool = False
    ) -> Dict[str, CacheItem]:
        """Batched point-reads: probe + device-side row gather in fixed-size
        chunks, one host sync per chunk.  KIND_CACHED_RESP rows (GLOBAL
        broadcast cache, not bucket state) are skipped unless asked for."""
        with self._lock:
            return self._read_items_locked(keys, include_cached)

    def _read_items_locked(
        self, keys: Sequence[str], include_cached: bool = False
    ) -> Dict[str, CacheItem]:
        """read_items_bulk body; caller holds `_lock` (write-through capture
        reads back rows within the same critical section as the step)."""
        from gubernator_tpu.ops.state import KIND_CACHED_RESP

        B = self.cfg.batch_size
        now = self.clock.millisecond_now()
        hashes = np.array(
            [np.uint64(key_hash64(k)) for k in keys], dtype=np.uint64
        ).view(np.int64)
        out: Dict[str, CacheItem] = {}
        for lo in range(0, len(keys), B):
            chunk_keys = keys[lo:lo + B]
            padded = np.zeros(B, dtype=np.int64)
            padded[: len(chunk_keys)] = hashes[lo:lo + B]
            found, slot = self._probe(self.table, padded, np.int64(now))
            rows = {
                f: np.asarray(getattr(self.table, f)[slot])
                for f in self.table._fields
            }
            found = np.asarray(found)
            for j, k in enumerate(chunk_keys):
                if not found[j]:
                    continue
                if (
                    rows["kind"][j] == KIND_CACHED_RESP
                    and not include_cached
                ):
                    continue
                out[k] = _row_to_item(rows, j, k)
        return out

    # -- GLOBAL broadcast receive ----------------------------------------
    def apply_cached_rows(self, rows: List[tuple]) -> None:
        """Upsert owner-broadcast statuses: rows of
        (hash_key_str, algorithm, limit, remaining, status, reset_time) —
        the UpdatePeerGlobals receive path (gubernator.go:464-479)."""
        if not rows:
            return
        if self._keymap is not None:
            with self._keymap_lock:
                for key, *_ in rows:
                    self._keymap[key_hash64(key)] = key
        B = self.cfg.batch_size
        now = self.clock.millisecond_now()
        with self._lock:
            for lo in range(0, len(rows), B):
                chunk = rows[lo:lo + B]
                pad = B - len(chunk)
                cr = CachedRows(
                    key_hash=np.array(
                        [np.uint64(key_hash64(k)).view(np.int64)
                         for k, *_ in chunk] + [0] * pad,
                        dtype=np.int64,
                    ),
                    algo=np.array(
                        [c[1] for c in chunk] + [0] * pad, dtype=np.int32
                    ),
                    limit=np.array(
                        [c[2] for c in chunk] + [0] * pad, dtype=np.int64
                    ),
                    remaining=np.array(
                        [c[3] for c in chunk] + [0] * pad, dtype=np.int64
                    ),
                    status=np.array(
                        [c[4] for c in chunk] + [0] * pad, dtype=np.int32
                    ),
                    reset_time=np.array(
                        [c[5] for c in chunk] + [0] * pad, dtype=np.int64
                    ),
                )
                self.table = self._store_cached(self.table, cr, np.int64(now))

    # -- cache item access (GLOBAL path + persistence SPI) ---------------
    def get_cache_item(self, key: str) -> Optional[CacheItem]:
        """Point read of one key; reads only the key's bucket (`ways` slots),
        not the whole table."""
        ways = self.cfg.ways
        nb = self.cfg.num_slots // ways
        bucket = key_hash64(key) & (nb - 1)
        now = self.clock.millisecond_now()
        with self._lock:
            return probe_bucket(self.table, bucket * ways, ways, key, now)

    def snapshot(self) -> Dict[str, np.ndarray]:
        """Device->host DMA of the whole table (Loader save path,
        workers.go:467-530)."""
        with self._lock:
            return table_to_host(self.table)

    def _install_table(self, arrays: Dict[str, np.ndarray]) -> None:
        """Replace the live table from host arrays (checkpoint restore)."""
        from gubernator_tpu.ops.state import table_from_host

        if arrays["key"].shape[0] != self.cfg.num_slots:
            raise ValueError(
                f"checkpoint has {arrays['key'].shape[0]} slots, backend "
                f"expects {self.cfg.num_slots}"
            )
        with self._lock, jax.default_device(self._device):
            self.table = table_from_host(arrays)

    def occupancy(self) -> int:
        with self._lock:
            return int(np.asarray(self.table.occupancy()))

    def table_stats_dispatch(self, shadow_fps: np.ndarray):
        """Dispatch the gubstat census (ops/state.table_stats) against
        the live table under the lock and return a zero-arg fetch
        closure.  The kernel is read-only and NON-donated, so the
        serving table is untouched and the dispatched result buffers
        are pinned to this table version — the sampler fetches them
        off the request path (a ring host job or an executor thread)
        while the lock is long released.  Every leaf of the fetched
        TableStats carries a leading shard axis (length 1 here; the
        mesh backend returns one row per shard)."""
        from gubernator_tpu.ops.state import TableStats, table_stats

        now = np.int64(self.clock.millisecond_now())
        fps = np.asarray(shadow_fps, dtype=np.int64)
        with self._lock:
            st = table_stats(self.table, fps, now, ways=self.cfg.ways)

        def fetch() -> "TableStats":
            return TableStats(*[np.asarray(a)[None] for a in st])

        return fetch

    # -- tiered table (runtime/coldtier.py; docs/tiering.md) -------------
    def occupancy_dispatch(self):
        """Dispatch the resident-slot count under the lock and return a
        zero-arg fetch closure — the tier manager's watermark read.
        Split from occupancy() so a ring host job never blocks the
        runner on the device->host scalar sync (the manager fetches on
        its own executor, the gubstat discipline)."""
        with self._lock:
            occ = self.table.occupancy()

        def fetch() -> int:
            return int(np.asarray(occ))

        return fetch

    def demote_extract_dispatch(self, protect_fps: np.ndarray,
                                batch: int):
        """ONE donated ops/state.demote_extract dispatch under the lock:
        the device picks the `batch` coldest unprotected live bucket
        rows, gathers their fields, and clears the slots atomically.
        Returns a zero-arg fetch closure yielding (packed int64
        [10, batch] in DEMOTE_ROW_FIELDS order, float64[batch]
        remaining_f) — dispatched on the ring runner, fetched off it."""
        from gubernator_tpu.ops.state import demote_extract

        now = np.int64(self.clock.millisecond_now())
        fps = np.asarray(protect_fps, dtype=np.int64)
        with self._lock:
            self.table, packed, rf = demote_extract(
                self.table, fps, now, ways=self.cfg.ways, batch=batch
            )

        def fetch():
            return (
                fetch_ravel([packed])[0].reshape(10, batch),
                fetch_ravel([rf])[0],
            )

        return fetch

    def migrate_inject_dispatch(self, cols: Dict[str, np.ndarray]):
        """Dispatch-only form of migrate_inject_rows for the tier
        promote path: the donated upsert-or-merge chunks go out under
        the lock; the returned fetch closure resolves the (injected,
        merged) counts off the runner thread.  Same kernel, same merge
        algebra — only the host sync moves."""
        from gubernator_tpu.ops.state import migrate_inject
        from gubernator_tpu.ops.step import BucketRows

        B = self.cfg.batch_size
        now = np.int64(self.clock.millisecond_now())
        n = len(cols["key_hash"])

        # locate_slots resolves at most INSERT_ROUNDS (= 3) same-bucket
        # insert conflicts per dispatch; a 4th contender ends transient
        # and load_rows drops it — losing the row's consumed budget.
        # Spread same-bucket rows across successive dispatches so every
        # lane can claim a slot.
        nb = self.cfg.num_slots // self.cfg.ways
        fps = np.asarray(cols["key_hash"], dtype=np.int64)
        bucket = fps.view(np.uint64) & np.uint64(nb - 1)
        rank = np.zeros(n, dtype=np.int64)
        seen: Dict[int, int] = {}
        for i in range(n):
            b = int(bucket[i])
            rank[i] = seen.get(b, 0)
            seen[b] = int(rank[i]) + 1
        wave = rank // 3
        chunks = []
        for w in range(int(wave.max()) + 1 if n else 0):
            widx = np.flatnonzero(wave == w)
            for lo in range(0, len(widx), B):
                chunks.append(widx[lo:lo + B])

        resident_devs = []
        actives = []
        with self._lock:
            for sel in chunks:
                pad = B - len(sel)

                def col(f, dt):
                    return np.concatenate([
                        np.asarray(cols[f], dtype=dt)[sel],
                        np.zeros(pad, dtype=dt),
                    ])

                rows = BucketRows(
                    key_hash=col("key_hash", np.int64),
                    algo=col("algo", np.int32),
                    limit=col("limit", np.int64),
                    duration=col("duration", np.int64),
                    remaining=col("remaining", np.int64),
                    remaining_f=col("remaining_f", np.float64),
                    t0=col("t0", np.int64),
                    status=col("status", np.int32),
                    burst=col("burst", np.int64),
                    expire_at=col("expire_at", np.int64),
                )
                self.table, resident = migrate_inject(
                    self.table, rows, now, ways=self.cfg.ways
                )
                resident_devs.append(resident)
                actives.append(np.asarray(rows.key_hash) != 0)

        def fetch():
            if not resident_devs:
                return 0, 0
            injected = merged = 0
            for res, act in zip(fetch_ravel(resident_devs), actives):
                res = np.asarray(res)
                injected += int((act & ~res).sum())
                merged += int((act & res).sum())
            return injected, merged

        return fetch


class Tally(NamedTuple):
    """Per-call metric increments (gubernator.go:59-113 counters)."""

    checks: int
    over_limit: int
    not_persisted: int
    cache_hits: int = 0


def resp_rounds_to_host(round_resps) -> List[Dict[str, np.ndarray]]:
    """DMA one list of device Resp rounds to host numpy dicts (single sync)."""
    return [
        {
            "status": np.asarray(r.status),
            "remaining": np.asarray(r.remaining),
            "reset_time": np.asarray(r.reset_time),
            "limit": np.asarray(r.limit),
            "persisted": np.asarray(r.persisted),
            "found": np.asarray(r.found),
            "stored": np.asarray(r.stored),
            "cached": np.asarray(r.cached),
            "stored_status": np.asarray(r.stored_status),
        }
        for r in round_resps
    ]


def fetch_ravel(arrs) -> List[np.ndarray]:
    """ONE device->host round-trip for many same-dtype device arrays: ravel-
    concat on device, single transfer, split + reshape on host.

    On remote-device rigs every host fetch costs a full tunnel
    round-trip even when the data is already computed, so a merge's N
    response buffers fetched separately pay N cycles — packed they pay
    one (measured 307ms -> 119ms for four [8, 4096] rounds).  Co-located
    the concat is a trivial device op."""
    if not arrs:
        return []
    if len(arrs) == 1:
        return [np.asarray(arrs[0])]
    # Mixed dtypes would silently promote under concatenate and come back
    # cast; callers must pack per-dtype groups separately.
    assert all(a.dtype == arrs[0].dtype for a in arrs), (
        [a.dtype for a in arrs]
    )
    import jax.numpy as jnp

    flat = jnp.concatenate([a.ravel() for a in arrs])
    host = np.asarray(flat)
    out = []
    off = 0
    for a in arrs:
        n = int(np.prod(a.shape))
        out.append(host[off:off + n].reshape(a.shape))
        off += n
    return out


def _packed_resp_dict(a: np.ndarray) -> Dict[str, np.ndarray]:
    """apply_batch_packed row order -> named host columns; `a` is
    [9, B] (single table) or [n, 9, B] (grid, leading shard dim)."""
    sl = (slice(None),) * (a.ndim - 2)
    return {
        "status": a[sl + (0,)],
        "limit": a[sl + (1,)],
        "remaining": a[sl + (2,)],
        "reset_time": a[sl + (3,)],
        "persisted": a[sl + (4,)],
        "found": a[sl + (5,)],
        "stored": a[sl + (6,)],
        "cached": a[sl + (7,)],
        "stored_status": a[sl + (8,)],
    }


def packed_rounds_to_host(round_packed) -> List[Dict[str, np.ndarray]]:
    """Host view of packed int64[9, B] responses (apply_batch_packed row
    order) — ONE transfer for all rounds (fetch_ravel)."""
    return [
        _packed_resp_dict(a) for a in fetch_ravel(list(round_packed))
    ]


def tally_from_rounds(rounds, round_host) -> "Tally":
    """Vectorized Tally over packed rounds (active lanes only) — the
    columnar analog of unmarshal_responses' per-request counting.

    Host arrays may be tier-sliced narrower than the round's [.., B]
    masks; lanes beyond the tier are inactive by construction, so the
    mask is sliced to match."""
    checks = over = notp = hits = 0
    for db, h in zip(rounds, round_host):
        act = np.asarray(db.active)[..., : h["status"].shape[-1]]
        checks += int(act.sum())
        over += int(((h["status"] == 1) & act).sum())
        notp += int(((h["persisted"] == 0) & act).sum())
        hits += int(((h["found"] != 0) & act).sum())
    return Tally(checks, over, notp, hits)


def unmarshal_responses(
    n_reqs: int,
    errors: Dict[int, str],
    positions: Sequence[tuple],
    round_host: List[Dict[str, np.ndarray]],
) -> tuple:
    """Build per-request RateLimitResp from packed positions.

    `positions[i]` is (round, *index) where *index indexes the response
    arrays directly — (lane,) for the single-table backend, (shard, lane)
    for the mesh backend.  Returns (responses, Tally).
    """
    out: List[RateLimitResp] = []
    checks = over = notp = hits = 0
    for i in range(n_reqs):
        err = errors.get(i)
        if err is not None:
            out.append(RateLimitResp(error=err))
            continue
        rnd, *idx_l = positions[i]
        idx = tuple(idx_l)
        r = round_host[rnd]
        resp = RateLimitResp(
            status=Status(int(r["status"][idx])),
            limit=int(r["limit"][idx]),
            remaining=int(r["remaining"][idx]),
            reset_time=int(r["reset_time"][idx]),
        )
        out.append(resp)
        checks += 1
        if resp.status == Status.OVER_LIMIT:
            over += 1
        if not r["persisted"][idx]:
            notp += 1
        if r["found"][idx]:
            hits += 1
    return out, Tally(checks, over, notp, hits)


def probe_bucket(
    table: SlotTable,
    lo: int,
    ways: int,
    key: str,
    now: int,
    include_cached: bool = True,
) -> Optional[CacheItem]:
    """Host-side point read of one bucket: DMA `ways` rows starting at `lo`
    and return the live item for `key`, if any (the WorkerPool.GetCacheItem
    analog, workers.go:614-646; expired rows read as misses like
    lrucache.go:115-127).  With include_cached=False, GLOBAL broadcast rows
    (KIND_CACHED_RESP — replicated responses, not bucket state) read as
    misses."""
    from gubernator_tpu.ops.state import KIND_CACHED_RESP

    rows = {
        f: np.asarray(getattr(table, f)[lo:lo + ways])
        for f in table._fields
    }
    h = int(np.uint64(key_hash64(key)).view(np.int64))
    for w in range(ways):
        if rows["key"][w] == h and rows["expire_at"][w] > now:
            if not include_cached and rows["kind"][w] == KIND_CACHED_RESP:
                return None
            return _row_to_item(rows, w, key)
    return None


def _row_to_item(snap: Dict[str, np.ndarray], s: int, key: str) -> CacheItem:
    from gubernator_tpu.core.types import Algorithm

    algo = Algorithm(int(snap["algo"][s]))
    remaining: float
    if algo == Algorithm.LEAKY_BUCKET:
        remaining = float(snap["remaining_f"][s])
    else:
        remaining = int(snap["remaining"][s])
    return CacheItem(
        key=key,
        algorithm=algo,
        expire_at=int(snap["expire_at"][s]),
        limit=int(snap["limit"][s]),
        duration=int(snap["duration"][s]),
        remaining=remaining,
        created_at=int(snap["t0"][s]),
        status=Status(int(snap["status"][s])),
        burst=int(snap["burst"][s]),
    )
