"""Host runtime: device backend, batcher, service, peers, daemon."""
