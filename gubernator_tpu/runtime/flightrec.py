"""Flight recorder: bounded in-memory telemetry + breach-triggered dumps.

The serving path's black box.  Three jobs, all bounded-memory and
off the hot path:

1. **Ring buffer** — recent request/batch records (device-step wall
   time, batch size, outcome mix, peer batch sends, loop stalls) in a
   fixed-size deque.  Producers are the layers that already hold the
   Metrics bundle (runtime/backend.py, parallel/sharded.py,
   net/peer_client.py, the daemon's stats interceptor); a record is a
   dict append under a cheap threading lock — safe from both the event
   loop and the device-executor threads.

2. **SLO evaluation** — a rolling window of gRPC request latencies
   feeds p50/p99 gauges (`gubernator_slo_p50_seconds` /
   `_p99_seconds`) every sampler tick; a window whose p99 exceeds the
   configured target (GUBER_SLO_P99_MS, north star p99 < 2ms)
   increments `gubernator_slo_breach_total` and — outside a cooldown —
   dumps a JSON snapshot to disk.  A check-error storm (error count in
   the trailing window over `error_storm`) triggers the same dump.

3. **Event-loop lag sampling** — the production port of raceguard's
   stall detector (testing/raceguard.py times Handle._run by patching
   asyncio internals; a daemon cannot).  Here a periodic task measures
   how late its own wakeup fires: `lag = now - (t0 + interval)`.  Any
   single callback that hogs the loop delays the wakeup by its runtime,
   so the sample is a faithful lower bound on the worst stall in the
   tick — with zero patching and one timer per daemon.  Exposed as
   `gubernator_event_loop_lag_seconds`; samples over `stall_ms` land in
   the ring.

On breach it can also start a time-boxed `jax.profiler` trace
(`profile_secs` > 0) so the host-side records line up with XLA traces —
runtime/tracing.py's device_step_annotation marks the device steps
inside them.

Discipline (gubguard-enforced): nothing here touches a device array
(host-sync), dump writes and profiler start/stop run in an executor
(async-blocking), and `_lock` is registered last in the global lock
ranking (tools/gubguard/lockorder.py) — recorder calls may run under
`backend._lock` but never take another lock while holding their own.
"""
from __future__ import annotations

import asyncio
import collections
import json
import logging
import os
import threading
import time
from typing import Callable, Deque, Dict, List, Optional, Tuple

from gubernator_tpu.runtime import tracing

log = logging.getLogger("gubernator_tpu.flightrec")

DEFAULT_SLO_P99_MS = 2.0  # BASELINE.json north star: p99 < 2ms
DEFAULT_RING = 512
DEFAULT_WINDOW_S = 10.0
DEFAULT_SAMPLE_INTERVAL_S = 0.25


def _quantiles(values: List[float]) -> Tuple[float, float]:
    """(p50, p99) by nearest-rank on a sorted copy — same convention as
    bench_e2e._percentiles up to interpolation, cheap enough to run
    every sampler tick on a bounded window."""
    if not values:
        return 0.0, 0.0
    s = sorted(values)
    n = len(s)
    p50 = s[min(n - 1, int(0.50 * (n - 1) + 0.5))]
    p99 = s[min(n - 1, int(0.99 * (n - 1) + 0.5))]
    return p50, p99


class FlightRecorder:
    """Bounded ring of recent serving records + SLO breach detection."""

    def __init__(
        self,
        metrics=None,
        slo_p99_ms: float = DEFAULT_SLO_P99_MS,
        dump_dir: str = "flightrec-dumps",
        ring_size: int = DEFAULT_RING,
        window_s: float = DEFAULT_WINDOW_S,
        min_samples: int = 20,
        error_storm: int = 100,
        stall_ms: float = 50.0,
        cooldown_s: float = 30.0,
        sample_interval_s: float = DEFAULT_SAMPLE_INTERVAL_S,
        profile_secs: float = 0.0,
    ) -> None:
        self.metrics = metrics
        self.slo_p99_ms = slo_p99_ms
        self.dump_dir = dump_dir
        self.window_s = window_s
        self.min_samples = min_samples
        self.error_storm = error_storm
        self.stall_ms = stall_ms
        self.cooldown_s = cooldown_s
        self.sample_interval_s = sample_interval_s
        self.profile_secs = profile_secs
        self._lock = threading.Lock()
        self._ring: Deque[Dict] = collections.deque(maxlen=ring_size)
        # (monotonic ts, latency seconds) request samples; sized so a
        # window at high rate still bounds memory — percentiles are over
        # the trailing window_s INTERSECTED with this cap.
        self._lat: Deque[Tuple[float, float]] = collections.deque(
            maxlen=8192
        )
        self._errors: Deque[float] = collections.deque(maxlen=8192)
        # Mirrors of the Prometheus counters (the artifact is readable
        # without a scrape; tests assert both agree).
        self.breaches = 0
        self.dumps = 0
        self.last_p50_ms = 0.0
        self.last_p99_ms = 0.0
        self.last_lag_ms = 0.0
        self.max_lag_ms = 0.0
        self.last_dump_path: Optional[str] = None
        self._last_dump_mono: float = -1e9
        # Pressure signal (docs/hotkeys.md): monotonic timestamp of the
        # first evaluation of the CURRENT unbroken run of p99 breaches,
        # None while healthy.  Drives hot-key promotion scores, the
        # owner's pressure advertisement on RPC trailing metadata
        # (daemon.py), and SLO shedding (service.shed_level).
        self._pressure_since: Optional[float] = None
        self.pressure_events = 0
        self._profiling = False
        self._task: Optional[asyncio.Task] = None
        self._started_wall = time.time()
        # Extra snapshot blocks: name -> zero-arg provider returning a
        # JSON-able value (or None to skip).  The daemon registers the
        # gubstat table census here so every breach/SIGUSR2 dump carries
        # the last device-table state alongside the ring.  Providers
        # must never raise into a dump — failures drop the block.
        self.extras: Dict[str, Callable[[], object]] = {}

    # -- producers (any thread) ------------------------------------------
    def record(self, kind: str, **fields) -> None:
        """Append one record to the ring.  Called from the loop AND from
        device-executor threads; must never block beyond the dict append.

        When the producer runs inside a sampled trace (the span plane
        binds its context on whichever thread executes a stage — the
        coalescer's fetch stage, the ring runner, the event loop), the
        record carries the trace/span ids, so a breach dump's ring can
        be joined against the trace behind its p99 bucket."""
        rec = {"ts": time.time(), "kind": kind}
        if tracing.enabled():
            ctx = tracing.current_context()
            if ctx is not None and ctx.sampled:
                rec["trace_id"] = ctx.trace_id_hex()
                rec["span_id"] = ctx.span_id_hex()
        rec.update(fields)
        with self._lock:
            self._ring.append(rec)

    def record_batch(
        self,
        size: int,
        step_ms: float,
        over_limit: int = 0,
        errors: int = 0,
        peer: str = "",
        kind: str = "device_step",
        rounds_per_dispatch: float = None,
    ) -> None:
        """One device step / peer batch: the ISSUE's record shape
        (batch size, outcome mix, peer, step wall time).  Ring records
        (kind="ring_iter") carry the running dispatch-amortization
        factor so a breach dump shows whether megaround was actually
        amortizing when the tail spiked (docs/ring.md)."""
        self.record(
            kind, size=int(size), step_ms=round(step_ms, 3),
            over_limit=int(over_limit), errors=int(errors),
            **({"peer": peer} if peer else {}),
            **(
                {"rounds_per_dispatch": float(rounds_per_dispatch)}
                if rounds_per_dispatch is not None else {}
            ),
        )

    def record_bubble(self, lane: str, wait_ms: float) -> None:
        """One pipelined-drain bubble (runtime/fastpath.py): a ready
        merge stalled `wait_ms` waiting for a fetch slot while the
        dispatch stage sat idle.  Sustained bubbles with saturated
        pipeline occupancy are the signal to raise
        GUBER_PIPELINE_DEPTH."""
        self.record(
            "fastlane_bubble", lane=lane, wait_ms=round(wait_ms, 3)
        )

    def observe_request(
        self, duration_s: float, trace_id: Optional[str] = None
    ) -> None:
        """One served request's latency into the rolling SLO window;
        `trace_id` (hex) tags the sample as an exemplar, so a breach
        dump can name the slowest traces in its window."""
        self._lat.append((time.monotonic(), duration_s, trace_id))

    def note_error(self, n: int = 1) -> None:
        now = time.monotonic()
        for _ in range(min(n, 64)):  # storm detection, not exact counting
            self._errors.append(now)

    # -- evaluation ------------------------------------------------------
    def percentiles(self) -> Tuple[float, float, int]:
        """(p50_ms, p99_ms, n) over the trailing window."""
        cutoff = time.monotonic() - self.window_s
        window = [d for ts, d, _t in list(self._lat) if ts >= cutoff]
        p50, p99 = _quantiles(window)
        return p50 * 1e3, p99 * 1e3, len(window)

    def slow_exemplars(self, limit: int = 8) -> List[Dict]:
        """The slowest trace-tagged samples in the trailing window —
        the OpenMetrics-exemplar view of the SLO histogram, readable
        straight from a dump: each entry names a trace id an operator
        (or trace_smoke) can pull from the span plane."""
        cutoff = time.monotonic() - self.window_s
        tagged = [
            (d, t) for ts, d, t in list(self._lat)
            if ts >= cutoff and t
        ]
        tagged.sort(reverse=True)
        return [
            {"ms": round(d * 1e3, 3), "trace_id": t}
            for d, t in tagged[:limit]
        ]

    def error_rate(self) -> int:
        cutoff = time.monotonic() - self.window_s
        return sum(1 for ts in list(self._errors) if ts >= cutoff)

    # -- pressure (docs/hotkeys.md) --------------------------------------
    def pressure_ratio(self) -> float:
        """Rolling p99 over the SLO target (1.0 = exactly at target);
        the multiplier in the hot-key promotion score and the value the
        owner advertises while pressured.  0 with no samples."""
        if self.slo_p99_ms <= 0:
            return 0.0
        return self.last_p99_ms / self.slo_p99_ms

    def pressure_active(self) -> bool:
        """True while the CURRENT run of breach evaluations is unbroken
        (an evaluation back under target clears it — including the
        window draining empty after traffic stops)."""
        return self._pressure_since is not None

    def pressure_sustained_s(self) -> float:
        """Seconds the current breach run has lasted (0 when healthy) —
        the shedding plane's escalation clock."""
        if self._pressure_since is None:
            return 0.0
        return max(0.0, time.monotonic() - self._pressure_since)

    def evaluate(self) -> Optional[str]:
        """One SLO evaluation: refresh the gauges, return a dump reason
        ('slo_breach' / 'error_storm') when a trigger fired outside the
        cooldown, else None.  Sync + lock-free on the hot structures so
        tests can drive it directly."""
        p50, p99, n = self.percentiles()
        self.last_p50_ms, self.last_p99_ms = p50, p99
        m = self.metrics
        if m is not None:
            m.slo_p50.set(p50 / 1e3)
            m.slo_p99.set(p99 / 1e3)
        reason: Optional[str] = None
        breaching = n >= self.min_samples and p99 > self.slo_p99_ms
        if breaching:
            self.breaches += 1
            if m is not None:
                m.slo_breach_total.inc()
            reason = "slo_breach"
        # Pressure transitions (docs/hotkeys.md): the sustained-breach
        # clock the hot-key and shedding planes key off.
        if breaching and self._pressure_since is None:
            self._pressure_since = time.monotonic()
            self.pressure_events += 1
            self.record("pressure", state="start", p99_ms=round(p99, 3))
        elif not breaching and self._pressure_since is not None:
            self._pressure_since = None
            self.record("pressure", state="clear", p99_ms=round(p99, 3))
        if self.error_storm and self.error_rate() >= self.error_storm:
            reason = reason or "error_storm"
        if reason is None:
            return None
        if time.monotonic() - self._last_dump_mono < self.cooldown_s:
            return None
        return reason

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        """Arm the sampler on the running loop (Daemon.start)."""
        if self._task is None:
            self._task = asyncio.ensure_future(self._run())

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            await asyncio.gather(self._task, return_exceptions=True)
            self._task = None
        self._stop_profiler()

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        interval = self.sample_interval_s
        while True:
            t0 = loop.time()
            await asyncio.sleep(interval)
            lag = max(0.0, loop.time() - t0 - interval)
            lag_ms = lag * 1e3
            self.last_lag_ms = lag_ms
            self.max_lag_ms = max(self.max_lag_ms, lag_ms)
            if self.metrics is not None:
                self.metrics.loop_lag.set(lag)
            if lag_ms > self.stall_ms:
                self.record("loop_stall", lag_ms=round(lag_ms, 1))
            reason = self.evaluate()
            if reason is not None:
                try:
                    await self.dump(reason)
                except Exception as e:  # noqa: BLE001 — keep sampling
                    log.error("flight recorder dump failed: %s", e)

    # -- dumps -----------------------------------------------------------
    def snapshot(self, limit: Optional[int] = None) -> Dict:
        """The dump payload (also served by /debug/flightrec)."""
        with self._lock:
            ring = list(self._ring)
        if limit is not None:
            ring = ring[-limit:]
        p50, p99, n = self.percentiles()
        out = {
            "version": 1,
            "pid": os.getpid(),
            "started": self._started_wall,
            "now": time.time(),
            "slo_p99_ms": self.slo_p99_ms,
            "window_s": self.window_s,
            "rolling": {
                "p50_ms": round(p50, 3),
                "p99_ms": round(p99, 3),
                "samples": n,
                "errors_in_window": self.error_rate(),
            },
            "slow_exemplars": self.slow_exemplars(),
            "loop_lag_ms": {
                "last": round(self.last_lag_ms, 2),
                "max": round(self.max_lag_ms, 2),
            },
            "breaches": self.breaches,
            "dumps": self.dumps,
            "pressure": {
                "active": self.pressure_active(),
                "sustained_s": round(self.pressure_sustained_s(), 2),
                "ratio": round(self.pressure_ratio(), 3),
                "events": self.pressure_events,
            },
            "ring": ring,
        }
        for name, provider in self.extras.items():
            try:
                val = provider()
            except Exception:
                continue
            if val is not None:
                out[name] = val
        return out

    async def dump(self, reason: str) -> str:
        """Write a JSON snapshot; optionally start a time-boxed
        jax.profiler trace.  File I/O runs in an executor — the loop
        serves traffic while the black box writes."""
        self._last_dump_mono = time.monotonic()
        self.dumps += 1
        if self.metrics is not None:
            self.metrics.flightrec_dump_total.labels(reason=reason).inc()
        payload = self.snapshot()
        payload["reason"] = reason
        # Trace-tagged dump: every trace id the window knows about —
        # ring records tagged by the span plane, plus the slowest
        # exemplars — pulls its full in-process span tree into the
        # artifact, so the dump CONTAINS the trace behind the breach
        # instead of merely naming it.
        trace_ids = {
            r["trace_id"] for r in payload["ring"] if "trace_id" in r
        } | {e["trace_id"] for e in payload["slow_exemplars"]}
        payload["traces"] = tracing.recent_spans_for(trace_ids)
        path = os.path.join(
            self.dump_dir,
            "flightrec-%d-%d-%s.json" % (os.getpid(), self.dumps, reason),
        )
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, self._write, path, payload)
        self.last_dump_path = path
        self.record("dump", reason=reason, path=path)
        log.warning("flight recorder dump (%s): %s", reason, path)
        if self.profile_secs > 0:
            await loop.run_in_executor(None, self._start_profiler)
            if self._profiling:
                loop.call_later(self.profile_secs, self._schedule_stop)
        return path

    @staticmethod
    def _write(path: str, payload: Dict) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=1)
        os.replace(tmp, path)

    # -- profiler (best effort, time-boxed) ------------------------------
    def _start_profiler(self) -> None:
        if self._profiling:
            return
        try:
            import jax

            trace_dir = os.path.join(self.dump_dir, "profile")
            os.makedirs(trace_dir, exist_ok=True)
            jax.profiler.start_trace(trace_dir)
            self._profiling = True
            log.warning(
                "flight recorder started a %.1fs jax.profiler trace in %s",
                self.profile_secs, trace_dir,
            )
        except Exception as e:  # noqa: BLE001 — profiling is optional
            log.warning("could not start jax.profiler trace: %s", e)

    def _schedule_stop(self) -> None:
        # call_later callback: never block the loop on trace writing.
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            self._stop_profiler()
            return
        loop.run_in_executor(None, self._stop_profiler)

    def _stop_profiler(self) -> None:
        if not self._profiling:
            return
        self._profiling = False
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001
            log.warning("could not stop jax.profiler trace: %s", e)


def recorder_from_config(conf, metrics) -> Optional[FlightRecorder]:
    """Build a recorder from a DaemonConfig (None when disarmed)."""
    if not getattr(conf, "flightrec", False):
        return None
    return FlightRecorder(
        metrics=metrics,
        slo_p99_ms=conf.slo_p99_ms,
        dump_dir=conf.flightrec_dir or "flightrec-dumps",
        ring_size=conf.flightrec_ring,
        profile_secs=conf.flightrec_profile_s,
    )
