"""Gubscope: end-to-end request attribution through the serving pipeline.

The reference wraps nearly every function in holster/OTel scopes
(gubernator.go:118-121, workers.go:250-253, algorithms.go:32-35) and
exports to Jaeger/OTLP via standard env vars (jaegertracing.md).  This
runtime's request path is a deep async pipeline — coalesced merges,
dispatch/fetch stages, ring slots, a runner thread, FIFO host jobs, peer
forwards — so a span plane that only knows the RPC boundary cannot
answer "where did the 300ms go".  This module is the attribution core:

  * **Spans** are lightweight in-process records (trace/span ids,
    parent, attributes, links, wall times) — no OpenTelemetry package is
    required to create, propagate, or assert on them.  When the OTel SDK
    and OTLP exporter packages ARE installed (the `[tracing]` extra) and
    `OTEL_EXPORTER_OTLP_ENDPOINT` is set, finished spans are bridged to
    OTLP; otherwise they stay in-process (a bounded recent-span ring
    that the flight recorder attaches to breach dumps).
  * **Context** rides a contextvar on the event loop and is carried
    EXPLICITLY across every thread hand-off (coalescer entries, ring
    jobs) — contextvars do not cross `run_in_executor`, so each async
    seam stores the submitting context and re-binds it on the worker
    (`wrap` / `use_context`).
  * **Cross-peer**: `grpc_metadata()` renders the current context as a
    w3c `traceparent` header for outbound peer RPCs;
    `parse_traceparent()` is the server-side extract (daemon.py's
    tracing interceptor), so one trace spans a multi-daemon cluster.
  * **Sampling** follows the OTel env spec (`OTEL_TRACES_SAMPLER` /
    `OTEL_TRACES_SAMPLER_ARG`): parent-based by construction (a child
    inherits its parent's decision), with the root decision drawn from
    the configured ratio.  `always_off`/`off` disables tracing outright.

Disabled is the default and costs (almost) nothing: every entry point
checks one module global and returns before allocating anything — the
hot path creates zero spans and zero contexts until `init_tracing()`
arms the plane (tests/test_tracing.py pins this).

`device_step_annotation` additionally marks device steps with
`jax.profiler.TraceAnnotation` so host spans line up with XLA traces in
profiler dumps (the classic dispatch path and the ring runner both use
it).
"""
from __future__ import annotations

import contextlib
import contextvars
import logging
import os
import threading
import time
from collections import deque
from typing import Dict, Iterable, Iterator, List, Optional, Sequence

log = logging.getLogger("gubernator_tpu.tracing")

# Bounded ring of recently finished (sampled) spans: the in-process
# trace tail the flight recorder attaches to breach dumps.  Fixed cap —
# a span record is small and 512 covers several breach windows.
RECENT_SPAN_CAP = 512

_SAMPLER_ALIASES = {
    "on": "always_on",
    "off": "always_off",
    "parentbased_always_on": "always_on",
    "parentbased_always_off": "always_off_root",
    "parentbased_traceidratio": "traceidratio",
}


class SpanContext:
    """Immutable (trace_id, span_id, sampled) triple — what crosses
    every async seam and the wire (w3c traceparent)."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: int, span_id: int, sampled: bool) -> None:
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled

    def traceparent(self) -> str:
        return "00-%032x-%016x-%s" % (
            self.trace_id, self.span_id, "01" if self.sampled else "00"
        )

    def trace_id_hex(self) -> str:
        return "%032x" % self.trace_id

    def span_id_hex(self) -> str:
        return "%016x" % self.span_id

    def __repr__(self) -> str:  # debugging/test output
        return f"<SpanContext {self.traceparent()}>"


def parse_traceparent(value: str) -> Optional[SpanContext]:
    """Parse a w3c `traceparent` header; None on anything malformed
    (never raises — this runs on untrusted RPC metadata)."""
    try:
        parts = value.strip().split("-")
        if len(parts) != 4:
            return None
        version, tid, sid, flags = parts
        if len(version) != 2 or len(tid) != 32 or len(sid) != 16:
            return None
        if int(version, 16) < 0 or version == "ff":
            return None
        trace_id = int(tid, 16)
        span_id = int(sid, 16)
        if trace_id == 0 or span_id == 0:
            return None
        sampled = bool(int(flags, 16) & 0x01)
        return SpanContext(trace_id, span_id, sampled)
    except (ValueError, AttributeError):
        return None


class Span:
    """One finished-or-in-flight sampled span.  Mutation (attributes,
    links) is single-writer by construction: the thread running the
    spanned section.  `end()` is idempotent and hands the span to the
    exporters."""

    __slots__ = (
        "name", "context", "parent_id", "start_ns", "end_ns",
        "attributes", "links", "error",
    )

    def __init__(
        self,
        name: str,
        context: SpanContext,
        parent_id: Optional[int],
        attributes: Optional[Dict] = None,
        links: Sequence[SpanContext] = (),
    ) -> None:
        self.name = name
        self.context = context
        self.parent_id = parent_id
        self.start_ns = time.time_ns()
        self.end_ns: Optional[int] = None
        self.attributes: Dict = dict(attributes) if attributes else {}
        self.links: List[SpanContext] = [
            l for l in links if l is not None
        ]
        self.error: Optional[str] = None

    def set_attribute(self, key: str, value) -> None:
        self.attributes[key] = value

    def add_link(self, ctx: Optional[SpanContext]) -> None:
        if ctx is not None:
            self.links.append(ctx)

    def duration_ms(self) -> float:
        end = self.end_ns if self.end_ns is not None else time.time_ns()
        return (end - self.start_ns) / 1e6

    def end(self, error: Optional[str] = None) -> None:
        if self.end_ns is not None:
            return
        self.end_ns = time.time_ns()
        if error is not None:
            self.error = error
        st = _state
        if st is not None:
            st.finish(self)

    def to_dict(self) -> Dict:
        """JSON-friendly form (breach dumps, smoke artifacts)."""
        return {
            "name": self.name,
            "trace_id": self.context.trace_id_hex(),
            "span_id": self.context.span_id_hex(),
            "parent_id": (
                "%016x" % self.parent_id
                if self.parent_id is not None else None
            ),
            "start_ns": self.start_ns,
            "duration_ms": round(self.duration_ms(), 3),
            "attributes": dict(self.attributes),
            "links": [
                {"trace_id": l.trace_id_hex(), "span_id": l.span_id_hex()}
                for l in self.links
            ],
            "error": self.error,
        }


class TracingStatus:
    """What `init_tracing` actually armed — the honest exporter status
    the old bool return hid (a set OTLP endpoint with the exporter
    packages missing used to report success while spans went nowhere).
    Truthy iff tracing is active, for old-style callers."""

    __slots__ = (
        "enabled", "service_name", "sampler", "ratio",
        "exporter", "exporter_error", "reason",
    )

    def __init__(self, enabled, service_name="", sampler="", ratio=1.0,
                 exporter="none", exporter_error=None, reason=""):
        self.enabled = enabled
        self.service_name = service_name
        self.sampler = sampler
        self.ratio = ratio
        # "otlp" | "memory" | "none" | an explicit exporter's class name
        self.exporter = exporter
        self.exporter_error = exporter_error
        self.reason = reason

    def __bool__(self) -> bool:
        return self.enabled

    def as_dict(self) -> Dict:
        return {
            "enabled": self.enabled,
            "service": self.service_name,
            "sampler": self.sampler,
            "ratio": self.ratio,
            "exporter": self.exporter,
            "exporter_error": self.exporter_error,
            "reason": self.reason,
        }


class _OTLPBridge:
    """Adapter from this module's spans to the OTel SDK's OTLP/HTTP
    exporter (the `[tracing]` extra).  Construction raises ImportError
    when the packages are absent — init_tracing reports that instead of
    pretending spans export."""

    def __init__(self, service_name: str) -> None:
        from opentelemetry import trace as otel_trace
        from opentelemetry.exporter.otlp.proto.http.trace_exporter import (
            OTLPSpanExporter,
        )
        from opentelemetry.sdk.resources import Resource
        from opentelemetry.sdk.trace import ReadableSpan
        from opentelemetry.sdk.trace.export import BatchSpanProcessor
        from opentelemetry.sdk.util.instrumentation import (
            InstrumentationScope,
        )

        self._otel_trace = otel_trace
        self._ReadableSpan = ReadableSpan
        self._resource = Resource.create({"service.name": service_name})
        self._scope = InstrumentationScope("gubernator_tpu")
        self._processor = BatchSpanProcessor(OTLPSpanExporter())

    def _ctx(self, trace_id: int, span_id: int):
        t = self._otel_trace
        return t.SpanContext(
            trace_id=trace_id, span_id=span_id, is_remote=False,
            trace_flags=t.TraceFlags(t.TraceFlags.SAMPLED),
        )

    def export(self, span: Span) -> None:
        t = self._otel_trace
        readable = self._ReadableSpan(
            name=span.name,
            context=self._ctx(span.context.trace_id, span.context.span_id),
            parent=(
                self._ctx(span.context.trace_id, span.parent_id)
                if span.parent_id is not None else None
            ),
            resource=self._resource,
            attributes=dict(span.attributes),
            events=(),
            links=[
                t.Link(self._ctx(l.trace_id, l.span_id))
                for l in span.links
            ],
            kind=t.SpanKind.INTERNAL,
            instrumentation_scope=self._scope,
            status=t.Status(
                t.StatusCode.ERROR if span.error else t.StatusCode.UNSET,
                span.error,
            ),
            start_time=span.start_ns,
            end_time=span.end_ns,
        )
        self._processor.on_end(readable)

    def shutdown(self) -> None:
        self._processor.shutdown()


class _TraceState:
    """Armed tracing plane: sampler + exporters + counters + the
    recent-span ring.  `_lock` guards only its own counters/deque and is
    never held across another lock (ranked last with flightrec._lock in
    tools/gubguard/lockorder.py)."""

    def __init__(self, service_name, sampler, ratio, exporters,
                 exporter_kind, exporter_error) -> None:
        self.service_name = service_name
        self.sampler = sampler
        self.ratio = ratio
        self.exporters = list(exporters)
        self.exporter_kind = exporter_kind
        self.exporter_error = exporter_error
        self._lock = threading.Lock()
        self.spans_started = 0
        self.spans_exported = 0
        self.spans_dropped = 0
        self.recent: deque = deque(maxlen=RECENT_SPAN_CAP)
        # 64-bit threshold for the traceidratio root decision.
        self._threshold = int(min(max(ratio, 0.0), 1.0) * (1 << 64))

    def sample_root(self, trace_id: int) -> bool:
        return (trace_id & ((1 << 64) - 1)) < self._threshold

    def note_started(self) -> None:
        with self._lock:
            self.spans_started += 1

    def finish(self, span: Span) -> None:
        with self._lock:
            self.recent.append(span)
        for exp in self.exporters:
            try:
                exp.export(span)
                with self._lock:
                    self.spans_exported += 1
            except Exception as e:  # noqa: BLE001 — never fail the caller
                with self._lock:
                    self.spans_dropped += 1
                log.debug("span export failed: %s", e)


_state: Optional[_TraceState] = None
_current: contextvars.ContextVar[Optional[SpanContext]] = (
    contextvars.ContextVar("gubernator_tpu_trace_ctx", default=None)
)
_CURRENT = object()  # sentinel: "resolve the parent from the contextvar"


def enabled() -> bool:
    """One global check — the hot path's whole cost when disabled."""
    return _state is not None


def current_context() -> Optional[SpanContext]:
    if _state is None:
        return None
    return _current.get()


def grpc_metadata():
    """Outbound w3c propagation: (("traceparent", ...),) for the current
    context, or None (no context / tracing disabled) — safe to pass
    straight to grpc's `metadata=` kwarg either way."""
    if _state is None:
        return None
    ctx = _current.get()
    if ctx is None:
        return None
    return (("traceparent", ctx.traceparent()),)


def _new_trace_id() -> int:
    tid = int.from_bytes(os.urandom(16), "big")
    return tid or 1


def _new_span_id() -> int:
    sid = int.from_bytes(os.urandom(8), "big")
    return sid or 1


def _begin(state, name, parent, links, attrs):
    """(span-or-None, child context).  A Span exists only when the
    context is sampled; an unsampled context still propagates so the
    decision stays consistent downstream and across peers."""
    span_id = _new_span_id()
    if parent is not None:
        trace_id = parent.trace_id
        sampled = parent.sampled
        parent_id = parent.span_id
    else:
        trace_id = _new_trace_id()
        sampled = state.sample_root(trace_id)
        parent_id = None
    ctx = SpanContext(trace_id, span_id, sampled)
    if not sampled:
        return None, ctx
    state.note_started()
    return Span(name, ctx, parent_id, attrs, links), ctx


def start_span(
    name: str,
    parent: Optional[SpanContext],
    links: Iterable[Optional[SpanContext]] = (),
    **attrs,
) -> Optional[Span]:
    """Manually managed span (caller must `end()` it) with an EXPLICIT
    parent — the form the cross-thread seams use (coalescer merges, ring
    iterations), where the submitting context was captured earlier.
    Returns None when tracing is disabled or the parent is unsampled."""
    st = _state
    if st is None or parent is None or not parent.sampled:
        return None
    sp, _ctx = _begin(
        st, name, parent, [l for l in links if l is not None], attrs
    )
    return sp


@contextlib.contextmanager
def span(
    name: str,
    parent=_CURRENT,
    links: Iterable[Optional[SpanContext]] = (),
    require_parent: bool = False,
    **attrs,
) -> Iterator[Optional[Span]]:
    """Span context manager; yields the Span (None when unsampled or
    disabled) and binds the child context for the duration so nested
    spans / flight-recorder records / outbound RPCs attribute to it.

    `parent` defaults to the current context; pass an explicit
    SpanContext to re-root (server-side traceparent extract, thread
    hand-offs).  `require_parent=True` makes the span a pure
    pass-through when no parent exists — internal pipeline stages use it
    so an untraced request never starts a spurious root trace."""
    st = _state
    if st is None:
        yield None
        return
    pa = _current.get() if parent is _CURRENT else parent
    if require_parent and pa is None:
        yield None
        return
    sp, ctx = _begin(
        st, name, pa, [l for l in links if l is not None], attrs
    )
    token = _current.set(ctx)
    try:
        yield sp
    except BaseException as e:
        if sp is not None:
            sp.end(error=repr(e))
        raise
    finally:
        _current.reset(token)
        if sp is not None:
            sp.end()


@contextlib.contextmanager
def use_context(ctx: Optional[SpanContext]) -> Iterator[None]:
    """Bind an explicitly carried context on the current thread (ring
    runner, pool workers) without opening a new span."""
    if _state is None or ctx is None:
        yield
        return
    token = _current.set(ctx)
    try:
        yield
    finally:
        _current.reset(token)


def wrap(fn, name: str, parent: Optional[SpanContext], **attrs):
    """Wrap a zero-arg callable in a child span of `parent`, binding the
    context on whichever thread runs it.  Returns `fn` unchanged when
    tracing is disabled or there is no parent — the executor seams call
    this unconditionally and pay nothing in the disabled path."""
    if _state is None or parent is None:
        return fn

    def _traced():
        with span(name, parent=parent, **attrs):
            return fn()

    return _traced


@contextlib.contextmanager
def device_step_annotation(name: str = "gubernator_device_step"):
    """XLA-profiler-visible annotation around a device step, nested in
    the current trace context when tracing is armed — host spans and
    profiler TraceMe marks then line up in a capture."""
    import jax

    with span(name, require_parent=True):
        with jax.profiler.TraceAnnotation(name):
            yield


# -- lifecycle / introspection -------------------------------------------

def _resolve_sampler(sampler: Optional[str], sampler_arg) -> tuple:
    """(canonical sampler name, root ratio).  Parent-based behavior is
    structural here (children always inherit), so the parentbased_*
    spellings only choose the ROOT policy."""
    raw = (
        sampler
        or os.environ.get("OTEL_TRACES_SAMPLER")
        or "parentbased_always_on"
    ).strip().lower()
    canon = _SAMPLER_ALIASES.get(raw, raw)
    if canon == "always_on":
        return raw, 1.0
    if canon == "always_off_root":
        return raw, 0.0
    if canon == "always_off":
        return raw, 0.0
    if canon == "traceidratio":
        arg = sampler_arg
        if arg is None:
            arg = os.environ.get("OTEL_TRACES_SAMPLER_ARG", "1.0")
        try:
            ratio = float(arg)
        except (TypeError, ValueError):
            log.warning(
                "bad OTEL_TRACES_SAMPLER_ARG %r; sampling everything", arg
            )
            ratio = 1.0
        return raw, ratio
    log.warning("unknown OTEL_TRACES_SAMPLER %r; using always_on", raw)
    return raw, 1.0


def init_tracing(
    service_name: Optional[str] = None,
    exporter=None,
    sampler: Optional[str] = None,
    sampler_arg=None,
) -> TracingStatus:
    """Arm the tracing plane from the standard OTEL_* env spec
    (OTEL_SERVICE_NAME, OTEL_TRACES_SAMPLER[_ARG],
    OTEL_EXPORTER_OTLP_ENDPOINT) and/or an explicit exporter.

    Returns a TracingStatus with the REAL exporter state: a configured
    OTLP endpoint whose exporter packages are missing reports
    `exporter_error` (spans then stay in-process — recent-span ring +
    breach dumps — instead of silently vanishing).  Disabled outcomes
    (no OTEL_* configuration at all, or sampler `always_off`/`off`)
    leave the hot path span-free; the status says which."""
    global _state
    service_name = (
        service_name
        or os.environ.get("OTEL_SERVICE_NAME")
        or "gubernator-tpu"
    )
    sampler_name, ratio = _resolve_sampler(sampler, sampler_arg)
    endpoint = os.environ.get("OTEL_EXPORTER_OTLP_ENDPOINT", "")
    if _SAMPLER_ALIASES.get(sampler_name, sampler_name) == "always_off":
        _state = None
        return TracingStatus(
            False, service_name, sampler_name, 0.0,
            reason="sampler is off; tracing disabled",
        )
    opted_in = (
        exporter is not None
        or bool(endpoint)
        or sampler is not None
        or "OTEL_TRACES_SAMPLER" in os.environ
    )
    if not opted_in:
        _state = None
        return TracingStatus(
            False, service_name, sampler_name, ratio,
            reason=(
                "no OTEL_* configuration and no explicit exporter; "
                "tracing disabled"
            ),
        )
    exporters = []
    exporter_kind = "none"
    exporter_error = None
    if exporter is not None:
        exporters.append(exporter)
        exporter_kind = type(exporter).__name__
    if endpoint:
        try:
            exporters.append(_OTLPBridge(service_name))
            exporter_kind = "otlp"
        except Exception as e:  # noqa: BLE001 — ImportError et al.
            exporter_error = f"OTLP exporter unavailable: {e}"
            log.warning(
                "OTEL_EXPORTER_OTLP_ENDPOINT is set but the OTLP "
                "exporter packages are missing (`pip install "
                "gubernator-tpu[tracing]`); spans will NOT be exported "
                "— they stay in-process (recent-span ring, breach "
                "dumps) only: %s", e,
            )
    _state = _TraceState(
        service_name, sampler_name, ratio, exporters,
        exporter_kind, exporter_error,
    )
    return TracingStatus(
        True, service_name, sampler_name, ratio,
        exporter=exporter_kind, exporter_error=exporter_error,
    )


def shutdown_tracing() -> None:
    """Disarm (tests, daemon teardown): later spans are no-ops again."""
    global _state
    st = _state
    _state = None
    if st is not None:
        for exp in st.exporters:
            close = getattr(exp, "shutdown", None)
            if callable(close):
                try:
                    close()
                except Exception as e:  # noqa: BLE001
                    log.debug("exporter shutdown failed: %s", e)


def debug_vars() -> Dict:
    """The /debug/vars `tracing` block: enabled, sampler, exporter
    status, span counters."""
    st = _state
    if st is None:
        return {"enabled": False}
    with st._lock:
        started = st.spans_started
        exported = st.spans_exported
        dropped = st.spans_dropped
        recent = len(st.recent)
    return {
        "enabled": True,
        "service": st.service_name,
        "sampler": st.sampler,
        "ratio": st.ratio,
        "exporter": {
            "kind": st.exporter_kind,
            "error": st.exporter_error,
        },
        "spans": {
            "started": started,
            "exported": exported,
            "dropped": dropped,
            "recent": recent,
        },
    }


def recent_spans_for(
    trace_ids: Iterable[str], limit: int = 256
) -> List[Dict]:
    """Recently finished spans belonging to the given trace ids (hex
    strings) — the flight recorder attaches these to a breach dump so
    the dump carries the full in-process trace of the offending
    merge."""
    st = _state
    if st is None:
        return []
    want = set(trace_ids)
    if not want:
        return []
    with st._lock:
        spans = list(st.recent)
    out = [
        sp.to_dict() for sp in spans
        if sp.context.trace_id_hex() in want
    ]
    return out[-limit:]
