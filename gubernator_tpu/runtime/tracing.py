"""OpenTelemetry tracing — host spans around the request path.

The reference wraps nearly every function in holster/OTel scopes
(gubernator.go:118-121, workers.go:250-253, algorithms.go:32-35) and
exports to Jaeger/OTLP via standard env vars (jaegertracing.md).  Here
tracing is opt-in and degrades to no-ops when the SDK or an exporter is
absent: `init_tracing()` wires the provider from OTEL_* env vars;
`span(name)` is an async-context/decorator used by the service; device
steps additionally get `jax.profiler.TraceAnnotation` marks so host spans
line up with XLA traces in profiler dumps.
"""
from __future__ import annotations

import contextlib
import logging
import os
from typing import Iterator, Optional

log = logging.getLogger("gubernator_tpu.tracing")

_tracer = None


def init_tracing(service_name: str = "gubernator-tpu") -> bool:
    """Initialize the OTel tracer provider from standard OTEL_* env vars
    (OTEL_EXPORTER_OTLP_ENDPOINT, OTEL_TRACES_SAMPLER, ...).  Returns True
    when tracing is active."""
    global _tracer
    try:
        from opentelemetry import trace
        from opentelemetry.sdk.resources import Resource
        from opentelemetry.sdk.trace import TracerProvider
    except ImportError:
        log.info("opentelemetry SDK not available; tracing disabled")
        return False

    provider = TracerProvider(
        resource=Resource.create({"service.name": service_name})
    )
    endpoint = os.environ.get("OTEL_EXPORTER_OTLP_ENDPOINT")
    if endpoint:
        try:
            from opentelemetry.exporter.otlp.proto.http.trace_exporter import (
                OTLPSpanExporter,
            )
            from opentelemetry.sdk.trace.export import BatchSpanProcessor

            provider.add_span_processor(
                BatchSpanProcessor(OTLPSpanExporter())
            )
        except ImportError:
            log.warning(
                "OTEL_EXPORTER_OTLP_ENDPOINT set but the OTLP exporter "
                "package is missing; spans will not be exported"
            )
    trace.set_tracer_provider(provider)
    _tracer = trace.get_tracer("gubernator_tpu")
    return True


@contextlib.contextmanager
def span(name: str, **attrs) -> Iterator[None]:
    """Span context; no-op when tracing is uninitialized."""
    if _tracer is None:
        yield
        return
    with _tracer.start_as_current_span(name) as s:
        for k, v in attrs.items():
            s.set_attribute(k, v)
        yield


@contextlib.contextmanager
def device_step_annotation(name: str = "gubernator_device_step"):
    """XLA-profiler-visible annotation around a device step, nested in the
    current OTel span when active."""
    import jax

    with span(name):
        with jax.profiler.TraceAnnotation(name):
            yield
