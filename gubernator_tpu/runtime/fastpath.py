"""The compiled host hot path: raw wire bytes -> device step -> wire bytes.

The object path (pb2 -> RateLimitReq dataclasses -> packer -> device ->
RateLimitResp -> pb2) costs several microseconds of Python per request,
which caps a daemon at ~10k checks/s while the device kernel does hundreds
of millions — the round-2 verdict's top gap.  The reference has no such
tax: its whole host loop is compiled Go (workers.go:249-314,
peer_client.go:450-509, generated pb marshalers).

This module is the equivalent compiled lane.  For eligible requests the
daemon hands the raw gRPC payload straight here:

    C++ parse  (native/gubtpu.cpp gub_parse_reqs2: wire -> columns + XXH64)
    numpy      (burst defaults, behavior masks, shard routing)
    C++ pack   (gub_assign_rounds: duplicate-key round/lane assignment)
    numpy      (scatter columns into fixed-shape DeviceBatch rounds)
    device     (backend.step_rounds: the same jitted kernels as check();
                sketch-named lanes take one CMS step instead)
    numpy      (gather packed responses back to request order)
    C++ emit   (gub_serialize_resps2: columns -> response wire bytes)

No per-request Python objects exist anywhere on this path.  Concurrent
RPCs coalesce into shared device steps (the LocalBatcher discipline,
runtime/service.py) by concatenating their columns before packing.

Eligibility — anything else falls back to the object path, which remains
the semantic reference:
  - native library loadable;
  - a Store / Loader attached stays ON the lane: residency comes from
    the step's own `found` column (no pre-step probe fetch — a warm
    drain pays ONE combined response+capture fetch, storeless parity),
    Store.get runs only for cold keys, whose drains repair in place
    (_repair_cold_store_keys), and write-through rows are captured
    with ONE packed device gather (ticketed on_change delivery, like
    the object path's batch-boundary fix).
    The SPI itself takes Python objects, so the lane decodes one
    request per UNIQUE key per drain — the only per-key host cost;
    on_change fires once per unique key per DRAIN (coalesced RPCs
    share one delivery; final store state matches the object path);
  - GLOBAL is served HERE — use_cached lanes for non-owned reads,
    queued hits/updates for the managers, and node-owned lanes on a
    mesh service ingesting into the collective GlobalEngine's
    replicated table (client path; the peer RPC keeps RPC-tier
    semantics like _check_local); MULTI_REGION serves like a plain
    lane with owner-side hits queued to the region manager (one
    decode per unique key);
  - sketch-tier names are served HERE too: the parser's name_hash
    column routes them to SketchBackend.check_cols (one CMS step per
    merge), with GLOBAL stripped exactly like the object path's
    routing (service.py) so they count once at the key's owner;
  - for the client-facing RPC: either single-node, or the columnar
    router (vectorized ring lookup + zero-copy forwards) when the ring
    hash matches the device fingerprint hash.
    Peer-to-peer batches (GetPeerRateLimits) are always local by
    construction, so the fast lane also serves the owner side of
    forwarded traffic in a cluster.
"""
from __future__ import annotations

import asyncio
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from gubernator_tpu import native
from gubernator_tpu.core.config import MAX_BATCH_SIZE
from gubernator_tpu.runtime import tracing
from gubernator_tpu.core.interval import (
    GregorianError,
    gregorian_duration,
    gregorian_expiration,
)
from gubernator_tpu.core.types import Behavior
from gubernator_tpu.ops.batch import DeviceBatch, _empty_batch

_ERR_EMPTY_KEY = b"field 'unique_key' cannot be empty"
_ERR_EMPTY_NAME = b"field 'namespace' cannot be empty"
_ERR_GREG = 3  # parse err code for host-side Gregorian failures

_GLOBAL = int(Behavior.GLOBAL)
_MULTI_REGION = int(Behavior.MULTI_REGION)

# The sketch tier's response annotation (object path: metadata
# {"tier": "sketch"}, runtime/sketch_backend.py).
_TIER_SKETCH_FRAME = native.meta_frame(b"tier", b"sketch")


class _Coalescer:
    """The drain discipline shared by the machinery, sketch, and engine
    lanes: arrivals accumulate in the queue; each drain takes the WHOLE
    queue as one merge (bigger merges amortize the per-merge device
    round-trip).  `process` runs on a pool thread with the drained entry
    list; results deliver through each entry's future.

    Two-stage pipeline (the r5 E2E artifact showed the device->host
    response fetch dominating the merge cycle while the old discipline
    serialized it behind the next merge's dispatch):

      dispatch stage — serialized (`max_inflight`, default 1).  `process`
        packs and dispatches the device step (holding the backend lock)
        and returns a zero-arg FETCH CONTINUATION instead of results.
        The table-update chain already serializes correctly on the XLA
        stream, so merge N+1 may dispatch the moment merge N's dispatch
        returns.
      fetch stage — depth-`pipeline_depth` (GUBER_PIPELINE_DEPTH).  The
        continuation syncs the response to host and unmarshals; out-of-
        order completion is safe because results flow through per-entry
        futures.  A fetch SLOT is taken before dispatching, so at most
        `pipeline_depth` merges are outstanding end-to-end; the time a
        ready drain spends waiting for a slot is the pipeline's bubble
        (tracked in `bubble_s` + the bubble metrics).

    Steady-state throughput moves from B/(dispatch+fetch) toward
    B/max(dispatch, fetch).  Maximal merges are preserved — this
    pipelines ACROSS merges, it never splits one (the r5 A/B pinned
    monotone 1>2>3>4>6 for splitting).  `process` may also return a
    plain result list (single-phase; the fetch stage is then a no-op) —
    tests and simple lanes use that form.

    Adaptive sparse overlap (`sparse_limit` > 0) is the depth-k special
    case of the same mechanism: a drain no bigger than `sparse_limit`
    requests that finds every base fetch slot busy may take one of
    OVERLAP_SLOTS sparse fetch slots instead of waiting — at low load a
    small arrival then costs ~1 device round-trip even when the pipeline
    is full (r5: small-batch p50 156 -> 86ms; the reference's batcher
    fires its window early when sparse, peer_client.go:373-446).  Under
    load drains exceed the limit and the maximal-merge discipline holds.
    """

    OVERLAP_SLOTS = 3

    def __init__(self, pool, process, max_inflight: int = 1,
                 sparse_limit: int = 0, size_of=None,
                 pipeline_depth: int = 1, metrics=None,
                 lane: str = "") -> None:
        if pipeline_depth < 1:
            raise ValueError(
                f"pipeline_depth must be >= 1, got {pipeline_depth}"
            )
        self._pool = pool
        self._process = process
        self._queue: asyncio.Queue = asyncio.Queue()
        self._task: Optional[asyncio.Task] = None
        self._dispatch_sem = asyncio.Semaphore(max_inflight)
        self._fetch = asyncio.Semaphore(pipeline_depth)
        self._overlap = asyncio.Semaphore(self.OVERLAP_SLOTS)
        self._sparse_limit = sparse_limit
        self._size_of = size_of or (lambda e: 1)
        self._dispatches: set = set()
        self._closed = False
        self.pipeline_depth = pipeline_depth
        self._metrics = metrics
        self._lane = lane
        # Observability: total drains / drains that rode a sparse fetch
        # slot / drains that had to wait for a fetch slot (each wait is
        # one pipeline bubble; bubble_s accumulates the idle time).
        self.drains = 0
        self.overlap_drains = 0
        self.waited_drains = 0
        self.bubble_s = 0.0
        # Cumulative stage wall time (the bench artifact's dispatch vs
        # fetch budget split; mirrors fastpath_stage_duration sums).
        self.dispatch_s = 0.0
        self.fetch_s = 0.0
        # Merges currently in flight (dispatch or fetch stage) and the
        # peak ever observed — the pipeline-occupancy view.
        self.inflight = 0
        self.max_inflight_seen = 0

    def debug_vars(self) -> dict:
        """The /debug/vars view of this lane's drain discipline."""
        return {
            "drains": self.drains,
            "overlap_drains": self.overlap_drains,
            "waited_drains": self.waited_drains,
            "bubble_ms_total": round(self.bubble_s * 1e3, 3),
            "dispatch_ms_total": round(self.dispatch_s * 1e3, 3),
            "fetch_ms_total": round(self.fetch_s * 1e3, 3),
            "inflight": self.inflight,
            "max_inflight_seen": self.max_inflight_seen,
            "pipeline_depth": self.pipeline_depth,
        }

    def _count_drain(self, kind: str) -> None:
        m = self._metrics
        if m is not None:
            m.fastpath_drains.labels(lane=self._lane, kind=kind).inc()

    def _note_stage(self, stage: str, dt_s: float) -> None:
        if stage == "dispatch":
            self.dispatch_s += dt_s
        else:
            self.fetch_s += dt_s
        m = self._metrics
        if m is not None:
            m.fastpath_stage_duration.labels(
                lane=self._lane, stage=stage
            ).observe(dt_s)

    def _note_bubble(self, dt_s: float) -> None:
        self.bubble_s += dt_s
        m = self._metrics
        if m is not None:
            m.fastpath_bubble_seconds.labels(lane=self._lane).inc(dt_s)
            fr = getattr(m, "flightrec", None)
            if fr is not None:
                fr.record_bubble(self._lane, dt_s * 1e3)

    async def do(self, entry):
        """Submit an entry and await its result."""
        if self._closed:
            raise RuntimeError("fastpath closed")
        entry.fut = asyncio.get_running_loop().create_future()
        if tracing.enabled():
            # Carry the request's trace context across the coalescer
            # seam: the merge dispatch runs on a pool thread where the
            # submitting task's contextvars are invisible.
            try:
                entry.trace_ctx = tracing.current_context()
            except AttributeError:
                pass  # foreign entry types (tests) without the slot
        if self._task is None:
            self._task = asyncio.ensure_future(self._run())
        await self._queue.put(entry)
        return await entry.fut

    def _drain_into(self, entries: list) -> None:
        while True:
            try:
                entries.append(self._queue.get_nowait())
            except asyncio.QueueEmpty:
                return

    async def _acquire_fetch_slot(self, entries: list):
        """Take a fetch slot for one merge BEFORE its dispatch (bounds
        outstanding merges to pipeline_depth + sparse slots).  Returns
        the semaphore to release when the merge's fetch completes."""
        if not self._fetch.locked():
            await self._fetch.acquire()  # immediate
            return self._fetch
        if (
            self._sparse_limit > 0
            and not self._overlap.locked()
            and sum(self._size_of(e) for e in entries)
            <= self._sparse_limit
        ):
            # Sparse drain while the pipeline is full: overlap on a
            # sparse slot instead of waiting out a fetch.
            await self._overlap.acquire()
            self.overlap_drains += 1
            self._count_drain("overlap")
            return self._overlap
        # Loaded: hold for a slot (the pipeline bubble); arrivals keep
        # accumulating and ship as ONE bigger merge.
        self.waited_drains += 1
        self._count_drain("waited")
        t0 = time.monotonic()
        await self._fetch.acquire()
        self._note_bubble(time.monotonic() - t0)
        self._drain_into(entries)
        return self._fetch

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            first = await self._queue.get()
            entries = [first]
            self._drain_into(entries)
            self.drains += 1
            self._count_drain("total")
            fetch_sem = None
            try:
                fetch_sem = await self._acquire_fetch_slot(entries)
                # Dispatch serialization: the previous merge's dispatch
                # stage is short (no response sync), so this rarely
                # blocks; any arrivals during a wait still merge in.
                if self._dispatch_sem.locked():
                    await self._dispatch_sem.acquire()
                    self._drain_into(entries)
                else:
                    await self._dispatch_sem.acquire()
            except asyncio.CancelledError:
                # Shutdown while holding dequeued entries: fail them
                # instead of orphaning their awaiting handlers.
                if fetch_sem is not None:
                    fetch_sem.release()
                for en in entries:
                    if not en.fut.done():
                        en.fut.set_exception(
                            RuntimeError("fastpath closed")
                        )
                raise
            self.inflight += 1
            if self.inflight > self.max_inflight_seen:
                self.max_inflight_seen = self.inflight
            m = self._metrics
            if m is not None:
                m.fastpath_pipeline_occupancy.labels(
                    lane=self._lane
                ).observe(self.inflight)
            task = asyncio.ensure_future(
                self._dispatch(loop, entries, fetch_sem)
            )
            self._dispatches.add(task)
            task.add_done_callback(self._dispatches.discard)

    @staticmethod
    def _once(fn):
        """At-most-once wrapper for a fetch continuation: the normal
        path and the orphan resubmit below may both submit it; only the
        first execution runs the closure."""
        ran = [False]
        gate = threading.Lock()

        def run_once():
            with gate:
                if ran[0]:
                    return None
                ran[0] = True
            return fn()

        return run_once

    def _merge_span(self, entries):
        """(merge span, stage parent ctx) for one drained entry list:
        the span's parent is the first SAMPLED member's context and
        every other member attaches as a span link — the merge is the
        join point of N concurrent request traces, and the links are
        what lets any member's trace find the shared device round.
        (None, None) when tracing is off or no member carried a
        context."""
        if not tracing.enabled():
            return None, None
        ctxs = [
            c for c in (getattr(e, "trace_ctx", None) for e in entries)
            if c is not None
        ]
        if not ctxs:
            return None, None
        parent = next((c for c in ctxs if c.sampled), ctxs[0])
        msp = tracing.start_span(
            "fastpath.merge", parent,
            links=[c for c in ctxs if c is not parent],
            lane=self._lane, entries=len(entries),
        )
        if msp is not None:
            msp.set_attribute(
                "size", int(sum(self._size_of(e) for e in entries))
            )
        return msp, (msp.context if msp is not None else parent)

    async def _dispatch(self, loop, entries, fetch_sem) -> None:
        """One merge's pipeline: dispatch stage on a pool thread (holds
        the dispatch slot), then — if `process` returned a continuation —
        the fetch stage on another pool pass (holds only the fetch slot,
        so the next merge dispatches concurrently)."""
        fetch_fn = None
        msp, stage_ctx = self._merge_span(entries)
        try:
            t0 = time.monotonic()
            try:
                res = await loop.run_in_executor(
                    self._pool,
                    tracing.wrap(
                        lambda: self._process(entries),
                        "fastpath.dispatch", stage_ctx, lane=self._lane,
                    ),
                )
            finally:
                # Dispatch stage over (or failed): the next merge may
                # dispatch while this one fetches.
                self._dispatch_sem.release()
                self._note_stage("dispatch", time.monotonic() - t0)
            if callable(res):
                fetch_fn = self._once(res)
                t0 = time.monotonic()
                outs = await loop.run_in_executor(
                    self._pool,
                    tracing.wrap(
                        fetch_fn,
                        "fastpath.fetch", stage_ctx, lane=self._lane,
                    ),
                )
                self._note_stage("fetch", time.monotonic() - t0)
            else:
                outs = res  # single-phase process
        except BaseException as e:  # CancelledError is a BaseException
            if fetch_fn is not None and isinstance(
                e, asyncio.CancelledError
            ):
                # The dispatch stage already mutated device/store state
                # (donated table step, write-through ticket); a fetch
                # continuation that never runs would leak its ticket
                # and wedge every later Store.on_change delivery in
                # cond.wait.  Submit it straight to the pool — detached
                # from this cancelled task; the at-most-once gate makes
                # this a no-op when the awaited run already started.
                # FastPath.close() joins the pool, so the side effects
                # land before teardown.  The entries still fail below.
                self._pool.submit(fetch_fn)
            if msp is not None:
                msp.end(error=repr(e))
            err = (
                RuntimeError("fastpath closed")
                if isinstance(e, asyncio.CancelledError) else e
            )
            for en in entries:
                if not en.fut.done():
                    en.fut.set_exception(err)
            if isinstance(e, asyncio.CancelledError):
                raise
        else:
            for en, out in zip(entries, outs):
                if not en.fut.done():
                    en.fut.set_result(out)
        finally:
            self.inflight -= 1
            fetch_sem.release()
            if msp is not None:
                msp.end()

    async def close(self) -> None:
        self._closed = True  # new do() calls fail fast, never respawn _run
        if self._task is not None:
            self._task.cancel()
            await asyncio.gather(self._task, return_exceptions=True)
            self._task = None
        # Let in-flight dispatches finish (their entries get results).
        if self._dispatches:
            await asyncio.gather(
                *list(self._dispatches), return_exceptions=True
            )
        # Entries still queued (never dequeued by _run) must fail too.
        while not self._queue.empty():
            en = self._queue.get_nowait()
            if not en.fut.done():
                en.fut.set_exception(RuntimeError("fastpath closed"))


class FastPath:
    """Per-service compiled lane with a coalescing columnar batcher.

    `max_inflight` bounds concurrent DISPATCH stages (default 1: every
    drain takes the WHOLE queue as one maximal merge — the r2 A/B pinned
    monotone 1>2>3>4>6 throughput for splitting big merges, 51k vs 24k
    checks/s through a ~65ms-RTT tunnel).  `pipeline_depth` bounds
    OUTSTANDING merges (dispatched, response not yet fetched): the
    response round-trip that used to serialize behind the next dispatch
    now overlaps it, so maximal merges pipeline without ever being
    split (docs/pipeline.md).  Dispatch order is serialized by the
    backend lock; cascade merges hold that lock across their whole
    read -> replay -> write-back window, which serializes them against
    every other mutation path (this lane, the object path, the GLOBAL
    managers) exactly like any other single-writer section.

    `serve_mode` picks the drain discipline (docs/ring.md): "classic"
    forces depth 1, "pipelined" is the depth-k overlap above, and
    "ring" hands plain merges to the device-resident serving loop
    (runtime/ring.py) — packed straight into ring slot layout, fetched
    by the ring runner off the request path — with locked cascade/
    store merges, sketch readbacks, and engine (GLOBAL collective)
    readbacks riding the runner as FIFO host jobs.  Both the
    single-table and the mesh backend serve ring mode (the mesh via the
    shard_map ring step, parallel/sharded.make_mesh_ring_step); only a
    backend without ring support — or a broken ring — falls back to the
    pipelined discipline."""

    def __init__(self, service, max_inflight: int = 1,
                 sparse_limit: int = 64,
                 pipeline_depth: int = 2,
                 serve_mode: str = "pipelined",
                 ring_slots: int = 8,
                 ring_rounds: int = 4,
                 ring_max_linger_us: float = 200.0) -> None:
        from gubernator_tpu.core.config import normalize_serve_mode

        if max_inflight < 1:
            raise ValueError(
                f"fastpath max_inflight must be >= 1, got {max_inflight}"
            )
        if pipeline_depth < 1:
            raise ValueError(
                f"fastpath pipeline_depth must be >= 1, "
                f"got {pipeline_depth}"
            )
        serve_mode = normalize_serve_mode(serve_mode)
        self.s = service
        metrics = service.metrics
        # Drain discipline (docs/ring.md): classic = strict depth-1,
        # pipelined = depth-k fetch overlap, ring = the device-resident
        # serving loop (runtime/ring.py) with NO blocking fetch on the
        # request path, megaround = ring plus the adaptive round
        # accumulator (dispatch amortized across up to
        # ring_slots x ring_rounds rounds), persistent = the ring
        # protocol served by the persistent Pallas decision kernel.
        # Single-table AND mesh backends serve ring/megaround; only a
        # backend without ring support degrades to pipelined, and
        # persistent degrades to megaround wherever the kernel cannot
        # compile — with the probe's reason kept for /debug/vars
        # (docs/ring.md's capability matrix).
        self.serve_mode = serve_mode  # requested
        self._ring = None
        self.persistent_status = None
        if serve_mode == "classic":
            pipeline_depth = 1
        elif serve_mode in ("ring", "megaround", "persistent"):
            backend = service.backend
            persistent = False
            if serve_mode == "persistent":
                ok, reason = getattr(
                    backend, "persistent_serve_supported",
                    lambda: (
                        False, "backend has no persistent serve kernel"
                    ),
                )()
                self.persistent_status = {
                    "supported": bool(ok), "reason": reason,
                }
                if ok:
                    persistent = True
                else:
                    # Honest fallback: megaround is the next-best
                    # dispatch-amortization tier, everywhere.
                    serve_mode = "megaround"
            rounds = 1 if serve_mode == "ring" else max(ring_rounds, 1)
            if getattr(backend, "ring_supported", lambda: False)():
                from gubernator_tpu.runtime.ring import RingBackend

                self._ring = RingBackend(
                    backend, slots=ring_slots, metrics=metrics,
                    rounds=rounds,
                    max_linger_us=(
                        ring_max_linger_us if rounds > 1 else 0.0
                    ),
                    persistent=persistent,
                )
                # The coalescer's fetch stage in ring mode only waits on
                # a published slot (cheap), so let enough merges be
                # outstanding to keep the ring runner fed — and in
                # megaround mode, enough to let a backlog actually form
                # past the base tier (the accumulator's load signal).
                pipeline_depth = max(
                    pipeline_depth, min(ring_slots * rounds, 8)
                )
            else:
                serve_mode = "pipelined"  # docs/ring.md fallback rule
        self.effective_serve_mode = serve_mode
        # Blocking device->host fetches performed ON the request path
        # (a coalescer dispatch/fetch stage), by lane.  The ring
        # acceptance criterion: steady-state == 0 in ring mode
        # (scripts/ring_smoke.py; bench_e2e budget split).
        self.blocking_fetches = {"mach": 0, "sketch": 0, "engine": 0}
        # Worker budget: one thread per concurrent dispatch stage plus
        # one per outstanding fetch (pipeline depth + sparse overlap
        # slots) — a fetch blocked on the device (or on a write-through
        # ticket) must never starve the next merge's dispatch in this
        # very pool.
        self._pool = ThreadPoolExecutor(
            max_workers=max_inflight + pipeline_depth + (
                _Coalescer.OVERLAP_SLOTS if sparse_limit > 0 else 0
            ),
            thread_name_prefix="tpu-fastlane",
        )
        self._mach = _Coalescer(
            self._pool, self._process, max_inflight,
            sparse_limit=sparse_limit,
            size_of=lambda e: e.cols.n,
            pipeline_depth=pipeline_depth,
            metrics=metrics, lane="mach",
        )
        # The sketch and engine lanes each coalesce cross-RPC into one
        # maximal merge at a time, on DEDICATED workers so machinery
        # syncs can't starve them (and vice versa); each lane pipelines
        # its own dispatch/fetch stages at the same depth.
        self._sketch_pool = ThreadPoolExecutor(
            max_workers=1 + pipeline_depth,
            thread_name_prefix="tpu-fastlane-sketch",
        )
        self._sketch_lane = (
            _Coalescer(self._sketch_pool, self._sketch_process,
                       pipeline_depth=pipeline_depth,
                       metrics=metrics, lane="sketch")
            if service.sketch_backend is not None else None
        )
        self._engine_pool = ThreadPoolExecutor(
            max_workers=1 + pipeline_depth,
            thread_name_prefix="tpu-fastlane-engine",
        )
        self._engine_lane = (
            _Coalescer(self._engine_pool, self._engine_process,
                       pipeline_depth=pipeline_depth,
                       metrics=metrics, lane="engine")
            if service.global_engine is not None else None
        )
        self.pipeline_depth = pipeline_depth
        # Servings since start (observability; also asserted in tests to
        # prove the fast lane actually ran).
        self.served = 0
        self.fallbacks = 0
        self._owner_frames: Dict[bytes, bytes] = {}
        # (membership_version, combined hash array) — see _sketch_hashes.
        self._sk_hashes: Optional[Tuple[int, np.ndarray]] = None

    def debug_vars(self) -> dict:
        """The /debug/vars view: per-lane drain/pipeline counters."""
        lanes = {"mach": self._mach.debug_vars()}
        if self._sketch_lane is not None:
            lanes["sketch"] = self._sketch_lane.debug_vars()
        if self._engine_lane is not None:
            lanes["engine"] = self._engine_lane.debug_vars()
        out = {
            "served": self.served,
            "fallbacks": self.fallbacks,
            "pipeline_depth": self.pipeline_depth,
            "serve_mode": self.serve_mode,
            "effective_serve_mode": self.effective_serve_mode,
            "blocking_fetches": dict(self.blocking_fetches),
            "lanes": lanes,
        }
        if self._ring is not None:
            out["ring"] = self._ring.debug_vars()
        if self.persistent_status is not None:
            # Honest capability reporting for GUBER_SERVE_MODE=
            # persistent: whether the Pallas serve kernel armed, and
            # the probe's reason when it degraded to megaround.
            out["persistent"] = dict(self.persistent_status)
        return out

    def _ring_live(self):
        """The RingBackend, if this merge may enter it (None once the
        ring broke or closed — the per-merge fallback to pipelined)."""
        r = self._ring
        return r if (r is not None and r.available()) else None

    # -- eligibility -----------------------------------------------------
    def _eligible(self) -> bool:
        # Persistence (Store/Loader/keymap) is served ON the lane:
        # seeding/capture batch columnarly per drain (_process), so a
        # store-attached deployment keeps the compiled path.
        return native.available()

    def _sketch_hashes(self) -> np.ndarray:
        """XXH64 fingerprints of the sketch-tier names (route key for the
        parser's name_hash column; the same 64-bit fingerprint stance the
        slot table takes on full keys).  Runtime-spilled names
        (SketchBackend.spill_name) append to the configured set; the
        combined array is cached per membership version — this runs in
        the per-RPC parse path."""
        sb = self.s.sketch_backend
        ver = sb.membership_version
        if self._sk_hashes is None or self._sk_hashes[0] != ver:
            base = native.hash_keys(sorted(sb.cfg.names))
            dyn = sb.dynamic_hashes()
            combined = (
                base if len(dyn) == 0 else np.concatenate([base, dyn])
            )
            self._sk_hashes = (ver, combined)
        return self._sk_hashes[1]

    def _owner_frame(self, addr: bytes) -> bytes:
        f = self._owner_frames.get(addr)
        if f is None:
            f = native.meta_frame(b"owner", addr)
            self._owner_frames[addr] = f
        return f

    def _single_node(self) -> bool:
        """True when no request can need a peer forward: an empty picker,
        or a one-peer picker where that peer is this node."""
        pick = self.s.local_picker
        sz = pick.size()
        if sz == 0:
            return True
        if sz > 1:
            return False
        return pick.peers()[0].info().is_owner

    # -- entry point -----------------------------------------------------
    async def check_raw(
        self, payload: bytes, peer_rpc: bool
    ) -> Optional[bytes]:
        """Serve a GetRateLimits(Req) / GetPeerRateLimits(Req) payload on
        the compiled lane; None = caller must take the object path.
        Raises ApiError on an oversized batch (same contract as the
        object path)."""
        from gubernator_tpu.runtime.service import ApiError

        if not self._eligible():
            self.fallbacks += 1
            return None
        if self.s.shed_level() > 0:
            # SLO-driven shedding is active (docs/hotkeys.md):
            # priority ordering is per request NAME, so the object path
            # applies it — the lane steps aside while this node sheds
            # (an overload condition; the columnar win is moot).
            self.fallbacks += 1
            return None
        rs = self.s.reshard
        if rs is not None and rs.active():
            # A handoff is in flight on this node (docs/resharding.md):
            # covered keys must forward-back / serve the bounded shadow
            # and rerouted keys must leave this table — per-key routing
            # the object path owns.  The lane steps aside for the
            # window (seconds per remap); every other daemon keeps its
            # compiled lane.
            self.fallbacks += 1
            return None
        if self.s.regions is not None:
            # Planet-scale regions (docs/multiregion.md): a remote-homed
            # key must serve the bounded `.region-carve` slot, and the
            # home pick is a per-key rendezvous over STRING hashes
            # (`key@region`) the columnar router cannot express — served
            # on the compiled lane it would answer from the raw row at
            # the full limit, breaking the region bound.  The object
            # path owns region routing.
            self.fallbacks += 1
            return None
        routed = not peer_rpc and not self._single_node()
        if routed and not self._can_route():
            self.fallbacks += 1
            return None
        if routed and len(self.s.local_picker.ring_arrays()[2]) == 0:
            # Empty ring: fall back BEFORE any metric side effects so the
            # object path (which re-runs validation and increments the
            # same counters) can't double-count.  There is no await
            # between here and _serve_routed's ring read, so the router
            # below never sees an empty ring.
            self.fallbacks += 1
            return None
        cols = native.parse_reqs(payload)
        if cols is None:
            self.fallbacks += 1
            return None
        n = cols.n
        if n > MAX_BATCH_SIZE:
            # Metric parity with the object path (service.py rejects with
            # the same counter on the client RPC, none on the peer RPC).
            if peer_rpc:
                raise ApiError(
                    "OUT_OF_RANGE",
                    "'PeerRequest.rate_limits' list too large; max size "
                    "is '%d'" % MAX_BATCH_SIZE,
                )
            self.s.metrics.note_check_error("Request too large")
            raise ApiError(
                "OUT_OF_RANGE",
                "Requests.RateLimits list too large; max size is '%d'"
                % MAX_BATCH_SIZE,
            )
        if not peer_rpc and n and cols.err.any():
            # Metric parity with the object path's client-side validation
            # rejections (gubernator.go:229, 235).
            n_inv = int(((cols.err == 1) | (cols.err == 2)).sum())
            if n_inv:
                self.s.metrics.note_check_error("Invalid request", n_inv)
        sk: Optional[np.ndarray] = None
        if self.s.sketch_backend is not None and n:
            sk = np.isin(cols.name_hash, self._sketch_hashes()) & (
                cols.err == 0
            )
            if sk.any():
                # Sketch names don't compose with GLOBAL replication —
                # strip the flag so they route plainly to the key's owner
                # and count ONCE there (service.py's routing does the
                # same on the object path).
                cols.behavior[sk] &= ~_GLOBAL
            else:
                sk = None
        is_global = (cols.behavior & _GLOBAL) != 0
        if n == 0:
            return b""
        if not peer_rpc:
            # concurrent_checks parity with service.get_rate_limits.
            self.s._inflight_checks += 1
            self.s.metrics.concurrent_checks.observe(
                self.s._inflight_checks
            )
        # Hot-key detection (docs/hotkeys.md): feed the tracker the
        # parsed fingerprint/hits columns once, at the point of no
        # return — every fallback already happened, so the object path
        # can never observe the same batch again.  Zero fingerprints
        # (errored lanes) are ignored by the tracker.
        if self.s.hotkeys is not None:
            self.s.note_traffic(cols.hash, cols.hits)
        try:
            if routed:
                return await self._serve_routed(
                    payload, cols, n, is_global, sk
                )
            return await self._serve(
                payload, cols, n, is_global, sk, peer_rpc
            )
        finally:
            if not peer_rpc:
                self.s._inflight_checks -= 1

    def _prep_greg(self, cols, exclude=None) -> Tuple[
        np.ndarray, np.ndarray, np.ndarray, Dict[int, bytes]
    ]:
        """Host-side Gregorian expiry (rare; only flagged lanes loop).
        Marks failed lanes in cols.err and zeroes their hashes.
        `exclude` masks lanes whose tier ignores duration entirely (the
        sketch tier, which neither computes nor errors on Gregorian —
        matching SketchBackend.check)."""
        n = cols.n
        greg_expire = np.zeros(n, dtype=np.int64)
        greg_duration = np.zeros(n, dtype=np.int64)
        is_greg = (
            cols.behavior & int(Behavior.DURATION_IS_GREGORIAN)
        ) != 0
        # Validation errors take precedence: the object path's packer
        # rejects an empty name/key BEFORE evaluating the Gregorian
        # duration, so an already-errored lane must keep its error.
        is_greg &= cols.err == 0
        if exclude is not None:
            is_greg &= ~exclude
        err_extra: Dict[int, bytes] = {}
        if is_greg.any():
            now_dt = self.s.clock.now()
            for i in np.flatnonzero(is_greg):
                i = int(i)
                try:
                    greg_expire[i] = gregorian_expiration(
                        now_dt, int(cols.duration[i])
                    )
                    greg_duration[i] = gregorian_duration(
                        now_dt, int(cols.duration[i])
                    )
                except GregorianError as e:
                    err_extra[i] = str(e).encode()
                    cols.err[i] = _ERR_GREG
                    cols.hash[i] = 0
        return is_greg, greg_expire, greg_duration, err_extra

    def _error_strings(self, cols, err_extra) -> List[bytes]:
        """Per-request error bytes (b'' on clean lanes)."""
        out = [b""] * cols.n
        if cols.err.any():
            for i in np.flatnonzero(cols.err):
                i = int(i)
                code = int(cols.err[i])
                out[i] = (
                    err_extra.get(i, b"")
                    if code == _ERR_GREG
                    else (_ERR_EMPTY_KEY if code == 1 else _ERR_EMPTY_NAME)
                )
        return out

    async def _serve_cols(
        self, payload, cols, is_greg, ge, gd, use_cached=None
    ) -> Tuple[np.ndarray, ...]:
        """Submit columns to the coalescing batcher; returns the seven
        response arrays (status, limit, remaining, reset_time, stored,
        stored_status, cap_ok — the last three feed the GLOBAL broadcast
        capture).  `payload` is the raw wire bytes the columns were
        spliced from — the persistence SPI decodes per-unique-key
        requests from it."""
        return await self._mach.do(_Entry(
            payload=payload,
            cols=cols,
            is_greg=is_greg,
            greg_expire=ge,
            greg_duration=gd,
            use_cached=(
                use_cached if use_cached is not None
                else np.zeros(cols.n, dtype=bool)
            ),
        ))

    def _decode_req(self, payload, cols, i: int):
        """Decode ONE request's spliced wire frame into a RateLimitReq."""
        from gubernator_tpu.net.grpc_api import req_from_pb
        from gubernator_tpu.proto import gubernator_pb2 as pb

        frame = payload[
            cols.msg_off[i]:cols.msg_off[i] + cols.msg_len[i]
        ]
        return req_from_pb(pb.GetRateLimitsReq.FromString(frame).requests[0])

    def _decode_unique(self, payload, cols, idx, last=False):
        """Yield (req, group_indices) for each UNIQUE key hash among the
        request indices `idx` — one protobuf decode per unique key (the
        managers aggregate by key anyway, global.go:87-95).  `last`
        decodes the group's LAST arrival instead of its first: the
        update queue is last-write-wins per key (queue_update), and the
        broadcast's zero-hit re-read uses the queued request's params —
        first-occurrence params would recreate the bucket differently
        on an algorithm/burst change within one batch."""
        if not len(idx):
            return
        order = idx[np.argsort(cols.hash[idx], kind="stable")]
        hs = cols.hash[order]
        bounds = np.flatnonzero(
            np.concatenate([[True], hs[1:] != hs[:-1]])
        )
        for b_i, lo in enumerate(bounds):
            hi = bounds[b_i + 1] if b_i + 1 < len(bounds) else len(order)
            group = order[lo:hi]
            fi = int(group[-1] if last else group[0])
            yield self._decode_req(payload, cols, fi), group

    def _queue_global(self, payload, cols, idx) -> None:
        """Queue GLOBAL hits (non-owner) for the request indices `idx` —
        the deferred QueueHit of gubernator.go:429-432.  Errored lanes
        are pre-filtered by the caller: a queued errored hit is dropped
        by the owner's validation with no state effect anywhere, so the
        bookkeeping difference from the object path (which queues before
        validating) is unobservable."""
        from dataclasses import replace as dc_replace

        if not len(idx):
            return
        mgr = self.s.global_mgr
        for req, group in self._decode_unique(payload, cols, idx):
            total = int(cols.hits[group].sum())
            mgr.queue_hit(dc_replace(req, hits=total))

    def _queue_global_updates(self, payload, cols, is_global,
                              owned=None, peer_rpc=False,
                              capture=None) -> None:
        """Queue owner-side broadcast updates for GLOBAL lanes — GREGORIAN-
        errored lanes included: the reference QueueUpdates before the
        algorithm runs (gubernator.go:617-619), so with last-write-wins
        per key an errored occurrence can cancel a valid one's pending
        broadcast.  The fast lane reproduces that exactly: the LAST
        arrival per key wins, valid or not.  VALIDATION-errored lanes
        (empty name/key) queue only on the peer RPC: the client RPC
        rejects them before routing (gubernator.go:228-237) so they never
        reach the algorithm, while the peer RPC validates owner-side
        AFTER QueueUpdate.

        `owned` (routed path) masks node-owned lanes.  Which branch an
        errored lane takes depends on where its error was detected:
        validation errors have hash 0 from the parser and route through
        the decode branch below, with ownership decided from the decoded
        key string like the object path's routing; Gregorian errors on
        the ROUTED path keep their true hash in `cols` (only
        serve_local's subset copy was zeroed), so they group with the
        valid lanes — same last-write-wins outcome either way.

        `capture` = (stored_status, stored, reset, limit, cap_ok)
        full-size response columns from this drain: each queued update
        carries the post-step stored state of its LAST arrival, which the
        broadcast ships directly instead of re-running a zero-hit read —
        equal by construction to global.go:205-250's re-read of a bucket
        row (token reports the sticky stored status; leaky always
        re-reads UNDER; reset/remaining are the post-step stored values;
        a lane whose request errored re-captures the error, which the
        broadcast skips exactly as it skips a failed re-read).  A capture
        is kept ONLY when `cap_ok` marks the arrival as its key's last
        mutating occurrence across the WHOLE merged drain (computed in
        _process over every coalesced RPC — a later occurrence, even from
        another concurrent call, moves the row past the capture, and the
        flush-time re-read would then apply the queued request's now
        stale params to the newer row, a reference quirk the re-read
        fallback preserves exactly; sketch lanes never reach _process's
        machinery merge, so their cap_ok stays False).  Later DRAINS
        degrade captures via _touch_captures.  The only intended
        divergences from flush-time
        re-reads: sub-window leaky time-regen (zero under a frozen
        clock) and no resurrection of keys evicted between drain and
        flush."""
        idx = np.flatnonzero(is_global)
        if not len(idx):
            return
        hv = cols.hash[idx]
        valid = idx[hv != 0]
        if owned is not None:
            valid = valid[owned[valid]]
        best: Dict[str, Tuple[int, object]] = {}
        for req, group in self._decode_unique(
            payload, cols, valid, last=True
        ):
            best[req.hash_key()] = (int(group[-1]), req)
        err_lanes = idx[hv == 0]
        if len(err_lanes) and not peer_rpc:
            # Client path: only Gregorian failures reached the algorithm;
            # validation errors were rejected before routing.
            err_lanes = err_lanes[cols.err[err_lanes] == _ERR_GREG]
        if len(err_lanes):
            from gubernator_tpu.runtime.service import PoolEmptyError

            sk_be = self.s.sketch_backend
            for i in err_lanes:
                i = int(i)
                req = self._decode_req(payload, cols, i)
                if sk_be is not None and sk_be.handles(req):
                    # The object path strips GLOBAL from sketch names
                    # unconditionally (errored or not) — a sketch key
                    # never queues an exact-table broadcast.
                    continue
                key = req.hash_key()
                if owned is not None:
                    try:
                        if not self.s.get_peer(key).info().is_owner:
                            continue
                    except PoolEmptyError:
                        continue
                cur = best.get(key)
                if cur is None or i > cur[0]:
                    best[key] = (i, req)
        mgr = self.s.global_mgr
        if capture is None:
            for _, req in best.values():
                mgr.queue_update(req)
            return
        from gubernator_tpu.core.types import RateLimitResp, Status

        sst, sto, rst, lm, cap_ok = capture
        for i, req in best.values():
            if cols.err[i] != 0:
                # Errored last arrival: the re-read would fail the same
                # way and broadcast nothing — capture a sentinel error so
                # the broadcast skips this key (last-write-wins cancel,
                # immune to later mutations: the QUEUED params stay
                # errored).
                st: Optional[RateLimitResp] = RateLimitResp(
                    error="capture: errored lane"
                )
            elif not cap_ok[i]:
                st = None  # a later occurrence moved the row — re-read
            elif int(cols.behavior[i]) & int(Behavior.RESET_REMAINING):
                # The flush-time re-read of a RESET_REMAINING request
                # re-runs the reset (algorithms.go:78-90 precedes the
                # hits==0 early-out) — a mutating read the capture
                # cannot represent.
                st = None
            elif int(cols.algo[i]) == 1 and int(sto[i]) > int(
                cols.burst[i] if cols.burst[i] != 0 else cols.limit[i]
            ):
                # Leaky row overfilled past burst (negative hits): the
                # next read — including the flush re-read — clamps and
                # WRITES remaining back to burst (algorithms.go:372-376).
                # Another mutating read; keep it.
                st = None
            else:
                st = RateLimitResp(
                    status=Status(int(sst[i])),
                    limit=int(lm[i]),
                    remaining=int(sto[i]),
                    reset_time=int(rst[i]),
                )
            mgr.queue_update(req, st)

    def _touch_captures(self, cols, sk=None, eng=None) -> None:
        """Degrade stale captured GLOBAL broadcast rows for every key
        this drain mutated on the machinery table (a non-GLOBAL request
        must not let a pending capture ship pre-mutation state — the
        re-read fallback then sees the post-mutation row, exactly like
        the reference's flush-time read).  Near-free while no captures
        are pending; lanes that re-queue an update below simply
        re-capture fresh state (touch runs first)."""
        mgr = self.s.global_mgr
        if not mgr._pending_h:
            return
        mask = cols.err == 0
        if sk is not None:
            mask &= ~sk
        # Engine lanes stay in the set: they mutate the engine's own
        # tables, but engine services never create RPC captures, so
        # touching them is a no-op — not worth a mask.
        if mask.any():
            mgr.touch_hashes(cols.hash[mask])

    def _queue_multiregion(self, payload, cols, idx) -> None:
        """Queue owner-side MULTI_REGION hits for the request indices
        `idx` toward the cross-region manager (the object path's
        queue_hits call in _check_local, gubernator.go:600-631)."""
        from dataclasses import replace as dc_replace

        if not len(idx):
            return
        mgr = self.s.multi_region_mgr
        for req, group in self._decode_unique(payload, cols, idx):
            total = int(cols.hits[group].sum())
            mgr.queue_hits(dc_replace(req, hits=total))

    async def _serve_split(
        self, payload, cols, is_greg, ge, gd, use_cached, sk, eng=None
    ) -> Tuple[np.ndarray, ...]:
        """Serve a column set, splitting sketch-named lanes to the CMS
        step and engine lanes (node-owned GLOBAL on a mesh service) to
        the collective GlobalEngine; the rest rides the exact machinery.
        All branches run concurrently and scatter into full-size
        response arrays."""
        no_sk = sk is None or not sk.any()
        no_eng = eng is None or not eng.any()
        if no_sk and no_eng:
            return await self._serve_cols(
                payload, cols, is_greg, ge, gd, use_cached=use_cached
            )
        n = cols.n
        sk_m = sk if sk is not None else np.zeros(n, dtype=bool)
        eng_m = eng if eng is not None else np.zeros(n, dtype=bool)
        sk_idx = np.flatnonzero(sk_m)
        eng_idx = np.flatnonzero(eng_m)
        ex_idx = np.flatnonzero(~sk_m & ~eng_m)
        status = np.zeros(n, dtype=np.int64)
        out_lim = np.zeros(n, dtype=np.int64)
        remaining = np.zeros(n, dtype=np.int64)
        reset = np.zeros(n, dtype=np.int64)
        # Post-step stored columns (machinery lanes only — sketch/engine
        # lanes never feed the RPC broadcast capture, so their cap_ok
        # stays False).
        stored = np.zeros(n, dtype=np.int64)
        stored_st = np.zeros(n, dtype=np.int64)
        cap_ok = np.zeros(n, dtype=bool)
        loop = asyncio.get_running_loop()

        async def run_sketch() -> None:
            kh = cols.hash[sk_idx]
            hh = cols.hits[sk_idx]
            ll = cols.limit[sk_idx]
            st, rem, rst = await self._sketch_lane.do(
                _SketchEntry(kh, hh, ll)
            )
            status[sk_idx] = st
            out_lim[sk_idx] = ll
            remaining[sk_idx] = rem
            reset[sk_idx] = rst

        async def run_engine() -> None:
            st, lm, rem, rst = await self._engine_lane.do(
                _EngineEntry(payload, cols, eng_idx, is_greg, ge, gd)
            )
            status[eng_idx] = st
            out_lim[eng_idx] = lm
            remaining[eng_idx] = rem
            reset[eng_idx] = rst
            # Open the sync window for the queued hits (the object
            # path's notify at service.py:405; asyncio.Event — must run
            # on the loop thread, hence here and not in _engine_process).
            if self.s._collective_loop is not None:
                self.s._collective_loop.notify()

        async def run_exact() -> None:
            sub = cols.subset(ex_idx)
            st, lm, rem, rst, sto, sst, cok = await self._serve_cols(
                payload, sub, is_greg[ex_idx], ge[ex_idx], gd[ex_idx],
                use_cached=(
                    use_cached[ex_idx] if use_cached is not None else None
                ),
            )
            status[ex_idx] = st
            out_lim[ex_idx] = lm
            remaining[ex_idx] = rem
            reset[ex_idx] = rst
            stored[ex_idx] = sto
            stored_st[ex_idx] = sst
            cap_ok[ex_idx] = cok

        tasks = []
        if len(sk_idx):
            tasks.append(run_sketch())
        if len(eng_idx):
            tasks.append(run_engine())
        if len(ex_idx):
            tasks.append(run_exact())
        await asyncio.gather(*tasks)
        return status, out_lim, remaining, reset, stored, stored_st, cap_ok

    def _engine_process(self, entries):
        """Merged columnar serving for node-owned GLOBAL lanes on the
        mesh GlobalEngine — one coalescer drain = ONE engine lock hold
        and dispatch chain (runs on the engine lane's worker thread).
        Dispatch stage: aggregate + pack + serve_packed (engine lock);
        the returned closure (host fetch, unmarshal, tally, deferred
        sync) is the fetch stage.

        Per ENTRY, duplicates aggregate to one lane per unique key
        (hits summed, first occurrence's params, shared response) —
        mirroring one GlobalEngine.check call.  ACROSS entries the same
        key keeps separate lanes, which assign_rounds places in later
        rounds — so a drain of N entries is semantically N sequential
        engine calls, amortized into one round-trip."""
        from gubernator_tpu.parallel.global_sync import _ARRIVAL_SHIFT
        from gubernator_tpu.parallel.sharded import (
            packed_grid_rounds_to_host,
        )
        from gubernator_tpu.runtime.backend import (
            Tally,
            tally_from_rounds,
        )

        engine = self.s.global_engine
        cfg = self.s.backend.cfg
        n_shards, B = cfg.num_shards, cfg.batch_size
        shift = np.uint64(_ARRIVAL_SHIFT)  # vectorized arrival_dev

        per = []
        for e in entries:
            sub_h = e.cols.hash[e.idx]
            uniq, first, inv = np.unique(
                sub_h, return_index=True, return_inverse=True
            )
            rep = e.idx[first]             # first occurrence per key
            m = len(uniq)
            # Exact int64 sums (float64 bincount weights would corrupt
            # hits above 2^53 and diverge from the pending queue).
            hits_sum = np.zeros(m, dtype=np.int64)
            np.add.at(hits_sum, inv, e.cols.hits[e.idx])
            burst = e.cols.burst[rep]
            burst = np.where(burst == 0, e.cols.limit[rep], burst)
            per.append((e, uniq, inv, rep, m, hits_sum, burst))

        def cat(parts):
            # Uncontended drains (one entry) skip the copies.
            return parts[0] if len(parts) == 1 else np.concatenate(parts)

        h_all = cat([p[1] for p in per])
        offs = np.zeros(len(per) + 1, dtype=np.int64)
        np.cumsum([p[4] for p in per], out=offs[1:])
        sh = (
            (h_all.view(np.uint64) >> shift) % np.uint64(n_shards)
        ).astype(np.int32)
        rnd, lane, n_rounds = native.assign_rounds(h_all, sh, n_shards, B)
        values = dict(
            key_hash=h_all,
            hits=cat([p[5] for p in per]),
            limit=cat([p[0].cols.limit[p[3]] for p in per]),
            duration=cat([p[0].cols.duration[p[3]] for p in per]),
            algo=cat([p[0].cols.algo[p[3]] for p in per]),
            burst=cat([p[6] for p in per]),
            reset_remaining=cat([
                (p[0].cols.behavior[p[3]]
                 & int(Behavior.RESET_REMAINING)) != 0
                for p in per
            ]),
            is_greg=cat([p[0].is_greg[p[3]] for p in per]),
            greg_expire=cat([p[0].ge[p[3]] for p in per]),
            greg_duration=cat([p[0].gd[p[3]] for p in per]),
            use_cached=np.ones(len(h_all), dtype=bool),
        )
        rounds, order, bounds = _build_rounds(
            values, rnd, lane, sh, n_rounds, n_shards, B
        )
        # _decode_unique yields groups in ascending-hash order — exactly
        # each entry's uniq order — so the decoded reqs zip with the
        # computed sums and arrival shards (one source of truth).
        pend = []
        for i, (e, _uniq, _inv, _rep, _m, hits_sum, _burst) in enumerate(
            per
        ):
            off = int(offs[i])
            for j, (req, _group) in enumerate(
                self._decode_unique(e.payload, e.cols, e.idx)
            ):
                pend.append(
                    (req, int(hits_sum[j]), int(sh[off + j]))
                )
        resps, want_sync = engine.serve_packed(rounds, pend)

        def fetch_body() -> List[Tuple[np.ndarray, ...]]:
            host = packed_grid_rounds_to_host(resps)

            mt = len(h_all)
            st_u = np.zeros(mt, dtype=np.int64)
            lm_u = np.zeros(mt, dtype=np.int64)
            rem_u = np.zeros(mt, dtype=np.int64)
            rst_u = np.zeros(mt, dtype=np.int64)
            for r_idx in range(n_rounds):
                sel = order[bounds[r_idx]:bounds[r_idx + 1]]
                hr = host[r_idx]
                at = (sh[sel], lane[sel])
                st_u[sel] = hr["status"][at]
                lm_u[sel] = hr["limit"][at]
                rem_u[sel] = hr["remaining"][at]
                rst_u[sel] = hr["reset_time"][at]

            t = tally_from_rounds(rounds, host)
            self.s.backend._add_tally(Tally(
                checks=mt,
                over_limit=int((st_u == 1).sum()),
                not_persisted=t.not_persisted,
                cache_hits=t.cache_hits,
            ))
            if want_sync:
                engine.sync()
            outs: List[Tuple[np.ndarray, ...]] = []
            for i, (_e, _uq, inv, _rep, _m, _hits, _bst) in enumerate(per):
                lo, hi = int(offs[i]), int(offs[i + 1])
                outs.append((
                    st_u[lo:hi][inv], lm_u[lo:hi][inv],
                    rem_u[lo:hi][inv], rst_u[lo:hi][inv],
                ))
            return outs

        # Ring discipline: the engine readback (and a triggered sync's
        # collective + write-through) runs on the ring runner, FIFO with
        # the ring iterations — the mesh request path stays fetch-free
        # even for GLOBAL lanes (the sketch-lane pattern).
        wait_body = None
        ring = self._ring_live()
        if ring is not None:
            from gubernator_tpu.runtime.ring import RingClosedError

            try:
                wait_body = ring.submit_host(fetch_body)
            except RingClosedError:
                wait_body = None

        def fetch() -> List[Tuple[np.ndarray, ...]]:
            if wait_body is not None:
                return wait_body()
            self.blocking_fetches["engine"] += 1
            return fetch_body()

        return fetch

    @staticmethod
    def _sketch_meta(n: int, sk) -> Tuple[Optional[bytes],
                                          Optional[np.ndarray]]:
        """(meta_blob, meta_off) tagging sketch lanes tier=sketch."""
        if sk is None or not sk.any():
            return None, None
        metas = [
            _TIER_SKETCH_FRAME if sk[i] else b"" for i in range(n)
        ]
        off = np.zeros(n + 1, dtype=np.int64)
        np.cumsum([len(m) for m in metas], out=off[1:])
        return b"".join(metas), off

    async def _serve(
        self, payload, cols, n: int, is_global, sk, peer_rpc=False
    ) -> bytes:
        """Single-node / peer-RPC path: everything is local (and owned,
        so GLOBAL lanes serve authoritatively and queue broadcast
        updates).  On a mesh service the CLIENT path routes GLOBAL lanes
        to the collective GlobalEngine; the peer RPC keeps RPC-tier
        semantics (machinery serve + queued update) like the object
        path's _check_local — engine keys sync over ICI, cross-node
        forwards ride the managers."""
        is_greg, ge, gd, err_extra = self._prep_greg(cols, exclude=sk)
        use_engine = self.s.global_engine is not None and not peer_rpc
        eng = None
        if use_engine and is_global.any():
            eng = is_global & (cols.err == 0)
            if not eng.any():
                eng = None
        status, limit, remaining, reset, stored, stored_st, cap_ok = (
            await self._serve_split(
                payload, cols, is_greg, ge, gd, None, sk, eng
            )
        )
        if eng is not None:
            # Metric parity: the object path's routing counts engine
            # requests under the "global" source label.
            self.s.metrics.getratelimit_counter.labels("global").inc(
                int(eng.sum())
            )
        self._touch_captures(cols, sk, eng)
        if is_global.any() and not use_engine:
            # With a collective engine, GLOBAL lanes (errored included)
            # belong to the engine path on the object flow — the RPC
            # update manager is never consulted.
            self._queue_global_updates(
                payload, cols, is_global, peer_rpc=peer_rpc,
                capture=(stored_st, stored, reset, limit, cap_ok),
            )
        mr = (cols.behavior & _MULTI_REGION) != 0
        if mr.any():
            self._queue_multiregion(
                payload, cols, np.flatnonzero(mr & (cols.err == 0))
            )
        errs = self._error_strings(cols, err_extra)
        err_off = np.zeros(n + 1, dtype=np.int64)
        np.cumsum([len(e) for e in errs], out=err_off[1:])
        meta_blob, meta_off = self._sketch_meta(n, sk)
        self.served += n
        return native.serialize_resps(
            status, limit, remaining, reset, b"".join(errs), err_off,
            meta_blob, meta_off,
        )

    def _can_route(self) -> bool:
        """Columnar routing serves every selectable ring hash: xx rings
        drive the owner lookup straight from the C++ parse fingerprint
        (XXH64 of the hash-key string); fnv1/fnv1a rings — placement
        interop with mixed reference/tpu clusters
        (replicated_hash.go:33) — get a vectorized second hash column
        from gub_fnv_hashkey_batch."""
        from gubernator_tpu.core.hashing import fnv1_64, fnv1a_64
        from gubernator_tpu.net.replicated_hash import xx_64

        return self.s.local_picker.hash_fn in (xx_64, fnv1_64, fnv1a_64)

    async def _serve_routed(
        self, payload: bytes, cols, n: int, is_global, sk
    ) -> bytes:
        """Multi-node client path: vectorized consistent-hash routing with
        zero-copy forwards.

        One np.searchsorted over the vnode ring maps every request to its
        owner; locally-owned (and errored) lanes ride the normal columnar
        lane, while each remote owner receives ONE GetPeerRateLimits RPC
        whose payload is spliced verbatim from this request's wire bytes —
        no re-encoding in either direction (the reference's asyncRequests
        + peer batcher, gubernator.go:327-416, with the per-request python
        replaced by array ops).  Failed forwards fall back to the object
        path's ownership-retry loop per request."""
        picker = self.s.local_picker
        ring, ring_idx, peers = picker.ring_arrays()
        # check_raw gated on a non-empty ring with no await in between;
        # a fallback here would double-count the validation metrics the
        # caller already incremented, so the invariant must hold.
        assert peers, "check_raw gates on a non-empty ring"
        from gubernator_tpu.net.replicated_hash import xx_64

        if picker.hash_fn is xx_64:
            h_route = cols.hash
        else:
            # fnv1/fnv1a interop ring (_can_route admitted it): hash the
            # spliced hash-key bytes with the ring's own function —
            # placement stays identical to a reference node's.
            from gubernator_tpu.core.hashing import fnv1_64

            h_route = native.fnv_hashkey_batch(
                payload, cols,
                "fnv1" if picker.hash_fn is fnv1_64 else "fnv1a",
            )
        h_u = h_route.view(np.uint64)
        slot = np.searchsorted(ring, h_u, side="left")
        slot[slot == len(ring)] = 0
        owner = ring_idx[slot]  # peer index per request
        is_owner = np.array(
            [p.info().is_owner for p in peers], dtype=bool
        )
        owned = is_owner[owner]
        # GLOBAL never forwards: non-owned GLOBAL serves from the local
        # replica via use_cached lanes (stale-but-fast reads,
        # gubernator.go:420-460) with the hit queued to the owner.
        glob_cached = is_global & ~owned & (cols.err == 0)
        local_mask = (cols.err != 0) | owned | is_global
        # Hot-key widening (docs/hotkeys.md): lanes for keys this node
        # actively mirrors (hot AND owner pressured AND we are a
        # next-arc replica) leave the forward sets and serve from the
        # local mirror allowance via the object path — the hot-set is
        # tiny and the per-request hop replaces a forwarded RPC to an
        # overloaded owner, not a columnar serve.
        mirror_fps = self.s.active_mirror_fps()
        mirror_mask = None
        if len(mirror_fps):
            mirror_mask = (
                np.isin(cols.hash, mirror_fps)
                & ~local_mask
            )
            if sk is not None:
                mirror_mask &= ~sk
            if not mirror_mask.any():
                mirror_mask = None

        status = np.zeros(n, dtype=np.int64)
        out_lim = np.zeros(n, dtype=np.int64)
        remaining = np.zeros(n, dtype=np.int64)
        reset = np.zeros(n, dtype=np.int64)
        stored = np.zeros(n, dtype=np.int64)
        stored_st = np.zeros(n, dtype=np.int64)
        cap_ok = np.zeros(n, dtype=bool)
        errs: List[bytes] = [b""] * n
        metas: List[bytes] = [b""] * n

        async def serve_local(idx: np.ndarray) -> None:
            sub = cols.subset(idx)
            sub_sk = sk[idx] if sk is not None else None
            is_greg, ge, gd, err_extra = self._prep_greg(
                sub, exclude=sub_sk
            )
            # _prep_greg marked Gregorian failures on the subset COPY —
            # propagate so the GLOBAL queue/metadata block (filtered on
            # cols.err == 0) never replicates or annotates a failed lane.
            cols.err[idx] = sub.err
            sub_eng = None
            if self.s.global_engine is not None:
                # Node-owned GLOBAL lanes ride the collective engine
                # (service.py routing: owner + engine -> engine_idx).
                sub_eng = (
                    is_global[idx] & owned[idx] & (sub.err == 0)
                )
                if not sub_eng.any():
                    sub_eng = None
            st, lm, rem, rst, sto, sst, cok = await self._serve_split(
                payload, sub, is_greg, ge, gd, glob_cached[idx], sub_sk,
                sub_eng,
            )
            status[idx] = st
            out_lim[idx] = lm
            remaining[idx] = rem
            reset[idx] = rst
            stored[idx] = sto
            stored_st[idx] = sst
            cap_ok[idx] = cok
            self._touch_captures(sub, sub_sk, sub_eng)
            sub_errs = self._error_strings(sub, err_extra)
            for j, i in enumerate(idx):
                if sub_errs[j]:
                    errs[int(i)] = sub_errs[j]
            if sub_sk is not None:
                for i in idx[sub_sk]:
                    metas[int(i)] = _TIER_SKETCH_FRAME
            # Metric parity with the object path's routing: non-owned
            # GLOBAL reads and engine-served lanes count as "global",
            # everything else owner-side counts as "local".
            n_glob = int(glob_cached[idx].sum()) + (
                int(sub_eng.sum()) if sub_eng is not None else 0
            )
            m = self.s.metrics.getratelimit_counter
            if n_glob:
                m.labels("global").inc(n_glob)
            if len(idx) - n_glob:
                m.labels("local").inc(len(idx) - n_glob)

        async def forward(peer, idx: np.ndarray) -> None:
            import grpc as grpc_mod

            from gubernator_tpu.net.peer_client import PeerNotReadyError

            addr = peer.info().grpc_address.encode()
            sub_pay = b"".join(
                payload[cols.msg_off[i]:cols.msg_off[i] + cols.msg_len[i]]
                for i in idx
            )
            self.s.metrics.getratelimit_counter.labels("forward").inc(
                len(idx)
            )
            try:
                raw = await peer.get_peer_rate_limits_raw(sub_pay)
            except Exception as e:  # noqa: BLE001
                # Retry ONLY the failures the object path retries
                # (NotReady / UNAVAILABLE / CANCELLED, which _forward
                # re-resolves with backoff — gubernator.go:382-395).
                # Anything else may follow a delivered batch, and a
                # re-send would double-count the hits.
                retriable = isinstance(e, PeerNotReadyError) or (
                    isinstance(e, grpc_mod.aio.AioRpcError)
                    and e.code() in (
                        grpc_mod.StatusCode.UNAVAILABLE,
                        grpc_mod.StatusCode.CANCELLED,
                    )
                )
                if retriable:
                    await forward_fallback(peer, idx)
                else:
                    msg = (
                        "Error while fetching rate limit from peer "
                        f"'{peer.info().grpc_address}': {e}"
                    ).encode()
                    for i in idx:
                        errs[int(i)] = msg
                return
            rc = native.parse_resps(raw)
            if rc is None or rc.n != len(idx):
                # A response ARRIVED, so the peer applied the batch —
                # never re-send; report the protocol error instead.
                msg = (
                    "peer '%s' returned %s responses for %d requests"
                    % (
                        peer.info().grpc_address,
                        "unparseable" if rc is None else rc.n,
                        len(idx),
                    )
                ).encode()
                for i in idx:
                    errs[int(i)] = msg
                return
            status[idx] = rc.status
            out_lim[idx] = rc.limit
            remaining[idx] = rc.remaining
            reset[idx] = rc.reset_time
            owner_frame = self._owner_frame(addr)
            for j, i in enumerate(idx):
                i = int(i)
                if rc.err_len[j]:
                    o = int(rc.err_off[j])
                    errs[i] = raw[o:o + int(rc.err_len[j])]
                # Splice the owner's metadata frames verbatim (tier tags
                # etc.), then append this hop's owner annotation.
                m = b""
                if rc.meta_len[j] > 0:
                    o = int(rc.meta_off[j])
                    m = raw[o:o + int(rc.meta_len[j])]
                metas[i] = m + owner_frame

        async def forward_fallback(peer, idx: np.ndarray) -> None:
            """Re-route failed forwards through the object path's retry
            loop (ownership changes, NotReady backoff — service._forward).
            """
            async def one(i: int) -> None:
                req = self._decode_req(payload, cols, i)
                resp = await self.s._forward(peer, req, req.hash_key())
                status[i] = int(resp.status)
                out_lim[i] = resp.limit
                remaining[i] = resp.remaining
                reset[i] = resp.reset_time
                if resp.error:
                    errs[i] = resp.error.encode()
                if resp.metadata:
                    metas[i] = b"".join(
                        native.meta_frame(k.encode(), v.encode())
                        for k, v in resp.metadata.items()
                    )

            await asyncio.gather(*(one(int(i)) for i in idx))

        async def serve_mirror(idx: np.ndarray) -> None:
            """Hot lanes served from the local mirror allowance
            (service._mirror_serve: bounded carve-out + async
            reconcile to the owner)."""
            async def one(i: int) -> None:
                req = self._decode_req(payload, cols, i)
                resp = await self.s._mirror_serve(
                    req, peers[int(owner[i])]
                )
                status[i] = int(resp.status)
                out_lim[i] = resp.limit
                remaining[i] = resp.remaining
                reset[i] = resp.reset_time
                if resp.error:
                    errs[i] = resp.error.encode()
                if resp.metadata:
                    metas[i] = b"".join(
                        native.meta_frame(k.encode(), v.encode())
                        for k, v in resp.metadata.items()
                    )

            await asyncio.gather(*(one(int(i)) for i in idx))

        tasks = []
        local_idx = np.flatnonzero(local_mask)
        if len(local_idx):
            tasks.append(serve_local(local_idx))
        forwardable = ~local_mask
        if mirror_mask is not None:
            forwardable = forwardable & ~mirror_mask
            tasks.append(serve_mirror(np.flatnonzero(mirror_mask)))
        remote_idx = np.flatnonzero(forwardable)
        if len(remote_idx):
            for pi in np.unique(owner[remote_idx]):
                idx = remote_idx[owner[remote_idx] == pi]
                tasks.append(forward(peers[int(pi)], idx))
        await asyncio.gather(*tasks)

        if is_global.any():
            # Deferred GLOBAL replication (gubernator.go:429-432, 617):
            # non-owned keys queue their hits toward the owner; owned keys
            # queue broadcast updates.  Owner metadata on the served reads.
            gc_idx = np.flatnonzero(glob_cached & (cols.err == 0))
            for i in gc_idx:
                metas[int(i)] = self._owner_frame(
                    peers[int(owner[int(i)])].info().grpc_address.encode()
                )
            self._queue_global(payload, cols, gc_idx)
            if self.s.global_engine is None:
                # Owner-side updates broadcast via the RPC manager only
                # when no collective engine owns replication (the engine
                # broadcasts through sync + the _engine_synced bridge).
                self._queue_global_updates(
                    payload, cols, is_global, owned=owned,
                    capture=(stored_st, stored, reset, out_lim, cap_ok),
                )

        mr = (cols.behavior & _MULTI_REGION) != 0
        if mr.any():
            # Owner-side queueing only: non-owned lanes were forwarded
            # (the owner's peer-RPC lane queues them), and non-owned
            # GLOBAL cached reads don't queue (the object path's
            # `if cached: continue`, service._check_local).
            self._queue_multiregion(
                payload, cols,
                np.flatnonzero(mr & owned & (cols.err == 0)),
            )

        err_off = np.zeros(n + 1, dtype=np.int64)
        np.cumsum([len(e) for e in errs], out=err_off[1:])
        meta_off = np.zeros(n + 1, dtype=np.int64)
        np.cumsum([len(m) for m in metas], out=meta_off[1:])
        self.served += n
        return native.serialize_resps(
            status, out_lim, remaining, reset,
            b"".join(errs), err_off, b"".join(metas), meta_off,
        )

    # -- persistence SPI on the lane -------------------------------------
    def _persist_decode(self, entries) -> Dict[int, list]:
        """Per-unique-key request decodes for the persistence SPI
        (Store.get / Store.on_change / the Loader keymap take Python
        objects — the one per-KEY host cost the lane pays with
        persistence attached; everything else stays columnar).

        Returns fp(int64) -> [hash_key_str, first_req, capture_req],
        in first-arrival entry order.  `capture_req` is None when every
        occurrence is a GLOBAL cached read (use_cached) — such keys are
        excluded from write-through like _capture_write_through."""
        uniq: Dict[int, list] = {}
        for e in entries:
            valid = np.flatnonzero(e.cols.hash != 0)
            for req, group in self._decode_unique(e.payload, e.cols, valid):
                fp = int(e.cols.hash[group[0]])
                uc = e.use_cached[group]
                cap = None
                if not uc.all():
                    cap = req if not uc[0] else self._decode_req(
                        e.payload, e.cols, int(group[~uc][0])
                    )
                cur = uniq.get(fp)
                if cur is None:
                    uniq[fp] = [req.hash_key(), req, cap]
                elif cur[2] is None and cap is not None:
                    cur[2] = cap
        return uniq

    def _note_spill_pressure(self, entries, h_mach, foundv, persv) -> None:
        """Feed the sketch tier's dynamic-spillover policy with this
        drain's per-name exact-tier pressure (SketchTierConfig
        spill_inserts/spill_transients): insert lanes' key fingerprints
        (the backend's per-name HyperLogLog turns them into a DISTINCT-
        key estimate, immune to expiry/re-insert churn) and slot-denied
        transients (full-bucket pressure).  `h_mach` is the machinery
        hash column (cascade-diverted lanes zeroed — they had no device
        round).  One sort groups hot lanes by name — no per-name array
        scans (a name-sweep attack makes U ≈ n) — and name strings
        decode lazily, only for threshold-crossing names."""
        if len(entries) == 1:
            names = entries[0].cols.name_hash
        else:
            names = np.concatenate(
                [e.cols.name_hash for e in entries]
            )
        act = h_mach != 0
        ins = act & (foundv == 0) & (persv != 0)
        tra = act & (persv == 0)
        hot = np.flatnonzero(ins | tra)
        if not len(hot):
            return
        order = hot[np.argsort(names[hot], kind="stable")]
        ns = names[order]
        bounds = np.flatnonzero(
            np.concatenate([[True], ns[1:] != ns[:-1]])
        )
        items = []
        first_idx: Dict[int, int] = {}
        for b_i, lo in enumerate(bounds):
            hi = bounds[b_i + 1] if b_i + 1 < len(bounds) else len(order)
            grp = order[lo:hi]
            nh = int(ns[lo])
            first_idx[nh] = int(grp[0])
            items.append((
                nh,
                h_mach[grp[ins[grp]]],
                int(tra[grp].sum()),
            ))

        def decode_names(nh: int) -> str:
            i0 = first_idx[nh]
            off = 0
            for e in entries:
                if i0 < off + e.cols.n:
                    return self._decode_req(
                        e.payload, e.cols, i0 - off
                    ).name
                off += e.cols.n
            raise AssertionError("index outside drain")

        self.s.sketch_backend.note_exact_pressure_batch(
            items, decode_names
        )

    def _repair_cold_store_keys(
        self, backend, uniq, foundv, h, cols_d, sh_all, n_shards, B,
        now_ms, out_arrays,
    ):
        """Post-step Store.get for COLD keys (backend lock held, response
        already fetched): the step's `found` column replaces the pre-step
        residency probe — a warm store drain pays no probe fetch at all.

        A key whose first occurrence missed (`found` False: absent or
        expired, exactly the probe's liveness test) consults the Store
        (algorithms.go:45-51).  Live store state REPAIRS the drain: the
        store row replaces the fresh bucket the step created (load_rows
        overwrites in place on key match — the fresh row's decrements are
        discarded), every occurrence of the key re-runs on the seeded
        row, and the re-run's responses overwrite the originals — the
        final row and responses are bit-identical to the object path's
        seed-then-step.  The optimistic capture pre-dates the repair, so
        the caller refetches it (packed here with the repair responses:
        a COLD drain pays 2 fetches, matching the old probe path; warm
        drains pay 1).  The lone divergence from seed-then-step: under
        full-bucket insert pressure the fresh insert or the repair upsert
        may each go transient — the same acceptable-loss corner every
        insert path shares (architecture.md:5-11).

        Returns None when nothing needed repair, else (new capture
        token, its prefetched int host chunks)."""
        from gubernator_tpu.runtime.backend import (
            _packed_resp_dict,
            fetch_ravel,
        )

        uq, first = np.unique(h, return_index=True)
        fidx = dict(zip(uq.tolist(), first.tolist()))
        fps = list(uniq.keys())
        seeded = backend._store_seed_misses(
            [int(np.int64(fp).view(np.uint64)) for fp in fps],
            [uniq[fp][1] for fp in fps],
            [bool(foundv[fidx[fp]]) for fp in fps],
            now_ms,
        )
        if not seeded:
            return None
        rep_fps = [fps[i] for i in seeded]
        R = np.flatnonzero(np.isin(h, np.array(rep_fps, dtype=np.int64)))
        r_sh = sh_all[R]
        rrnd, rlane, rn = native.assign_rounds(
            h[R], r_sh if n_shards > 1 else None, n_shards, B
        )
        rvals = {"key_hash": h[R]}
        rvals.update({k: v[R] for k, v in cols_d.items()})
        r_rounds, r_order, r_bounds = _build_rounds(
            rvals, rrnd, rlane, r_sh, rn, n_shards, B
        )
        r_resps = backend._dispatch_rounds_locked(r_rounds)
        cap_fps = np.array(
            [fp for fp, v in uniq.items() if v[2] is not None],
            dtype=np.int64,
        )
        cap_token = backend._gather_rows_dispatch(cap_fps, now_ms)
        cap_ints = backend._gather_rows_int_arrays(cap_token)
        hosts = fetch_ravel(list(r_resps) + cap_ints)
        nr = len(r_resps)
        rhost = [_packed_resp_dict(a) for a in hosts[:nr]]
        (status, out_lim, remaining, reset, stored, cachedv,
         stored_st) = out_arrays
        for r_idx in range(rn):
            sub = r_order[r_bounds[r_idx]:r_bounds[r_idx + 1]]
            sel = R[sub]
            hr = rhost[r_idx]
            if n_shards > 1:
                idx = (r_sh[sub], rlane[sub])
            else:
                idx = (rlane[sub],)
            status[sel] = hr["status"][idx]
            out_lim[sel] = hr["limit"][idx]
            remaining[sel] = hr["remaining"][idx]
            reset[sel] = hr["reset_time"][idx]
            stored[sel] = hr["stored"][idx]
            cachedv[sel] = hr["cached"][idx]
            stored_st[sel] = hr["stored_status"][idx]
        return cap_token, hosts[nr:]

    def _build_captured(self, uniq, cap_fps, a, rf) -> list:
        """CacheItems from the packed gather columns (GATHER_ROW_FIELDS
        order) — misses and KIND_CACHED_RESP rows are skipped exactly like
        _read_items_locked."""
        from gubernator_tpu.core.types import Algorithm, CacheItem, Status
        from gubernator_tpu.ops.state import KIND_CACHED_RESP

        out = []
        for j, fp in enumerate(cap_fps):
            if not a[0, j] or a[1, j] == KIND_CACHED_RESP:
                continue
            key, _req, cap_req = uniq[int(fp)]
            algo = Algorithm(int(a[2, j]))
            remaining = (
                float(rf[j]) if algo == Algorithm.LEAKY_BUCKET
                else int(a[5, j])
            )
            out.append((cap_req, CacheItem(
                key=key,
                algorithm=algo,
                expire_at=int(a[9, j]),
                limit=int(a[3, j]),
                duration=int(a[4, j]),
                remaining=remaining,
                created_at=int(a[6, j]),
                status=Status(int(a[7, j])),
                burst=int(a[8, j]),
            )))
        return out

    # -- merge processing (runs on _pool threads via _Coalescer) ---------
    def _sketch_process(self, entries: Sequence["_SketchEntry"]):
        """One CMS dispatch for a drained sketch-entry list (cross-RPC
        coalescing; duplicate keys landing in one device chunk share its
        pre-chunk estimate — the CMS's documented batch-granularity
        approximation).  Dispatch stage: concat + device dispatch under
        the sketch lock; the returned closure is the fetch stage."""
        if len(entries) == 1:
            kh, hh, ll = entries[0].kh, entries[0].hits, entries[0].limits
        else:
            kh = np.concatenate([e.kh for e in entries])
            hh = np.concatenate([e.hits for e in entries])
            ll = np.concatenate([e.limits for e in entries])
        fetch_cols = self.s.sketch_backend.check_cols_begin(kh, hh, ll)
        wait_cols = None
        ring = self._ring_live()
        if ring is not None:
            # Ring discipline: the CMS readback runs on the ring runner
            # (sketch state is independent of the slot table, so FIFO
            # placement is for fetch-offloading, not ordering).
            from gubernator_tpu.runtime.ring import RingClosedError

            try:
                wait_cols = ring.submit_host(fetch_cols)
            except RingClosedError:
                wait_cols = None

        def fetch() -> List[Tuple[np.ndarray, ...]]:
            if wait_cols is not None:
                st, rem, rst = wait_cols()
            else:
                self.blocking_fetches["sketch"] += 1
                st, rem, rst = fetch_cols()
            outs: List[Tuple[np.ndarray, ...]] = []
            off = 0
            for e in entries:
                k = len(e.kh)
                outs.append((st[off:off + k], rem[off:off + k],
                             rst[off:off + k]))
                off += k
            return outs

        return fetch

    def _process(self, entries: Sequence["_Entry"]):
        """Pack -> step for a coalesced entry list (runs on a fast-lane
        pool thread; everything here is numpy/C++/device).  This is the
        DISPATCH stage of the pipelined drain: it returns a zero-arg
        fetch closure (host sync + gather + persistence delivery) that
        the coalescer runs on its fetch stage, so the next merge's
        dispatch overlaps this merge's device->host readback.

        Duplicate-heavy batches (Zipfian hot keys) would otherwise explode
        into one device round PER OCCURRENCE of the hottest key; eligible
        duplicate groups instead take the host-cascade path (_plan_cascade):
        one read lane, an exact host-side replay of the per-occurrence
        algorithm branches, and one effective write-back lane — two rounds
        total regardless of skew."""
        cfg = self.s.backend.cfg
        n_shards = cfg.num_shards
        B = cfg.batch_size

        if len(entries) == 1:
            c = entries[0].cols
            h, hits, lim, dur = c.hash, c.hits, c.limit, c.duration
            algo, burst, behavior = c.algo, c.burst, c.behavior
            is_greg = entries[0].is_greg
            ge, gd = entries[0].greg_expire, entries[0].greg_duration
            use_cached = entries[0].use_cached
        else:
            h = np.concatenate([e.cols.hash for e in entries])
            hits = np.concatenate([e.cols.hits for e in entries])
            lim = np.concatenate([e.cols.limit for e in entries])
            dur = np.concatenate([e.cols.duration for e in entries])
            algo = np.concatenate([e.cols.algo for e in entries])
            burst = np.concatenate([e.cols.burst for e in entries])
            behavior = np.concatenate([e.cols.behavior for e in entries])
            is_greg = np.concatenate([e.is_greg for e in entries])
            ge = np.concatenate([e.greg_expire for e in entries])
            gd = np.concatenate([e.greg_duration for e in entries])
            use_cached = np.concatenate([e.use_cached for e in entries])
        n = len(h)

        burst = np.where(burst == 0, lim, burst)
        reset_remaining = (behavior & int(Behavior.RESET_REMAINING)) != 0

        plan = _plan_cascade(h, hits, reset_remaining, is_greg,
                             lim, dur, algo, burst, use_cached)

        from gubernator_tpu.runtime.backend import packed_rounds_to_host

        backend = self.s.backend
        store = backend.store
        uniq = (
            self._persist_decode(entries)
            if (store is not None or backend._keymap is not None)
            else None
        )
        if uniq and backend._keymap is not None:
            with backend._keymap_lock:
                km = backend._keymap
                for fp, (key, _r, _c) in uniq.items():
                    km[int(np.int64(fp).view(np.uint64))] = key
            backend._maybe_prune_keymap()
        do_store = store is not None and bool(uniq)
        if plan is None:
            h_mach, hits_mach = h, hits
        else:
            h_mach = h.copy()
            hits_mach = hits.copy()
            h_mach[plan.occ] = 0          # divert cascade occurrences
            h_mach[plan.firsts] = h[plan.firsts]  # keep one READ lane
            hits_mach[plan.firsts] = 0

        if n_shards > 1:
            from gubernator_tpu.parallel.mesh import shard_of_hash
            from gubernator_tpu.parallel.sharded import (
                packed_grid_rounds_to_host as to_host,
            )

            sh_all = shard_of_hash(h, n_shards).astype(np.int32)
        else:
            to_host = packed_rounds_to_host
            sh_all = np.zeros(n, dtype=np.int32)
        rnd, lane, n_rounds = native.assign_rounds(
            h_mach, sh_all if n_shards > 1 else None, n_shards, B
        )

        values = dict(
            key_hash=h_mach, hits=hits_mach, limit=lim, duration=dur,
            algo=algo, burst=burst, reset_remaining=reset_remaining,
            is_greg=is_greg, greg_expire=ge, greg_duration=gd,
            use_cached=use_cached,
        )
        # Ring-eligible merge (plain): scatter the parsed columns
        # STRAIGHT into ring slot layout — no DeviceBatch objects exist
        # between the C++ parse and the device loop.  On a mesh backend
        # the scatter targets shard-grid slots ([n_shards, tb] per field
        # row), so the columns land exactly where the shard_map ring
        # step reads them.
        ring = (
            self._ring_live()
            if (plan is None and not do_store)
            else None
        )
        ring_qs = None
        if ring is not None:
            ring_qs, order, bounds = _build_rounds_q(
                values, rnd, lane, n_rounds, backend._tiers,
                sh_all=sh_all if n_shards > 1 else None,
                n_shards=n_shards,
            )
            rounds = [_QRound(ring_qs[i, 10] != 0)
                      for i in range(n_rounds)]
        else:
            rounds, order, bounds = _build_rounds(
                values, rnd, lane, sh_all, n_rounds, n_shards, B
            )

        status = np.zeros(n, dtype=np.int64)
        out_lim = np.zeros(n, dtype=np.int64)
        remaining = np.zeros(n, dtype=np.int64)
        reset = np.zeros(n, dtype=np.int64)
        stored = np.zeros(n, dtype=np.int64)
        cachedv = np.zeros(n, dtype=np.int64)
        stored_st = np.zeros(n, dtype=np.int64)
        foundv = np.zeros(n, dtype=np.int64)
        persv = np.zeros(n, dtype=np.int64)

        def gather(host) -> None:
            for r_idx in range(n_rounds):
                sel = order[bounds[r_idx]:bounds[r_idx + 1]]
                hr = host[r_idx]
                if n_shards > 1:
                    idx = (sh_all[sel], lane[sel])
                else:
                    idx = (lane[sel],)
                status[sel] = hr["status"][idx]
                out_lim[sel] = hr["limit"][idx]
                remaining[sel] = hr["remaining"][idx]
                reset[sel] = hr["reset_time"][idx]
                stored[sel] = hr["stored"][idx]
                cachedv[sel] = hr["cached"][idx]
                stored_st[sel] = hr["stored_status"][idx]
                foundv[sel] = hr["found"][idx]
                persv[sel] = hr["persisted"][idx]

        t_step0 = time.monotonic()
        host_box: List = []  # [host] once the response reaches host

        def finish() -> List[Tuple[np.ndarray, ...]]:
            return self._finish_process(
                entries, host_box[0], rounds, h, h_mach, foundv, persv,
                status, out_lim, remaining, reset, stored, stored_st,
                t_step0,
            )

        if plan is None and not do_store:
            if ring is not None:
                # Ring merge (docs/ring.md): the pre-packed slots enter
                # the request ring and the device loop applies them; this
                # fetch stage only WAITS on the published response slot —
                # the actual device->host readback happens on the ring
                # runner, off the request path entirely.
                from gubernator_tpu.runtime.ring import RingClosedError

                try:
                    wait_rounds = ring.submit_q(ring_qs)
                except RingClosedError:
                    # Broke/closed between the check and the submit,
                    # with NOTHING enqueued: rebuild DeviceBatch rounds
                    # and take the pipelined path below (rare; the ring
                    # never reopens).  A multi-chunk submit that loses
                    # the ring part-way raises PartialSubmitError
                    # instead — deliberately NOT caught here: the
                    # queued chunks' device effects may already have
                    # landed, so re-dispatching would double-apply
                    # them; the error propagates and fails the merge.
                    rounds, order, bounds = _build_rounds(
                        values, rnd, lane, sh_all, n_rounds, n_shards, B
                    )
                else:
                    def fetch_ring() -> List[Tuple[np.ndarray, ...]]:
                        host_box.append(wait_rounds())
                        gather(host_box[0])
                        return finish()

                    return fetch_ring
            # Plain merge: dispatch under the backend lock; the response
            # sync rides the coalescer's FETCH stage, so the next
            # maximal merge dispatches while this one's response syncs
            # (depth bounded by GUBER_PIPELINE_DEPTH).
            fetch_host = backend.step_rounds_begin(
                rounds, add_tally=False
            )

            def fetch_plain() -> List[Tuple[np.ndarray, ...]]:
                host_box.append(fetch_host())
                self.blocking_fetches["mach"] += 1
                gather(host_box[0])
                return finish()

            return fetch_plain

        # Cascade merge: the read -> host replay -> write-back window
        # must not interleave with ANY other step on these keys — from
        # this lane, the object path, or the GLOBAL managers — so the
        # whole window runs under the backend lock (the same
        # single-writer discipline as every other mutation path).  The
        # write-back itself needs no response sync: the replay already
        # produced every response, and dispatch order serializes it.
        #
        # Store drains take this branch too, with NO pre-step
        # residency probe: the step itself answers residency through
        # its `found` column, so a warm drain pays ONE combined
        # response+capture fetch — storeless parity — instead of the
        # probe fetch + combined fetch it used to (algorithms.go:45-51
        # consults the store only on cache miss; misses repair below).
        # The lock is held through the fetch: a cold key was served
        # from a FRESH row that the repair replaces, and no other
        # drain may observe the interim state.  These in-lock fetches
        # belong to the DISPATCH stage by necessity; what moves to the
        # fetch stage is the rf fetch + write-through delivery below.
        cap_token = wt_seq = None
        cap_fps = int_hosts = None

        def locked_merge() -> None:
            # The whole locked window, wrapped so the ring discipline can
            # run it verbatim on the ring runner (submit_host) — its
            # in-lock host syncs then happen off the request path, FIFO
            # with the ring iterations, and write-through tickets keep
            # dispatch order against ring steps.  One nonlocal set: the
            # captures fetch_locked_merge needs.
            nonlocal cap_token, wt_seq, cap_fps, int_hosts
            with backend._lock:
                resps = backend._dispatch_rounds_locked(rounds)
                if plan is not None:
                    host_box.append(to_host(resps))
                    gather(host_box[0])
                    wb = _run_cascade(
                        plan, h, hits, lim, dur, algo, burst,
                        status, out_lim, remaining, reset, stored, cachedv,
                        stored_st,
                    )
                    if wb is not None:
                        (wb_h, wb_hits, wb_lim, wb_dur, wb_algo,
                         wb_burst) = wb
                        wb_sh = (
                            shard_of_hash(wb_h, n_shards).astype(np.int32)
                            if n_shards > 1 else None
                        )
                        wrnd, wlane, wn = native.assign_rounds(
                            wb_h, wb_sh, n_shards, B
                        )
                        m = len(wb_h)
                        wvals = dict(
                            key_hash=wb_h, hits=wb_hits, limit=wb_lim,
                            duration=wb_dur, algo=wb_algo, burst=wb_burst,
                            reset_remaining=np.zeros(m, dtype=bool),
                            is_greg=np.zeros(m, dtype=bool),
                            greg_expire=np.zeros(m, dtype=np.int64),
                            greg_duration=np.zeros(m, dtype=np.int64),
                        )
                        wb_rounds, _, _ = _build_rounds(
                            wvals, wrnd, wlane,
                            wb_sh if wb_sh is not None
                            else np.zeros(m, dtype=np.int32),
                            wn, n_shards, B,
                        )
                        backend._dispatch_rounds_locked(wb_rounds)
                if do_store:
                    from gubernator_tpu.runtime.backend import (
                        _packed_resp_dict,
                        fetch_ravel,
                    )

                    now_ms = backend.clock.millisecond_now()
                    cap_fps = np.array(
                        [fp for fp, v in uniq.items() if v[2] is not None],
                        dtype=np.int64,
                    )
                    # Optimistic capture: dispatched with the step so the
                    # warm path fetches response + capture in ONE
                    # round-trip; a repair below re-dispatches it.
                    cap_token = backend._gather_rows_dispatch(
                        cap_fps, now_ms
                    )
                    cap_ints = backend._gather_rows_int_arrays(cap_token)
                    if plan is None:
                        hosts = fetch_ravel(list(resps) + cap_ints)
                        nr = len(resps)
                        host_box.append(
                            [_packed_resp_dict(hh) for hh in hosts[:nr]]
                        )
                        gather(host_box[0])
                        int_hosts = hosts[nr:]
                    else:
                        int_hosts = fetch_ravel(cap_ints)
                    rep = self._repair_cold_store_keys(
                        backend, uniq, foundv, h, dict(
                            hits=hits, limit=lim, duration=dur, algo=algo,
                            burst=burst, reset_remaining=reset_remaining,
                            is_greg=is_greg, greg_expire=ge,
                            greg_duration=gd, use_cached=use_cached,
                        ),
                        sh_all, n_shards, B, now_ms,
                        (status, out_lim, remaining, reset, stored,
                         cachedv, stored_st),
                    )
                    if rep is not None:
                        # Rows changed under the optimistic capture —
                        # refetch it (packed with the repair responses
                        # inside _repair_cold_store_keys).
                        cap_token, int_hosts = rep
                    wt_seq = backend._wt_ticket()

        ring = self._ring_live()
        wait_locked = None
        if ring is not None:
            # Ring discipline: the locked window (with its in-lock host
            # syncs) runs on the ring runner, FIFO with the ring
            # iterations — the request path only waits on the result.
            from gubernator_tpu.runtime.ring import RingClosedError

            try:
                wait_locked = ring.submit_host(locked_merge)
            except RingClosedError:
                ring = None
        if ring is None:
            self.blocking_fetches["mach"] += 1
            locked_merge()

        def fetch_locked_merge() -> List[Tuple[np.ndarray, ...]]:
            # Fetch stage of a cascade/store merge: the response host
            # sync already happened inside the lock (cascade/repair
            # correctness); what remains is the remaining_f fetch, the
            # capture build, and the Store.on_change delivery — user
            # code plus a ticket wait that must never block the next
            # merge's dispatch.
            if wait_locked is not None:
                wait_locked()
            if do_store:
                from gubernator_tpu.runtime.backend import fetch_ravel

                captured: list = []
                try:
                    rf_hosts = None
                    if bool((algo == 1).any()):
                        # The one residual request-path sync a store
                        # drain keeps in ring mode: the leaky-capture
                        # remaining_f readback (ordering-free, so it
                        # needn't ride the runner).
                        self.blocking_fetches["mach"] += 1
                        rf_hosts = fetch_ravel(
                            backend._gather_rows_rf_arrays(cap_token)
                        )
                    a_cols, rf_col = backend._gather_rows_build(
                        cap_token, len(cap_fps), int_hosts, rf_hosts
                    )
                    captured = self._build_captured(
                        uniq, cap_fps, a_cols, rf_col
                    )
                finally:
                    # The ticket MUST be redeemed even if any fetch
                    # fails (the step already happened; a skipped
                    # redemption wedges every later delivery in
                    # cond.wait) — hence the rf sync sits INSIDE this
                    # try as well.
                    backend._deliver_write_through(captured, wt_seq)
            return finish()

        return fetch_locked_merge

    def _finish_process(
        self, entries, host, rounds, h, h_mach, foundv, persv,
        status, out_lim, remaining, reset, stored, stored_st, t_step0,
    ) -> List[Tuple[np.ndarray, ...]]:
        """Shared tail of a machinery merge's fetch stage: tallies,
        flight-recorder record, spill pressure, the GLOBAL capture-
        validity mask, and the per-entry split."""
        from gubernator_tpu.runtime.backend import (
            Tally,
            tally_from_rounds,
        )

        backend = self.s.backend
        n = len(h)
        # Metric parity: checks/over-limit from the per-REQUEST outputs
        # (cascade occurrences never had their own device lane); cache
        # hit/miss + eviction tallies from the device rounds.
        valid = h != 0
        t = tally_from_rounds(rounds, host)
        n_over = int((status[valid] == 1).sum())
        backend._add_tally(Tally(
            checks=int(valid.sum()),
            over_limit=n_over,
            not_persisted=t.not_persisted,
            cache_hits=t.cache_hits,
        ))
        fr = getattr(self.s.metrics, "flightrec", None)
        if fr is not None:
            fr.record_batch(
                int(valid.sum()), (time.monotonic() - t_step0) * 1e3,
                over_limit=n_over, kind="fastlane_drain",
            )

        # Gubstat per-tenant ledger: same validity stance as the tally
        # above (per-request status column, errored lanes masked).
        # Fast-lane traffic is plane-direct — derived shadow keys are
        # only synthesized on the object path — and name strings decode
        # lazily, at most once per newly-admitted tenant.
        ta = getattr(self.s, "tenants", None)
        if ta is not None:
            if len(entries) == 1:
                t_names = entries[0].cols.name_hash
                t_hits = entries[0].cols.hits
            else:
                t_names = np.concatenate(
                    [e.cols.name_hash for e in entries]
                )
                t_hits = np.concatenate([e.cols.hits for e in entries])

            def _decode_tenant(i: int):
                off2 = 0
                for e in entries:
                    if i < off2 + e.cols.n:
                        return self._decode_req(
                            e.payload, e.cols, i - off2
                        ).name
                    off2 += e.cols.n
                return None

            ta.record_fast(t_names, t_hits, status, valid, _decode_tenant)

        sb = self.s.sketch_backend
        if sb is not None and sb.spill_enabled:
            # h_mach, not h: cascade-diverted duplicate occurrences never
            # got a device lane — their persv stays 0 and raw h would
            # count them as fake transients (a healthy hot key would
            # self-degrade under Zipfian traffic).
            self._note_spill_pressure(entries, h_mach, foundv, persv)

        # GLOBAL broadcast capture validity, judged over the WHOLE merged
        # drain (entries are concurrent RPCs; a per-entry view would miss
        # another RPC's later occurrence of the same key): a lane may
        # capture only if it is its key's LAST mutating occurrence in the
        # merge.  Judged here — not at queue time — because entries queue
        # their updates in COMPLETION order (remote forwards differ in
        # latency), so a stale earlier occurrence could otherwise
        # overwrite a fresh capture; with this mask it degrades to
        # (req, None) instead, and the flush re-reads.  h == 0 lanes
        # (errored) mutate nothing and never capture.
        cap_ok = np.zeros(n, dtype=bool)
        mut_idx = np.flatnonzero(h != 0)
        if len(mut_idx):
            last_of: Dict[int, int] = {}
            for j in mut_idx:
                last_of[int(h[j])] = int(j)
            cap_ok[list(last_of.values())] = True

        # Split back per entry (stored/stored_status/cap_ok feed the
        # GLOBAL broadcast capture; see _queue_global_updates).
        outs: List[Tuple[np.ndarray, ...]] = []
        off = 0
        for e in entries:
            k = e.cols.n
            outs.append((
                status[off:off + k], out_lim[off:off + k],
                remaining[off:off + k], reset[off:off + k],
                stored[off:off + k], stored_st[off:off + k],
                cap_ok[off:off + k],
            ))
            off += k
        return outs

    async def close(self) -> None:
        # Machinery first (its in-flight dispatches may still fan into
        # the sketch lane), then the sketch lane; both refuse new work
        # the moment their close() starts.  The ring closes AFTER the
        # coalescers: their in-flight fetch stages wait on ring slots,
        # so the runner must stay alive until they drain (ring.close
        # then publishes/fails whatever is left).
        await self._mach.close()
        if self._sketch_lane is not None:
            await self._sketch_lane.close()
        if self._engine_lane is not None:
            await self._engine_lane.close()
        if self._ring is not None:
            self._ring.close()
        self._pool.shutdown(wait=True)
        self._sketch_pool.shutdown(wait=True)
        self._engine_pool.shutdown(wait=True)


class _Entry:
    """Machinery-lane coalescer entry (fut assigned by _Coalescer.do)."""

    __slots__ = (
        "payload", "cols", "is_greg", "greg_expire", "greg_duration",
        "use_cached", "fut", "trace_ctx",
    )

    def __init__(self, payload, cols, is_greg, greg_expire, greg_duration,
                 use_cached):
        self.payload = payload
        self.cols = cols
        self.is_greg = is_greg
        self.greg_expire = greg_expire
        self.greg_duration = greg_duration
        self.use_cached = use_cached
        self.fut = None
        self.trace_ctx = None


class _SketchEntry:
    """Sketch-lane coalescer entry (fut assigned by _Coalescer.do)."""

    __slots__ = ("kh", "hits", "limits", "fut", "trace_ctx")

    def __init__(self, kh, hits, limits):
        self.kh = kh
        self.hits = hits
        self.limits = limits
        self.fut = None
        self.trace_ctx = None


class _EngineEntry:
    """Engine-lane coalescer entry (fut assigned by _Coalescer.do)."""

    __slots__ = (
        "payload", "cols", "idx", "is_greg", "ge", "gd", "fut",
        "trace_ctx",
    )

    def __init__(self, payload, cols, idx, is_greg, ge, gd):
        self.payload = payload
        self.cols = cols
        self.idx = idx
        self.is_greg = is_greg
        self.ge = ge
        self.gd = gd
        self.fut = None
        self.trace_ctx = None


def _build_rounds(values, rnd, lane, sh_all, n_rounds, n_shards, B):
    """Scatter columnar values into fixed-shape DeviceBatch rounds.
    Returns (rounds, order, bounds) — order/bounds group request indices
    by round for the response gather."""
    ok = np.flatnonzero(rnd >= 0)
    order = ok[np.argsort(rnd[ok], kind="stable")]
    bounds = np.searchsorted(rnd[order], np.arange(n_rounds + 1))
    rounds: List[DeviceBatch] = []
    for r_idx in range(n_rounds):
        grid = _empty_batch((n_shards, B))
        sel = order[bounds[r_idx]:bounds[r_idx + 1]]
        s_m, l_m = sh_all[sel], lane[sel]
        for f, v in values.items():
            getattr(grid, f)[s_m, l_m] = v[sel]
        grid.active[s_m, l_m] = True
        rounds.append(
            grid if n_shards > 1 else DeviceBatch(*[a[0] for a in grid])
        )
    return rounds, order, bounds


# Ring slot row order == DeviceBatch field order == unpack_batch_q rows.
_Q_ROW = {
    f: i for i, f in enumerate((
        "key_hash", "hits", "limit", "duration", "algo", "burst",
        "reset_remaining", "is_greg", "greg_expire", "greg_duration",
        "active", "use_cached",
    ))
}


class _QRound:
    """tally_from_rounds-compatible view of one prepacked ring slot
    (only `.active` is ever read on the ring path)."""

    __slots__ = ("active",)

    def __init__(self, active: np.ndarray) -> None:
        self.active = active


def _build_rounds_q(values, rnd, lane, n_rounds, tiers,
                    sh_all=None, n_shards=1):
    """Scatter columnar values STRAIGHT into ring slot layout — one
    int64[k, 12, tb] stacked request block (pack_batch_q row order), or
    int64[k, 12, n_shards, tb] on a mesh backend, where the parser's
    columns land in shard-grid slots with one scatter per field —
    skipping DeviceBatch assembly entirely.  Returns (qs, order, bounds)
    with order/bounds exactly as _build_rounds computes them."""
    ok = np.flatnonzero(rnd >= 0)
    order = ok[np.argsort(rnd[ok], kind="stable")]
    bounds = np.searchsorted(rnd[order], np.arange(n_rounds + 1))
    # Lanes fill contiguously from 0 per (round, shard) (assign_rounds),
    # so the max assigned lane bounds the highest used one — the same
    # compiled-tier rule as backend.tier_of.
    occ = int(lane[ok].max()) + 1 if len(ok) else 0
    tb = next((t for t in tiers if occ <= t), tiers[-1])
    grid = n_shards > 1
    shape = (n_rounds, 12, n_shards, tb) if grid else (n_rounds, 12, tb)
    qs = np.zeros(shape, dtype=np.int64)
    for r_idx in range(n_rounds):
        sel = order[bounds[r_idx]:bounds[r_idx + 1]]
        l_m = lane[sel]
        q = qs[r_idx]
        idx = (sh_all[sel], l_m) if grid else (l_m,)
        for f, v in values.items():
            q[(_Q_ROW[f],) + idx] = v[sel]
        q[(_Q_ROW["active"],) + idx] = 1
    return qs, order, bounds


class _CascadePlan:
    __slots__ = ("occ", "firsts", "groups", "inv", "first_idx")

    def __init__(self, occ, firsts, groups, inv, first_idx):
        self.occ = occ          # bool[n]: occurrence is in a cascade group
        self.firsts = firsts    # int[-]: first-occurrence index per group
        self.groups = groups    # int[-]: group ids (into inv's codomain)
        self.inv = inv          # int[n]: np.unique inverse (key group id)
        self.first_idx = first_idx    # int[nb]: first occurrence per group


def _plan_cascade(h, hits, reset_remaining, is_greg, lim, dur, algo, burst,
                  use_cached):
    """Pick duplicate-key groups the host can serve without one device
    round per occurrence.

    Exact-cascade groups: >1 occurrence of a key where every occurrence
    has positive hits, no RESET_REMAINING, no Gregorian duration, and
    identical limit/duration/algorithm/burst.  use_cached (GLOBAL
    non-owner) groups qualify too when the flag is UNIFORM across the
    group — the replay branches on the read lane's `cached` flag: a
    verbatim broadcast-row serve copies to every occurrence (the device
    mutates nothing on such reads), while a pre-broadcast bucket runs
    the standard lattice replay.  The per-occurrence branch order of
    the kernel (over-at-zero / exact / over-more / under) is a pure
    function of the running remaining, replayable on host from the
    read lane's post-step `stored` value.

    Mixed cached/uncached groups (ownership changed mid-stream) and
    everything else keep the round-per-occurrence machinery."""
    uniq, first_idx, inv, counts = np.unique(
        h, return_index=True, return_inverse=True, return_counts=True
    )
    dup = (counts > 1) & (uniq != 0)
    if not dup.any():
        return None
    nb = len(uniq)
    same = np.ones(nb, dtype=bool)
    for arr in (lim, dur, burst, algo.astype(np.int64)):
        diff = arr != arr[first_idx][inv]
        same &= np.bincount(
            inv, weights=diff.astype(np.float64), minlength=nb
        ) == 0
    cached_mixed = (
        use_cached != use_cached[first_idx][inv]
    )
    same &= np.bincount(
        inv, weights=cached_mixed.astype(np.float64), minlength=nb
    ) == 0

    bad_occ = (hits <= 0) | reset_remaining | is_greg
    grp_bad = np.bincount(
        inv, weights=bad_occ.astype(np.float64), minlength=nb
    ) > 0
    casc = dup & ~grp_bad & same

    if not casc.any():
        return None
    return _CascadePlan(
        occ=casc[inv],
        firsts=first_idx[casc],
        groups=np.flatnonzero(casc),
        inv=inv,
        first_idx=first_idx,
    )


def _run_cascade(plan, h, hits, lim, dur, algo, burst,
                 status, out_lim, remaining, reset, stored, cachedv,
                 stored_st=None):
    """Replay each cascade group's occurrences on host, writing their
    responses in place, and build the effective write-back columns.

    The replay is bit-exact against the kernel for eligible groups:
    token (algorithms.go:162-195) and leaky (algorithms.go:395-426) share
    the branch lattice over the running remaining, and leaky's float
    fraction is invariant under integer-hit subtraction so the integer
    `stored` seed suffices.  A read lane answered VERBATIM from a live
    broadcast row (`cachedv`, the GLOBAL non-owner steady state) copies
    its response to every occurrence with no write-back — the device
    mutates nothing on such reads, so each occurrence would read the
    identical row.  Two deliberate, documented divergences:
    the table's sticky Status field holds the write-back's value rather
    than the last occurrence's, and a fully-drained leaky group's expiry
    refresh rides an over-limit touch lane."""
    wb_h: List[int] = []
    wb_hits: List[int] = []
    wb_lim: List[int] = []
    wb_dur: List[int] = []
    wb_algo: List[int] = []
    wb_burst: List[int] = []

    # Occurrence lists per group, in arrival order, via one argsort.
    order = np.argsort(plan.inv, kind="stable")
    sorted_inv = plan.inv[order]
    for g in plan.groups:
        lo = np.searchsorted(sorted_inv, g)
        hi = np.searchsorted(sorted_inv, g, side="right")
        occ = order[lo:hi]
        fi = occ[0]
        if cachedv[fi]:
            # Verbatim broadcast-row serve: share, mutate nothing.
            rest = occ[1:]
            status[rest] = status[fi]
            out_lim[rest] = out_lim[fi]
            remaining[rest] = remaining[fi]
            reset[rest] = reset[fi]
            continue
        lim0 = int(lim[fi])
        algo0 = int(algo[fi])
        reset0 = int(reset[fi])
        r0 = int(stored[fi])
        leaky = algo0 == 1
        rate_i = int(float(dur[fi]) / float(lim0)) if (leaky and lim0) else 0
        # Token status is STICKY: under/exact occurrences report the
        # STORED status (te_resp_status = s_status in the kernel), which
        # only flips to OVER on an over-at-zero hit.  The read lane's
        # response status IS the stored status.  Leaky reports fresh.
        st0 = int(status[fi])
        flip = False  # an over-at-zero occurred (token stored -> OVER)
        r = r0
        for i in occ:
            hc = int(hits[i])
            if r == 0:
                if not leaky and not flip:
                    flip = True  # sticky stored-status transition
                    st0 = 1
                st, rr = 1, r
            elif r == hc:
                r = 0
                st, rr = (0 if leaky else st0), 0
            elif hc > r:
                st, rr = 1, r
            else:
                r -= hc
                st, rr = (0 if leaky else st0), r
            status[i] = st
            out_lim[i] = lim0
            remaining[i] = rr
            reset[i] = reset0 + (r0 - rr) * rate_i if leaky else reset0
        # Post-replay stored columns (the GLOBAL broadcast capture reads
        # the LAST occurrence): running remaining, and the sticky token
        # status st0 with replay flips applied (leaky stores UNDER).
        stored[occ] = r
        if stored_st is not None:
            stored_st[occ] = 0 if leaky else st0

        def wb_lane(h_val: int) -> None:
            wb_h.append(int(h[fi]))
            wb_hits.append(h_val)
            wb_lim.append(lim0)
            wb_dur.append(int(dur[fi]))
            wb_algo.append(algo0)
            wb_burst.append(int(burst[fi]))

        eff = r0 - r
        if eff > 0:
            wb_lane(eff)
        elif leaky:
            # Over-limit "touch": refreshes the sliding expiry the way
            # every nonzero-hit occurrence does, mutating nothing else.
            wb_lane(int(burst[fi]) + 1)
        if flip:
            # Reproduce the stored-status flip on device: after the eff
            # lane drained the bucket to 0, one more hit is over-at-zero
            # — it stores OVER and mutates nothing else (a later batch's
            # under-branch response reports this stored status, so
            # skipping it would diverge from the object path).
            wb_lane(1)
    if not wb_h:
        return None
    return (
        np.array(wb_h, dtype=np.int64),
        np.array(wb_hits, dtype=np.int64),
        np.array(wb_lim, dtype=np.int64),
        np.array(wb_dur, dtype=np.int64),
        np.array(wb_algo, dtype=np.int32),
        np.array(wb_burst, dtype=np.int64),
    )
