"""Hot-key detection: owner-pressure-gated promotion into an exact hot-set.

A zipfian workload funnels its hottest keys onto single ring owners;
the breaker/degraded plane (docs/resilience.md) only reacts once an
owner is DEAD, while an overloaded-but-alive owner keeps absorbing the
whole cluster's hottest traffic until its p99 blows through the SLO.
This module is the detection half of the survival plane
(docs/hotkeys.md): every node tracks the per-key rate of the traffic
it routes in a host-side count-min sketch (`HostCMS`,
runtime/sketch_backend.py — the CMS tier's estimator on the host) and
promotes keys into a small EXACT hot-set when their pressure score

    score(key) = estimated hits/s (this node's local view)
                 x owner SLO-pressure ratio (p99 / target)

stays past ``GUBER_HOTKEY_THRESHOLD`` for ``promote_windows``
consecutive windows, demoting after ``demote_windows`` windows below —
hysteresis, so a key hovering at the threshold cannot flap the set.

The pressure factor is the 1909.08969 gate: with the owner healthy the
ratio is 0, every score is 0, and NOTHING ever promotes — mirroring
and its bounded over-admission are provably inactive until pressure is
measured.  Owner pressure arrives per peer on RPC trailing metadata
(net/peer_client.py) or, for keys this node owns, from its own flight
recorder (runtime/flightrec.py); the tracker only sees it through the
``pressure_fn`` callback the service wires.

Threading: `observe()` runs on the event loop (object path) and on
fast-lane drains; all mutable state sits under ``_lock`` —
``hotkey._lock`` in the gubguard global lock ranking
(docs/invariants.md), acquired while holding nothing and holding
nothing else inside.  The hot-set is additionally published as an
atomically swapped frozenset + int64 array so ``is_hot`` and the
fast-lane mask need no lock at all.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

# A mirror check serves `<unique_key>` + this suffix from its own local
# slot, so mirror admission state never collides with the real key's
# rows (the SHADOW_SUFFIX convention, runtime/service.py).
MIRROR_SUFFIX = ".hot-mirror"

# Pressure ratios are clamped here before entering scores: a wildly
# breached SLO must not promote every key in sight, and the candidate
# admission floor (threshold / cap) stays meaningful.
RATIO_CAP = 8.0

_EMPTY_I64 = np.empty(0, dtype=np.int64)


def fp64(h: int) -> int:
    """Normalize a 64-bit fingerprint to the int64 (two's-complement)
    form the device columns and this tracker use."""
    return int(np.int64(np.uint64(h & 0xFFFFFFFFFFFFFFFF)))


class HotKeyTracker:
    """Windowed CMS + hysteresis hot-set (one per service instance)."""

    def __init__(
        self,
        cfg,
        metrics=None,
        time_fn: Callable[[], float] = time.monotonic,
        depth: int = 4,
        width: int = 4096,
    ) -> None:
        from gubernator_tpu.runtime.sketch_backend import HostCMS

        self.cfg = cfg
        self.metrics = metrics
        self._time = time_fn
        self._lock = threading.Lock()
        self._cms = HostCMS(depth=depth, width=width)
        self._win_start: Optional[float] = None
        self._window_idx = 0
        # Candidate fingerprints whose CMS estimate crossed the
        # admission floor THIS window (scored at the roll; bounded).
        self._cand: set = set()
        # fp -> [consecutive over-threshold windows, last window index]
        self._streak: Dict[int, List[int]] = {}
        # fp -> {"since", "miss", "score"} for promoted keys.
        self._hot: Dict[int, Dict] = {}
        # Lock-free read views, swapped atomically on change.
        self.hot_set: frozenset = frozenset()
        self.hot_arr: np.ndarray = _EMPTY_I64
        self.version = 0
        self.promotions = 0
        self.demotions = 0
        # fp -> owner pressure ratio (service wires _owner_pressure_of;
        # tests wire a constant).  None scores everything 0.
        self.pressure_fn: Optional[Callable[[int], float]] = None
        # Called (outside the lock) with the demoted fingerprints so
        # the service can drop their mirror slots.
        self.on_demote: Optional[Callable[[List[int]], None]] = None
        # Optional fp -> key-string labels for debug_vars (fed by the
        # mirror path, which has the decoded request anyway).
        self._names: Dict[int, str] = {}
        # Candidate admission floor: a key cannot score >= threshold
        # unless its windowed count reaches threshold*window/RATIO_CAP
        # (the ratio is clamped), so admitting only such keys loses
        # nothing while bounding the exact-count set.
        self._floor = max(
            1.0, cfg.threshold * cfg.window_s / RATIO_CAP
        )
        self._cand_cap = max(4 * cfg.max_hot, 256)

    # -- hot path (lock-free) --------------------------------------------
    def is_hot(self, fp: int) -> bool:
        return fp in self.hot_set

    # -- producers -------------------------------------------------------
    def observe(
        self, key_hashes: np.ndarray, hits: np.ndarray
    ) -> None:
        """One routed batch: int64 fingerprints + per-request hits.
        Zero fingerprints (the parser's error sentinel) are ignored;
        each request weighs max(hits, 1) — a read still costs the owner
        a served request.  Rolls the window when its boundary passed."""
        if not self.cfg.enabled or not len(key_hashes):
            return
        now = self._time()
        events = None
        with self._lock:
            self._roll_locked(now)
            valid = key_hashes != 0
            kh = key_hashes[valid] if not valid.all() else key_hashes
            if not len(kh):
                return
            w = np.maximum(
                hits[valid] if not valid.all() else hits, 1
            )
            self._cms.update(kh, w)
            if len(self._cand) < self._cand_cap:
                est = self._cms.estimate(kh)
                for fp in kh[est >= self._floor]:
                    self._cand.add(int(fp))
                    if len(self._cand) >= self._cand_cap:
                        break
            events = self._pending_events
            self._pending_events = None
        if events:
            self._fire(events)

    _pending_events = None  # (promoted, demoted) staged under the lock

    def poll(self) -> None:
        """Roll the window with no traffic (idle demotion; also the
        debug endpoints' refresh): a hot-set must collapse after the
        skew stops even if nothing arrives to trigger observe()."""
        if not self.cfg.enabled:
            return
        events = None
        with self._lock:
            self._roll_locked(self._time())
            events = self._pending_events
            self._pending_events = None
        if events:
            self._fire(events)

    def note_name(self, fp: int, key: str) -> None:
        """Label a fingerprint for debug output (bounded; best effort)."""
        if len(self._names) < 4 * self.cfg.max_hot:
            self._names[fp] = key

    # -- window machinery (under _lock) ----------------------------------
    def _roll_locked(self, now: float) -> None:
        if self._win_start is None:
            self._win_start = now
            return
        w = self.cfg.window_s
        elapsed = now - self._win_start
        if elapsed < w:
            return
        promoted, demoted = self._evaluate_locked()
        idle = int(elapsed // w) - 1
        if idle > 0:
            # Windows with zero observe() calls are zero-score windows:
            # every hot key misses them, every streak breaks.
            demoted.extend(self._idle_locked(idle))
            self._streak.clear()
        self._win_start = now - (elapsed % w)
        self._window_idx += 1 + max(idle, 0)
        self._cms.clear()
        self._cand.clear()
        if promoted or demoted:
            self._publish_locked()
            self._pending_events = (promoted, demoted)

    def _evaluate_locked(self):
        thr = self.cfg.threshold
        pf = self.pressure_fn
        widx = self._window_idx
        scores: Dict[int, float] = {}
        for fp in self._cand:
            rate = self._cms.estimate_one(fp) / self.cfg.window_s
            ratio = 0.0
            if pf is not None:
                ratio = min(max(pf(fp), 0.0), RATIO_CAP)
            scores[fp] = rate * ratio
        promoted: List[int] = []
        demoted: List[int] = []
        # Demotion: a hot key scoring under the threshold (including
        # keys with no traffic at all this window) accrues misses.
        for fp, st in list(self._hot.items()):
            sc = scores.get(fp, 0.0)
            st["score"] = sc
            if sc >= thr:
                st["miss"] = 0
            else:
                st["miss"] += 1
                if st["miss"] >= self.cfg.demote_windows:
                    del self._hot[fp]
                    demoted.append(fp)
                    self.demotions += 1
        # Promotion: consecutive over-threshold windows.
        for fp, sc in scores.items():
            if fp in self._hot:
                continue
            if sc < thr:
                self._streak.pop(fp, None)
                continue
            st = self._streak.get(fp)
            run = st[0] + 1 if st is not None and st[1] == widx - 1 else 1
            if run >= self.cfg.promote_windows:
                if len(self._hot) < self.cfg.max_hot:
                    self._hot[fp] = {
                        "since": self._time(), "miss": 0, "score": sc,
                    }
                    promoted.append(fp)
                    self.promotions += 1
                    self._streak.pop(fp, None)
                # At capacity the streak holds, ready to promote the
                # moment a slot frees.
                else:
                    self._streak[fp] = [run, widx]
            else:
                self._streak[fp] = [run, widx]
        # Streaks that skipped a window are stale.
        for fp, st in list(self._streak.items()):
            if st[1] < widx - 1:
                del self._streak[fp]
        return promoted, demoted

    def _idle_locked(self, k: int) -> List[int]:
        demoted: List[int] = []
        for fp, st in list(self._hot.items()):
            st["miss"] += k
            st["score"] = 0.0
            if st["miss"] >= self.cfg.demote_windows:
                del self._hot[fp]
                demoted.append(fp)
                self.demotions += 1
        return demoted

    def _publish_locked(self) -> None:
        self.hot_set = frozenset(self._hot)
        self.hot_arr = (
            np.fromiter(self._hot, dtype=np.int64, count=len(self._hot))
            if self._hot else _EMPTY_I64
        )
        self.version += 1

    # -- event fan-out (outside the lock) --------------------------------
    def _fire(self, events) -> None:
        promoted, demoted = events
        m = self.metrics
        if m is not None:
            if promoted:
                m.hotkey_promotions.inc(len(promoted))
            if demoted:
                m.hotkey_demotions.inc(len(demoted))
            m.hotkey_hot_keys.set(len(self.hot_set))
            fr = getattr(m, "flightrec", None)
            if fr is not None:
                for fp in promoted:
                    fr.record(
                        "hotkey", event="promote", fp="%016x" % (fp &
                        0xFFFFFFFFFFFFFFFF),
                        key=self._names.get(fp, ""),
                    )
                for fp in demoted:
                    fr.record(
                        "hotkey", event="demote", fp="%016x" % (fp &
                        0xFFFFFFFFFFFFFFFF),
                        key=self._names.get(fp, ""),
                    )
        if demoted and self.on_demote is not None:
            self.on_demote(demoted)

    # -- observability ---------------------------------------------------
    def debug_vars(self) -> Dict:
        with self._lock:
            hot = {
                "%016x" % (fp & 0xFFFFFFFFFFFFFFFF): {
                    "key": self._names.get(fp, ""),
                    "score": round(st["score"], 1),
                    "miss_windows": st["miss"],
                }
                for fp, st in self._hot.items()
            }
        return {
            "enabled": self.cfg.enabled,
            "threshold": self.cfg.threshold,
            "hot": hot,
            "hot_keys": len(hot),
            "promotions": self.promotions,
            "demotions": self.demotions,
            "window_s": self.cfg.window_s,
            "promote_windows": self.cfg.promote_windows,
            "demote_windows": self.cfg.demote_windows,
        }
