"""Client SDK (the analog of reference client.go:31-104 and the generated
python client, python/gubernator/__init__.py).

Three tiers over the same wire contract, all working against any
wire-compatible daemon (gubernator-tpu or the reference service):

  V1Client / AsyncV1Client   object clients (python protobuf), hardened
                             with tuned channel options and a default
                             RPC deadline — `timeout=None` forever-hangs
                             are opt-in, never the default;
  FastV1Client               the compiled lane: request batches are
                             serialized and responses unmarshalled by
                             the native codec (native/gubtpu.cpp) over a
                             raw-bytes gRPC method, so a check never
                             constructs a python protobuf object —
                             attacking the ~1.3ms of python client
                             machinery the BENCH_E2E artifacts measure;
  LeasedClient / AsyncLeasedClient
                             client-side admission (docs/leases.md;
                             arXiv:2510.04516): a bounded local
                             allowance granted by each key's owner is
                             burned with ZERO RPCs, refreshed in the
                             background below a low-water mark,
                             reconciled on an interval, and degraded
                             transparently to per-call GetRateLimits on
                             refusal, expiry, or non-leasable behaviors.
"""
from __future__ import annotations

import asyncio
import random
import string
import threading
import time
import uuid
from dataclasses import dataclass, replace as dc_replace
from typing import Dict, List, Optional, Sequence, Tuple

import grpc
import grpc.aio

from gubernator_tpu.core.config import LeaseConfig, lease_config_from_env
from gubernator_tpu.core.types import (
    HealthCheckResp,
    LeaseGrant,
    RateLimitReq,
    RateLimitResp,
    ReconcileItem,
    Status,
)
from gubernator_tpu.net import grpc_api
from gubernator_tpu.proto import gubernator_pb2 as pb

# Duration constants in milliseconds (client.go:31-35).
MILLISECOND = 1
SECOND = 1000 * MILLISECOND
MINUTE = 60 * SECOND

# Default per-RPC deadline.  The old default (timeout=None) hangs a
# caller forever against a wedged daemon or a black-holed connection —
# the worst failure mode for a rate-limit check, which callers sit on
# their serving paths.  Pass timeout=None explicitly to opt back in.
DEFAULT_RPC_TIMEOUT_S = 30.0

# Tuned channel defaults for every client in this module: keepalive
# probes detect half-dead connections (NAT idle reaps, silent peer
# death) instead of letting the next check eat a full deadline, and the
# 4MB message caps match the daemon's own receive cap (daemon.py) so a
# count-capped batch with long keys never fails asymmetrically.
DEFAULT_CHANNEL_OPTIONS: Tuple[Tuple[str, int], ...] = (
    ("grpc.keepalive_time_ms", 60_000),
    ("grpc.keepalive_timeout_ms", 10_000),
    ("grpc.http2.max_pings_without_data", 0),
    ("grpc.keepalive_permit_without_calls", 1),
    ("grpc.max_receive_message_length", 4 * 1024 * 1024),
    ("grpc.max_send_message_length", 4 * 1024 * 1024),
)


def channel_options(
    extra: Optional[Sequence[Tuple[str, int]]] = None,
) -> List[Tuple[str, int]]:
    """DEFAULT_CHANNEL_OPTIONS merged with caller overrides (an option
    named in `extra` replaces the default of the same name)."""
    if not extra:
        return list(DEFAULT_CHANNEL_OPTIONS)
    names = {k for k, _ in extra}
    return [
        (k, v) for k, v in DEFAULT_CHANNEL_OPTIONS if k not in names
    ] + list(extra)


def hash_key(r: RateLimitReq) -> str:
    """Canonical cache key (client.go:37-39)."""
    return r.hash_key()


def to_timestamp(ms_from_now: float) -> int:
    """Unix-ms timestamp `ms_from_now` in the future (client.go:69-74)."""
    return int(time.time() * 1000) + int(ms_from_now)


def from_timestamp(ts_ms: int) -> float:
    """Milliseconds until `ts_ms` (client.go:77-85)."""
    return max(0.0, ts_ms - time.time() * 1000)


def sleep_until_reset(reset_time_ms: int) -> None:
    """Block until a rate limit resets (python client helper,
    python/gubernator/__init__.py:14-21)."""
    time.sleep(from_timestamp(reset_time_ms) / 1000.0)


def random_string(prefix: str = "", n: int = 10) -> str:
    """Test helper (client.go:88-95)."""
    return prefix + "".join(
        random.choices(string.ascii_letters + string.digits, k=n)
    )


class V1Client:
    """Synchronous object client."""

    def __init__(
        self,
        address: str = "localhost:1051",
        credentials: Optional[grpc.ChannelCredentials] = None,
        options: Optional[Sequence[Tuple[str, int]]] = None,
    ) -> None:
        opts = channel_options(options)
        if credentials is not None:
            self._channel = grpc.secure_channel(
                address, credentials, options=opts
            )
        else:
            self._channel = grpc.insecure_channel(address, options=opts)
        self._stub = grpc_api.V1Stub(self._channel)

    def get_rate_limits(
        self,
        reqs: Sequence[RateLimitReq],
        timeout: Optional[float] = DEFAULT_RPC_TIMEOUT_S,
    ) -> List[RateLimitResp]:
        resp = self._stub.GetRateLimits(
            pb.GetRateLimitsReq(
                requests=[grpc_api.req_to_pb(r) for r in reqs]
            ),
            timeout=timeout,
        )
        return [grpc_api.resp_from_pb(m) for m in resp.responses]

    def health_check(
        self, timeout: Optional[float] = DEFAULT_RPC_TIMEOUT_S
    ) -> HealthCheckResp:
        return grpc_api.health_from_pb(
            self._stub.HealthCheck(pb.HealthCheckReq(), timeout=timeout)
        )

    def close(self) -> None:
        self._channel.close()

    def __enter__(self) -> "V1Client":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class AsyncV1Client:
    """asyncio object client."""

    def __init__(
        self,
        address: str = "localhost:1051",
        credentials: Optional[grpc.ChannelCredentials] = None,
        options: Optional[Sequence[Tuple[str, int]]] = None,
    ) -> None:
        opts = channel_options(options)
        if credentials is not None:
            self._channel = grpc.aio.secure_channel(
                address, credentials, options=opts
            )
        else:
            self._channel = grpc.aio.insecure_channel(
                address, options=opts
            )
        self._stub = grpc_api.V1Stub(self._channel)

    async def get_rate_limits(
        self,
        reqs: Sequence[RateLimitReq],
        timeout: Optional[float] = DEFAULT_RPC_TIMEOUT_S,
    ) -> List[RateLimitResp]:
        resp = await self._stub.GetRateLimits(
            pb.GetRateLimitsReq(
                requests=[grpc_api.req_to_pb(r) for r in reqs]
            ),
            timeout=timeout,
        )
        return [grpc_api.resp_from_pb(m) for m in resp.responses]

    async def health_check(
        self, timeout: Optional[float] = DEFAULT_RPC_TIMEOUT_S
    ) -> HealthCheckResp:
        return grpc_api.health_from_pb(
            await self._stub.HealthCheck(pb.HealthCheckReq(), timeout=timeout)
        )

    async def close(self) -> None:
        await self._channel.close()


# --------------------------------------------------------------------------
# Compiled client path (native/gubtpu.cpp)
# --------------------------------------------------------------------------

def _parse_meta(payload: bytes, off: int, ln: int) -> Dict[str, str]:
    """Decode a ParsedResps metadata span (concatenated field-6 map-entry
    wire frames) into a dict — rare (forwarded-owner / tier tags), so a
    small python walk is fine."""
    out: Dict[str, str] = {}
    p, end = off, off + ln

    def varint(p: int) -> Tuple[int, int]:
        v = s = 0
        while True:
            b = payload[p]
            p += 1
            v |= (b & 0x7F) << s
            if not (b & 0x80):
                return v, p
            s += 7

    try:
        while p < end:
            tag, p = varint(p)
            sz, p = varint(p)
            q, qend = p, p + sz
            p = qend
            key = value = ""
            while q < qend:
                t, q = varint(q)
                l, q = varint(q)
                if (t >> 3) == 1:
                    key = payload[q:q + l].decode("utf-8", "replace")
                elif (t >> 3) == 2:
                    value = payload[q:q + l].decode("utf-8", "replace")
                q += l
            if key:
                out[key] = value
    except IndexError:
        pass  # malformed span — return what decoded
    return out


class FastV1Client:
    """Synchronous compiled client: request batches serialize and
    responses unmarshal in the native C++ codec over a raw-bytes gRPC
    method, so a check never builds a python protobuf object.  Falls
    back to python-protobuf encoding transparently when the native
    library is unavailable (`native.available()` reports which lane is
    live — the `codec` attribute names it honestly)."""

    def __init__(
        self,
        address: str = "localhost:1051",
        credentials: Optional[grpc.ChannelCredentials] = None,
        options: Optional[Sequence[Tuple[str, int]]] = None,
    ) -> None:
        from gubernator_tpu import native

        self._native = native
        self.codec = "native" if native.available() else "python"
        opts = channel_options(options)
        if credentials is not None:
            self._channel = grpc.secure_channel(
                address, credentials, options=opts
            )
        else:
            self._channel = grpc.insecure_channel(address, options=opts)
        # Raw bytes both ways: serialization happens in the codec, not
        # in grpc's (de)serializer hooks.
        self._call = self._channel.unary_unary(
            f"/{grpc_api.V1_SERVICE}/GetRateLimits"
        )

    def encode(self, reqs: Sequence[RateLimitReq]) -> bytes:
        payload = self._native.encode_reqs(reqs)
        if payload is None:
            payload = pb.GetRateLimitsReq(
                requests=[grpc_api.req_to_pb(r) for r in reqs]
            ).SerializeToString()
        return payload

    def decode(self, raw: bytes) -> List[RateLimitResp]:
        cols = self._native.parse_resps(raw)
        if cols is None:
            msg = pb.GetRateLimitsResp.FromString(raw)
            return [grpc_api.resp_from_pb(m) for m in msg.responses]
        # One bulk host conversion per column (these are numpy parser
        # outputs; tolist() beats n scalar __getitem__ round trips).
        status = cols.status.tolist()
        limit = cols.limit.tolist()
        remaining = cols.remaining.tolist()
        reset_time = cols.reset_time.tolist()
        err_off = cols.err_off.tolist()
        err_len = cols.err_len.tolist()
        meta_off = cols.meta_off.tolist()
        meta_len = cols.meta_len.tolist()
        out: List[RateLimitResp] = []
        for i in range(cols.n):
            err = ""
            if err_len[i]:
                o, l = err_off[i], err_len[i]
                err = raw[o:o + l].decode("utf-8", "replace")
            meta: Dict[str, str] = {}
            if meta_len[i] > 0:
                meta = _parse_meta(raw, meta_off[i], meta_len[i])
            out.append(RateLimitResp(
                status=Status(status[i]),
                limit=limit[i],
                remaining=remaining[i],
                reset_time=reset_time[i],
                error=err,
                metadata=meta,
            ))
        return out

    def get_rate_limits(
        self,
        reqs: Sequence[RateLimitReq],
        timeout: Optional[float] = DEFAULT_RPC_TIMEOUT_S,
    ) -> List[RateLimitResp]:
        raw = self._call(self.encode(reqs), timeout=timeout)
        return self.decode(raw)

    def get_rate_limits_raw(
        self,
        payload: bytes,
        timeout: Optional[float] = DEFAULT_RPC_TIMEOUT_S,
    ) -> bytes:
        """Pre-encoded request bytes in, raw response bytes out — for
        callers that cache an encoded batch (steady repeated loads)."""
        return self._call(payload, timeout=timeout)

    def close(self) -> None:
        self._channel.close()

    def __enter__(self) -> "FastV1Client":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# --------------------------------------------------------------------------
# Client-side admission (docs/leases.md)
# --------------------------------------------------------------------------

@dataclass
class _ClientLease:
    allowance: int
    allowance_left: int
    expires_at: int  # unix ms
    reset_time: int
    limit: int


@dataclass
class _LeaseStats:
    checks: int = 0
    local_admitted: int = 0
    fallback_checks: int = 0
    check_rpcs: int = 0
    lease_rpcs: int = 0
    reconcile_rpcs: int = 0
    reconcile_dropped_hits: int = 0
    refusals: int = 0

    @property
    def rpcs(self) -> int:
        return self.check_rpcs + self.lease_rpcs + self.reconcile_rpcs

    def as_dict(self) -> Dict[str, int]:
        d = {f: getattr(self, f) for f in (
            "checks", "local_admitted", "fallback_checks", "check_rpcs",
            "lease_rpcs", "reconcile_rpcs", "reconcile_dropped_hits",
            "refusals",
        )}
        d["rpcs"] = self.rpcs
        return d


# How long a refused key stays degraded to per-call checks before the
# client asks again (prevents a refusal storm against a shedding owner).
_REFUSAL_COOLDOWN_S = 1.0


class _LeaseTable:
    """The transport-agnostic half of a leased client: grant state,
    local burn, low-water/renewal bookkeeping, burned-hit take.  All
    methods are quick dict work under one lock — safe from both a sync
    caller thread and an asyncio loop."""

    def __init__(self, cfg: LeaseConfig) -> None:
        self.cfg = cfg
        self._lock = threading.Lock()
        self._leases: Dict[str, _ClientLease] = {}
        self._templates: Dict[str, RateLimitReq] = {}
        self._burned: Dict[str, int] = {}
        self._wanted: Dict[str, RateLimitReq] = {}
        self._refused_until: Dict[str, float] = {}
        # Keys this client was EVER granted and has not yet released:
        # a later refusal (e.g. a failed renewal) drops the local lease
        # entry, but the owner still holds the grant until its TTL —
        # close() must release these too.
        self._granted: set = set()
        self.stats = _LeaseStats()

    @staticmethod
    def leasable(r: RateLimitReq) -> bool:
        from gubernator_tpu.runtime.lease import NON_LEASABLE

        return (
            bool(r.unique_key)
            and bool(r.name)
            and r.limit > 0
            and r.hits > 0
            and not (int(r.behavior) & int(NON_LEASABLE))
        )

    def try_burn(self, r: RateLimitReq) -> Optional[RateLimitResp]:
        """Admit `r` from the local allowance — the zero-RPC path.
        None means the caller must fall back to a per-call check (and a
        grant was queued for the background refresher if the limit is
        leasable at all)."""
        now_ms = int(time.time() * 1000)
        with self._lock:
            self.stats.checks += 1
            if not self.leasable(r):
                self.stats.fallback_checks += 1
                return None
            key = r.hash_key()
            lease = self._leases.get(key)
            if lease is not None and lease.expires_at <= now_ms:
                # Expired grants burn nothing (the owner already
                # re-collects the slot on its sweep).
                self._leases.pop(key, None)
                lease = None
            if lease is None or lease.allowance_left < r.hits:
                self._note_want_locked(key, r)
                self.stats.fallback_checks += 1
                return None
            lease.allowance_left -= r.hits
            self._burned[key] = self._burned.get(key, 0) + r.hits
            self._templates.setdefault(key, dc_replace(r, hits=0))
            if lease.allowance_left < lease.allowance * self.cfg.low_water:
                self._note_want_locked(key, r)
            self.stats.local_admitted += 1
            return RateLimitResp(
                status=Status.UNDER_LIMIT,
                limit=r.limit,
                remaining=lease.allowance_left,
                reset_time=lease.reset_time,
                metadata={"lease": "local"},
            )

    def _note_want_locked(self, key: str, r: RateLimitReq) -> None:
        if time.monotonic() < self._refused_until.get(key, 0.0):
            return
        self._wanted.setdefault(key, dc_replace(r, hits=0))

    def needs_refresh(self) -> bool:
        with self._lock:
            return bool(self._wanted)

    def take_work(
        self, reconcile_due: bool = False,
    ) -> Tuple[List[RateLimitReq], List[ReconcileItem]]:
        """(lease requests, reconcile items) for one background tick.
        Burned counters are TAKEN only when a reconcile is due — a
        failed reconcile then drops them (at-most-once; the owner may
        have applied a mid-RPC failure's hits already, and the carve
        slot bounds admission regardless).  A wanted key that also has
        burned counts to report rides the reconcile as a renew=True
        item (the renewal piggyback — one RPC refreshes AND reconciles)
        instead of a separate Lease call."""
        with self._lock:
            items: List[ReconcileItem] = []
            burned: Dict[str, int] = {}
            if reconcile_due:
                burned, self._burned = self._burned, {}
            for key, hits in burned.items():
                tmpl = self._templates.get(key)
                if tmpl is None:
                    continue
                renew = key in self._wanted
                if renew:
                    self._wanted.pop(key, None)
                items.append(ReconcileItem(
                    request=dc_replace(tmpl, hits=hits), renew=renew
                ))
            wanted = list(self._wanted.values())
            self._wanted.clear()
            return wanted, items

    def drop_burn(self, items: List[ReconcileItem]) -> None:
        with self._lock:
            for it in items:
                self.stats.reconcile_dropped_hits += it.request.hits

    def apply_grants(self, grants: List[LeaseGrant]) -> None:
        now = time.monotonic()
        with self._lock:
            for g in grants:
                if not g.key:
                    continue
                if g.granted:
                    self._granted.add(g.key)
                    self._leases[g.key] = _ClientLease(
                        allowance=g.allowance,
                        allowance_left=g.allowance,
                        expires_at=g.expires_at,
                        reset_time=g.reset_time,
                        limit=g.limit,
                    )
                    self._refused_until.pop(g.key, None)
                elif g.refusal and g.refusal != "released":
                    self.stats.refusals += 1
                    self._refused_until[g.key] = (
                        now + _REFUSAL_COOLDOWN_S
                    )
                    self._leases.pop(g.key, None)

    def release_items(self) -> List[ReconcileItem]:
        """Final reconcile payload: remaining burned counts + a release
        for every held grant (the graceful-shutdown path)."""
        with self._lock:
            items: List[ReconcileItem] = []
            burned, self._burned = self._burned, {}
            keys = set(burned) | set(self._leases) | self._granted
            for key in keys:
                tmpl = self._templates.get(key)
                if tmpl is None:
                    continue
                items.append(ReconcileItem(
                    request=dc_replace(tmpl, hits=burned.get(key, 0)),
                    release=True,
                ))
            self._leases.clear()
            self._granted.clear()
            self._wanted.clear()
            return items

    def debug_vars(self) -> dict:
        with self._lock:
            return {
                "stats": self.stats.as_dict(),
                "leases": {
                    k: {
                        "allowance_left": v.allowance_left,
                        "expires_at": v.expires_at,
                    }
                    for k, v in self._leases.items()
                },
            }


class LeasedClient:
    """Synchronous leased client: checks burn a locally held allowance
    with ZERO RPCs; a background thread acquires grants for new keys,
    refreshes them below the low-water mark, and reconciles burned hits
    on `reconcile_ms`.  Anything the lease plane cannot serve — refused
    or expired grants, non-leasable behaviors, hits past the remaining
    allowance — degrades transparently to per-call GetRateLimits.

    `lease` knob defaults come from the lease env knobs
    (core.config.lease_config_from_env; deploy/example.conf's lease
    section), so a client deploys with the same one-config-surface
    discipline as the daemon."""

    def __init__(
        self,
        address: str = "localhost:1051",
        credentials: Optional[grpc.ChannelCredentials] = None,
        options: Optional[Sequence[Tuple[str, int]]] = None,
        client_id: Optional[str] = None,
        lease: Optional[LeaseConfig] = None,
    ) -> None:
        self.client_id = client_id or f"leased-{uuid.uuid4().hex[:12]}"
        cfg = lease or lease_config_from_env()
        self.table = _LeaseTable(cfg)
        opts = channel_options(options)
        if credentials is not None:
            self._channel = grpc.secure_channel(
                address, credentials, options=opts
            )
        else:
            self._channel = grpc.insecure_channel(address, options=opts)
        self._v1 = grpc_api.V1Stub(self._channel)
        self._peers = grpc_api.PeersV1Stub(self._channel)
        self._closed = False
        self._wake = threading.Event()
        self._last_reconcile = time.monotonic()
        self._thread = threading.Thread(
            target=self._run, name="leased-client", daemon=True
        )
        self._thread.start()

    # -- checks ----------------------------------------------------------
    def get_rate_limits(
        self,
        reqs: Sequence[RateLimitReq],
        timeout: Optional[float] = DEFAULT_RPC_TIMEOUT_S,
    ) -> List[RateLimitResp]:
        out: List[Optional[RateLimitResp]] = [None] * len(reqs)
        fallback: List[int] = []
        for i, r in enumerate(reqs):
            resp = self.table.try_burn(r)
            if resp is not None:
                out[i] = resp
            else:
                fallback.append(i)
        if self.table.needs_refresh():
            self._wake.set()
        if fallback:
            self.table.stats.check_rpcs += 1
            resp = self._v1.GetRateLimits(
                pb.GetRateLimitsReq(requests=[
                    grpc_api.req_to_pb(reqs[i]) for i in fallback
                ]),
                timeout=timeout,
            )
            for i, m in zip(fallback, resp.responses):
                out[i] = grpc_api.resp_from_pb(m)
        return [r if r is not None else RateLimitResp() for r in out]

    # -- background lease/reconcile loop ---------------------------------
    def _run(self) -> None:
        interval = self.table.cfg.reconcile_ms / 1000.0
        while not self._closed:
            # Wake early for low-water refreshes / new wanted keys; the
            # timeout is the reconcile cadence.
            self._wake.wait(timeout=interval / 4)
            self._wake.clear()
            if self._closed:
                break
            try:
                self._tick()
            except Exception:  # noqa: BLE001 — keep the cadence
                time.sleep(min(interval, 0.2))

    def _tick(self) -> None:
        now = time.monotonic()
        interval = self.table.cfg.reconcile_ms / 1000.0
        due = now - self._last_reconcile >= interval
        wanted, items = self.table.take_work(reconcile_due=due)
        if wanted:
            self.table.stats.lease_rpcs += 1
            try:
                resp = self._peers.Lease(
                    _lease_req_pb(self.client_id, wanted),
                    timeout=DEFAULT_RPC_TIMEOUT_S,
                )
                self.table.apply_grants([
                    grpc_api.lease_grant_from_pb(g) for g in resp.grants
                ])
            except Exception:  # noqa: BLE001 — degrade, retry later
                pass
        if due:
            self._last_reconcile = now
            if items:
                self.table.stats.reconcile_rpcs += 1
                try:
                    resp = self._peers.Reconcile(
                        _reconcile_req_pb(self.client_id, items),
                        timeout=DEFAULT_RPC_TIMEOUT_S,
                    )
                    self.table.apply_grants([
                        grpc_api.lease_grant_from_pb(g)
                        for g in resp.grants
                    ])
                except Exception:  # noqa: BLE001 — at-most-once: drop
                    self.table.drop_burn(items)

    # -- lifecycle -------------------------------------------------------
    def stats(self) -> Dict[str, int]:
        return self.table.stats.as_dict()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._wake.set()
        self._thread.join(timeout=5.0)
        items = self.table.release_items()
        if items:
            try:
                self._peers.Reconcile(
                    _reconcile_req_pb(self.client_id, items),
                    timeout=DEFAULT_RPC_TIMEOUT_S,
                )
            except Exception:  # noqa: BLE001 — owner sweeps anyway
                self.table.drop_burn(items)
        self._channel.close()

    def __enter__(self) -> "LeasedClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class AsyncLeasedClient:
    """asyncio twin of LeasedClient: same _LeaseTable engine, with the
    grant/reconcile loop as a background task on the caller's loop."""

    def __init__(
        self,
        address: str = "localhost:1051",
        credentials: Optional[grpc.ChannelCredentials] = None,
        options: Optional[Sequence[Tuple[str, int]]] = None,
        client_id: Optional[str] = None,
        lease: Optional[LeaseConfig] = None,
    ) -> None:
        self.client_id = client_id or f"leased-{uuid.uuid4().hex[:12]}"
        cfg = lease or lease_config_from_env()
        self.table = _LeaseTable(cfg)
        opts = channel_options(options)
        if credentials is not None:
            self._channel = grpc.aio.secure_channel(
                address, credentials, options=opts
            )
        else:
            self._channel = grpc.aio.insecure_channel(
                address, options=opts
            )
        self._v1 = grpc_api.V1Stub(self._channel)
        self._peers = grpc_api.PeersV1Stub(self._channel)
        self._closed = False
        self._wake: Optional[asyncio.Event] = None
        self._task: Optional[asyncio.Task] = None
        self._last_reconcile = time.monotonic()

    def _ensure_loop(self) -> None:
        if self._task is None:
            self._wake = asyncio.Event()
            self._task = asyncio.ensure_future(self._run())

    async def get_rate_limits(
        self,
        reqs: Sequence[RateLimitReq],
        timeout: Optional[float] = DEFAULT_RPC_TIMEOUT_S,
    ) -> List[RateLimitResp]:
        self._ensure_loop()
        out: List[Optional[RateLimitResp]] = [None] * len(reqs)
        fallback: List[int] = []
        for i, r in enumerate(reqs):
            resp = self.table.try_burn(r)
            if resp is not None:
                out[i] = resp
            else:
                fallback.append(i)
        if self.table.needs_refresh() and self._wake is not None:
            self._wake.set()
        if fallback:
            self.table.stats.check_rpcs += 1
            resp = await self._v1.GetRateLimits(
                pb.GetRateLimitsReq(requests=[
                    grpc_api.req_to_pb(reqs[i]) for i in fallback
                ]),
                timeout=timeout,
            )
            for i, m in zip(fallback, resp.responses):
                out[i] = grpc_api.resp_from_pb(m)
        return [r if r is not None else RateLimitResp() for r in out]

    async def _run(self) -> None:
        interval = self.table.cfg.reconcile_ms / 1000.0
        while not self._closed:
            try:
                await asyncio.wait_for(
                    self._wake.wait(), timeout=interval / 4
                )
            except asyncio.TimeoutError:
                pass
            self._wake.clear()
            if self._closed:
                break
            try:
                await self._tick()
            except Exception:  # noqa: BLE001 — keep the cadence
                await asyncio.sleep(min(interval, 0.2))

    async def _tick(self) -> None:
        now = time.monotonic()
        interval = self.table.cfg.reconcile_ms / 1000.0
        due = now - self._last_reconcile >= interval
        wanted, items = self.table.take_work(reconcile_due=due)
        if wanted:
            self.table.stats.lease_rpcs += 1
            try:
                resp = await self._peers.Lease(
                    _lease_req_pb(self.client_id, wanted),
                    timeout=DEFAULT_RPC_TIMEOUT_S,
                )
                self.table.apply_grants([
                    grpc_api.lease_grant_from_pb(g) for g in resp.grants
                ])
            except Exception:  # noqa: BLE001
                pass
        if due:
            self._last_reconcile = now
            if items:
                self.table.stats.reconcile_rpcs += 1
                try:
                    resp = await self._peers.Reconcile(
                        _reconcile_req_pb(self.client_id, items),
                        timeout=DEFAULT_RPC_TIMEOUT_S,
                    )
                    self.table.apply_grants([
                        grpc_api.lease_grant_from_pb(g)
                        for g in resp.grants
                    ])
                except Exception:  # noqa: BLE001
                    self.table.drop_burn(items)

    def stats(self) -> Dict[str, int]:
        return self.table.stats.as_dict()

    async def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._task is not None:
            self._wake.set()
            self._task.cancel()
            await asyncio.gather(self._task, return_exceptions=True)
            self._task = None
        items = self.table.release_items()
        if items:
            try:
                await self._peers.Reconcile(
                    _reconcile_req_pb(self.client_id, items),
                    timeout=DEFAULT_RPC_TIMEOUT_S,
                )
            except Exception:  # noqa: BLE001
                self.table.drop_burn(items)
        await self._channel.close()


def _lease_req_pb(client_id: str, reqs: Sequence[RateLimitReq]):
    from gubernator_tpu.proto import peers_pb2

    return peers_pb2.LeaseReq(
        client_id=client_id,
        requests=[grpc_api.req_to_pb(r) for r in reqs],
    )


def _reconcile_req_pb(client_id: str, items: Sequence[ReconcileItem]):
    from gubernator_tpu.proto import peers_pb2

    return peers_pb2.ReconcileReq(
        client_id=client_id,
        items=[grpc_api.reconcile_item_to_pb(it) for it in items],
    )
