"""Client SDK (the analog of reference client.go:31-104 and the generated
python client, python/gubernator/__init__.py).

Sync and async variants over the same wire stubs; works against any
wire-compatible daemon (gubernator-tpu or the reference service).
"""
from __future__ import annotations

import random
import string
import time
from typing import List, Optional, Sequence

import grpc
import grpc.aio

from gubernator_tpu.core.types import (
    HealthCheckResp,
    RateLimitReq,
    RateLimitResp,
)
from gubernator_tpu.net import grpc_api
from gubernator_tpu.proto import gubernator_pb2 as pb

# Duration constants in milliseconds (client.go:31-35).
MILLISECOND = 1
SECOND = 1000 * MILLISECOND
MINUTE = 60 * SECOND


def hash_key(r: RateLimitReq) -> str:
    """Canonical cache key (client.go:37-39)."""
    return r.hash_key()


def to_timestamp(ms_from_now: float) -> int:
    """Unix-ms timestamp `ms_from_now` in the future (client.go:69-74)."""
    return int(time.time() * 1000) + int(ms_from_now)


def from_timestamp(ts_ms: int) -> float:
    """Milliseconds until `ts_ms` (client.go:77-85)."""
    return max(0.0, ts_ms - time.time() * 1000)


def sleep_until_reset(reset_time_ms: int) -> None:
    """Block until a rate limit resets (python client helper,
    python/gubernator/__init__.py:14-21)."""
    time.sleep(from_timestamp(reset_time_ms) / 1000.0)


def random_string(prefix: str = "", n: int = 10) -> str:
    """Test helper (client.go:88-95)."""
    return prefix + "".join(
        random.choices(string.ascii_letters + string.digits, k=n)
    )


class V1Client:
    """Synchronous client."""

    def __init__(
        self,
        address: str = "localhost:1051",
        credentials: Optional[grpc.ChannelCredentials] = None,
    ) -> None:
        if credentials is not None:
            self._channel = grpc.secure_channel(address, credentials)
        else:
            self._channel = grpc.insecure_channel(address)
        self._stub = grpc_api.V1Stub(self._channel)

    def get_rate_limits(
        self,
        reqs: Sequence[RateLimitReq],
        timeout: Optional[float] = None,
    ) -> List[RateLimitResp]:
        resp = self._stub.GetRateLimits(
            pb.GetRateLimitsReq(
                requests=[grpc_api.req_to_pb(r) for r in reqs]
            ),
            timeout=timeout,
        )
        return [grpc_api.resp_from_pb(m) for m in resp.responses]

    def health_check(
        self, timeout: Optional[float] = None
    ) -> HealthCheckResp:
        return grpc_api.health_from_pb(
            self._stub.HealthCheck(pb.HealthCheckReq(), timeout=timeout)
        )

    def close(self) -> None:
        self._channel.close()

    def __enter__(self) -> "V1Client":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class AsyncV1Client:
    """asyncio client."""

    def __init__(
        self,
        address: str = "localhost:1051",
        credentials: Optional[grpc.ChannelCredentials] = None,
    ) -> None:
        if credentials is not None:
            self._channel = grpc.aio.secure_channel(address, credentials)
        else:
            self._channel = grpc.aio.insecure_channel(address)
        self._stub = grpc_api.V1Stub(self._channel)

    async def get_rate_limits(
        self,
        reqs: Sequence[RateLimitReq],
        timeout: Optional[float] = None,
    ) -> List[RateLimitResp]:
        resp = await self._stub.GetRateLimits(
            pb.GetRateLimitsReq(
                requests=[grpc_api.req_to_pb(r) for r in reqs]
            ),
            timeout=timeout,
        )
        return [grpc_api.resp_from_pb(m) for m in resp.responses]

    async def health_check(
        self, timeout: Optional[float] = None
    ) -> HealthCheckResp:
        return grpc_api.health_from_pb(
            await self._stub.HealthCheck(pb.HealthCheckReq(), timeout=timeout)
        )

    async def close(self) -> None:
        await self._channel.close()
