"""Scenario runner (docs/loadgen.md): boots (or targets) a cluster,
precomputes every phase's arrival schedule, drives them open-loop,
applies fault hooks at phase boundaries, and ends in the scenario's
merged-ledger verdict plus a BENCH_E2E-compatible artifact.

The runner is the composition point: schedule.py plans, engine.py
dispatches and records, spec.py/scenarios.py decide pass/fail, and
report.py shapes the proof into an artifact bench_gate can gate on.
"""
from __future__ import annotations

import asyncio
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..core.config import LoadConfig
from ..runtime.metrics import HdrRecorder
from . import report, schedule
from .engine import PhaseTracker, open_loop
from .scenarios import CONF_OVERRIDES, SCENARIOS, hot_key_index
from .spec import PhaseSpec, RunContext, ScenarioSpec


def resolve_scenario(name: str) -> ScenarioSpec:
    try:
        return SCENARIOS[name]
    except KeyError:
        raise ValueError(
            f"unknown scenario {name!r} (GUBER_LOAD_SCENARIO / "
            f"--scenario): one of {sorted(SCENARIOS)}"
        ) from None


def scaled_phases(
    spec: ScenarioSpec, cfg: LoadConfig
) -> List[Tuple[PhaseSpec, float, float]]:
    """(phase, actual_duration_s, target_rps): phase durations are
    nominal weights rescaled so the whole scenario spans
    GUBER_LOAD_DURATION; rps defaults to GUBER_LOAD_TARGET_RPS."""
    total = sum(p.duration_s for p in spec.phases)
    scale = cfg.duration_s / total
    return [
        (p, p.duration_s * scale, p.target_rps or cfg.target_rps)
        for p in spec.phases
    ]


def build_schedules(
    spec: ScenarioSpec, cfg: LoadConfig
) -> List[schedule.Schedule]:
    """Every phase's plan, precomputed before the first RPC — seeds
    derived per phase from the one GUBER_LOAD_SEED, so identical seeds
    reproduce identical arrival times AND key draws."""
    return [
        schedule.build(
            p.arrivals, p.keys,
            schedule.derive_seed(cfg.seed, f"{spec.name}/{i}/{p.name}"),
            rps, dur, spec.key_universe, p.params,
        )
        for i, (p, dur, rps) in enumerate(scaled_phases(spec, cfg))
    ]


def _dump_flightrec(cluster, reason: str) -> None:
    for d in cluster.daemons:
        if d.flightrec is not None:
            path = cluster.run(d.flightrec.dump(reason))
            print(f"flightrec dump ({d.grpc_address}): {path}")


def run_scenario(
    name: str,
    cfg: LoadConfig,
    cluster=None,
    addresses: Optional[Sequence[str]] = None,
    profile_dir: Optional[str] = None,
    num_daemons: int = 2,
) -> Dict:
    """Run one scenario end to end and return
    {"verdict", "artifact", "phase_stats", ...}.  Raises
    AssertionError when the scenario's ledger verdict fails.

    `cluster`: an existing testing.Cluster to drive (kept running).
    `addresses`: external daemon addresses — only scenarios whose
    hooks/verdicts don't need in-process daemons can run this way.
    Neither: boots its own in-process `num_daemons` cluster.
    """
    spec = resolve_scenario(name)
    if addresses and spec.needs_cluster:
        raise ValueError(
            f"scenario {name!r} needs an in-process cluster (fault "
            "hooks / breaker introspection) and cannot drive external "
            "addresses"
        )

    scheds = build_schedules(spec, cfg)
    phases = scaled_phases(spec, cfg)

    from ..testing import ChaosInjector, ChaosPlan

    injector = ChaosInjector(ChaosPlan(seed=cfg.seed))
    injector.set_active(False)  # armed only by fault hooks

    own_cluster = False
    conf = None
    if cluster is None and not addresses:
        from ..core.config import DaemonConfig
        from ..testing import Cluster

        overrides = CONF_OVERRIDES.get(name, dict)()
        conf = DaemonConfig(
            chaos=injector,
            flightrec=True,
            flightrec_dir=os.environ.get(
                "GUBER_FLIGHTREC_DIR", "flightrec-dumps"
            ),
            **overrides,
        )
        cluster = Cluster.start_with(
            list(spec.datacenters) or [""] * num_daemons,
            conf_template=conf,
        )
        own_cluster = True
    elif cluster is not None:
        conf = cluster.daemons[0].conf
        inj = getattr(conf, "chaos", None)
        if inj is not None:
            injector = inj

    addrs = list(addresses) if addresses else cluster.addresses()
    ctx = RunContext(spec, cfg, cluster, injector, addrs)
    ctx.state["conf_template"] = conf
    ctx.state["hot_key_idx"] = hot_key_index(spec, scheds)

    latency = {p.name: HdrRecorder() for p, _, _ in phases}
    skew = HdrRecorder()
    tracker = PhaseTracker(
        spec.name,
        daemons=ctx.daemons,
        profile_dir=profile_dir,
    )
    wall: Dict[str, float] = {}

    async def drive() -> None:
        from ..client import AsyncV1Client
        from ..core.types import RateLimitReq, Status

        clients = [
            AsyncV1Client(addrs[i % len(addrs)])
            for i in range(max(1, min(cfg.clients, 64)))
        ]
        n_sent = 0

        async def send(key_idx: int) -> bool:
            nonlocal n_sent
            n_sent += 1
            c = clients[n_sent % len(clients)]
            r = (await c.get_rate_limits([
                RateLimitReq(
                    name=spec.tenant,
                    unique_key=spec.key_name(key_idx),
                    hits=1, limit=spec.limit,
                    duration=spec.window_ms,
                )
            ], timeout=5.0))[0]
            if r.error != "":
                raise RuntimeError(r.error)
            return r.status == Status.UNDER_LIMIT

        try:
            for (p, dur, rps), sched in zip(phases, scheds):
                tracker.enter(p.name, profile=p.profile)
                if p.fault is not None:
                    await spec.hooks[p.fault](ctx)
                t0 = time.monotonic()
                ctx.counts_by_phase[p.name] = await open_loop(
                    send, sched, latency[p.name], skew
                )
                wall[p.name] = time.monotonic() - t0
            tracker.exit()
        finally:
            tracker.exit()
            for c in clients:
                await c.close()

    t_run = time.monotonic()
    try:
        if cluster is not None:
            # Drive on the cluster's own loop: grpc.aio channels and
            # the daemons' servers then share one poller (a second
            # loop's poller races grpc's completion queue into benign
            # but noisy BlockingIOError callbacks).
            cluster.run(drive(), timeout=cfg.duration_s * 10 + 120.0)
        else:
            asyncio.run(drive())
        verdict = spec.verdict(ctx)
    except BaseException:
        if own_cluster:
            _dump_flightrec(cluster, f"load-{name}-failure")
        raise
    finally:
        if own_cluster:
            cluster.stop()
    total_wall = time.monotonic() - t_run

    overall = HdrRecorder()
    for h in latency.values():
        overall.merge(h)

    phase_stats = {
        p.name: {
            "arrivals": len(sched),
            "intended_rps": round(len(sched) / dur, 1) if dur else 0.0,
            "wall_s": round(wall.get(p.name, 0.0), 3),
            "recorder": latency[p.name],
        }
        for (p, dur, rps), sched in zip(phases, scheds)
    }

    artifact = report.build_artifact(
        spec=spec, cfg=cfg, verdict=verdict, overall=overall,
        skew=skew, phase_stats=phase_stats, total_wall_s=total_wall,
    )
    return {
        "scenario": spec.name,
        "seed": cfg.seed,
        "verdict": verdict,
        "artifact": artifact,
        "phase_stats": phase_stats,
        "overall": overall,
        "skew": skew,
    }
