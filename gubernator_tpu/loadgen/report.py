"""BENCH_E2E-compatible artifact rows for scenario runs
(docs/loadgen.md).

The artifact is the same shape bench_e2e.py emits — a top-level
platform-honest label plus one JSON line per result — so
scripts/bench_gate.py gates scenario runs with the same machinery:
per-scenario keys (config, scenario, phase, platform), p50 regression
past the threshold + noise floor fails, a scenario key with no
baseline warns instead of hard-failing on first appearance.

Every row carries the OPEN-LOOP percentiles (latency from intended
send) and the run's intended-vs-actual send skew, so a reader can
tell a slow server from a lagging generator.
"""
from __future__ import annotations

from typing import Dict

LOAD_CONFIG = "load_scenario"

# Required fields of a scenario artifact row (load_smoke validates).
ROW_REQUIRED = (
    "config", "scenario", "phase", "platform",
    "p50_ms", "p99_ms", "p999_ms", "checks_per_sec",
    "arrivals", "send_skew_p99_ms",
)


def _platform() -> str:
    """The ACTUAL jax platform (platform honesty: a cpu artifact must
    never gate a tpu recording as if hardware were comparable)."""
    try:
        import jax

        return jax.default_backend()
    except Exception:
        return "unknown"


def _ms(v_s: float) -> float:
    return round(v_s * 1e3, 3)


def _row(scenario: str, phase: str, platform: str, recorder,
         arrivals: int, wall_s: float, skew) -> Dict:
    p50, p99, p999 = recorder.percentiles((0.50, 0.99, 0.999))
    return {
        "config": LOAD_CONFIG,
        "scenario": scenario,
        "phase": phase,
        "platform": platform,
        "p50_ms": _ms(p50),
        "p99_ms": _ms(p99),
        "p999_ms": _ms(p999),
        "checks_per_sec": round(arrivals / wall_s, 1) if wall_s else 0.0,
        "arrivals": arrivals,
        "send_skew_p99_ms": _ms(skew.percentile(0.99)),
        "open_loop": True,
    }


def build_artifact(spec, cfg, verdict: Dict, overall, skew,
                   phase_stats: Dict, total_wall_s: float) -> Dict:
    """The artifact dict: top-level platform + note, one row per phase
    plus the overall row (per-phase budget split rides the phase rows'
    wall_share)."""
    platform = _platform()
    rows = []
    total_arrivals = sum(s["arrivals"] for s in phase_stats.values())
    for phase, stats in phase_stats.items():
        row = _row(
            spec.name, phase, platform, stats["recorder"],
            stats["arrivals"], stats["wall_s"], skew,
        )
        row["intended_rps"] = stats["intended_rps"]
        row["wall_s"] = stats["wall_s"]
        row["wall_share"] = (
            round(stats["wall_s"] / total_wall_s, 3)
            if total_wall_s else 0.0
        )
        rows.append(row)
    overall_row = _row(
        spec.name, "overall", platform, overall,
        total_arrivals, total_wall_s, skew,
    )
    overall_row["seed"] = cfg.seed
    overall_row["verdict"] = {
        k: v for k, v in verdict.items()
        if isinstance(v, (int, float, str, bool))
    }
    rows.append(overall_row)
    return {
        "harness": (
            f"gubernator-tpu-gubload --scenario {spec.name} "
            f"--seed {cfg.seed} --duration {cfg.duration_s} "
            f"--target-rps {cfg.target_rps}"
        ),
        "platform": platform,
        "note": (
            "open-loop scenario run (docs/loadgen.md): latency from "
            "INTENDED send time against a precomputed seeded arrival "
            "schedule — coordinated-omission-free; the verdict block "
            "is the merged /debug/vars ledger proof of the admission "
            "bound this run operated under."
        ),
        "results": rows,
    }


def validate_row(row: Dict) -> None:
    """Schema check for one scenario row (load_smoke's gate)."""
    missing = [f for f in ROW_REQUIRED if f not in row]
    if missing:
        raise AssertionError(
            f"scenario artifact row missing fields {missing}: {row}"
        )
    for f in ("p50_ms", "p99_ms", "p999_ms", "checks_per_sec",
              "send_skew_p99_ms"):
        if not isinstance(row[f], (int, float)):
            raise AssertionError(
                f"scenario artifact row field {f!r} is not numeric: "
                f"{row[f]!r}"
            )
    if row["config"] != LOAD_CONFIG:
        raise AssertionError(
            f"scenario row config {row['config']!r} != {LOAD_CONFIG!r}"
        )
