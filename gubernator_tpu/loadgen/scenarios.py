"""The scenario library (docs/loadgen.md): seven declarative open-loop
scenarios, each ending in a pass/fail verdict asserted from the merged
/debug/vars ledger — admission bounds exactly, shed/over-admission
attribution, reconvergence after heal.  No scenario reports latency
without proving its admission bound first.

Scenario windows (window_ms) always outlive the run, so every key
spans at most ONE rate-limit window and the bounds are exact counts,
not rate estimates.  Saturating scenarios (diurnal, burststorm,
flashcrowd) expect the default gubload env scale — shrink the run
far enough that nothing saturates and their denied>0 assertions fail
honestly rather than report a tail that proved nothing.
"""
from __future__ import annotations

import asyncio
from typing import Dict

import numpy as np

from .spec import (
    PhaseSpec,
    RunContext,
    ScenarioSpec,
    assert_admission_bound,
    assert_reconverged,
    merged_tenant,
)

WINDOW_MS = 300_000  # outlives any sane run: one window per key


def _exact_ledger(ctx: RunContext, facts: Dict) -> None:
    """Fault-free scenarios: the ledger and the client agree EXACTLY —
    every owner-side decision reached a client and vice versa."""
    totals = ctx.totals()
    assert facts["ledger_allowed"] == totals.admitted, (
        f"{ctx.spec.name}: ledger allowed {facts['ledger_allowed']} != "
        f"client-observed admitted {totals.admitted}"
    )
    assert facts["ledger_denied"] == totals.denied, (
        f"{ctx.spec.name}: ledger denied {facts['ledger_denied']} != "
        f"client-observed denied {totals.denied}"
    )
    assert totals.errors == 0, (
        f"{ctx.spec.name}: {totals.errors} errors in a fault-free run"
    )


# -- fault-free shape scenarios ----------------------------------------


def _steady_verdict(ctx: RunContext) -> Dict:
    facts = assert_admission_bound(ctx)
    _exact_ledger(ctx, facts)
    assert facts["ledger_denied"] == 0, (
        f"steady: {facts['ledger_denied']} denials under a "
        "non-saturating limit"
    )
    return facts


STEADY = ScenarioSpec(
    name="steady",
    description="Steady Poisson arrivals, uniform keys, non-saturating "
    "limit: the ledger and the client must agree exactly, zero denials.",
    phases=(
        PhaseSpec("warm", 0.25, "steady", "uniform",
                  params={}, target_rps=None),
        PhaseSpec("cruise", 0.75, "steady", "uniform", profile=True),
    ),
    limit=1_000_000, window_ms=WINDOW_MS, key_universe=64,
    tenant="load.steady", verdict=_steady_verdict,
)


def _diurnal_verdict(ctx: RunContext) -> Dict:
    facts = assert_admission_bound(ctx)
    _exact_ledger(ctx, facts)
    assert facts["ledger_denied"] > 0, (
        "diurnal: the crest never saturated any key — the wave proved "
        "nothing (raise GUBER_LOAD_TARGET_RPS / GUBER_LOAD_DURATION)"
    )
    return facts


DIURNAL = ScenarioSpec(
    name="diurnal",
    description="A compressed diurnal wave (sinusoidal rate, trough "
    "20% of crest): keys saturate at the crest, the exact bound holds.",
    phases=(
        PhaseSpec("wave", 1.0, "diurnal", "uniform",
                  params={"base_fraction": 0.2}, profile=True),
    ),
    limit=8, window_ms=WINDOW_MS, key_universe=32,
    tenant="load.diurnal", verdict=_diurnal_verdict,
)


def _burst_verdict(ctx: RunContext) -> Dict:
    facts = assert_admission_bound(ctx)
    _exact_ledger(ctx, facts)
    assert facts["ledger_denied"] > 0, (
        "burststorm: bursts never saturated any key (raise "
        "GUBER_LOAD_TARGET_RPS / GUBER_LOAD_DURATION)"
    )
    return facts


BURSTSTORM = ScenarioSpec(
    name="burststorm",
    description="Square-wave burst storm (bursts at full rate over a "
    "20% floor): saturation inside bursts, exact bound across them.",
    phases=(
        PhaseSpec("storm", 1.0, "burst", "uniform",
                  params={"base_fraction": 0.2}, profile=True),
    ),
    limit=10, window_ms=WINDOW_MS, key_universe=16,
    tenant="load.burst", verdict=_burst_verdict,
)


def _flashcrowd_verdict(ctx: RunContext) -> Dict:
    facts = assert_admission_bound(ctx)
    _exact_ledger(ctx, facts)
    assert facts["ledger_denied"] > 0, (
        "flashcrowd: the crowd never saturated the hot key (raise "
        "GUBER_LOAD_TARGET_RPS / GUBER_LOAD_DURATION)"
    )
    # The hot head: the most-drawn key across the run's schedules must
    # hold its limit EXACTLY — the whole point of a flash crowd.
    hot_idx = int(ctx.state["hot_key_idx"])
    totals = ctx.totals()
    hot_admitted = totals.per_key_admitted.get(hot_idx, 0)
    assert hot_admitted <= ctx.spec.limit, (
        f"flashcrowd: hot key {ctx.spec.key_name(hot_idx)} admitted "
        f"{hot_admitted} > limit {ctx.spec.limit}"
    )
    assert hot_admitted == ctx.spec.limit, (
        f"flashcrowd: hot key only admitted {hot_admitted}/"
        f"{ctx.spec.limit} — the crowd never arrived"
    )
    facts["hot_key"] = ctx.spec.key_name(hot_idx)
    facts["hot_key_admitted"] = hot_admitted
    return facts


FLASHCROWD = ScenarioSpec(
    name="flashcrowd",
    description="Zipfian hot-key flash crowd over a warm uniform "
    "floor: the hot head saturates its limit exactly, the global "
    "bound holds.",
    phases=(
        PhaseSpec("warm", 0.25, "steady", "uniform",
                  params={}, target_rps=None),
        PhaseSpec("crowd", 0.6, "steady", "zipf",
                  params={"s": 1.4}, profile=True),
        PhaseSpec("cool", 0.15, "steady", "uniform"),
    ),
    limit=40, window_ms=WINDOW_MS, key_universe=64,
    tenant="load.flash", verdict=_flashcrowd_verdict,
)


# -- reshard-under-churn -----------------------------------------------


async def _churn_join(ctx: RunContext) -> None:
    """Membership churn, live: boot a joiner and push it into the ring
    at phase entry, so this phase's arrivals flow WHILE handoff windows
    drain rows to the new owner."""
    from dataclasses import replace

    from ..core.config import fast_test_behaviors
    from ..daemon import Daemon
    from ..testing.cluster import TEST_DEVICE

    cluster = ctx.cluster
    conf = ctx.state["conf_template"]

    async def boot():
        c = replace(
            conf,
            grpc_listen_address="127.0.0.1:0",
            http_listen_address="127.0.0.1:0",
            behaviors=fast_test_behaviors(),
            device=TEST_DEVICE,
        )
        d = Daemon(c)
        await d.start()
        d.conf.advertise_address = d.grpc_address
        return d

    joiner = await asyncio.to_thread(
        lambda: cluster.run(boot(), timeout=300.0)
    )
    ctx.state["joiner"] = joiner
    cluster.daemons.append(joiner)
    await asyncio.to_thread(
        lambda: cluster.run(cluster._push_peers(), timeout=60.0)
    )


async def _churn_leave(ctx: RunContext) -> None:
    """Graceful LEAVE mid-run: the joiner drains its rows back to the
    survivors and departs; the drain phase's arrivals land on the
    post-leave ring."""
    cluster = ctx.cluster
    joiner = ctx.state["joiner"]
    shipped = await asyncio.to_thread(
        lambda: cluster.run(joiner.drain(), timeout=60.0)
    )
    ctx.state["drain_shipped"] = shipped
    # The joiner's per-node tenant ledger departs with it; its FINAL
    # scrape keeps the run's merged accounting whole (spec.merged_tenant
    # extra_scrapes).  to_thread: the scrape is a blocking HTTP GET
    # against a server on THIS loop — inline it would deadlock.
    from ..cli import gubtop

    scrape = await asyncio.to_thread(gubtop.scrape, joiner.http_address)
    assert "error" not in scrape, (
        f"reshard_churn: departing joiner {joiner.http_address} "
        f"unscrapeable: {scrape.get('error')}"
    )
    ctx.state.setdefault("departed_scrapes", {})[
        joiner.http_address
    ] = scrape
    cluster.daemons.remove(joiner)
    await asyncio.to_thread(
        lambda: cluster.run(cluster._push_peers(), timeout=60.0)
    )
    await asyncio.to_thread(
        lambda: cluster.run(joiner.close(), timeout=60.0)
    )


def _churn_verdict(ctx: RunContext) -> Dict:
    t = merged_tenant(ctx.daemons, ctx.spec.tenant)
    # Rows that moved during a handoff window may over-admit through
    # the joiner's bounded .handoff-shadow carve — the ledger
    # attributes every such admission, so the exact bound is
    # limit x keys + the attributed carve (docs/resharding.md).
    shadow = t["over_admitted"].get("handoff-shadow", 0)
    facts = assert_admission_bound(ctx, extra_allowance=shadow)
    facts["handoff_shadow_admitted"] = shadow
    facts["drain_shipped"] = ctx.state.get("drain_shipped", 0)
    assert ctx.state.get("drain_shipped", 0) >= 0
    # Conservation across BOTH remaps: post-churn the survivors answer
    # every key error-free and no breaker is stuck.
    facts.update(assert_reconverged(ctx))
    return facts


RESHARD_CHURN = ScenarioSpec(
    name="reshard_churn",
    description="Open-loop traffic across a live JOIN + graceful "
    "LEAVE: handoff windows drain under load, admission stays inside "
    "limit x keys + the ledger-attributed handoff-shadow carve.",
    phases=(
        PhaseSpec("warm", 0.3, "steady", "uniform"),
        PhaseSpec("join", 0.4, "steady", "uniform", fault="join",
                  profile=True),
        PhaseSpec("leave", 0.3, "steady", "uniform", fault="leave"),
    ),
    limit=25, window_ms=WINDOW_MS, key_universe=48,
    tenant="load.churn", verdict=_churn_verdict,
    hooks={"join": _churn_join, "leave": _churn_leave},
    needs_cluster=True,
)


# -- partition-while-leased --------------------------------------------

_LEASE_FRACTION = 0.25
_LEASE_KEY_IDX = 0


def _lease_conf_overrides() -> Dict:
    from ..core.config import CircuitConfig, LeaseConfig

    return {
        "lease": LeaseConfig(
            fraction=_LEASE_FRACTION, ttl_ms=60_000, max_holders=1,
            reconcile_ms=300, low_water=0.0,
        ),
        # Fast breaker schedule so post-heal half-open probes fit the
        # run budget (the chaos_smoke lease discipline).
        "circuit": CircuitConfig(
            failure_threshold=3, base_backoff_s=0.1,
            max_backoff_s=1.0, jitter=0.2,
        ),
    }


async def _lease_grant(ctx: RunContext) -> None:
    """Acquire a lease grant through a proxy daemon BEFORE the
    partition: the holder must be talking to a non-owner so the cut
    severs holder->owner, not holder->proxy."""
    import time as _t

    from ..client import LeasedClient
    from ..core.types import RateLimitReq, Status

    spec = ctx.spec
    cluster = ctx.cluster
    key = spec.key_name(_LEASE_KEY_IDX)
    hash_key = f"{spec.tenant}_{key}"
    owner = cluster.owner_daemon_of(hash_key)
    proxy = next(d for d in cluster.daemons if d is not owner)
    lc = LeasedClient(
        proxy.grpc_address,
        lease=proxy.conf.lease,
        client_id="gubload-holder",
    )
    req = RateLimitReq(name=spec.tenant, unique_key=key, hits=1,
                       limit=spec.limit, duration=spec.window_ms)
    ctx.state.update(
        lease_client=lc, lease_owner=owner, lease_req=req,
        lease_grant_admitted=0,
    )

    def acquire() -> int:
        admitted = 0
        deadline = _t.monotonic() + 15.0
        while not any(
            v.allowance_left > 0 for v in lc.table._leases.values()
        ):
            rs = lc.get_rate_limits([req])
            admitted += sum(
                1 for r in rs
                if r.error == "" and r.status == Status.UNDER_LIMIT
            )
            if _t.monotonic() > deadline:
                raise AssertionError(
                    f"lease grant never arrived: {lc.stats()}"
                )
            _t.sleep(0.05)
        return admitted

    ctx.state["lease_grant_admitted"] = await asyncio.to_thread(acquire)


async def _lease_partition(ctx: RunContext) -> None:
    """Cut the owner off, then burn the holder's full allowance — and
    prove it can never burn one hit more — while this phase's open-loop
    arrivals keep hammering the partitioned ring."""
    spec = ctx.spec
    owner = ctx.state["lease_owner"]
    lc = ctx.state["lease_client"]
    req = ctx.state["lease_req"]
    allowance = int(spec.limit * _LEASE_FRACTION)
    ctx.injector.set_active(True)
    ctx.injector.partition(
        {owner.grpc_address},
        {d.grpc_address for d in ctx.cluster.daemons if d is not owner},
    )

    def burn() -> int:
        before = lc.stats()["local_admitted"]
        for _ in range(allowance + 20):
            lc.get_rate_limits([req])
        return lc.stats()["local_admitted"] - before

    burned = await asyncio.to_thread(burn)
    assert burned == allowance, (
        f"partition_leased: holder burned {burned}, grant was "
        f"{allowance} — the client-side bound leaked"
    )
    ctx.state["lease_burned"] = burned


async def _lease_heal(ctx: RunContext) -> None:
    ctx.injector.heal()
    lc = ctx.state.pop("lease_client")
    await asyncio.to_thread(lc.close)


def _lease_verdict(ctx: RunContext) -> Dict:
    spec = ctx.spec
    allowance = int(spec.limit * _LEASE_FRACTION)
    t = merged_tenant(ctx.daemons, spec.tenant)
    # One grant landed, so the merged ledger must attribute EXACTLY one
    # allowance of lease-grant over-admission — the live form of
    # limit x (1 + holders x fraction) (docs/leases.md).
    over = t["over_admitted"].get("lease-grant", 0)
    assert over == allowance, (
        f"partition_leased: live lease-grant over-admission {over} != "
        f"allowance {allowance}"
    )
    facts = assert_admission_bound(ctx, extra_allowance=allowance)
    facts["lease_allowance"] = allowance
    facts["lease_burned_under_partition"] = ctx.state["lease_burned"]
    totals = ctx.totals()
    assert totals.errors > 0, (
        "partition_leased: no client-visible errors — the partition "
        "never bit"
    )
    facts.update(assert_reconverged(ctx))
    return facts


PARTITION_LEASED = ScenarioSpec(
    name="partition_leased",
    description="A lease holder is partitioned from its key's owner "
    "mid-run: it burns exactly its allowance and never one hit more; "
    "the merged ledger attributes exactly one lease-grant carve; "
    "breakers re-close after heal.",
    phases=(
        PhaseSpec("grant", 0.25, "steady", "uniform", fault="grant"),
        PhaseSpec("partition", 0.45, "steady", "uniform",
                  fault="partition", profile=True),
        PhaseSpec("heal", 0.3, "steady", "uniform", fault="heal"),
    ),
    limit=200, window_ms=WINDOW_MS, key_universe=24,
    tenant="load.lease", verdict=_lease_verdict,
    hooks={
        "grant": _lease_grant,
        "partition": _lease_partition,
        "heal": _lease_heal,
    },
    needs_cluster=True,
)
# -- region_failover ---------------------------------------------------

_REGION_FRACTION = 0.25


def _region_conf_overrides() -> Dict:
    from ..core.config import CircuitConfig, RegionConfig

    return {
        "region": RegionConfig(
            enabled=True, fraction=_REGION_FRACTION,
            reconcile_ms=200, drift_max=100_000,
        ),
        # Fast breaker schedule so the WAN reconcile arcs re-close
        # inside the heal phase budget.
        "circuit": CircuitConfig(
            failure_threshold=3, base_backoff_s=0.1,
            max_backoff_s=1.0, jitter=0.2,
        ),
    }


async def _region_partition(ctx: RunContext) -> None:
    """Sever the WAN: cut the cluster along its data-center groups.
    Client traffic keeps flowing to BOTH regions — active-active means
    the partition is invisible on the request path (remote-homed keys
    keep serving from their bounded carve; burns queue as drift)."""
    groups: Dict[str, set] = {}
    for d in ctx.cluster.daemons:
        groups.setdefault(d.conf.data_center, set()).add(d.grpc_address)
    assert len(groups) >= 2, f"region_failover needs >= 2 regions: {groups}"
    ctx.injector.set_active(True)
    ctx.injector.partition(*groups.values())
    ctx.state["region_groups"] = groups


async def _region_heal(ctx: RunContext) -> None:
    ctx.injector.heal()


def _region_verdict(ctx: RunContext) -> Dict:
    import time as _t

    spec = ctx.spec
    carve_per_key = int(spec.limit * _REGION_FRACTION)
    keys = spec.key_universe

    # Reconvergence from the region surface first: every daemon's
    # drift drains to zero and every degraded link re-homes through
    # REGION_PREPARE -> TRANSFER -> CUTOVER back to remote.
    deadline = _t.monotonic() + 25.0
    while True:
        vars_ = [d.service.regions.debug_vars() for d in ctx.daemons]
        drained = all(v["drift"] == 0 for v in vars_)
        rehomed = all(
            lk["state"] == "remote"
            for v in vars_ for lk in v["links"].values()
        )
        if drained and rehomed:
            break
        if _t.monotonic() > deadline:
            raise AssertionError(
                f"region_failover: drift never reconverged: {vars_}"
            )
        _t.sleep(0.2)
    dropped = sum(v["reconcile_dropped"] for v in vars_)
    assert dropped == 0, (
        f"region_failover: {dropped} burns dropped as ambiguous — a "
        "clean partition is provably-unsent, nothing may drop"
    )
    rehomes = sum(v["rehomes"] for v in vars_)
    assert rehomes >= 1, (
        f"region_failover: no link ever re-homed after heal: {vars_}"
    )

    totals = ctx.totals()
    # Active-active is the point: a region partition produces ZERO
    # client-visible errors — the request path never crosses the WAN.
    assert totals.errors == 0, (
        f"region_failover: {totals.errors} client-visible errors — "
        "the partition leaked onto the request path"
    )
    # The paper bound on the client surface: per key at most
    # limit x (1 + remote_regions x fraction) unique admissions.
    client_bound = keys * int(spec.limit * (1 + _REGION_FRACTION))
    assert totals.admitted <= client_bound, (
        f"region_failover: client admissions {totals.admitted} > "
        f"bound {client_bound}"
    )

    t = merged_tenant(ctx.daemons, spec.tenant)
    over = t["over_admitted"].get("region-carve", 0)
    assert 0 < over <= carve_per_key * keys, (
        f"region_failover: region-carve over-admission {over} outside "
        f"(0, {carve_per_key} x {keys}] — the carve plane is unbounded "
        "or never served"
    )
    # Ledger allowance: each carve admission counts once at the carve
    # (over-admission) and its reconciled burn may count once more at
    # the home row — hence 2 x the carve budget on top of the base.
    facts = assert_admission_bound(
        ctx, extra_allowance=2 * carve_per_key * keys
    )
    facts.update({
        "region_carve_over": over,
        "region_rehomes": rehomes,
        "region_drift": 0,
        "client_admission_bound": client_bound,
    })
    facts.update(assert_reconverged(ctx))
    return facts


REGION_FAILOVER = ScenarioSpec(
    name="region_failover",
    description="A two-region active-active cluster is cut in half "
    "mid-run: remote-homed keys keep serving from their bounded "
    "region carve with zero client-visible errors, drift reconverges "
    "after heal, every link re-homes, and the merged ledger keeps "
    "region-carve over-admission within fraction x limit per key.",
    phases=(
        PhaseSpec("steady", 0.3, "steady", "uniform"),
        PhaseSpec("partition", 0.4, "steady", "uniform",
                  fault="partition", profile=True),
        PhaseSpec("heal", 0.3, "steady", "uniform", fault="heal"),
    ),
    limit=200, window_ms=WINDOW_MS, key_universe=24,
    tenant="load.region", verdict=_region_verdict,
    hooks={
        "partition": _region_partition,
        "heal": _region_heal,
    },
    needs_cluster=True,
    datacenters=("east", "east", "west", "west"),
)


SCENARIOS = {
    s.name: s
    for s in (STEADY, DIURNAL, BURSTSTORM, FLASHCROWD, RESHARD_CHURN,
              PARTITION_LEASED, REGION_FAILOVER)
}

def _churn_conf_overrides() -> Dict:
    from ..core.config import ReshardConfig

    return {
        "reshard": ReshardConfig(
            handoff_fraction=_LEASE_FRACTION, timeout_s=30.0,
            release_linger_s=2.0,
        ),
    }


# Per-scenario DaemonConfig override factories (runner.py applies them
# over the conf template before boot).
CONF_OVERRIDES = {
    "partition_leased": _lease_conf_overrides,
    "reshard_churn": _churn_conf_overrides,
    "region_failover": _region_conf_overrides,
}


def hot_key_index(spec: ScenarioSpec, schedules) -> int:
    """The most-drawn key index across a run's phase schedules — the
    flash-crowd head (deterministic from the seed)."""
    counts = np.zeros(spec.key_universe, dtype=np.int64)
    for sched in schedules:
        np.add.at(counts, sched.key_idx, 1)
    return int(np.argmax(counts))
