"""Gubload: the open-loop million-client scenario harness
(docs/loadgen.md; ROADMAP item 5).

Layers:
  schedule.py   deterministic seeded arrival plans (intended-send
                timestamps + key draws; worker-shardable)
  engine.py     non-blocking open-loop dispatch, latency from INTENDED
                send into HdrRecorder (coordinated-omission-free),
                phase-linked attribution (flightrec / spans / gauge /
                optional jax.profiler)
  spec.py       declarative scenario specs + merged-ledger verdict
                helpers (the chaos_smoke idiom)
  scenarios.py  the scenario library (steady, diurnal, burststorm,
                flashcrowd, reshard_churn, partition_leased)
  runner.py     composition: cluster, phases, hooks, verdict
  report.py     BENCH_E2E-compatible artifact rows bench_gate gates on
"""
from .engine import OutcomeCounts, PhaseTracker, closed_loop, open_loop
from .report import build_artifact, validate_row
from .runner import build_schedules, resolve_scenario, run_scenario
from .scenarios import SCENARIOS
from .schedule import Schedule, build, derive_seed
from .spec import PhaseSpec, RunContext, ScenarioSpec

__all__ = [
    "OutcomeCounts", "PhaseSpec", "PhaseTracker", "RunContext",
    "SCENARIOS", "Schedule", "ScenarioSpec", "build", "build_artifact",
    "build_schedules", "closed_loop", "derive_seed", "open_loop",
    "resolve_scenario", "run_scenario", "validate_row",
]
