"""Declarative scenario specs + ledger-derived verdicts
(docs/loadgen.md).

A scenario is data, not code: an ordered list of phases (each with an
arrival process, key distribution, optional fault hook reusing
testing/chaos.py) plus a verdict function.  The verdict runs AFTER the
last phase and asserts its pass/fail conditions from the merged
/debug/vars ledger the way scripts/chaos_smoke.py does — the live
production surface an operator sees, never test internals — so a
scenario run is a proof artifact: no scenario reports latency without
also proving its admission bound.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Tuple


@dataclass(frozen=True)
class PhaseSpec:
    """One phase of a scenario: `arrivals` (steady/diurnal/burst) at
    `target_rps` peak for `duration_s`, hitting `keys`-distributed
    (uniform/zipf) indexes.  `fault` names a hook from the scenario's
    `hooks` map, awaited at phase entry (chaos injection, partition,
    heal, lease side-channels).  `profile` requests a time-boxed
    jax.profiler capture at this phase's boundary when the run was
    given --profile-dir."""

    name: str
    duration_s: float
    arrivals: str = "steady"
    keys: str = "uniform"
    target_rps: Optional[float] = None   # None: the run's TARGET_RPS
    params: Dict = field(default_factory=dict)
    fault: Optional[str] = None
    profile: bool = False


@dataclass(frozen=True)
class ScenarioSpec:
    """The declarative scenario: phases + the rate limit they drive +
    the ledger verdict.  `verdict(ctx)` raises AssertionError on fail
    and returns a dict of proven facts for the artifact row.
    `hooks[name](ctx)` are async fault hooks; `needs_cluster` marks
    scenarios whose hooks/verdicts require in-process daemons (chaos
    injection / breaker introspection) and cannot drive an external
    address list."""

    name: str
    description: str
    phases: Tuple[PhaseSpec, ...]
    limit: int
    window_ms: int
    key_universe: int
    tenant: str
    verdict: Callable[["RunContext"], Dict]
    hooks: Dict[str, Callable] = field(default_factory=dict)
    needs_cluster: bool = False
    # Per-daemon data-center tags for the booted cluster (empty =
    # `num_daemons` single-region daemons).  Multi-region scenarios
    # (docs/multiregion.md) pin their topology here — the region name
    # IS the data-center tag, so ["east","east","west","west"] boots
    # two two-node regions.
    datacenters: Tuple[str, ...] = ()

    def __post_init__(self) -> None:
        if not self.phases:
            raise ValueError(f"scenario {self.name!r} has no phases")
        for p in self.phases:
            if p.fault is not None and p.fault not in self.hooks:
                raise ValueError(
                    f"scenario {self.name!r} phase {p.name!r} names "
                    f"unknown fault hook {p.fault!r}"
                )

    def key_name(self, idx: int) -> str:
        return f"{self.name}-k{idx}"


class RunContext:
    """Everything a fault hook or verdict can touch: the in-process
    cluster (None when driving external addresses), the chaos
    injector, the run config, client-observed outcome tallies, and a
    scratch dict hooks use to pass state to the verdict."""

    def __init__(self, spec, cfg, cluster, injector, addresses):
        self.spec = spec
        self.cfg = cfg
        self.cluster = cluster
        self.injector = injector
        self.addresses = list(addresses)
        self.counts_by_phase: Dict[str, object] = {}
        self.state: Dict = {}

    @property
    def daemons(self):
        return [] if self.cluster is None else self.cluster.daemons

    def totals(self):
        from .engine import OutcomeCounts

        total = OutcomeCounts()
        for c in self.counts_by_phase.values():
            total.merge(c)
        return total


# -- the merged /debug/vars ledger (the chaos_smoke idiom) -------------


def merged_tenant(daemons, name: str, extra_scrapes: Dict = None
                  ) -> Dict:
    """The cluster-wide per-tenant ledger, merged from LIVE /debug/vars
    scrapes with gubtop's own merge (docs/observability.md): local
    serves only per node make the sum exact, so over-admission bounds
    are asserted against what an operator actually sees.

    `extra_scrapes`: final scrapes of daemons that have since LEFT the
    cluster (a departed node's tallies are still part of the run's
    accounting — churn hooks capture them right before close)."""
    from ..cli import gubtop

    scrapes = {d.http_address: gubtop.scrape(d.http_address)
               for d in daemons}
    scrapes.update(extra_scrapes or {})
    for t in gubtop._merge_tenants(scrapes, 64):
        if t["name"] == name:
            return t
    raise AssertionError(
        f"tenant {name!r} missing from merged /debug/vars ledgers: "
        f"{[v.get('tenants') for v in scrapes.values()]}"
    )


def assert_admission_bound(ctx: RunContext, extra_allowance: int = 0
                           ) -> Dict:
    """The admission bound every scenario must prove before it may
    report latency: merged-ledger allowed <= limit x keys (+ any
    proven shadow carve), and the ledger accounts for at least every
    client-observed admission.  Scenario windows outlive the run, so
    each key spans at most ONE window and the bound is exact — not a
    rate estimate."""
    spec = ctx.spec
    t = merged_tenant(
        ctx.daemons, spec.tenant,
        extra_scrapes=ctx.state.get("departed_scrapes"),
    )
    totals = ctx.totals()
    bound = spec.limit * spec.key_universe + extra_allowance
    assert t["allowed"] <= bound, (
        f"{spec.name}: ledger over-admission: allowed={t['allowed']} "
        f"> bound {bound} (= {spec.limit} x {spec.key_universe} keys"
        f"{f' + {extra_allowance} carve' if extra_allowance else ''})"
    )
    assert t["allowed"] >= totals.admitted, (
        f"{spec.name}: ledger allowed={t['allowed']} < client-observed "
        f"admissions {totals.admitted} — lost accounting"
    )
    return {
        "ledger_allowed": t["allowed"],
        "ledger_denied": t["denied"],
        "ledger_shed": t.get("shed", 0),
        "client_admitted": totals.admitted,
        "client_denied": totals.denied,
        "client_errors": totals.errors,
        "admission_bound": bound,
    }


def assert_reconverged(ctx: RunContext, probes: int = 8,
                       timeout_s: float = 20.0) -> Dict:
    """Post-heal reconvergence from the production surface: every
    breaker re-closes and a probe round from every daemon serves
    error-free (the chaos_smoke quiesce loop)."""
    import time as _t

    from ..client import V1Client
    from ..core.types import RateLimitReq

    assert ctx.cluster is not None, "reconvergence needs the cluster"
    clients = [V1Client(a) for a in ctx.cluster.addresses()]
    try:
        deadline = _t.monotonic() + timeout_s
        while True:
            clean = True
            for c in clients:
                for r in c.get_rate_limits([
                    RateLimitReq(
                        name=f"{ctx.spec.tenant}.quiesce",
                        unique_key=f"q{i}", hits=1,
                        limit=1_000_000, duration=60_000,
                    )
                    for i in range(probes)
                ], timeout=30):
                    if r.error != "":
                        clean = False
            states = ctx.cluster.breaker_states()
            stuck = [
                (a, pa, s)
                for a, peers in states.items()
                for pa, s in peers.items()
                if s not in ("closed", "disabled")
            ]
            if clean and not stuck:
                return {"reconverged": True, "stuck_breakers": 0}
            if _t.monotonic() > deadline:
                raise AssertionError(
                    f"{ctx.spec.name}: never reconverged after heal: "
                    f"clean={clean} stuck={stuck}"
                )
            _t.sleep(0.1)
    finally:
        for c in clients:
            c.close()
