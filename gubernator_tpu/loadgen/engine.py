"""The open-loop dispatch engine + phase-linked attribution
(docs/loadgen.md).

Open loop means the arrival schedule is the clock: each request is
dispatched at its precomputed intended-send time as a free-running
asyncio task, and its latency is recorded from the INTENDED send time
— never from when the event loop actually got around to sending it.
A slow response therefore delays nothing and hides nothing: if the
server stalls 200ms, every arrival scheduled inside the stall records
its full queueing delay, which is exactly the signal a closed-loop
driver destroys (it would sit waiting on one response, silently not
sending — coordinated omission).  ``closed_loop`` is the honest
comparator: tests/test_loadgen.py pins the divergence with an induced
stall.

The engine also records intended-vs-actual send skew into a second
recorder: skew tells you when the *generator* fell behind (an
overloaded client machine flatters tails in a different way), so the
artifact row can prove the load was actually delivered on plan.
"""
from __future__ import annotations

import asyncio
import os
import time
from typing import Awaitable, Callable, Dict, List, Optional, Sequence

from ..runtime import tracing
from ..runtime.metrics import HdrRecorder

# send(key_idx) -> True (admitted) | False (denied) ; raises on error.
SendFn = Callable[[int], Awaitable[bool]]


class OutcomeCounts:
    """Client-observed outcome tally for one phase (the verdict's
    client side of the ledger cross-check)."""

    def __init__(self) -> None:
        self.admitted = 0
        self.denied = 0
        self.errors = 0
        self.per_key_admitted: Dict[int, int] = {}

    def merge(self, other: "OutcomeCounts") -> "OutcomeCounts":
        self.admitted += other.admitted
        self.denied += other.denied
        self.errors += other.errors
        for k, n in other.per_key_admitted.items():
            self.per_key_admitted[k] = (
                self.per_key_admitted.get(k, 0) + n
            )
        return self


async def open_loop(
    send: SendFn,
    schedule,
    latency: HdrRecorder,
    skew: HdrRecorder,
    counts: Optional[OutcomeCounts] = None,
) -> OutcomeCounts:
    """Dispatch `schedule` open-loop: every arrival fires at its
    intended time regardless of outstanding responses; latency is
    recorded from intended-send, send skew (actual - intended) is
    recorded separately.  Returns the outcome tally."""
    out = counts if counts is not None else OutcomeCounts()
    loop = asyncio.get_running_loop()
    start = loop.time()
    tasks: List[asyncio.Task] = []

    async def one(intended: float, key_idx: int) -> None:
        actual = loop.time()
        skew.record(max(0.0, actual - intended))
        try:
            admitted = await send(int(key_idx))
        except Exception:
            out.errors += 1
        else:
            if admitted:
                out.admitted += 1
                out.per_key_admitted[int(key_idx)] = (
                    out.per_key_admitted.get(int(key_idx), 0) + 1
                )
            else:
                out.denied += 1
        # From INTENDED send: queueing delay the server imposed on this
        # arrival is part of its latency, even if the generator itself
        # dispatched late (that lateness is separately in `skew`).
        latency.record(loop.time() - intended)

    for t_off, key_idx in zip(schedule.times_s, schedule.key_idx):
        intended = start + float(t_off)
        delay = intended - loop.time()
        if delay > 0:
            await asyncio.sleep(delay)
        tasks.append(asyncio.ensure_future(one(intended, key_idx)))
    if tasks:
        await asyncio.gather(*tasks)
    return out


async def closed_loop(
    send: SendFn,
    schedule,
    latency: HdrRecorder,
    counts: Optional[OutcomeCounts] = None,
) -> OutcomeCounts:
    """The coordinated-omission-prone comparator: one request in
    flight, next send waits for the previous response, latency from the
    ACTUAL send.  Kept only so the divergence is demonstrable
    (tests/test_loadgen.py) — never used for reported numbers."""
    out = counts if counts is not None else OutcomeCounts()
    loop = asyncio.get_running_loop()
    start = loop.time()
    for t_off, key_idx in zip(schedule.times_s, schedule.key_idx):
        delay = start + float(t_off) - loop.time()
        if delay > 0:
            await asyncio.sleep(delay)
        t0 = loop.time()
        try:
            admitted = await send(int(key_idx))
        except Exception:
            out.errors += 1
        else:
            if admitted:
                out.admitted += 1
            else:
                out.denied += 1
        latency.record(loop.time() - t0)
    return out


class PhaseTracker:
    """Phase-linked attribution: one object per scenario run that
    propagates phase boundaries into every observability plane —
    flightrec ring records (kind="load_phase"), the daemon's
    /debug/vars `load` block (gubtop's per-node load line), the
    gubernator_load_active gauge, a gubscope span per phase, and an
    optional time-boxed jax.profiler capture.

    `daemons` is the in-process daemon list (empty when driving an
    external cluster — span attribution still applies, daemon-side
    markers are then the daemons' own business).
    """

    def __init__(
        self,
        scenario: str,
        daemons: Sequence = (),
        profile_dir: Optional[str] = None,
        profile_box_s: float = 2.0,
    ) -> None:
        self.scenario = scenario
        self.daemons = list(daemons)
        self.profile_dir = profile_dir
        self.profile_box_s = profile_box_s
        self._seq = 0
        self._span = None
        self._phase: Optional[str] = None
        self._profiling = False
        self._profile_stop_handle = None

    # -- lifecycle -----------------------------------------------------

    def enter(self, phase: str, profile: bool = False) -> None:
        self.exit()
        self._phase = phase
        self._seq += 1
        for d in self.daemons:
            fr = getattr(d, "flightrec", None)
            if fr is not None:
                fr.record(
                    "load_phase", scenario=self.scenario, phase=phase,
                    seq=self._seq, action="enter",
                )
            d.load_status = {
                "scenario": self.scenario,
                "phase": phase,
                "seq": self._seq,
                "since": time.time(),
            }
            m = getattr(d, "metrics", None)
            if m is not None:
                m.load_active.labels(
                    scenario=self.scenario, phase=phase
                ).set(1)
        if tracing.enabled():
            self._span = tracing.start_span(
                "load.phase", tracing.current_context(),
            )
            if self._span is not None:
                self._span.set_attribute("load.scenario", self.scenario)
                self._span.set_attribute("load.phase", phase)
                self._span.set_attribute("load.seq", self._seq)
        if profile and self.profile_dir:
            self._start_profiler(phase)

    def exit(self) -> None:
        if self._phase is None:
            return
        phase, self._phase = self._phase, None
        self._stop_profiler()
        for d in self.daemons:
            fr = getattr(d, "flightrec", None)
            if fr is not None:
                fr.record(
                    "load_phase", scenario=self.scenario, phase=phase,
                    seq=self._seq, action="exit",
                )
            d.load_status = None
            m = getattr(d, "metrics", None)
            if m is not None:
                try:
                    m.load_active.remove(self.scenario, phase)
                except KeyError:
                    pass
        if self._span is not None:
            self._span.end()
            self._span = None

    # -- optional time-boxed device profiling --------------------------

    def _start_profiler(self, phase: str) -> None:
        """Best-effort jax.profiler capture at a phase boundary, boxed
        to `profile_box_s` so a long phase can't fill the disk (the
        same discipline as flightrec's breach capture)."""
        try:
            import jax

            out = os.path.join(
                self.profile_dir, f"{self.scenario}-{phase}"
            )
            os.makedirs(out, exist_ok=True)
            jax.profiler.start_trace(out)
            self._profiling = True
            try:
                loop = asyncio.get_running_loop()
                self._profile_stop_handle = loop.call_later(
                    self.profile_box_s, self._stop_profiler
                )
            except RuntimeError:
                pass  # no loop: stopped at phase exit
        except Exception:
            self._profiling = False

    def _stop_profiler(self) -> None:
        if self._profile_stop_handle is not None:
            self._profile_stop_handle.cancel()
            self._profile_stop_handle = None
        if not self._profiling:
            return
        self._profiling = False
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:
            pass
