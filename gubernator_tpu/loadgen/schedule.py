"""Deterministic open-loop arrival schedules (docs/loadgen.md).

A schedule is the *plan* of a load phase, precomputed before the first
RPC leaves: every arrival's intended-send timestamp plus the key it
will hit.  The open-loop engine (engine.py) dispatches against these
intended times and records latency FROM them, so a stalled server
cannot delay the next arrival or flatter the tail (coordinated
omission — the closed-loop failure mode where a 200ms stall hides all
but one of its victims from the p99).

Determinism contract (pinned by golden digest in tests/test_loadgen.py):

  * Every draw flows from ``numpy.random.default_rng(seed)`` where the
    seed is derived by ``derive_seed`` from the scenario seed and a
    stable string path (the sha512 idiom testing/chaos.py uses —
    process-salted ``hash()`` would break cross-process replay).
  * Worker sharding is by arrival-index stride, so the union of any
    worker count's shards is the one full schedule and merged HDR
    state is identical for 1, 2, or N workers (merge is commutative).
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import List

import numpy as np


def derive_seed(seed: int, path: str) -> int:
    """A stable sub-seed for `path` (e.g. "flashcrowd/1/keys") — the
    same derivation in every process, unlike salted hash()."""
    digest = hashlib.sha512(f"{seed}/{path}".encode()).digest()
    return int.from_bytes(digest[:8], "big")


@dataclass(frozen=True)
class Schedule:
    """One phase's precomputed arrival plan.

    ``times_s`` are intended-send offsets from phase start (sorted,
    float64 seconds); ``key_idx[i]`` is the key-universe index arrival
    ``i`` hits.  Key *names* are materialized by the scenario (spec.py)
    so the same plan can drive different tenants.
    """

    times_s: np.ndarray
    key_idx: np.ndarray

    def __post_init__(self) -> None:
        if len(self.times_s) != len(self.key_idx):
            raise ValueError(
                f"schedule arrays disagree: {len(self.times_s)} times "
                f"vs {len(self.key_idx)} keys"
            )

    def __len__(self) -> int:
        return len(self.times_s)

    def digest(self) -> str:
        """Content digest over ns-quantized times + key draws — the
        schedule-determinism pin (identical seed => identical hex)."""
        h = hashlib.sha256()
        h.update(np.round(self.times_s * 1e9).astype(np.int64).tobytes())
        h.update(self.key_idx.astype(np.int64).tobytes())
        return h.hexdigest()

    def shard(self, workers: int) -> List["Schedule"]:
        """Stride-partition among `workers`: arrival i -> worker
        i % workers.  The shards' union is exactly this schedule, so
        per-worker recorders merge to the same state regardless of
        worker count or merge order."""
        if workers < 1:
            raise ValueError(f"workers must be >= 1, got {workers}")
        return [
            Schedule(self.times_s[w::workers], self.key_idx[w::workers])
            for w in range(workers)
        ]


# -- arrival processes (intended-send offsets) -------------------------


def poisson_times(seed: int, rps: float, duration_s: float) -> np.ndarray:
    """Steady Poisson arrivals: i.i.d. exponential inter-arrival gaps
    at `rps`, truncated to `duration_s`."""
    if rps <= 0 or duration_s <= 0:
        raise ValueError(
            f"rps and duration must be > 0, got {rps}, {duration_s}"
        )
    rng = np.random.default_rng(seed)
    # Over-draw, then truncate: 5 sigma headroom over the expectation.
    n = int(rps * duration_s + 5 * max(1.0, (rps * duration_s) ** 0.5)) + 8
    t = np.cumsum(rng.exponential(1.0 / rps, size=n))
    return t[t < duration_s]


def _thinned_times(
    seed: int, peak_rps: float, duration_s: float, rate_fn
) -> np.ndarray:
    """Inhomogeneous Poisson by thinning: candidates at `peak_rps`,
    kept with probability rate(t)/peak (Lewis & Shedler)."""
    cand = poisson_times(seed, peak_rps, duration_s)
    rng = np.random.default_rng(derive_seed(seed, "thin"))
    keep = rng.random(len(cand)) < (rate_fn(cand) / peak_rps)
    return cand[keep]


def diurnal_times(
    seed: int, base_rps: float, peak_rps: float,
    period_s: float, duration_s: float,
) -> np.ndarray:
    """A diurnal wave compressed to `period_s`: sinusoidal rate from
    `base_rps` (trough) to `peak_rps` (crest)."""
    if peak_rps < base_rps:
        raise ValueError(f"peak {peak_rps} < base {base_rps}")
    mid = (base_rps + peak_rps) / 2.0
    amp = (peak_rps - base_rps) / 2.0

    def rate(t):
        return mid + amp * np.sin(2 * np.pi * t / period_s)

    return _thinned_times(seed, peak_rps, duration_s, rate)


def burst_times(
    seed: int, base_rps: float, burst_rps: float,
    burst_every_s: float, burst_len_s: float, duration_s: float,
) -> np.ndarray:
    """Burst storm: `base_rps` background with `burst_rps` square-wave
    bursts of `burst_len_s` every `burst_every_s`."""
    if burst_rps < base_rps:
        raise ValueError(f"burst {burst_rps} < base {base_rps}")

    def rate(t):
        in_burst = np.mod(t, burst_every_s) < burst_len_s
        return np.where(in_burst, burst_rps, base_rps)

    return _thinned_times(seed, burst_rps, duration_s, rate)


# -- key draws ---------------------------------------------------------


def uniform_keys(seed: int, n: int, universe: int) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return rng.integers(0, universe, size=n, dtype=np.int64)


def zipf_keys(seed: int, s: float, n: int, universe: int) -> np.ndarray:
    """Seeded zipfian ranks in [0, universe) — the flash-crowd head.
    Same truncated-zipf construction as testing/chaos.zipf_keys, kept
    here so the load plane has no dependency on the test package."""
    rng = np.random.default_rng(seed)
    ranks = np.arange(1, universe + 1, dtype=np.float64)
    p = ranks ** (-s)
    p /= p.sum()
    return rng.choice(universe, size=n, p=p).astype(np.int64)


_ARRIVALS = {
    "steady": lambda seed, rps, dur, p: poisson_times(seed, rps, dur),
    "diurnal": lambda seed, rps, dur, p: diurnal_times(
        seed, p.get("base_fraction", 0.2) * rps, rps,
        p.get("period_s", dur), dur,
    ),
    "burst": lambda seed, rps, dur, p: burst_times(
        seed, p.get("base_fraction", 0.2) * rps, rps,
        p.get("burst_every_s", dur / 2.0),
        p.get("burst_len_s", dur / 4.0), dur,
    ),
}

_KEYS = {
    "uniform": lambda seed, n, universe, p: uniform_keys(
        seed, n, universe
    ),
    "zipf": lambda seed, n, universe, p: zipf_keys(
        seed, p.get("s", 1.3), n, universe
    ),
}


def build(
    kind: str, keys: str, seed: int, target_rps: float,
    duration_s: float, universe: int, params: dict = None,
) -> Schedule:
    """One phase's schedule: `kind` arrival process (steady / diurnal /
    burst) at `target_rps` peak over `duration_s`, hitting `keys`-drawn
    (uniform / zipf) indexes in [0, universe)."""
    p = params or {}
    try:
        arrivals = _ARRIVALS[kind]
    except KeyError:
        raise ValueError(
            f"unknown arrival kind {kind!r} "
            f"(one of {sorted(_ARRIVALS)})"
        ) from None
    try:
        draw = _KEYS[keys]
    except KeyError:
        raise ValueError(
            f"unknown key distribution {keys!r} (one of {sorted(_KEYS)})"
        ) from None
    t = arrivals(derive_seed(seed, f"{kind}/times"), target_rps,
                 duration_s, p)
    k = draw(derive_seed(seed, f"{keys}/keys"), len(t), universe, p)
    return Schedule(times_s=t, key_idx=k)
