"""ctypes bindings for the C++ host runtime (native/gubtpu.cpp).

Loads `libgubtpu.so` from this directory, building it with `make -C native`
on first use when a toolchain is present.  All entry points have pure-Python
fallbacks (core/hashing.py, ops/batch.py), so the library is an
accelerator, not a dependency; `available()` reports which path is active.
"""
from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

log = logging.getLogger("gubernator_tpu.native")

_SO_PATH = os.path.join(os.path.dirname(__file__), "libgubtpu.so")
_NATIVE_DIR = os.path.join(
    os.path.dirname(__file__), os.pardir, os.pardir, "native"
)
_lib: Optional[ctypes.CDLL] = None
_tried = False
_load_lock = threading.Lock()


def _build() -> bool:
    """Compile via make; the Makefile writes to a temp path and renames so
    concurrent builders (other processes) never expose a half-written .so."""
    if not os.path.isdir(_NATIVE_DIR):
        return False
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except (subprocess.SubprocessError, OSError) as e:
        log.info("native build unavailable (%s); using python paths", e)
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _load_lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        lib = _try_load()
        if lib is not None:
            try:
                lib = _bind(lib)
            except AttributeError as e:
                # Loaded fine but misses symbols: a STALE .so from an
                # older build.  Rebuild and retry like a failed dlopen.
                log.info("stale native library (%s); rebuilding", e)
                lib = None
        if lib is None:
            # Missing, stale, torn, or wrong-arch: rebuild once and retry.
            if _build():
                lib = _try_load()
                if lib is not None:
                    try:
                        lib = _bind(lib)
                    except AttributeError as e:
                        log.warning(
                            "rebuilt native library still missing "
                            "symbols: %s", e,
                        )
                        lib = None
        _lib = lib
        return _lib


def _try_load() -> Optional[ctypes.CDLL]:
    if not os.path.exists(_SO_PATH):
        return None
    try:
        return ctypes.CDLL(_SO_PATH)
    except OSError as e:
        log.warning("failed to load %s: %s", _SO_PATH, e)
        return None


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.gub_xxh64_batch.argtypes = [
        ctypes.c_char_p,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        ctypes.c_int64,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
    ]
    lib.gub_xxh64_batch.restype = None
    lib.gub_fnv_hashkey_batch.argtypes = [
        ctypes.c_char_p,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        ctypes.c_int64,
        ctypes.c_int32,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
    ]
    lib.gub_fnv_hashkey_batch.restype = None
    lib.gub_assign_rounds.argtypes = [
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        ctypes.c_void_p,  # shards (int32*) or None
        ctypes.c_int64,
        ctypes.c_int32,
        ctypes.c_int32,
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
    ]
    lib.gub_assign_rounds.restype = ctypes.c_int64
    lib.gub_count_reqs.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.gub_count_reqs.restype = ctypes.c_int64
    lib.gub_parse_reqs2.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.c_int64,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
    ]
    lib.gub_parse_reqs2.restype = ctypes.c_int64
    lib.gub_parse_resps2.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.c_int64,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
    ]
    lib.gub_parse_resps2.restype = ctypes.c_int64
    lib.gub_serialize_resps2.argtypes = [
        ctypes.c_int64,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        ctypes.c_char_p,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        ctypes.c_char_p,   # meta_blob (may be None)
        ctypes.c_void_p,   # meta_off (int64* or None)
        np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
        ctypes.c_int64,
    ]
    lib.gub_serialize_resps2.restype = ctypes.c_int64
    lib.gub_serialize_reqs.argtypes = [
        ctypes.c_int64,
        ctypes.c_char_p,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        ctypes.c_char_p,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
        ctypes.c_int64,
    ]
    lib.gub_serialize_reqs.restype = ctypes.c_int64
    return lib


def available() -> bool:
    return _load() is not None


def hash_keys(keys) -> np.ndarray:
    """XXH64 fingerprints (int64, 0 remapped to 1) of a list of strings."""
    lib = _load()
    n = len(keys)
    if lib is None:
        from gubernator_tpu.core.hashing import bulk_key_hash64

        return bulk_key_hash64(keys)
    encoded = [k.encode() for k in keys]
    offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum([len(e) for e in encoded], out=offsets[1:])
    blob = b"".join(encoded)
    out = np.empty(n, dtype=np.int64)
    lib.gub_xxh64_batch(blob, offsets, n, out)
    return out


def fnv_hashkey_batch(
    payload: bytes, cols, variant: str
) -> Optional[np.ndarray]:
    """FNV-1/FNV-1a ring hashes of each parsed request's hash key
    (name + "_" + unique_key), int64 two's-complement view; 0 on errored
    lanes.  `cols` is a ParsedReqs (its msg_off/msg_len frame table is
    re-walked).  Keeps the columnar router serving under the reference's
    fnv placement rings (replicated_hash.go:33) in mixed clusters.
    Returns None when the native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    out = np.empty(cols.n, dtype=np.int64)
    lib.gub_fnv_hashkey_batch(
        payload, cols.msg_off, cols.msg_len, cols.n,
        0 if variant == "fnv1" else 1, out,
    )
    return out


def assign_rounds(
    hashes: np.ndarray,
    shards: Optional[np.ndarray],
    n_shards: int,
    batch_size: int,
) -> Tuple[np.ndarray, np.ndarray, int]:
    """(round, lane) per request + round count; hashes==0 lanes skipped.

    Native only — callers fall back to the ops/batch.py python loop when
    `available()` is False.
    """
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    n = len(hashes)
    out_round = np.empty(n, dtype=np.int32)
    out_lane = np.empty(n, dtype=np.int32)
    shard_ptr = (
        shards.ctypes.data_as(ctypes.c_void_p)
        if shards is not None
        else None
    )
    n_rounds = lib.gub_assign_rounds(
        np.ascontiguousarray(hashes, dtype=np.int64),
        shard_ptr,
        n,
        n_shards,
        batch_size,
        out_round,
        out_lane,
    )
    return out_round, out_lane, int(n_rounds)


class ParsedReqs:
    """Columnar view of a GetRateLimitsReq payload (gub_parse_reqs2)."""

    __slots__ = (
        "n", "hash", "err", "hits", "limit", "duration", "algo",
        "behavior", "burst", "msg_off", "msg_len", "name_hash",
    )

    def __init__(self, n: int) -> None:
        self.n = n
        self.hash = np.empty(n, dtype=np.int64)
        self.err = np.empty(n, dtype=np.int32)
        self.hits = np.empty(n, dtype=np.int64)
        self.limit = np.empty(n, dtype=np.int64)
        self.duration = np.empty(n, dtype=np.int64)
        self.algo = np.empty(n, dtype=np.int32)
        self.behavior = np.empty(n, dtype=np.int64)
        self.burst = np.empty(n, dtype=np.int64)
        # Each request's raw wire frame within the payload (tag + length
        # varint + body) — splice these to forward without re-encoding.
        self.msg_off = np.empty(n, dtype=np.int64)
        self.msg_len = np.empty(n, dtype=np.int64)
        # XXH64 of the name field alone (0 when empty) — the route key
        # for name-scoped tiers (sketch).
        self.name_hash = np.empty(n, dtype=np.int64)

    def subset(self, idx: np.ndarray) -> "ParsedReqs":
        """Row-subset view (fancy-indexed copies) for split routing."""
        out = ParsedReqs.__new__(ParsedReqs)
        out.n = len(idx)
        for f in ("hash", "err", "hits", "limit", "duration", "algo",
                  "behavior", "burst", "msg_off", "msg_len", "name_hash"):
            setattr(out, f, getattr(self, f)[idx])
        return out


def parse_reqs(payload: bytes) -> Optional[ParsedReqs]:
    """Parse raw GetRateLimitsReq / GetPeerRateLimitsReq bytes into columns.
    Returns None when the native library is unavailable or the payload is
    malformed (callers fall back to python-protobuf for the real error)."""
    lib = _load()
    if lib is None:
        return None
    n = lib.gub_count_reqs(payload, len(payload))
    if n < 0:
        return None
    cols = ParsedReqs(int(n))
    got = lib.gub_parse_reqs2(
        payload, len(payload), n, cols.hash, cols.err, cols.hits,
        cols.limit, cols.duration, cols.algo, cols.behavior, cols.burst,
        cols.msg_off, cols.msg_len, cols.name_hash,
    )
    if got != n:
        return None
    return cols


class ParsedResps:
    """Columnar view of a GetPeerRateLimitsResp payload (gub_parse_resps2).
    err_off/err_len index into the payload bytes (lazy error slicing);
    meta_off/meta_len cover each item's metadata map entries as raw wire
    frames (meta_len -1 = fragmented, drop)."""

    __slots__ = (
        "n", "status", "limit", "remaining", "reset_time",
        "err_off", "err_len", "meta_off", "meta_len",
    )

    def __init__(self, n: int) -> None:
        self.n = n
        self.status = np.empty(n, dtype=np.int64)
        self.limit = np.empty(n, dtype=np.int64)
        self.remaining = np.empty(n, dtype=np.int64)
        self.reset_time = np.empty(n, dtype=np.int64)
        self.err_off = np.empty(n, dtype=np.int64)
        self.err_len = np.empty(n, dtype=np.int64)
        self.meta_off = np.empty(n, dtype=np.int64)
        self.meta_len = np.empty(n, dtype=np.int64)


def parse_resps(payload: bytes) -> Optional[ParsedResps]:
    """Parse raw GetRateLimitsResp / GetPeerRateLimitsResp bytes into
    columns; None when unavailable/malformed."""
    lib = _load()
    if lib is None:
        return None
    n = lib.gub_count_reqs(payload, len(payload))  # same field-1 framing
    if n < 0:
        return None
    cols = ParsedResps(int(n))
    got = lib.gub_parse_resps2(
        payload, len(payload), n, cols.status, cols.limit, cols.remaining,
        cols.reset_time, cols.err_off, cols.err_len, cols.meta_off,
        cols.meta_len,
    )
    if got != n:
        return None
    return cols


def encode_reqs(reqs) -> Optional[bytes]:
    """Emit GetRateLimitsReq / GetPeerRateLimitsReq wire bytes for a
    sequence of RateLimitReq dataclasses without constructing python
    protobuf objects — the compiled CLIENT codec (client.FastV1Client;
    gub_serialize_reqs).  Returns None when the native library is
    unavailable (callers fall back to python-protobuf)."""
    lib = _load()
    if lib is None:
        return None
    n = len(reqs)
    names = [r.name.encode() for r in reqs]
    keys = [r.unique_key.encode() for r in reqs]
    name_off = np.zeros(n + 1, dtype=np.int64)
    np.cumsum([len(b) for b in names], out=name_off[1:])
    key_off = np.zeros(n + 1, dtype=np.int64)
    np.cumsum([len(b) for b in keys], out=key_off[1:])

    def col(attr):
        return np.fromiter(
            (int(getattr(r, attr)) for r in reqs),
            dtype=np.int64, count=n,
        )

    # Worst case per item: 6 numeric fields at 11 B (negative int64
    # varints are 10 B + tag), two string frames at 6 B of framing, and
    # the item frame header — 96 B covers it with slack.
    cap = int(name_off[-1] + key_off[-1]) + n * 96 + 16
    out = np.empty(cap, dtype=np.uint8)
    written = lib.gub_serialize_reqs(
        n, b"".join(names), name_off, b"".join(keys), key_off,
        col("hits"), col("limit"), col("duration"), col("algorithm"),
        col("behavior"), col("burst"), out, cap,
    )
    if written < 0:
        raise RuntimeError("serialize_reqs buffer overflow")
    return out[:written].tobytes()


def _encode_varint(v: int) -> bytes:
    out = bytearray()
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)
    return bytes(out)


def meta_frame(key: bytes, value: bytes) -> bytes:
    """A complete metadata map-entry wire frame (RateLimitResp field 6:
    map<string,string>) for serialize_resps' meta_blob."""
    body = (
        b"\x0a" + _encode_varint(len(key)) + key
        + b"\x12" + _encode_varint(len(value)) + value
    )
    return b"\x32" + _encode_varint(len(body)) + body


def serialize_resps(
    status: np.ndarray,
    limit: np.ndarray,
    remaining: np.ndarray,
    reset_time: np.ndarray,
    err_blob: bytes,
    err_off: np.ndarray,
    meta_blob: Optional[bytes] = None,
    meta_off: Optional[np.ndarray] = None,
) -> bytes:
    """Emit GetRateLimitsResp / GetPeerRateLimitsResp wire bytes from packed
    response columns; meta_blob/meta_off add per-request pre-encoded
    metadata map-entry frames (see meta_frame; forwarded-owner and
    sketch-tier annotations).  Native only (callers gate on available())."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    n = len(status)
    # Worst case per item: 4 varint fields (<=11 B each) + submsg framing
    # (<=6 B) + error bytes (+3 B framing); metadata frames are verbatim.
    cap = (
        n * 64 + len(err_blob)
        + (len(meta_blob) if meta_blob else 0) + 16
    )
    out = np.empty(cap, dtype=np.uint8)
    if meta_off is not None:
        meta_off = np.ascontiguousarray(meta_off, dtype=np.int64)
        meta_off_ptr = meta_off.ctypes.data_as(ctypes.c_void_p)
    else:
        meta_off_ptr = None
    written = lib.gub_serialize_resps2(
        n,
        np.ascontiguousarray(status, dtype=np.int64),
        np.ascontiguousarray(limit, dtype=np.int64),
        np.ascontiguousarray(remaining, dtype=np.int64),
        np.ascontiguousarray(reset_time, dtype=np.int64),
        err_blob,
        np.ascontiguousarray(err_off, dtype=np.int64),
        meta_blob,
        meta_off_ptr,
        out,
        cap,
    )
    if written < 0:
        raise RuntimeError("serialize_resps buffer overflow")
    return out[:written].tobytes()
