"""Device ops layer: slot table, batch packing, vectorized bucket kernels.

Importing this package enables JAX x64 mode — the protocol's counters and
timestamps are int64 (proto gubernator.proto:140-161, store.go:29-43) and the
leaky-bucket remainder is float64.  TPU executes both via XLA's 32-bit-pair
emulation; the elementwise VPU work here is cheap relative to HBM traffic.
"""
import jax

jax.config.update("jax_enable_x64", True)
