"""Device ops layer: slot table, batch packing, vectorized bucket kernels.

Importing this package enables JAX x64 mode — the protocol's counters and
timestamps are int64 (proto gubernator.proto:140-161, store.go:29-43) and the
leaky-bucket remainder is float64.  TPU executes both via XLA's 32-bit-pair
emulation; the elementwise VPU work here is cheap relative to HBM traffic.

When the process explicitly selects the CPU platform (JAX_PLATFORMS=cpu),
any registered out-of-process TPU plugin ("axon") is deregistered: with the
plugin present, the first device->host transfer initializes its client and
every subsequent dispatch — including pure-CPU ones — pays a ~450us tunnel
round-trip (60x slowdown, measured with jax 0.9.0).  Deregistering is safe
here because the env var states CPU-only intent.
"""
import os

import jax

jax.config.update("jax_enable_x64", True)

if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
    jax.config.update("jax_platforms", "cpu")
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
