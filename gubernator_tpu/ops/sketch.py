"""Count-Min-sketch approximate rate limiter — the beyond-exact-state tier.

When the key cardinality outgrows exact per-key slots (the BASELINE.json
100M-key config), admission control degrades gracefully to a sliding-window
count-min sketch: O(1) memory per DECISION volume instead of per key, with a
bounded over-count (never under-count), so it can only over-limit hot tails —
the safe direction for abuse control.

Design (TPU-first):
- State is two [D, W] int32 sketches — current and previous window — plus
  the window index.  Estimated rate = cur + prev * overlap_fraction, the
  standard sliding-window approximation.
- The hot step (cms_step) is GATHER/SCATTER: take_along_axis reads the D
  bucket cells per key and `.at[].add` applies the hits; the window
  rotation is gated behind `lax.cond` so the steady state (inside a
  window) never rewrites the [D, W] tables.  Measured on TPU this beats
  the one-hot-matmul formulation at EVERY width — 3x at W=8192 and ~600x
  at W=2^20 (0.08 ms/step at batch 4096, ~48M checks/s device-side):
  the one-hot path materializes [D, B, W] intermediates and the
  ungated rotation streams the full tables through HBM every step,
  while the scatter path touches D*B cells.
- Large widths are therefore practical: W is bounded by HBM, not VMEM,
  though a CMS rarely needs it — its error bound e*N/W depends on window
  DECISION volume N, not key count.
- Row hashes are derived on device from the key fingerprint with D odd
  multipliers + shifts (multiply-shift hashing) — no host round trips.

cms_step_impl (one-hot matmuls over the MXU, ungated rotation) is kept
as the independently-derived SEMANTIC REFERENCE: both the scatter step
and the fused Pallas kernel (ops/pallas/cms_kernel.py) are
differentially tested bit-exact against it.

No reference analog: gubernator keeps exact state only and simply evicts
under pressure (lrucache.go:147-158), silently over-admitting at scale;
this tier is the TPU build's answer to the same pressure.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

DEFAULT_DEPTH = 4
DEFAULT_WIDTH = 8192

# Odd 64-bit multipliers for multiply-shift row hashing (splitmix64-style
# constants).
_ROW_MULTIPLIERS = (
    0x9E3779B97F4A7C15,
    0xBF58476D1CE4E5B9,
    0x94D049BB133111EB,
    0xD6E8FEB86659FD93,
    0xA5A3564DDF522B81,
    0xC2B2AE3D27D4EB4F,
    0x27D4EB2F165667C5,
    0x165667B19E3779F9,
)


class SketchState(NamedTuple):
    """Sliding-window CMS state."""

    cur: jax.Array       # int32[D, W] — counts in the current window
    prev: jax.Array      # int32[D, W] — counts in the previous window
    window_start: jax.Array  # int64 scalar — unix ms of window start
    window_ms: jax.Array     # int64 scalar — window length


def init_sketch(
    depth: int = DEFAULT_DEPTH,
    width: int = DEFAULT_WIDTH,
    window_ms: int = 1000,
) -> SketchState:
    if depth > len(_ROW_MULTIPLIERS):
        raise ValueError(f"depth must be <= {len(_ROW_MULTIPLIERS)}")
    if width & (width - 1):
        raise ValueError("width must be a power of two")
    z = lambda: jnp.zeros((depth, width), dtype=jnp.int32)
    return SketchState(
        cur=z(),
        prev=z(),
        window_start=jnp.int64(0),
        window_ms=jnp.int64(window_ms),
    )


def row_columns(key_hash: jax.Array, depth: int, width: int) -> jax.Array:
    """Per-row bucket columns [D, B] from int64 fingerprints [B].

    Multiply-shift: col_d = (h * m_d) >> (64 - log2(W)).
    """
    shift = 64 - (width.bit_length() - 1)
    h = key_hash.astype(jnp.uint64)
    cols = []
    for d in range(depth):
        m = jnp.uint64(_ROW_MULTIPLIERS[d])
        cols.append(((h * m) >> jnp.uint64(shift)).astype(jnp.int32))
    return jnp.stack(cols)


def _rotate(state: SketchState, now: jax.Array) -> Tuple[SketchState, jax.Array]:
    """Advance the sliding window.  One step behind -> cur becomes prev;
    further behind -> both clear.  Returns (state, overlap_weight_f32)."""
    now = jnp.asarray(now, dtype=jnp.int64)
    elapsed = now - state.window_start
    w = state.window_ms
    in_window = elapsed < w
    one_behind = (elapsed >= w) & (elapsed < 2 * w)
    new_start = jnp.where(
        in_window, state.window_start, now - (elapsed % w)
    )
    z = jnp.zeros_like(state.cur)
    new_prev = jnp.where(in_window, state.prev, jnp.where(one_behind, state.cur, z))
    new_cur = jnp.where(in_window, state.cur, z)
    frac = (
        1.0
        - (now - new_start).astype(jnp.float32)
        / w.astype(jnp.float32)
    )
    return (
        SketchState(new_cur, new_prev, new_start, state.window_ms),
        jnp.clip(frac, 0.0, 1.0),
    )


def cms_step_impl(
    state: SketchState,
    key_hash: jax.Array,   # int64[B]; 0 = inactive lane
    hits: jax.Array,       # int32[B]
    limit: jax.Array,      # int32[B] — per-lane window limit
    now: jax.Array,        # int64 scalar ms
) -> Tuple[SketchState, jax.Array, jax.Array]:
    """Apply one batch: returns (state', over_limit bool[B], estimate
    int32[B]).

    Estimate/decide BEFORE adding this batch's hits (like the exact token
    bucket: a request whose estimate already exceeds limit-hits is over),
    then scatter the admitted hits.  Duplicate keys in one batch are
    handled naturally — the one-hot matmul sums them into the same column;
    their lanes share one pre-batch estimate (a one-batch-granularity
    approximation consistent with CMS semantics).

    Over-limited hits are still counted (abusers stay counted, matching
    CMS-limiter practice — and unlike the exact bucket, which ignores
    over-limit hits).
    """
    depth, width = state.cur.shape
    state, overlap = _rotate(state, now)
    active = key_hash != 0
    cols = row_columns(key_hash, depth, width)           # [D, B]

    onehots = jax.nn.one_hot(cols, width, dtype=jnp.float32)  # [D, B, W]
    onehots = onehots * active[None, :, None]

    # Gather reads: est_d = onehot[d] @ (cur + prev*overlap) — MXU.
    eff = (
        state.cur.astype(jnp.float32)
        + state.prev.astype(jnp.float32) * overlap
    )                                                     # [D, W]
    reads = jnp.einsum("dbw,dw->db", onehots, eff)        # [D, B]
    estimate = jnp.min(reads, axis=0)                     # [B]

    over = active & (
        estimate + hits.astype(jnp.float32)
        > limit.astype(jnp.float32)
    ) & (hits > 0)

    # Scatter adds: upd_d = onehot[d].T @ hits — MXU.
    upd = jnp.einsum(
        "dbw,b->dw", onehots, hits.astype(jnp.float32)
    )                                                     # [D, W]
    new_cur = state.cur + upd.astype(jnp.int32)

    return (
        SketchState(new_cur, state.prev, state.window_start, state.window_ms),
        over,
        estimate.astype(jnp.int32),
    )


# The semantic reference, jitted (differential tests drive this).
cms_step_onehot = jax.jit(cms_step_impl, donate_argnums=(0,))


def _rotate_cond(
    state: SketchState, now: jax.Array
) -> Tuple[SketchState, jax.Array]:
    """_rotate with the table rewrite gated behind lax.cond: the steady
    state (now inside the current window) costs two scalar compares
    instead of streaming both [D, W] tables through HBM.  Bit-identical
    outcomes to _rotate (differentially tested)."""
    now = jnp.asarray(now, dtype=jnp.int64)
    elapsed = now - state.window_start
    w = state.window_ms

    def stay(s: SketchState) -> SketchState:
        return s

    def roll(s: SketchState) -> SketchState:
        one_behind = (elapsed >= w) & (elapsed < 2 * w)
        z = jnp.zeros_like(s.cur)
        return SketchState(
            cur=z,
            prev=jnp.where(one_behind, s.cur, z),
            window_start=now - (elapsed % w),
            window_ms=s.window_ms,
        )

    state = jax.lax.cond(elapsed < w, stay, roll, state)
    frac = (
        1.0
        - (now - state.window_start).astype(jnp.float32)
        / w.astype(jnp.float32)
    )
    return state, jnp.clip(frac, 0.0, 1.0)


def cms_step_scatter_impl(
    state: SketchState,
    key_hash: jax.Array,
    hits: jax.Array,
    limit: jax.Array,
    now: jax.Array,
) -> Tuple[SketchState, jax.Array, jax.Array]:
    """The hot-path step: gather reads + scatter adds, bit-exact against
    cms_step_impl (see the module docstring for the measured rationale).

    Duplicate keys in one batch behave identically to the reference:
    `.at[].add` sums same-cell hits the way the one-hot matmul does, and
    every duplicate lane reads the shared pre-batch estimate."""
    depth, width = state.cur.shape
    state, overlap = _rotate_cond(state, now)
    active = key_hash != 0
    cols = row_columns(key_hash, depth, width)            # [D, B]

    rc = jnp.take_along_axis(state.cur, cols, axis=1)
    rp = jnp.take_along_axis(state.prev, cols, axis=1)
    reads = rc.astype(jnp.float32) + rp.astype(jnp.float32) * overlap
    estimate = jnp.where(active, jnp.min(reads, axis=0), 0.0)  # [B]

    over = active & (
        estimate + hits.astype(jnp.float32)
        > limit.astype(jnp.float32)
    ) & (hits > 0)

    add = jnp.where(active, hits, 0).astype(jnp.int32)    # [B]
    d_idx = jnp.broadcast_to(jnp.arange(depth)[:, None], cols.shape)
    new_cur = state.cur.at[d_idx, cols].add(
        jnp.broadcast_to(add[None, :], cols.shape)
    )

    return (
        SketchState(new_cur, state.prev, state.window_start, state.window_ms),
        over,
        estimate.astype(jnp.int32),
    )


cms_step = jax.jit(cms_step_scatter_impl, donate_argnums=(0,))
