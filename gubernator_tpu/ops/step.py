"""The device step: batched lookup/insert + branchless bucket algorithms.

One jitted call applies a whole padded batch of rate-limit checks against the
slot table and returns per-lane responses:

    table', resp = apply_batch(table, batch, now)

This replaces the reference's per-request path
(worker channel -> algorithm fn -> LRU dict, workers.go:249-314 +
algorithms.go) with: bucket gather -> victim/claim resolution -> lane
arithmetic -> scatter.  Every ordered special case in algorithms.go is
re-derived as `jnp.where` lane selects; the differential test
(tests/test_differential.py) drives random op streams through this and the
sequential oracle (core/pymodel.py) and requires identical decisions.

Design notes:
- Lookup is W-way set-associative: bucket = key_hash & (num_buckets-1);
  num_buckets must be a power of two.
- Expired slots do not match (the reference cache returns a miss for expired
  items, lrucache.go:115-127); a request whose own slot expired prefers
  reusing that slot.
- Within-batch insert conflicts (two new keys choosing the same victim slot)
  are resolved with sort-based claim rounds — no O(num_slots) temporaries.
  After INSERT_ROUNDS, unresolved lanes are answered as "transient" new items
  (correct response, state not persisted) — the same acceptable-loss contract
  as reference cache eviction (architecture.md:5-11).
- Duplicate keys within a batch are the host packer's job (ops/batch.py
  rounds); this kernel assumes each active key appears once.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from gubernator_tpu.ops.state import KIND_BUCKET, KIND_CACHED_RESP, SlotTable

ALGO_TOKEN = 0
ALGO_LEAKY = 1
UNDER = 0
OVER = 1

INSERT_ROUNDS = 3


class Resp(NamedTuple):
    """Per-lane response arrays (RateLimitResp, gubernator.proto:169-182)."""

    status: jax.Array     # int32[B]
    limit: jax.Array      # int64[B]
    remaining: jax.Array  # int64[B]
    reset_time: jax.Array  # int64[B]
    persisted: jax.Array  # bool[B]; False = transient (state not stored)
    found: jax.Array      # bool[B]; matched a live slot
    # POST-step stored remaining (truncated for leaky) — differs from the
    # response `remaining` in corner branches (e.g. a token duration-renew
    # on a hits=0 read reports the pre-renew value, algorithms.go:167).
    # Seeds the fast lane's host-side duplicate cascade
    # (runtime/fastpath.py).
    stored: jax.Array     # int64[B]
    # Lane answered VERBATIM from a live KIND_CACHED_RESP row (the GLOBAL
    # broadcast read path) — no mutation happened; the fast lane's cached
    # duplicate cascade branches on this.
    cached: jax.Array     # bool[B]
    # POST-step stored Status column (the write-back's n_status): what a
    # hits=0 re-read of this row would report.  Token status is STICKY —
    # it differs from the response status on over-more hits, which report
    # OVER without storing it (algorithms.go:167-195); leaky rows store
    # UNDER always (status is computed per read, algorithms.go:395-426).
    # Lets the GLOBAL broadcast derive its rows from the drain's own
    # response instead of re-running zero-hit reads (global.go:205-250).
    stored_status: jax.Array  # int32[B]


class DeviceBatchJ(NamedTuple):
    """Device-side mirror of ops.batch.DeviceBatch."""

    key_hash: jax.Array
    hits: jax.Array
    limit: jax.Array
    duration: jax.Array
    algo: jax.Array
    burst: jax.Array
    reset_remaining: jax.Array
    is_greg: jax.Array
    greg_expire: jax.Array
    greg_duration: jax.Array
    active: jax.Array
    # GLOBAL read path (gubernator.go:434-447): lanes with use_cached set
    # answer verbatim from a live KIND_CACHED_RESP row (the owner's broadcast
    # status) without mutating it; on miss they fall through to the normal
    # algorithm ("process the rate limit like we own it").
    use_cached: jax.Array


def _f64(x: jax.Array) -> jax.Array:
    return x.astype(jnp.float64)


def _trunc_i64(x: jax.Array) -> jax.Array:
    """Go's int64(float64): truncation toward zero.

    Edge semantics are XLA convert's, differentially pinned against the
    oracle (core/pymodel.py _trunc; tests/test_differential.py::
    test_go_trunc_differential): -1.5 -> -1 (toward zero, not floor),
    exact through +/-2^62, out-of-range/inf SATURATE at the int64
    bounds, NaN -> 0.  Go's own spec leaves these implementation-
    dependent (amd64 CVTTSD2SI gives INT64_MIN for all three), so the
    saturating behavior is this build's documented contract.
    """
    return x.astype(jnp.int64)


def _sat_add_i64(a: jax.Array, b: jax.Array) -> jax.Array:
    """int64 a+b with two's-complement wrap replaced by saturation.

    Equivalent to clamping the exact unbounded-int sum, which is what
    the oracle mirror (core/pymodel.py _sat_add) computes — the
    differential suite holds the two bit-identical at the int64 corners
    (tests/test_gubrange.py).  Construction: clamp `b` into the room
    `a` leaves before the bound, then add — NO intermediate ever wraps
    (`max(a,0) ∈ [0,MAX]` so `MAX - max(a,0) ∈ [0,MAX]`, and the final
    sum is confined to [MIN,MAX] by the clip), which keeps the gubrange
    interval walk exact instead of a wrap-then-repair select that joins
    to the full int64 range.  Guards the expire/reset epoch math
    against hostile wire durations (the reference wraps silently here,
    algorithms.go:141 `now + r.Duration`); gubrange proves in-envelope
    inputs never come near saturation.
    """
    hi = jnp.int64(2**63 - 1)
    lo = jnp.int64(-(2**63))
    zero = jnp.int64(0)
    room_hi = hi - jnp.maximum(a, zero)
    room_lo = lo - jnp.minimum(a, zero)
    return a + jnp.clip(b, room_lo, room_hi)


def _sat_sub_i64(a: jax.Array, b: jax.Array) -> jax.Array:
    """int64 a-b saturating at the bounds (see _sat_add_i64).

    The subtrahend is clamped into [a-MAX, a-MIN] before subtracting;
    when a constraint endpoint is unrepresentable the corresponding
    clip bound degenerates to MIN/MAX (vacuous), so nothing wraps:
    `max(a,-1) - MAX ∈ [MIN,0]` and `min(a,-1) - MIN ∈ [0,MAX]`.
    """
    hi = jnp.int64(2**63 - 1)
    lo = jnp.int64(-(2**63))
    neg1 = jnp.int64(-1)
    b_lo = jnp.maximum(a, neg1) - hi
    b_hi = jnp.minimum(a, neg1) - lo
    return a - jnp.clip(b, b_lo, b_hi)


def _first_claim(tgt: jax.Array, attempt: jax.Array) -> jax.Array:
    """Of all lanes attempting the same target slot, the lowest lane wins.

    Sort-based, O(B log B), no table-sized temporaries.  Returns bool[B]
    winner mask.
    """
    sent = jnp.int64(1) << 62
    v = jnp.where(attempt, tgt, sent)
    order = jnp.argsort(v, stable=True)  # stable: equal slots -> lane order
    v_sorted = v[order]
    first = jnp.concatenate(
        [jnp.ones((1,), dtype=bool), v_sorted[1:] != v_sorted[:-1]]
    )
    win_sorted = first & (v_sorted != sent)
    return jnp.zeros(tgt.shape, dtype=bool).at[order].set(win_sorted)


def _member_of(sorted_vals: jax.Array, queries: jax.Array) -> jax.Array:
    """Membership of `queries` in `sorted_vals` via searchsorted."""
    pos = jnp.searchsorted(sorted_vals, queries)
    pos = jnp.clip(pos, 0, sorted_vals.shape[0] - 1)
    return sorted_vals[pos] == queries


def locate_slots(
    table: SlotTable,
    h: jax.Array,
    active: jax.Array,
    now: jax.Array,
    ways: int,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Set-associative lookup + insert-victim claim for a batch of keys.

    Returns (found, persist, slot, slot_safe): `found` lanes matched a live
    slot at `slot`; `persist & ~found` lanes won an insert victim at `slot`;
    `~persist` lanes could not claim a slot (transient).  Each active key
    must appear at most once in the batch (the packer's contract).
    """
    S = table.key.shape[0]
    nb = S // ways
    if nb & (nb - 1):
        raise ValueError(f"num_buckets ({nb}) must be a power of two")
    B = h.shape[0]

    bucket = (h.astype(jnp.uint64) & jnp.uint64(nb - 1)).astype(jnp.int64)
    sidx = bucket[:, None] * ways + jnp.arange(ways, dtype=jnp.int64)[None, :]

    cand_key = table.key[sidx]          # [B, W]
    cand_expire = table.expire_at[sidx]
    cand_touched = table.touched[sidx]

    keymatch = (cand_key == h[:, None]) & active[:, None]
    live = cand_expire > now
    match = keymatch & live
    found = match.any(axis=1)
    match_slot = bucket * ways + jnp.argmax(match, axis=1)

    # ---- victim scoring for inserts ------------------------------------
    # Preference: my own expired slot > empty > other expired > oldest touch.
    empty = cand_key == 0
    mine_stale = keymatch & ~live
    klass = jnp.where(
        mine_stale, 0, jnp.where(empty, 1, jnp.where(~live, 2, 3))
    ).astype(jnp.int64)
    vscore = klass * (jnp.int64(1) << 48) + cand_touched  # touched < 2^48 ms

    need = active & ~found
    inf = jnp.int64(1) << 62
    insert_slot = jnp.full((B,), -1, dtype=jnp.int64)
    won = jnp.zeros((B,), dtype=bool)

    for _ in range(INSERT_ROUNDS):
        # Slots reserved this batch: live matches + already-won inserts.
        reserved = jnp.sort(
            jnp.concatenate(
                [
                    jnp.where(found, match_slot, -1),
                    jnp.where(won, insert_slot, -1),
                ]
            )
        )
        blocked = _member_of(reserved, sidx.ravel()).reshape(sidx.shape)
        vs = jnp.where(blocked, inf, vscore)
        vmin = jnp.min(vs, axis=1)
        vslot = bucket * ways + jnp.argmin(vs, axis=1)
        attempt = need & ~won & (vmin < inf)
        win_now = _first_claim(vslot, attempt)
        insert_slot = jnp.where(win_now, vslot, insert_slot)
        won = won | win_now

    persist = found | won
    slot = jnp.where(found, match_slot, jnp.where(won, insert_slot, 0))
    slot_safe = jnp.clip(slot, 0, S - 1)
    return found, persist, slot, slot_safe


def apply_batch_impl(
    table: SlotTable,
    batch: DeviceBatchJ,
    now: jax.Array,
    ways: int = 8,
) -> Tuple[SlotTable, Resp]:
    """Apply one padded batch; returns (new_table, responses).

    Un-jitted traceable core — call `apply_batch` directly, or wrap this in
    `shard_map` for the mesh-sharded table (gubernator_tpu.parallel).
    """
    S = table.key.shape[0]
    now = jnp.asarray(now, dtype=jnp.int64)

    h = batch.key_hash
    active = batch.active
    found, persist, slot, slot_safe = locate_slots(table, h, active, now, ways)

    # ---- gather current rows -------------------------------------------
    g = lambda a: a[slot_safe]
    s_algo = g(table.algo)
    s_kind = g(table.kind)
    s_limit = g(table.limit)
    s_dur = g(table.duration)
    s_rem = g(table.remaining)
    s_rem_f = g(table.remaining_f)
    s_t0 = g(table.t0)
    s_status = g(table.status)
    s_burst = g(table.burst)
    s_expire = g(table.expire_at)

    r_hits, r_lim, r_dur = batch.hits, batch.limit, batch.duration
    r_burst = batch.burst
    is_greg = batch.is_greg
    greg_exp = batch.greg_expire
    greg_dur = batch.greg_duration
    req_token = batch.algo == ALGO_TOKEN
    req_leaky = batch.algo == ALGO_LEAKY
    reset = batch.reset_remaining

    is_bucket_row = found & (s_kind == KIND_BUCKET)
    # GLOBAL non-owner read (gubernator.go:434-447): a live cached broadcast
    # row answers verbatim, no mutation.  Without use_cached, a cached row is
    # treated like an algorithm-switch (overwritten via the new-item path).
    cached_hit = found & (s_kind == KIND_CACHED_RESP) & batch.use_cached
    # Path selection (see module docstring):
    tok_clear = req_token & reset & found  # algorithms.go:78-90 (pre type check)
    tok_exist = req_token & ~reset & is_bucket_row & (s_algo == ALGO_TOKEN)
    lky_exist = req_leaky & is_bucket_row & (s_algo == ALGO_LEAKY)
    is_new = active & ~tok_clear & ~tok_exist & ~lky_exist

    # ==== token bucket, existing item (algorithms.go:112-195) ===========
    limit_changed = s_limit != r_lim
    rem0 = jnp.where(
        limit_changed,
        jnp.maximum(_sat_sub_i64(_sat_add_i64(s_rem, r_lim), s_limit), 0),
        s_rem,
    )
    dur_changed = s_dur != r_dur
    expire1 = jnp.where(is_greg, greg_exp, _sat_add_i64(s_t0, r_dur))
    renew = dur_changed & (expire1 <= now)
    te_expire = jnp.where(
        dur_changed, jnp.where(renew, _sat_add_i64(now, r_dur), expire1),
        s_expire,
    )
    te_t0 = jnp.where(renew, now, s_t0)
    rem1 = jnp.where(renew, r_lim, rem0)

    h0 = r_hits == 0
    # "Already at the limit" tests the RESPONSE remaining (rem0, set before
    # the duration-renew branch mutates item remaining) — algorithms.go:167.
    over_zero = ~h0 & (rem0 == 0) & (r_hits > 0)
    exact = ~h0 & ~over_zero & (rem1 == r_hits)  # algorithms.go:176 (item rem)
    over_more = ~h0 & ~over_zero & ~exact & (r_hits > rem1)
    under = ~h0 & ~over_zero & ~exact & ~over_more

    te_rem = jnp.where(exact, 0, jnp.where(under, rem1 - r_hits, rem1))
    te_status = jnp.where(over_zero, OVER, s_status)
    te_resp_status = jnp.where(over_zero | over_more, OVER, s_status)
    te_resp_rem = jnp.where(exact | under, te_rem, rem0)
    te_resp_reset = te_expire

    # ==== token bucket, new item (algorithms.go:203-258) ================
    tn_over = r_hits > r_lim
    tn_rem = jnp.where(tn_over, r_lim, r_lim - r_hits)
    tn_expire = jnp.where(is_greg, greg_exp, _sat_add_i64(now, r_dur))
    tn_resp_status = jnp.where(tn_over, OVER, UNDER)

    # ==== leaky bucket, existing item (algorithms.go:327-426) ===========
    lb0 = jnp.where(reset & req_leaky, _f64(r_burst), s_rem_f)
    grow = (s_burst != r_burst) & (r_burst > _trunc_i64(lb0))
    lb1 = jnp.where(grow, _f64(r_burst), lb0)
    l_dur_c = jnp.where(is_greg, greg_exp - now, r_dur)
    safe_lim = jnp.where(r_lim == 0, 1, r_lim)
    l_rate = jnp.where(
        r_lim == 0,
        0.0,
        jnp.where(is_greg, _f64(greg_dur), _f64(r_dur)) / _f64(safe_lim),
    )
    # l_dur_c may be negative under Gregorian (greg_exp already passed);
    # saturating add keeps a hostile wire expiry from wrapping the epoch.
    le_expire = jnp.where(r_hits != 0, _sat_add_i64(now, l_dur_c), s_expire)
    elapsed = _f64(now - s_t0)
    leak = jnp.where(l_rate != 0.0, elapsed / l_rate, 0.0)
    leaked = _trunc_i64(leak) > 0
    lb2 = jnp.where(leaked, lb1 + leak, lb1)
    le_t0 = jnp.where(leaked, now, s_t0)
    lb3 = jnp.where(_trunc_i64(lb2) > r_burst, _f64(r_burst), lb2)
    lrem_i = _trunc_i64(lb3)
    lrate_i = _trunc_i64(l_rate)

    l_over_zero = (lrem_i == 0) & (r_hits > 0)
    l_exact = ~l_over_zero & (lrem_i == r_hits)
    l_over_more = ~l_over_zero & ~l_exact & (r_hits > lrem_i)
    l_take = l_exact | (~l_over_zero & ~l_exact & ~l_over_more & (r_hits != 0))
    lb4 = jnp.where(l_take, lb3 - _f64(r_hits), lb3)
    le_resp_rem = jnp.where(
        l_exact, 0, jnp.where(l_take, _trunc_i64(lb4), lrem_i)
    )
    # ResetTime = now + (limit - remaining) * rate computed in float64 and
    # truncated through the _trunc_i64 saturation contract: exact below
    # 2^53 (every realistic envelope), saturating instead of wrapping for
    # hostile wire limits/durations.  The oracle mirrors the same
    # float64 evaluation order bit-for-bit (core/pymodel.py).
    f_now = _f64(now)
    f_lim = _f64(r_lim)
    f_lrate = _f64(lrate_i)
    le_resp_reset = _trunc_i64(jnp.where(
        l_take,
        f_now + (f_lim - _f64(le_resp_rem)) * f_lrate,
        f_now + (f_lim - _f64(lrem_i)) * f_lrate,
    ))
    le_resp_status = jnp.where(l_over_zero | l_over_more, OVER, UNDER)

    # ==== leaky bucket, new item (algorithms.go:433-492) ================
    # Quirk preserved: rate uses RAW r.duration even under Gregorian
    # (algorithms.go:441 computes rate before the adjustment).
    ln_rate_i = _trunc_i64(
        jnp.where(r_lim == 0, 0.0, _f64(r_dur) / _f64(safe_lim))
    )
    ln_dur = jnp.where(is_greg, greg_exp - now, r_dur)
    ln_over = r_hits > r_burst
    ln_rem_f = jnp.where(ln_over, 0.0, _f64(r_burst - r_hits))
    ln_resp_rem = jnp.where(ln_over, 0, r_burst - r_hits)
    ln_resp_reset = _trunc_i64(
        f_now + (f_lim - _f64(ln_resp_rem)) * _f64(ln_rate_i)
    )
    ln_resp_status = jnp.where(ln_over, OVER, UNDER)
    ln_expire = _sat_add_i64(now, ln_dur)

    # ==== select per-lane outputs =======================================
    tok_new = is_new & req_token
    lky_new = is_new & req_leaky

    def sel(te, tn, le, ln, clear):
        x = jnp.where(tok_exist, te, 0)
        x = jnp.where(tok_new, tn, x)
        x = jnp.where(lky_exist, le, x)
        x = jnp.where(lky_new, ln, x)
        return jnp.where(tok_clear, clear, x)

    resp = Resp(
        status=jnp.where(
            cached_hit,
            s_status,
            sel(
                te_resp_status, tn_resp_status, le_resp_status, ln_resp_status,
                UNDER,
            ),
        ).astype(jnp.int32),
        limit=jnp.where(cached_hit, s_limit, jnp.where(active, r_lim, 0)),
        remaining=jnp.where(
            cached_hit,
            s_rem,
            sel(te_resp_rem, tn_rem, le_resp_rem, ln_resp_rem, r_lim),
        ),
        # Cached rows store ExpireAt = broadcast ResetTime (gubernator.go:466).
        reset_time=jnp.where(
            cached_hit,
            s_expire,
            sel(te_resp_reset, tn_expire, le_resp_reset, ln_resp_reset, 0),
        ),
        persisted=persist & active,
        found=found,
        stored=jnp.where(
            cached_hit,
            s_rem,
            sel(
                te_rem, tn_rem, _trunc_i64(lb4), _trunc_i64(ln_rem_f), r_lim
            ),
        ),
        cached=cached_hit,
        # Mirrors the write-back's n_status below (kept in sync).
        stored_status=jnp.where(
            cached_hit, s_status, sel(te_status, UNDER, 0, 0, 0)
        ).astype(jnp.int32),
    )

    # ==== write back ====================================================
    do_write = persist & active & ~cached_hit
    tgt = jnp.where(do_write, slot, S)  # S -> dropped by scatter mode

    n_key = jnp.where(tok_clear, 0, h)
    n_algo = jnp.where(tok_clear, 0, batch.algo).astype(jnp.int32)
    n_kind = jnp.zeros_like(s_kind)
    n_limit = sel(r_lim, r_lim, r_lim, r_lim, 0)
    # Stored duration: leaky-existing stores RAW r.duration (algorithms.go:340)
    # but leaky-new stores the COMPUTED duration (algorithms.go:457).
    n_dur = sel(r_dur, r_dur, r_dur, ln_dur, 0)
    n_rem = sel(te_rem, tn_rem, 0, 0, 0)
    n_rem_f = sel(0.0, 0.0, lb4, ln_rem_f, 0.0)
    n_t0 = sel(te_t0, now, le_t0, now, 0)
    n_status = sel(te_status, UNDER, 0, 0, 0).astype(jnp.int32)
    n_burst = sel(s_burst, 0, r_burst, r_burst, 0)
    n_expire = sel(te_expire, tn_expire, le_expire, ln_expire, 0)
    n_touched = jnp.where(tok_clear, 0, now)

    def scat(arr, val):
        return arr.at[tgt].set(val.astype(arr.dtype), mode="drop")

    new_table = SlotTable(
        key=scat(table.key, n_key),
        algo=scat(table.algo, n_algo),
        kind=scat(table.kind, n_kind),
        limit=scat(table.limit, n_limit),
        duration=scat(table.duration, n_dur),
        remaining=scat(table.remaining, n_rem),
        remaining_f=scat(table.remaining_f, n_rem_f),
        t0=scat(table.t0, n_t0),
        status=scat(table.status, n_status),
        burst=scat(table.burst, n_burst),
        expire_at=scat(table.expire_at, n_expire),
        touched=scat(table.touched, n_touched),
    )
    return new_table, resp


apply_batch = jax.jit(
    apply_batch_impl, static_argnames=("ways",), donate_argnums=(0,)
)


class BucketRows(NamedTuple):
    """A batch of full bucket rows for bulk upsert — the device side of the
    Loader restore stream (workers.go:340-426) and of Store.Get seeding
    (algorithms.go:45-51).  key_hash 0 = inactive lane."""

    key_hash: jax.Array    # int64[B]
    algo: jax.Array        # int32[B]
    limit: jax.Array       # int64[B]
    duration: jax.Array    # int64[B]
    remaining: jax.Array   # int64[B]
    remaining_f: jax.Array  # float64[B]
    t0: jax.Array          # int64[B]
    status: jax.Array      # int32[B]
    burst: jax.Array       # int64[B]
    expire_at: jax.Array   # int64[B]


def load_rows_impl(
    table: SlotTable,
    rows: BucketRows,
    now: jax.Array,
    ways: int = 8,
) -> SlotTable:
    """Upsert full bucket rows (KIND_BUCKET).  Keys unique within the batch."""
    S = table.key.shape[0]
    now = jnp.asarray(now, dtype=jnp.int64)
    active = rows.key_hash != 0
    _, persist, slot, _ = locate_slots(table, rows.key_hash, active, now, ways)
    do_write = persist & active
    tgt = jnp.where(do_write, slot, S)

    def scat(arr, val):
        return arr.at[tgt].set(val.astype(arr.dtype), mode="drop")

    return SlotTable(
        key=scat(table.key, rows.key_hash),
        algo=scat(table.algo, rows.algo),
        kind=scat(table.kind, jnp.full_like(rows.algo, KIND_BUCKET)),
        limit=scat(table.limit, rows.limit),
        duration=scat(table.duration, rows.duration),
        remaining=scat(table.remaining, rows.remaining),
        remaining_f=scat(table.remaining_f, rows.remaining_f),
        t0=scat(table.t0, rows.t0),
        status=scat(table.status, rows.status),
        burst=scat(table.burst, rows.burst),
        expire_at=scat(table.expire_at, rows.expire_at),
        touched=scat(table.touched, jnp.full_like(rows.key_hash, now)),
    )


load_rows = jax.jit(
    load_rows_impl, static_argnames=("ways",), donate_argnums=(0,)
)


def probe_batch_impl(
    table: SlotTable,
    h: jax.Array,
    now: jax.Array,
    ways: int = 8,
) -> Tuple[jax.Array, jax.Array]:
    """Read-only batched lookup: (found, slot) per lane.

    The batched analog of a cache-miss test (lrucache.go:111-127) — used by
    the Store write-through path to find which keys need `Store.Get` seeding
    before a batch, and to read back written rows for `Store.OnChange`.
    """
    S = table.key.shape[0]
    nb = S // ways
    bucket = (h.astype(jnp.uint64) & jnp.uint64(nb - 1)).astype(jnp.int64)
    sidx = bucket[:, None] * ways + jnp.arange(ways, dtype=jnp.int64)[None, :]
    match = (
        (table.key[sidx] == h[:, None])
        & (h[:, None] != 0)
        & (table.expire_at[sidx] > now)
    )
    found = match.any(axis=1)
    slot = bucket * ways + jnp.argmax(match, axis=1)
    return found, jnp.where(found, slot, 0)


probe_batch = jax.jit(probe_batch_impl, static_argnames=("ways",))


# Row order of gather_rows' packed int output (remaining_f travels as a
# separate float64 array: TPU's X64-emulation pass cannot rewrite an s64
# bitcast-convert, so the float is NOT bit-packed into the int stack).
GATHER_ROW_FIELDS = (
    "found", "kind", "algo", "limit", "duration", "remaining",
    "t0", "status", "burst", "expire_at",
)


def gather_rows_impl(
    table: SlotTable,
    h: jax.Array,
    now: jax.Array,
    ways: int = 8,
) -> Tuple[jax.Array, jax.Array]:
    """Columnar row read-back: probe + gather every CacheItem field for a
    hash batch as (int64[10, B] in GATHER_ROW_FIELDS order,
    float64[B] remaining_f) — two buffers fetched in one sync where
    per-field reads would cost a transfer each.  The compiled fast lane's
    Store.on_change capture (the batched analog of the read the reference
    does inline at algorithms.go:154-158); h=0 lanes read as not-found."""
    found, slot = probe_batch_impl(table, h, now, ways=ways)

    def g(arr):
        return arr[slot]

    packed = jnp.stack([
        found.astype(jnp.int64),
        g(table.kind).astype(jnp.int64),
        g(table.algo).astype(jnp.int64),
        g(table.limit),
        g(table.duration),
        g(table.remaining),
        g(table.t0),
        g(table.status).astype(jnp.int64),
        g(table.burst),
        g(table.expire_at),
    ])
    return packed, g(table.remaining_f)


gather_rows = jax.jit(gather_rows_impl, static_argnames=("ways",))


class CachedRows(NamedTuple):
    """A batch of owner-broadcast statuses (UpdatePeerGlobal rows,
    peers.proto:52-56): key fingerprint + the authoritative RateLimitResp."""

    key_hash: jax.Array   # int64[B]; 0 = inactive lane
    algo: jax.Array       # int32[B]
    limit: jax.Array      # int64[B]
    remaining: jax.Array  # int64[B]
    status: jax.Array     # int32[B]
    reset_time: jax.Array  # int64[B]


def store_cached_rows_impl(
    table: SlotTable,
    rows: CachedRows,
    now: jax.Array,
    ways: int = 8,
) -> SlotTable:
    """Broadcast-receive: upsert KIND_CACHED_RESP rows into a cache table.

    The device analog of UpdatePeerGlobals -> AddCacheItem
    (gubernator.go:464-479): the stored item IS the response, with
    ExpireAt = status.ResetTime.  Keys must be unique within the batch.
    """
    S = table.key.shape[0]
    now = jnp.asarray(now, dtype=jnp.int64)
    active = rows.key_hash != 0
    found, persist, slot, _ = locate_slots(
        table, rows.key_hash, active, now, ways
    )
    do_write = persist & active
    tgt = jnp.where(do_write, slot, S)

    def scat(arr, val):
        return arr.at[tgt].set(val.astype(arr.dtype), mode="drop")

    z = jnp.zeros_like(rows.key_hash)
    return SlotTable(
        key=scat(table.key, rows.key_hash),
        algo=scat(table.algo, rows.algo),
        kind=scat(table.kind, jnp.full_like(rows.algo, KIND_CACHED_RESP)),
        limit=scat(table.limit, rows.limit),
        duration=scat(table.duration, z),
        remaining=scat(table.remaining, rows.remaining),
        remaining_f=scat(table.remaining_f, z.astype(jnp.float64)),
        t0=scat(table.t0, z),
        status=scat(table.status, rows.status),
        burst=scat(table.burst, z),
        expire_at=scat(table.expire_at, rows.reset_time),
        touched=scat(table.touched, jnp.full_like(rows.key_hash, now)),
    )


store_cached_rows = jax.jit(
    store_cached_rows_impl, static_argnames=("ways",), donate_argnums=(0,)
)


def apply_batch_packed_impl(
    table: SlotTable,
    batch: DeviceBatchJ,
    now: jax.Array,
    ways: int = 8,
) -> Tuple[SlotTable, jax.Array]:
    """apply_batch with the response packed into ONE int64[9, B] array —
    a single device->host transfer per step instead of nine.  Matters when
    the host link has per-transfer latency (e.g. remote-device tunnels).

    Rows: status, limit, remaining, reset_time, persisted, found, stored,
    cached, stored_status.
    """
    new_table, r = apply_batch_impl(table, batch, now, ways)
    packed = jnp.stack([
        r.status.astype(jnp.int64),
        r.limit.astype(jnp.int64),
        r.remaining.astype(jnp.int64),
        r.reset_time.astype(jnp.int64),
        r.persisted.astype(jnp.int64),
        r.found.astype(jnp.int64),
        r.stored.astype(jnp.int64),
        r.cached.astype(jnp.int64),
        r.stored_status.astype(jnp.int64),
    ])
    return new_table, packed


apply_batch_packed = jax.jit(
    apply_batch_packed_impl, static_argnames=("ways",), donate_argnums=(0,)
)


def unpack_batch_q(q) -> DeviceBatchJ:
    """Device-side unpack of ONE int64[12, B] request array (row order =
    DeviceBatch field order; bools/int32s travel widened as int64)."""
    return DeviceBatchJ(
        key_hash=q[0], hits=q[1], limit=q[2], duration=q[3],
        algo=q[4].astype(jnp.int32), burst=q[5],
        reset_remaining=q[6].astype(bool), is_greg=q[7].astype(bool),
        greg_expire=q[8], greg_duration=q[9],
        active=q[10].astype(bool), use_cached=q[11].astype(bool),
    )


def apply_batch_packed_q_impl(
    table: SlotTable,
    q: jax.Array,
    now: jax.Array,
    ways: int = 8,
) -> Tuple[SlotTable, jax.Array]:
    """Fully packed step: ONE int64[12, B] host->device transfer in, ONE
    int64[9, B] transfer out.  Per-transfer link latency (remote-device
    tunnels) makes the 12-arrays-in form 12x more expensive; this is the
    single-device analog of the mesh path's pack_grid_batch."""
    return apply_batch_packed_impl(table, unpack_batch_q(q), now, ways)


apply_batch_packed_q = jax.jit(
    apply_batch_packed_q_impl, static_argnames=("ways",), donate_argnums=(0,)
)
