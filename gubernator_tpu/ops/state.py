"""The slot table: device-resident rate-limit state.

Replaces the reference's per-worker LRU dict (lrucache.go:32-223) with a
fixed-size, W-way set-associative table held as a struct-of-arrays on device.
A key's 64-bit fingerprint selects one bucket of `ways` slots; lookups gather
all ways and match on the stored fingerprint; inserts pick a victim way
(empty > expired > least-recently-touched).  Eviction is therefore
bucket-local pseudo-LRU rather than the reference's global list LRU
(lrucache.go:147-158) — the acceptable-loss design (architecture.md:5-11)
makes early eviction safe: it can only briefly over-admit.

All arrays share leading dimension S = num_slots so the table shards cleanly
along axis 0 over a device mesh (see gubernator_tpu.parallel.mesh).
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

# Slot `kind` values.
KIND_BUCKET = 0
KIND_CACHED_RESP = 1  # non-owner's cached GLOBAL broadcast (gubernator.go:464-479)


class SlotTable(NamedTuple):
    """Struct-of-arrays; one row = one CacheItem (cache.go:30-42) flattened
    together with its TokenBucketItem / LeakyBucketItem payload
    (store.go:29-43)."""

    key: jax.Array         # int64[S]; xxhash64 fingerprint; 0 = empty
    algo: jax.Array        # int32[S]; Algorithm enum
    kind: jax.Array        # int32[S]; KIND_*
    limit: jax.Array       # int64[S]
    duration: jax.Array    # int64[S]
    remaining: jax.Array   # int64[S]; token-bucket remaining / cached-resp remaining
    remaining_f: jax.Array  # float64[S]; leaky-bucket fractional remaining
    t0: jax.Array          # int64[S]; token CreatedAt / leaky UpdatedAt
    status: jax.Array      # int32[S]; token-bucket sticky status / cached-resp status
    burst: jax.Array       # int64[S]
    expire_at: jax.Array   # int64[S]; unix ms (CacheItem.ExpireAt)
    touched: jax.Array     # int64[S]; last-access stamp for victim choice

    @property
    def num_slots(self) -> int:
        return self.key.shape[0]

    def occupancy(self) -> jax.Array:
        return jnp.sum(self.key != 0)


def init_table(num_slots: int) -> SlotTable:
    """All-empty table.  num_slots must keep num_slots/ways a power of two
    (enforced at step-build time) so bucket selection is a mask, not a mod."""
    i64 = lambda: jnp.zeros((num_slots,), dtype=jnp.int64)
    i32 = lambda: jnp.zeros((num_slots,), dtype=jnp.int32)
    return SlotTable(
        key=i64(),
        algo=i32(),
        kind=i32(),
        limit=i64(),
        duration=i64(),
        remaining=i64(),
        remaining_f=jnp.zeros((num_slots,), dtype=jnp.float64),
        t0=i64(),
        status=i32(),
        burst=i64(),
        expire_at=i64(),
        touched=i64(),
    )


def table_to_host(table: SlotTable) -> dict:
    """DMA the table down as numpy for snapshot/Loader-save
    (the device analog of WorkerPool.Store streaming cache.Each(),
    workers.go:467-530)."""
    return {f: np.asarray(getattr(table, f)) for f in table._fields}


def table_from_host(arrs: dict) -> SlotTable:
    return SlotTable(**{f: jnp.asarray(arrs[f]) for f in SlotTable._fields})
