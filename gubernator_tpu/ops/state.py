"""The slot table: device-resident rate-limit state.

Replaces the reference's per-worker LRU dict (lrucache.go:32-223) with a
fixed-size, W-way set-associative table held as a struct-of-arrays on device.
A key's 64-bit fingerprint selects one bucket of `ways` slots; lookups gather
all ways and match on the stored fingerprint; inserts pick a victim way
(empty > expired > least-recently-touched).  Eviction is therefore
bucket-local pseudo-LRU rather than the reference's global list LRU
(lrucache.go:147-158) — the acceptable-loss design (architecture.md:5-11)
makes early eviction safe: it can only briefly over-admit.

All arrays share leading dimension S = num_slots so the table shards cleanly
along axis 0 over a device mesh (see gubernator_tpu.parallel.mesh).
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

# Slot `kind` values.
KIND_BUCKET = 0
KIND_CACHED_RESP = 1  # non-owner's cached GLOBAL broadcast (gubernator.go:464-479)


class SlotTable(NamedTuple):
    """Struct-of-arrays; one row = one CacheItem (cache.go:30-42) flattened
    together with its TokenBucketItem / LeakyBucketItem payload
    (store.go:29-43)."""

    key: jax.Array         # int64[S]; xxhash64 fingerprint; 0 = empty
    algo: jax.Array        # int32[S]; Algorithm enum
    kind: jax.Array        # int32[S]; KIND_*
    limit: jax.Array       # int64[S]
    duration: jax.Array    # int64[S]
    remaining: jax.Array   # int64[S]; token-bucket remaining / cached-resp remaining
    remaining_f: jax.Array  # float64[S]; leaky-bucket fractional remaining
    t0: jax.Array          # int64[S]; token CreatedAt / leaky UpdatedAt
    status: jax.Array      # int32[S]; token-bucket sticky status / cached-resp status
    burst: jax.Array       # int64[S]
    expire_at: jax.Array   # int64[S]; unix ms (CacheItem.ExpireAt)
    touched: jax.Array     # int64[S]; last-access stamp for victim choice

    @property
    def num_slots(self) -> int:
        return self.key.shape[0]

    def occupancy(self) -> jax.Array:
        return jnp.sum(self.key != 0)


def init_table(num_slots: int) -> SlotTable:
    """All-empty table.  num_slots must keep num_slots/ways a power of two
    (enforced at step-build time) so bucket selection is a mask, not a mod."""
    i64 = lambda: jnp.zeros((num_slots,), dtype=jnp.int64)
    i32 = lambda: jnp.zeros((num_slots,), dtype=jnp.int32)
    return SlotTable(
        key=i64(),
        algo=i32(),
        kind=i32(),
        limit=i64(),
        duration=i64(),
        remaining=i64(),
        remaining_f=jnp.zeros((num_slots,), dtype=jnp.float64),
        t0=i64(),
        status=i32(),
        burst=i64(),
        expire_at=i64(),
        touched=i64(),
    )


def table_to_host(table: SlotTable) -> dict:
    """DMA the table down as numpy for snapshot/Loader-save
    (the device analog of WorkerPool.Store streaming cache.Each(),
    workers.go:467-530)."""
    return {f: np.asarray(getattr(table, f)) for f in table._fields}


def table_from_host(arrs: dict) -> SlotTable:
    return SlotTable(**{f: jnp.asarray(arrs[f]) for f in SlotTable._fields})


# --------------------------------------------------------------------------
# Live slot migration (docs/resharding.md): row extract/inject kernels.
#
# A peer join/leave remaps the consistent hash; the rows whose arcs moved
# must LEAVE the old owner's table (or it would keep serving a key it no
# longer owns — an orphaned slot) and LAND in the new owner's, preserving
# remaining/t0/expire_at exactly so the limit window survives the remap.
# Extract is gather+clear fused in ONE donated kernel so the critical
# section under backend._lock is a single dispatch: between the gather
# and the clear nothing else can touch the table, making the handoff's
# "counters conserved" claim a per-row atomicity fact, not a protocol
# hope.  Inject is upsert-IF-ABSENT: a late or replayed Migrate chunk
# can never clobber state the receiver already created (the receiver's
# row is newer by construction — it was written after cutover or by a
# racing authoritative check).
# --------------------------------------------------------------------------


def migrate_extract_impl(
    table: SlotTable,
    h: jax.Array,       # int64[B] key fingerprints; 0 = inactive lane
    now: jax.Array,
    ways: int = 8,
):
    """Probe `h`, gather each found row's fields, and CLEAR the matched
    slots (key=0, expire_at=0) in the same step.  Returns
    (new_table, packed int64[10, B] in ops.step.GATHER_ROW_FIELDS order,
    float64[B] remaining_f)."""
    S = table.key.shape[0]
    nb = S // ways
    now = jnp.asarray(now, dtype=jnp.int64)
    bucket = (
        h.astype(jnp.uint64) & jnp.uint64(nb - 1)
    ).astype(jnp.int64)
    sidx = (
        bucket[:, None] * ways
        + jnp.arange(ways, dtype=jnp.int64)[None, :]
    )
    match = (
        (table.key[sidx] == h[:, None])
        & (h[:, None] != 0)
        & (table.expire_at[sidx] > now)
    )
    found = match.any(axis=1)
    slot = bucket * ways + jnp.argmax(match, axis=1)
    src = jnp.where(found, slot, 0)

    def g(arr):
        return arr[src]

    packed = jnp.stack([
        found.astype(jnp.int64),
        g(table.kind).astype(jnp.int64),
        g(table.algo).astype(jnp.int64),
        g(table.limit),
        g(table.duration),
        g(table.remaining),
        g(table.t0),
        g(table.status).astype(jnp.int64),
        g(table.burst),
        g(table.expire_at),
    ])
    rf = g(table.remaining_f)
    # Clear: drop the fingerprint AND the expiry so the slot reads as
    # empty to every probe/locate and as a first-choice victim.
    tgt = jnp.where(found, slot, S)
    new_table = table._replace(
        key=table.key.at[tgt].set(0, mode="drop"),
        expire_at=table.expire_at.at[tgt].set(0, mode="drop"),
    )
    return new_table, packed, rf


migrate_extract = jax.jit(
    migrate_extract_impl, static_argnames=("ways",), donate_argnums=(0,)
)


def migrate_inject_impl(
    table: SlotTable,
    rows,  # ops.step.BucketRows; key_hash 0 = inactive lane
    now: jax.Array,
    ways: int = 8,
):
    """Upsert migrated rows where the key is absent; where it is
    already resident, MERGE by subtracting the migrated row's consumed
    budget (limit - remaining, clamped at 0) from the resident row —
    discovery gives no ordering guarantees, so a receiver may have
    served a moved key (fresh row) before its migrated row arrives, and
    keeping either row alone would lose the other's admissions.  The
    merge conserves: total consumption is the sum of both rows',
    clamped at the limit — it can only LOWER remaining, never inflate
    admission.  Returns (new_table, bool[B] resident-before mask); the
    caller must guard against chunk replays (a re-delivered chunk would
    re-subtract) — runtime/reshard.py keys delivered fingerprints per
    handoff epoch."""
    # Runtime import: ops.step imports this module at load, so the
    # dependency must stay one-way at module scope.
    from gubernator_tpu.ops.step import load_rows_impl, probe_batch_impl

    now = jnp.asarray(now, dtype=jnp.int64)
    found, slot = probe_batch_impl(table, rows.key_hash, now, ways=ways)
    masked = rows._replace(
        key_hash=jnp.where(found, 0, rows.key_hash)
    )
    new_table = load_rows_impl(table, masked, now, ways=ways)
    # Merge-on-conflict: the probe's slots index rows load_rows did not
    # touch (conflict lanes were masked out of the upsert).
    active = rows.key_hash != 0
    conflict = found & active
    consumed_i = jnp.maximum(rows.limit - rows.remaining, 0)
    consumed_f = jnp.maximum(
        rows.limit.astype(jnp.float64) - rows.remaining_f, 0.0
    )
    is_leaky = rows.algo == 1
    src = jnp.where(conflict, slot, 0)
    merged_rem = jnp.maximum(
        new_table.remaining[src]
        - jnp.where(is_leaky, 0, consumed_i),
        0,
    )
    merged_rf = jnp.maximum(
        new_table.remaining_f[src]
        - jnp.where(is_leaky, consumed_f, 0.0),
        0.0,
    )
    S = table.key.shape[0]
    tgt = jnp.where(conflict, slot, S)
    new_table = new_table._replace(
        remaining=new_table.remaining.at[tgt].set(
            merged_rem, mode="drop"
        ),
        remaining_f=new_table.remaining_f.at[tgt].set(
            merged_rf, mode="drop"
        ),
    )
    return new_table, found


migrate_inject = jax.jit(
    migrate_inject_impl, static_argnames=("ways",), donate_argnums=(0,)
)


# --------------------------------------------------------------------------
# Tiered table (docs/tiering.md): the demotion kernel.
#
# HBM slot count — not kernel throughput — is the binding constraint at
# 100M+ keys, so the coldest residents spill to a host-RAM cold tier
# (runtime/coldtier.py) and promote back on access via migrate_inject.
# demote_extract is migrate_extract's per-row-atomicity shape pointed the
# other way: instead of probing caller-named fingerprints, the DEVICE
# picks the victims — the `batch` least-recently-touched live KIND_BUCKET
# rows (the per-slot `touched` word every step already maintains for
# bucket-local pseudo-LRU) — gathers their fields, and CLEARS the matched
# slots in the same donated dispatch.  Between the gather and the clear
# nothing else can touch the table, so a demoted row exists in exactly
# one tier at every instant the backend lock is free.  Shadow-plane rows
# (hot-mirror / lease-grant / degraded-shadow / handoff-shadow) carry
# derived-key fingerprints the HOST enumerates; they ride the `protect`
# list and are never demoted — their over-admission algebra assumes HBM
# residency.  KIND_CACHED_RESP rows (GLOBAL broadcast cache) are skipped
# device-side: they are a response cache, not bucket state.
# --------------------------------------------------------------------------

# Packed demote row layout: GATHER_ROW_FIELDS with the `found` word
# replaced by the row's own key fingerprint (the caller did not name the
# keys — the kernel picked them; 0 = inactive lane).  remaining_f rides
# alongside as float64[batch], exactly the migrate_extract wire shape.
DEMOTE_ROW_FIELDS = (
    "key", "kind", "algo", "limit", "duration", "remaining", "t0",
    "status", "burst", "expire_at",
)


def demote_extract_impl(
    table: SlotTable,
    protect: jax.Array,  # int64[M] shadow-plane fps; 0 = inactive
    now: jax.Array,
    ways: int = 8,
    batch: int = 64,
):
    """Pick the `batch` coldest (least-recently-touched) live
    KIND_BUCKET residents not on the `protect` list, gather their rows,
    and CLEAR the matched slots (key=0, expire_at=0) in the same
    donated step.  Returns (new_table, packed int64[10, batch] in
    DEMOTE_ROW_FIELDS order, float64[batch] remaining_f); lanes past
    the eligible population come back with key 0 and clear nothing."""
    S = table.key.shape[0]
    now = jnp.asarray(now, dtype=jnp.int64)
    alive = (table.key != 0) & (table.expire_at > now)
    eligible = alive & (table.kind == KIND_BUCKET)
    protected = (
        (table.key[:, None] == protect[None, :])
        & (protect[None, :] != 0)
    ).any(axis=1)
    eligible = eligible & ~protected
    # Victim score: last-touch stamp, ineligible slots pushed past any
    # real timestamp so top_k(-score) yields the `batch` coldest
    # eligible rows (the bucket-local pseudo-LRU word, applied
    # table-wide).
    big = jnp.iinfo(jnp.int64).max
    score = jnp.where(eligible, table.touched, big)
    neg, idx = jax.lax.top_k(-score, batch)
    idx = idx.astype(jnp.int64)
    sel = neg != -big
    src = jnp.where(sel, idx, 0)

    def g(arr):
        return jnp.where(sel, arr[src], 0)

    packed = jnp.stack([
        g(table.key),
        g(table.kind).astype(jnp.int64),
        g(table.algo).astype(jnp.int64),
        g(table.limit),
        g(table.duration),
        g(table.remaining),
        g(table.t0),
        g(table.status).astype(jnp.int64),
        g(table.burst),
        g(table.expire_at),
    ])
    rf = jnp.where(sel, table.remaining_f[src], 0.0)
    # Clear exactly like migrate_extract: drop the fingerprint AND the
    # expiry so the slot reads empty to every probe and first-choice to
    # every victim claim.
    tgt = jnp.where(sel, idx, S)
    new_table = table._replace(
        key=table.key.at[tgt].set(0, mode="drop"),
        expire_at=table.expire_at.at[tgt].set(0, mode="drop"),
    )
    return new_table, packed, rf


demote_extract = jax.jit(
    demote_extract_impl, static_argnames=("ways", "batch"),
    donate_argnums=(0,),
)


# --------------------------------------------------------------------------
# Gubstat (docs/observability.md): the one-pass state census.
#
# The table is the thing HBM capacity binds at scale, yet until now it
# exported a single occupancy scalar.  table_stats computes the whole
# introspection surface — occupancy, bucket-fill (probe/eviction
# pressure), slot-age and TTL-expiry histograms, the remaining-fraction
# distribution per algorithm, and a census of the reserved shadow-slot
# classes — in ONE non-donated device pass, so a periodic sampler can
# ride the ring runner's host-job queue without ever touching the
# request path (the table is read, never written, and never donated).
# --------------------------------------------------------------------------

# The reserved derived-slot suffix classes, in census-row order.  The
# table stores only 64-bit fingerprints, so the HOST enumerates the
# derived keys it knows about (runtime/service.derived_slot_fps-style)
# and passes their fingerprints per class; the kernel counts which are
# live residents.  Order is a wire contract with runtime/gubstat.py.
SHADOW_PLANES = (
    ".hot-mirror", ".lease-grant", ".degraded-shadow",
    ".handoff-shadow", ".region-carve",
)

# Slot-age / TTL-remaining histogram edges (ms): <=1s, <=10s, <=1m,
# <=10m, <=1h, >1h.  Fixed at trace time — bins are part of the
# compiled shape, one compile per table geometry.
AGE_BIN_EDGES_MS = (1_000, 10_000, 60_000, 600_000, 3_600_000)
AGE_BINS = len(AGE_BIN_EDGES_MS) + 1

# Remaining-fraction bins over [0, 1] (bin k covers [k/8, (k+1)/8)).
FRAC_BINS = 8


class TableStats(NamedTuple):
    """One sample of the state plane (all int64 counts)."""

    occupancy: jax.Array           # int64[]: slots with a fingerprint
    live: jax.Array                # int64[]: resident AND unexpired
    expired_resident: jax.Array    # int64[]: resident but TTL-passed
    bucket_fill: jax.Array         # int64[ways+1]: buckets with k residents
    slot_age: jax.Array            # int64[AGE_BINS]: now - t0, live only
    ttl_remaining: jax.Array       # int64[AGE_BINS]: expire_at - now, live
    remaining_fraction: jax.Array  # int64[2, FRAC_BINS]: per algo enum
    shadow_slots: jax.Array        # int64[len(SHADOW_PLANES)]: live carves


def table_stats_impl(
    table: SlotTable,
    shadow_fps: jax.Array,  # int64[len(SHADOW_PLANES), M]; 0 = inactive
    now: jax.Array,
    ways: int = 8,
) -> TableStats:
    """The full census in one read-only pass; never mutates, never
    donates — safe to dispatch against the live serving table under the
    backend lock (or as a ring host job) at any time."""
    S = table.key.shape[0]
    nb = S // ways
    now = jnp.asarray(now, dtype=jnp.int64)
    resident = table.key != 0
    alive = resident & (table.expire_at > now)
    occupancy = jnp.sum(resident, dtype=jnp.int64)
    live = jnp.sum(alive, dtype=jnp.int64)

    # Bucket-fill: residents per bucket -> histogram over 0..ways.  A
    # right-shifted distribution is probe/eviction pressure the scalar
    # occupancy cannot show (hash skew fills some buckets at ways while
    # the aggregate looks healthy).
    per_bucket = jnp.sum(
        resident.reshape(nb, ways), axis=1, dtype=jnp.int64
    )
    fill_levels = jnp.arange(ways + 1, dtype=jnp.int64)
    bucket_fill = jnp.sum(
        per_bucket[:, None] == fill_levels[None, :], axis=0,
        dtype=jnp.int64,
    )

    # Slot-age / TTL-remaining histograms (live slots only).
    edges = jnp.asarray(AGE_BIN_EDGES_MS, dtype=jnp.int64)
    bins = jnp.arange(AGE_BINS, dtype=jnp.int64)

    def hist(values: jax.Array) -> jax.Array:
        idx = jnp.sum(
            values[:, None] > edges[None, :], axis=1, dtype=jnp.int64
        )
        onehot = (idx[:, None] == bins[None, :]) & alive[:, None]
        return jnp.sum(onehot, axis=0, dtype=jnp.int64)

    slot_age = hist(now - table.t0)
    ttl_remaining = hist(table.expire_at - now)

    # Remaining-fraction distribution per algorithm.  Two licensed
    # to_f64 casts (remaining and limit — exact below 2^53 like the
    # step kernels' float sites); the bin index narrows to int32 (one
    # licensed to_i32 — FRAC_BINS bounds it).
    lim_f = jnp.maximum(table.limit.astype(jnp.float64), 1.0)
    rem_f = jnp.where(
        table.algo == 1,
        table.remaining_f,
        table.remaining.astype(jnp.float64),
    )
    frac = jnp.clip(rem_f / lim_f, 0.0, 1.0)
    fbin = jnp.minimum(
        (frac * FRAC_BINS).astype(jnp.int32), FRAC_BINS - 1
    )
    fbins = jnp.arange(FRAC_BINS, dtype=jnp.int32)
    onehot = fbin[:, None] == fbins[None, :]
    rows = []
    for algo in (0, 1):
        mask = alive & (table.algo == algo)
        rows.append(
            jnp.sum(onehot & mask[:, None], axis=0, dtype=jnp.int64)
        )
    remaining_fraction = jnp.stack(rows)

    # Shadow-slot census: probe each host-enumerated derived-key
    # fingerprint (the migrate_extract bucket walk, read-only) and
    # count live residents per suffix class.
    fp = shadow_fps.reshape(-1)
    bucket = (
        fp.astype(jnp.uint64) & jnp.uint64(nb - 1)
    ).astype(jnp.int64)
    sidx = (
        bucket[:, None] * ways
        + jnp.arange(ways, dtype=jnp.int64)[None, :]
    )
    match = (
        (table.key[sidx] == fp[:, None])
        & (fp[:, None] != 0)
        & (table.expire_at[sidx] > now)
    )
    shadow_slots = jnp.sum(
        match.any(axis=1).reshape(shadow_fps.shape), axis=1,
        dtype=jnp.int64,
    )

    return TableStats(
        occupancy=occupancy,
        live=live,
        expired_resident=occupancy - live,
        bucket_fill=bucket_fill,
        slot_age=slot_age,
        ttl_remaining=ttl_remaining,
        remaining_fraction=remaining_fraction,
        shadow_slots=shadow_slots,
    )


table_stats = jax.jit(table_stats_impl, static_argnames=("ways",))
