"""Host-side request packing into fixed-shape device batches.

The device step has ONE compiled shape: [batch_size] lanes.  The host packs
incoming RateLimitReq lists into padded arrays; anything data-dependent that
JAX cannot trace (string hashing, Gregorian calendar math, duplicate-key
rounds) happens here.

Duplicate keys: the reference serializes same-key requests through one worker
(workers.go:182-186), so each sees the state left by the previous.  A vmapped
kernel would see stale reads for duplicates in one batch, so the packer splits
a batch into ROUNDS — occurrence 0 of every key in round 0, occurrence 1 in
round 1, ... — and the runtime applies rounds sequentially.  Round 1+ is
almost always empty.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from gubernator_tpu.core import clock as clock_mod
from gubernator_tpu.core.hashing import key_hash64
from gubernator_tpu.core.interval import (
    GregorianError,
    gregorian_duration,
    gregorian_expiration,
)
from gubernator_tpu.core.types import (
    Algorithm,
    Behavior,
    RateLimitReq,
    has_behavior,
)


class DeviceBatch(NamedTuple):
    """Fixed-shape [B] request lanes (the traced view of RateLimitReq)."""

    key_hash: np.ndarray      # int64[B]; 0 on padding lanes
    hits: np.ndarray          # int64[B]
    limit: np.ndarray         # int64[B]
    duration: np.ndarray      # int64[B]
    algo: np.ndarray          # int32[B]
    burst: np.ndarray         # int64[B]; already defaulted to limit when 0
    reset_remaining: np.ndarray  # bool[B]
    is_greg: np.ndarray       # bool[B]
    greg_expire: np.ndarray   # int64[B]; host-precomputed interval end
    greg_duration: np.ndarray  # int64[B]; host-precomputed full interval ms
    active: np.ndarray        # bool[B]; False on padding lanes
    use_cached: np.ndarray    # bool[B]; GLOBAL read path (serve cached rows)


@dataclass
class PackedGrid:
    """Requests packed into [n_shards, batch_size] rounds."""

    rounds: List[DeviceBatch]  # arrays are [n_shards, batch_size]
    # For each original request i: (round, shard, lane); (-1,-1,-1) = errored.
    positions: List[Tuple[int, int, int]]
    errors: Dict[int, str]  # request index -> validation error


@dataclass
class PackedRounds:
    """One device batch split into sequential rounds for duplicate keys."""

    rounds: List[DeviceBatch]  # arrays are [batch_size]
    # For each original request i: (round_index, lane_index).
    positions: List[Tuple[int, int]]
    errors: Dict[int, str]  # request index -> validation error


def pack_requests_grid(
    reqs: Sequence[RateLimitReq],
    batch_size: int,
    n_shards: int,
    shard_fn,
    clock: Optional[clock_mod.Clock] = None,
    use_cached: Optional[Sequence[bool]] = None,
) -> PackedGrid:
    """Pack requests into rounds of fixed-shape [n_shards, batch_size] arrays.

    `shard_fn(hash_key) -> int` routes each key to its owning shard (the
    worker-pool hash range / peer ring analog, workers.go:182-186).

    Validation mirrors gubernator.go:228-237 (empty name / unique_key) plus
    Gregorian interval validation (interval.go:107,147) — failed requests get
    an error entry and no lane.

    Invariants: a key appears at most once per round (the kernel's unique-key
    contract), and occurrence k of a key lands in a strictly later round than
    occurrence k-1 (so same-key requests observe each other's effects in
    order, like the reference's per-key worker serialization).

    Dispatches to the C++ fast path (native/gubtpu.cpp: batched XXH64 +
    round assignment, with numpy-scatter lane fill) when the native library
    is loadable; this python loop is the semantic reference and fallback.
    The native path detects duplicates by 64-bit fingerprint rather than key
    string — safe, because fingerprint-colliding keys share a device slot
    and MUST be round-separated anyway.
    """
    from gubernator_tpu import native

    if native.available():
        return _pack_requests_grid_native(
            reqs, batch_size, n_shards, shard_fn, clock, use_cached
        )
    return _pack_requests_grid_py(
        reqs, batch_size, n_shards, shard_fn, clock, use_cached
    )


def _pack_requests_grid_py(
    reqs: Sequence[RateLimitReq],
    batch_size: int,
    n_shards: int,
    shard_fn,
    clock: Optional[clock_mod.Clock] = None,
    use_cached: Optional[Sequence[bool]] = None,
) -> PackedGrid:
    clock = clock or clock_mod.default_clock()
    now_dt = clock.now()

    positions: List[Tuple[int, int, int]] = [(-1, -1, -1)] * len(reqs)
    errors: Dict[int, str] = {}

    last_round: Dict[str, int] = {}
    round_keys: List[set] = []
    per_round: List[List[List[Tuple[int, RateLimitReq]]]] = []
    shard_cache: Dict[str, int] = {}
    for i, r in enumerate(reqs):
        # Validation order + messages match gubernator.go:228-237 (note the
        # reference reports an empty name as 'namespace').
        if not r.unique_key:
            errors[i] = "field 'unique_key' cannot be empty"
            continue
        if not r.name:
            errors[i] = "field 'namespace' cannot be empty"
            continue
        # Pre-validate Gregorian intervals so an invalid request never
        # claims a round/lane (it would leave phantom all-inactive rounds
        # and shift later requests' positions).
        if has_behavior(r.behavior, Behavior.DURATION_IS_GREGORIAN):
            try:
                gregorian_expiration(now_dt, r.duration)
            except GregorianError as e:
                errors[i] = str(e)
                continue
        key = r.hash_key()
        shard = shard_cache.get(key)
        if shard is None:
            shard = shard_fn(key)
            shard_cache[key] = shard
        rnd = last_round.get(key, -1) + 1
        while True:
            if rnd >= len(per_round):
                per_round.append([[] for _ in range(n_shards)])
                round_keys.append(set())
            if (
                len(per_round[rnd][shard]) < batch_size
                and key not in round_keys[rnd]
            ):
                break
            rnd += 1
        last_round[key] = rnd
        round_keys[rnd].add(key)
        per_round[rnd][shard].append((i, r))

    rounds: List[DeviceBatch] = []
    for rnd_idx, shards in enumerate(per_round):
        batches = [_empty_batch(batch_size) for _ in range(n_shards)]
        for shard, entries in enumerate(shards):
            for lane, (i, r) in enumerate(entries):
                positions[i] = (rnd_idx, shard, lane)
                _fill_lane(
                    batches[shard], lane, r, now_dt,
                    bool(use_cached[i]) if use_cached is not None else False,
                )
        rounds.append(
            DeviceBatch(
                *[
                    np.stack([getattr(b, f) for b in batches])
                    for f in DeviceBatch._fields
                ]
            )
        )

    return PackedGrid(rounds=rounds, positions=positions, errors=errors)


def _pack_requests_grid_native(
    reqs: Sequence[RateLimitReq],
    batch_size: int,
    n_shards: int,
    shard_fn,
    clock: Optional[clock_mod.Clock] = None,
    use_cached: Optional[Sequence[bool]] = None,
) -> PackedGrid:
    """C++-assisted packing: batched key hashing and round assignment in
    native code, lane fill as numpy scatters.  Same contract as the python
    reference (differential-tested in tests/test_native.py)."""
    from gubernator_tpu import native

    clock = clock or clock_mod.default_clock()
    now_dt = clock.now()
    n = len(reqs)
    errors: Dict[int, str] = {}

    keys: List[str] = [""] * n
    shard_arr = np.zeros(n, dtype=np.int32) if n_shards > 1 else None
    # Validation + per-request scalars (one python pass; everything
    # downstream is vectorized).
    hits = np.zeros(n, dtype=np.int64)
    limit = np.zeros(n, dtype=np.int64)
    duration = np.zeros(n, dtype=np.int64)
    algo = np.zeros(n, dtype=np.int32)
    burst = np.zeros(n, dtype=np.int64)
    reset = np.zeros(n, dtype=bool)
    is_greg = np.zeros(n, dtype=bool)
    greg_expire = np.zeros(n, dtype=np.int64)
    greg_duration = np.zeros(n, dtype=np.int64)
    cached = np.zeros(n, dtype=bool)

    shard_cache: Dict[str, int] = {}
    for i, r in enumerate(reqs):
        if not r.unique_key:
            errors[i] = "field 'unique_key' cannot be empty"
            continue
        if not r.name:
            errors[i] = "field 'namespace' cannot be empty"
            continue
        b = int(r.behavior)
        if b & int(Behavior.DURATION_IS_GREGORIAN):
            try:
                greg_expire[i] = gregorian_expiration(now_dt, r.duration)
                greg_duration[i] = gregorian_duration(now_dt, r.duration)
            except GregorianError as e:
                errors[i] = str(e)
                continue
            is_greg[i] = True
        key = r.hash_key()
        keys[i] = key
        if shard_arr is not None:
            s = shard_cache.get(key)
            if s is None:
                s = shard_fn(key)
                shard_cache[key] = s
            shard_arr[i] = s
        hits[i] = r.hits
        limit[i] = r.limit
        duration[i] = r.duration
        algo[i] = int(r.algorithm)
        burst[i] = r.burst if r.burst != 0 else r.limit
        reset[i] = bool(b & int(Behavior.RESET_REMAINING))
        if use_cached is not None:
            cached[i] = bool(use_cached[i])

    hashes = native.hash_keys(keys)
    for i in errors:
        hashes[i] = 0
    rnd, lane, n_rounds = native.assign_rounds(
        hashes, shard_arr, n_shards, batch_size
    )

    positions: List[Tuple[int, int, int]] = [
        (
            (int(rnd[i]), int(shard_arr[i]) if shard_arr is not None else 0,
             int(lane[i]))
            if rnd[i] >= 0
            else (-1, -1, -1)
        )
        for i in range(n)
    ]

    sh = shard_arr if shard_arr is not None else np.zeros(n, dtype=np.int32)
    # Group requests by round with ONE stable sort (O(n log n)), not a full
    # mask scan per round — duplicate-heavy batches make n_rounds ~ n.
    ok_idx = np.flatnonzero(rnd >= 0)
    order = ok_idx[np.argsort(rnd[ok_idx], kind="stable")]
    bounds = np.searchsorted(rnd[order], np.arange(n_rounds + 1))
    values = dict(
        key_hash=hashes, hits=hits, limit=limit, duration=duration,
        algo=algo, burst=burst, reset_remaining=reset, is_greg=is_greg,
        greg_expire=greg_expire, greg_duration=greg_duration,
        use_cached=cached,
    )
    rounds: List[DeviceBatch] = []
    for r_idx in range(n_rounds):
        batch = _empty_batch((n_shards, batch_size))
        sel = order[bounds[r_idx]:bounds[r_idx + 1]]
        s_m, l_m = sh[sel], lane[sel]
        for f, v in values.items():
            getattr(batch, f)[s_m, l_m] = v[sel]
        batch.active[s_m, l_m] = True
        rounds.append(batch)

    return PackedGrid(rounds=rounds, positions=positions, errors=errors)


def pack_requests(
    reqs: Sequence[RateLimitReq],
    batch_size: int,
    clock: Optional[clock_mod.Clock] = None,
    use_cached: Optional[Sequence[bool]] = None,
) -> PackedRounds:
    """Single-shard packing: the n_shards=1 view of pack_requests_grid."""
    grid = pack_requests_grid(
        reqs, batch_size, 1, lambda key: 0, clock, use_cached
    )
    return PackedRounds(
        rounds=[DeviceBatch(*[a[0] for a in rb]) for rb in grid.rounds],
        positions=[
            (rnd, lane) if rnd >= 0 else (-1, -1)
            for (rnd, _, lane) in grid.positions
        ],
        errors=grid.errors,
    )


_BATCH_DTYPES = dict(
    key_hash=np.int64,
    hits=np.int64,
    limit=np.int64,
    duration=np.int64,
    algo=np.int32,
    burst=np.int64,
    reset_remaining=bool,
    is_greg=bool,
    greg_expire=np.int64,
    greg_duration=np.int64,
    active=bool,
    use_cached=bool,
)


def _empty_batch(shape) -> DeviceBatch:
    """All-inactive batch of the given shape (int or tuple)."""
    return DeviceBatch(
        **{f: np.zeros(shape, dtype=dt) for f, dt in _BATCH_DTYPES.items()}
    )


def _fill_lane(
    b: DeviceBatch,
    lane: int,
    r: RateLimitReq,
    now_dt,
    use_cached: bool = False,
) -> None:
    """Fill one lane from a pre-validated request (Gregorian intervals were
    checked before the round/lane was claimed, so this cannot fail)."""
    is_greg = has_behavior(r.behavior, Behavior.DURATION_IS_GREGORIAN)
    if is_greg:
        b.greg_expire[lane] = gregorian_expiration(now_dt, r.duration)
        b.greg_duration[lane] = gregorian_duration(now_dt, r.duration)
    b.key_hash[lane] = np.int64(np.uint64(key_hash64(r.hash_key())).view(np.int64))
    b.hits[lane] = r.hits
    b.limit[lane] = r.limit
    b.duration[lane] = r.duration
    b.algo[lane] = int(r.algorithm)
    # Burst default (algorithms.go:271-272) applied host-side.
    b.burst[lane] = r.burst if r.burst != 0 else r.limit
    b.reset_remaining[lane] = has_behavior(r.behavior, Behavior.RESET_REMAINING)
    b.is_greg[lane] = is_greg
    b.active[lane] = True
    b.use_cached[lane] = use_cached
