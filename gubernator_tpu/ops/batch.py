"""Host-side request packing into fixed-shape device batches.

The device step has ONE compiled shape: [batch_size] lanes.  The host packs
incoming RateLimitReq lists into padded arrays; anything data-dependent that
JAX cannot trace (string hashing, Gregorian calendar math, duplicate-key
rounds) happens here.

Duplicate keys: the reference serializes same-key requests through one worker
(workers.go:182-186), so each sees the state left by the previous.  A vmapped
kernel would see stale reads for duplicates in one batch, so the packer splits
a batch into ROUNDS — occurrence 0 of every key in round 0, occurrence 1 in
round 1, ... — and the runtime applies rounds sequentially.  Round 1+ is
almost always empty.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from gubernator_tpu.core import clock as clock_mod
from gubernator_tpu.core.hashing import key_hash64
from gubernator_tpu.core.interval import (
    GregorianError,
    gregorian_duration,
    gregorian_expiration,
)
from gubernator_tpu.core.types import (
    Algorithm,
    Behavior,
    RateLimitReq,
    has_behavior,
)


class DeviceBatch(NamedTuple):
    """Fixed-shape [B] request lanes (the traced view of RateLimitReq)."""

    key_hash: np.ndarray      # int64[B]; 0 on padding lanes
    hits: np.ndarray          # int64[B]
    limit: np.ndarray         # int64[B]
    duration: np.ndarray      # int64[B]
    algo: np.ndarray          # int32[B]
    burst: np.ndarray         # int64[B]; already defaulted to limit when 0
    reset_remaining: np.ndarray  # bool[B]
    is_greg: np.ndarray       # bool[B]
    greg_expire: np.ndarray   # int64[B]; host-precomputed interval end
    greg_duration: np.ndarray  # int64[B]; host-precomputed full interval ms
    active: np.ndarray        # bool[B]; False on padding lanes
    use_cached: np.ndarray    # bool[B]; GLOBAL read path (serve cached rows)


@dataclass
class PackedGrid:
    """Requests packed into [n_shards, batch_size] rounds."""

    rounds: List[DeviceBatch]  # arrays are [n_shards, batch_size]
    # For each original request i: (round, shard, lane); (-1,-1,-1) = errored.
    positions: List[Tuple[int, int, int]]
    errors: Dict[int, str]  # request index -> validation error


@dataclass
class PackedRounds:
    """One device batch split into sequential rounds for duplicate keys."""

    rounds: List[DeviceBatch]  # arrays are [batch_size]
    # For each original request i: (round_index, lane_index).
    positions: List[Tuple[int, int]]
    errors: Dict[int, str]  # request index -> validation error


def pack_requests_grid(
    reqs: Sequence[RateLimitReq],
    batch_size: int,
    n_shards: int,
    shard_fn,
    clock: Optional[clock_mod.Clock] = None,
    use_cached: Optional[Sequence[bool]] = None,
) -> PackedGrid:
    """Pack requests into rounds of fixed-shape [n_shards, batch_size] arrays.

    `shard_fn(hash_key) -> int` routes each key to its owning shard (the
    worker-pool hash range / peer ring analog, workers.go:182-186).

    Validation mirrors gubernator.go:228-237 (empty name / unique_key) plus
    Gregorian interval validation (interval.go:107,147) — failed requests get
    an error entry and no lane.

    Invariants: a key appears at most once per round (the kernel's unique-key
    contract), and occurrence k of a key lands in a strictly later round than
    occurrence k-1 (so same-key requests observe each other's effects in
    order, like the reference's per-key worker serialization).
    """
    clock = clock or clock_mod.default_clock()
    now_dt = clock.now()

    positions: List[Tuple[int, int, int]] = [(-1, -1, -1)] * len(reqs)
    errors: Dict[int, str] = {}

    last_round: Dict[str, int] = {}
    round_keys: List[set] = []
    per_round: List[List[List[Tuple[int, RateLimitReq]]]] = []
    shard_cache: Dict[str, int] = {}
    for i, r in enumerate(reqs):
        # Validation order + messages match gubernator.go:228-237 (note the
        # reference reports an empty name as 'namespace').
        if not r.unique_key:
            errors[i] = "field 'unique_key' cannot be empty"
            continue
        if not r.name:
            errors[i] = "field 'namespace' cannot be empty"
            continue
        key = r.hash_key()
        shard = shard_cache.get(key)
        if shard is None:
            shard = shard_fn(key)
            shard_cache[key] = shard
        rnd = last_round.get(key, -1) + 1
        while True:
            if rnd >= len(per_round):
                per_round.append([[] for _ in range(n_shards)])
                round_keys.append(set())
            if (
                len(per_round[rnd][shard]) < batch_size
                and key not in round_keys[rnd]
            ):
                break
            rnd += 1
        last_round[key] = rnd
        round_keys[rnd].add(key)
        per_round[rnd][shard].append((i, r))

    rounds: List[DeviceBatch] = []
    for rnd_idx, shards in enumerate(per_round):
        batches = [_empty_batch(batch_size) for _ in range(n_shards)]
        for shard, entries in enumerate(shards):
            for lane, (i, r) in enumerate(entries):
                positions[i] = (rnd_idx, shard, lane)
                err = _fill_lane(
                    batches[shard], lane, r, now_dt,
                    bool(use_cached[i]) if use_cached is not None else False,
                )
                if err is not None:
                    errors[i] = err
                    positions[i] = (-1, -1, -1)
                    _clear_lane(batches[shard], lane)
        rounds.append(
            DeviceBatch(
                *[
                    np.stack([getattr(b, f) for b in batches])
                    for f in DeviceBatch._fields
                ]
            )
        )

    return PackedGrid(rounds=rounds, positions=positions, errors=errors)


def pack_requests(
    reqs: Sequence[RateLimitReq],
    batch_size: int,
    clock: Optional[clock_mod.Clock] = None,
    use_cached: Optional[Sequence[bool]] = None,
) -> PackedRounds:
    """Single-shard packing: the n_shards=1 view of pack_requests_grid."""
    grid = pack_requests_grid(
        reqs, batch_size, 1, lambda key: 0, clock, use_cached
    )
    return PackedRounds(
        rounds=[DeviceBatch(*[a[0] for a in rb]) for rb in grid.rounds],
        positions=[
            (rnd, lane) if rnd >= 0 else (-1, -1)
            for (rnd, _, lane) in grid.positions
        ],
        errors=grid.errors,
    )


def _empty_batch(batch_size: int) -> DeviceBatch:
    z64 = lambda: np.zeros(batch_size, dtype=np.int64)
    return DeviceBatch(
        key_hash=z64(),
        hits=z64(),
        limit=z64(),
        duration=z64(),
        algo=np.zeros(batch_size, dtype=np.int32),
        burst=z64(),
        reset_remaining=np.zeros(batch_size, dtype=bool),
        is_greg=np.zeros(batch_size, dtype=bool),
        greg_expire=z64(),
        greg_duration=z64(),
        active=np.zeros(batch_size, dtype=bool),
        use_cached=np.zeros(batch_size, dtype=bool),
    )


def _fill_lane(
    b: DeviceBatch,
    lane: int,
    r: RateLimitReq,
    now_dt,
    use_cached: bool = False,
) -> Optional[str]:
    is_greg = has_behavior(r.behavior, Behavior.DURATION_IS_GREGORIAN)
    if is_greg:
        try:
            b.greg_expire[lane] = gregorian_expiration(now_dt, r.duration)
            b.greg_duration[lane] = gregorian_duration(now_dt, r.duration)
        except GregorianError as e:
            return str(e)
    b.key_hash[lane] = np.int64(np.uint64(key_hash64(r.hash_key())).view(np.int64))
    b.hits[lane] = r.hits
    b.limit[lane] = r.limit
    b.duration[lane] = r.duration
    b.algo[lane] = int(r.algorithm)
    # Burst default (algorithms.go:271-272) applied host-side.
    b.burst[lane] = r.burst if r.burst != 0 else r.limit
    b.reset_remaining[lane] = has_behavior(r.behavior, Behavior.RESET_REMAINING)
    b.is_greg[lane] = is_greg
    b.active[lane] = True
    b.use_cached[lane] = use_cached
    return None


def _clear_lane(b: DeviceBatch, lane: int) -> None:
    for arr in b:
        arr[lane] = 0
