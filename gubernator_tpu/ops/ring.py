"""The ring-fed device loop kernel: one jitted call drains many rounds.

The classic and pipelined drain disciplines dispatch one `apply_batch`
round at a time and pay one device->host fetch per MERGE on the request
path (runtime/fastpath.py).  The ring discipline (runtime/ring.py,
GUBER_SERVE_MODE=ring) instead stacks every queued round into one
int64[k, 12, B] request-ring block and applies the whole block in a
single jitted scan:

    table', resps[k, 9, B], seq' = ring_step(table, qs, nows, seq)

Rounds apply IN ORDER (a duplicate-key merge's sequential rounds keep
observing each other's effects exactly as the round-at-a-time loop in
`_dispatch_rounds_locked` does), the table state is donated so the loop
updates in place, and `seq` — the ring's monotonically increasing
sequence word — advances by the consumed slot count and travels back
packed with the responses.  The host ring runner fetches (resps, seq)
in ONE transfer, off the request path, and publishes each round's
response to its waiting slot; the request path is enqueue -> wait on
the slot, with no blocking `device_get` anywhere.  The seq word is NOT
donated: under the runner's double buffering, iteration N's output word
must stay fetchable after iteration N+1 has already dispatched with it
as input — donating it would delete the very buffer the response
protocol spins on.

Inactive padding rounds (all-zero q rows: active column false on every
lane) are no-ops by construction — the ring pads a partial block up to
the smallest compiled slot tier so XLA never sees a new shape
(core/config.py's fixed-shape rule; one compile per tier at warmup).

The k=1 block is semantically `apply_batch_packed_q` plus the sequence
word; the differential suite pins ring mode bit-identical to the
classic drain (tests/test_differential.py, scripts/ring_smoke.py).

The SAME scan body serves the multi-chip mesh: `ring_step_impl` is the
per-shard local function of the shard_map-wrapped mesh ring step
(parallel/sharded.make_mesh_ring_step), which lifts the request block to
int64[k, 12, n_shards, B] over the sharded grid table and packs a
PER-SHARD monotone sequence word (int64[n_shards]) alongside the
responses — so the mesh-ring ≡ single-ring-per-shard equivalence holds
by construction, not by parallel maintenance of two kernels.

MEGAROUND (GUBER_RING_ROUNDS > 1, docs/ring.md): `mega_ring_step` scans
up to GUBER_RING_ROUNDS stacked ring rounds — int64[r, s, 12, B], i.e.
r x s packed rounds — per dispatch, amortizing the per-iteration XLA
entry + host->device round trip across the whole block.  It is a scan
OF ring_step_impl (table and seq threaded through the outer carry), so
the decision semantics are inherited, not duplicated; the adaptive
round accumulator in runtime/ring.py picks base vs mega tiers per
block (shallow queue dispatches immediately, a backlog widens to the
mega tier under a GUBER_RING_MAX_LINGER_US bound).  The mesh lift
(parallel/sharded.make_mesh_mega_ring_step) composes the same body
under shard_map, exactly like the base ring.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from gubernator_tpu.ops.state import SlotTable
from gubernator_tpu.ops.step import apply_batch_packed_q_impl


def ring_step_impl(
    table: SlotTable,
    qs: jax.Array,    # int64[k, 12, B] — k stacked request rounds
    nows: jax.Array,  # int64[k] — per-round clock (one value per block
    #                   in practice; per-slot for exactness under test)
    seq: jax.Array,   # int64[] — the ring sequence word
    ways: int = 8,
) -> Tuple[SlotTable, jax.Array, jax.Array]:
    """Apply `k` packed rounds in order; returns
    (new_table, int64[k, 9, B] packed responses, seq + k)."""

    def body(tbl, qn):
        q, now = qn
        tbl, resp = apply_batch_packed_q_impl(tbl, q, now, ways=ways)
        return tbl, resp

    table, resps = jax.lax.scan(body, table, (qs, nows))
    return table, resps, seq + jnp.int64(qs.shape[0])


ring_step = jax.jit(
    ring_step_impl, static_argnames=("ways",), donate_argnums=(0,)
)


def mega_ring_step_impl(
    table: SlotTable,
    qs: jax.Array,    # int64[r, s, 12, B] — r stacked ring rounds of s
    #                   slots each (the megaround block)
    nows: jax.Array,  # int64[r, s] — per-round clock
    seq: jax.Array,   # int64[] — the ring sequence word
    ways: int = 8,
) -> Tuple[SlotTable, jax.Array, jax.Array]:
    """Megaround serving: apply `r x s` packed rounds in order with ONE
    XLA entry — the dispatch-amortization step (GUBER_RING_ROUNDS x
    GUBER_RING_SLOTS rounds per host->device round trip).  Returns
    (new_table, int64[r, s, 9, B] packed responses, seq + r*s).

    Structurally a scan OF the ring scan: the outer scan threads the
    table and the sequence word through `ring_step_impl` — the exact
    per-slot-tier body the base ring dispatches — so megaround ≡ ring ≡
    classic holds by construction, not by parallel maintenance of a
    second decision kernel.  The flattened-round equivalence
    (mega(qs.reshape(r, s, ...)) == ring(qs[r*s, ...])) is pinned
    differentially in tests/test_ring.py."""

    def body(carry, qn):
        tbl, sq = carry
        q, now = qn
        tbl, resp, sq = ring_step_impl(tbl, q, now, sq, ways=ways)
        return (tbl, sq), resp

    (table, seq), resps = jax.lax.scan(body, (table, seq), (qs, nows))
    return table, resps, seq


mega_ring_step = jax.jit(
    mega_ring_step_impl, static_argnames=("ways",), donate_argnums=(0,)
)


def resolve_ring_tiers(slots: int) -> Tuple[int, ...]:
    """Compiled slot-count tiers for the ring block: powers of two up to
    `slots` (each costs one XLA compile at warmup; a partial block pads
    to the smallest tier that holds it, so the scan never recompiles)."""
    tiers = []
    t = 1
    while t < slots:
        tiers.append(t)
        t <<= 1
    tiers.append(slots)
    return tuple(tiers)


def resolve_mega_tiers(slots: int, rounds: int) -> Tuple[int, ...]:
    """Compiled MEGA slot tiers beyond the base ring capacity: `slots x m`
    total rounds for each ring-round tier m in (1, rounds] — the blocks
    `mega_ring_step` serves as int64[m, slots, 12, B].  Empty when
    rounds == 1 (megaround disabled; the base tiers are the whole
    ladder).  Each costs one XLA compile at warmup, like the base
    tiers."""
    return tuple(
        slots * m for m in resolve_ring_tiers(rounds) if m > 1
    )


def ring_tier_of(k: int, tiers: Tuple[int, ...]) -> int:
    """Smallest compiled tier holding `k` stacked rounds."""
    for t in tiers:
        if k <= t:
            return t
    return tiers[-1]
