"""Pallas TPU kernels for the hot ops.

Each kernel has a pure-XLA semantic reference in gubernator_tpu.ops and is
differentially tested against it (interpret mode on CPU)."""
