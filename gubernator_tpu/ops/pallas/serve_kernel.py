"""Persistent decision kernel: ONE Pallas launch drains a whole request
queue (docs/ring.md's "kill the last dispatch" direction).

Every ring iteration — even a megaround block — is still one XLA entry:
a host->device dispatch whose fixed cost dominates small-batch latency
on every rig we have measured (the ~13ms CPU-rig small-batch p50 vs the
µs the kernel math costs).  This kernel is the next structural step: a
long-lived `pallas_call` that OWNS the table block for the duration of
the launch and drains a device-resident request queue of `k` stacked
rounds across its sequential grid steps — the table lives in the
kernel's output refs from round to round (one HBM round trip per LAUNCH
instead of one XLA entry per ROUND), responses land in a device-resident
response queue, and the sequence word is written by the kernel itself so
the host response protocol is unchanged.

Decision semantics are INHERITED, not re-implemented: each grid step
reads the table refs and applies `ops/step.apply_batch_packed_q_impl` —
the exact body the ring scan runs — so the bit-exact differential
against `ring_step` (tests/test_serve_kernel.py) holds by construction.
The contract is ring_step's:

    table', resps[k, 9, B], seq' = persistent_serve_step(
        table, qs[k, 12, B], nows[k], seq)

CAPABILITY HONESTY (the GUBER_SERVE_MODE=persistent gate): the decision
body leans on gather/scatter patterns Mosaic cannot lower on every
toolchain, so `persistent_supported()` PROBES an actual compile on the
attached backend and reports the real outcome — a CPU backend reports
interpret-only (the emulation path the differential tests pin), and a
TPU whose Mosaic rejects the body reports the compiler's reason.  The
runtime (runtime/fastpath.py) degrades to megaround automatically in
both cases and surfaces the reason in /debug/vars.  This is a
PROTOTYPE of the decision loop's persistent form, not yet the
host-pinned-DMA ring of docs/ring.md's end state: the request queue is
still delivered per launch, but all `k` rounds inside it are served
without re-entering XLA dispatch.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from gubernator_tpu.ops.pallas.cms_kernel import _CompilerParams
from gubernator_tpu.ops.state import SlotTable
from gubernator_tpu.ops.step import apply_batch_packed_q_impl

_I0 = np.int32(0)  # i32 index-map constant (cms_kernel's x64 rule)

_N_COLS = len(SlotTable._fields)  # 12 table leaves


def _serve_kernel(ways, *refs):
    """One grid step = one packed round against the kernel-resident
    table.  Refs: (qs, nows, seq, 12 table cols in) then
    (12 table cols out, resps, seq out).  The table accumulates in the
    OUT refs across sequential grid steps (the cms_kernel pattern), so
    round b observes rounds [0, b)'s effects exactly like the ring
    scan's carry."""
    q_ref, now_ref, seq_ref = refs[0:3]
    tin = refs[3:3 + _N_COLS]
    tout = refs[3 + _N_COLS:3 + 2 * _N_COLS]
    resp_ref = refs[3 + 2 * _N_COLS]
    seq_out_ref = refs[4 + 2 * _N_COLS]
    b = pl.program_id(0)
    k = pl.num_programs(0)

    @pl.when(b == jnp.int32(0))
    def _init():
        for i_ref, o_ref in zip(tin, tout):
            o_ref[...] = i_ref[...]
        # The kernel writes the advanced sequence word itself — the
        # host response protocol (fetch resps + seq in one transfer,
        # verify against the mirror) is unchanged from ring_step.
        seq_out_ref[...] = seq_ref[...] + jnp.int64(k)

    table = SlotTable(*[o_ref[...] for o_ref in tout])
    tbl2, resp = apply_batch_packed_q_impl(
        table, q_ref[0], now_ref[0], ways=ways
    )
    for o_ref, col in zip(tout, tbl2):
        o_ref[...] = col
    resp_ref[0, :, :] = resp


def persistent_serve_step_impl(
    table: SlotTable,
    qs: jax.Array,    # int64[k, 12, B] — the device-resident queue
    nows: jax.Array,  # int64[k]
    seq: jax.Array,   # int64[] — the ring sequence word
    ways: int = 8,
    interpret: bool = False,
) -> Tuple[SlotTable, jax.Array, jax.Array]:
    """Drain `k` packed rounds in ONE kernel launch; returns
    (new_table, int64[k, 9, B] packed responses, seq + k) — the
    ring_step contract, differentially pinned bit-exact."""
    k, rows, B = qs.shape
    S = table.key.shape[0]
    seq1 = jnp.asarray(seq, dtype=jnp.int64).reshape(1)

    def col_spec():
        return pl.BlockSpec((S,), lambda b: (_I0,))

    outs = pl.pallas_call(
        functools.partial(_serve_kernel, ways),
        grid=(k,),
        in_specs=[
            pl.BlockSpec((1, rows, B), lambda b: (b, _I0, _I0)),
            pl.BlockSpec((1,), lambda b: (b,)),
            pl.BlockSpec((1,), lambda b: (_I0,)),
        ] + [col_spec() for _ in range(_N_COLS)],
        out_specs=[col_spec() for _ in range(_N_COLS)] + [
            pl.BlockSpec((1, 9, B), lambda b: (b, _I0, _I0)),
            pl.BlockSpec((1,), lambda b: (_I0,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((S,), jnp.asarray(a).dtype)
            for a in table
        ] + [
            jax.ShapeDtypeStruct((k, 9, B), jnp.int64),
            jax.ShapeDtypeStruct((1,), jnp.int64),
        ],
        # The table outputs are revisited by every grid step
        # (accumulation), so the grid must be sequential.
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)
        ),
        interpret=interpret,
    )(
        jnp.asarray(qs, dtype=jnp.int64),
        jnp.asarray(nows, dtype=jnp.int64),
        seq1,
        *table,
    )
    return (
        SlotTable(*outs[:_N_COLS]),
        outs[_N_COLS],
        outs[_N_COLS + 1][0],
    )


persistent_serve_step = jax.jit(
    persistent_serve_step_impl,
    static_argnames=("ways", "interpret"),
    donate_argnums=(0,),
)


def probe_compile(
    num_slots: int = 256, ways: int = 8, batch: int = 8
) -> Tuple[bool, str]:
    """Attempt an ACTUAL (non-interpret) lowering + compile of the
    kernel on the default backend, abstractly (no device memory is
    allocated).  Returns (ok, reason) — the honest capability signal
    GUBER_SERVE_MODE=persistent gates on."""
    i64 = jax.ShapeDtypeStruct((num_slots,), jnp.int64)
    i32 = jax.ShapeDtypeStruct((num_slots,), jnp.int32)
    f64 = jax.ShapeDtypeStruct((num_slots,), jnp.float64)
    table = SlotTable(
        key=i64, algo=i32, kind=i32, limit=i64, duration=i64,
        remaining=i64, remaining_f=f64, t0=i64, status=i32, burst=i64,
        expire_at=i64, touched=i64,
    )
    try:
        persistent_serve_step.lower(
            table,
            jax.ShapeDtypeStruct((2, 12, batch), jnp.int64),
            jax.ShapeDtypeStruct((2,), jnp.int64),
            jax.ShapeDtypeStruct((), jnp.int64),
            ways=ways,
        ).compile()
    except Exception as e:  # noqa: BLE001 — the reason IS the signal
        return False, f"persistent serve kernel failed to compile: {e}"
    return True, ""


def persistent_supported(platform: str) -> Tuple[bool, str]:
    """Capability report for a backend on `platform`: only a real TPU
    may even attempt the Mosaic compile — CPU/GPU report the interpret
    gap honestly instead of shipping an emulated 'persistent' mode that
    is slower than the scan it replaces."""
    if platform != "tpu":
        return False, (
            "persistent serve kernel needs a TPU backend (running on "
            f"{platform!r}; interpret mode serves the differential "
            "tests only)"
        )
    return probe_compile()
