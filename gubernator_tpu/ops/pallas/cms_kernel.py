"""Fused count-min-sketch step as a Pallas TPU kernel.

Semantic reference: gubernator_tpu.ops.sketch.cms_step_impl — same contract,
differentially tested (tests/test_sketch.py).

Fusion story: the XLA path materializes [D, B, W] one-hot tensors in HBM
(32MB+ at B=1024, W=8192) and runs 2D einsums over them.  This kernel
streams the batch through VMEM in blocks: per block it builds each row's
[BLK, W] one-hot on the fly, runs the read-gather and add-scatter as MXU
matmuls against the VMEM-resident sketch, and accumulates the new sketch in
the output ref across sequential grid steps — one HBM round-trip for the
sketch per batch instead of one per einsum operand.

Decisions read the PRE-batch sketch for every block (cur stays an input;
updates accumulate in out_cur), matching the reference semantics exactly.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gubernator_tpu.ops.sketch import SketchState, _rotate, row_columns

# jax 0.5 renamed TPUCompilerParams -> CompilerParams; serve both so the
# kernel traces (and interprets on CPU) across the supported range.
_CompilerParams = getattr(
    pltpu, "CompilerParams", None
) or pltpu.TPUCompilerParams

# 128 keeps the [BLK, W] one-hot at 4MB — safely under the 16MB VMEM
# scoped limit with double buffering — and measured fastest on v5e
# (49.6M decisions/s vs 34.2M at 256; 512 OOMs VMEM).
DEFAULT_BLOCK = 128

_I0 = np.int32(0)  # i32 index-map constant (see in_specs note below)


def _cms_kernel(
    overlap_ref,   # VMEM f32[1, 1]
    cur_ref,       # VMEM i32[D, W]      (whole sketch, every step)
    prev_ref,      # VMEM i32[D, W]
    cols_ref,      # VMEM i32[D, BLK]    (this block's columns)
    hits_ref,      # VMEM f32[1, BLK]
    limit_ref,     # VMEM f32[1, BLK]
    active_ref,    # VMEM f32[1, BLK]    (1.0 / 0.0)
    out_cur_ref,   # VMEM i32[D, W]      (accumulated across steps)
    over_ref,      # VMEM f32[1, BLK]
    est_ref,       # VMEM f32[1, BLK]
):
    b = pl.program_id(0)
    depth, width = cur_ref.shape
    blk = cols_ref.shape[1]

    @pl.when(b == jnp.int32(0))
    def _init():
        out_cur_ref[:, :] = cur_ref[:, :]

    # NOTE: x64 mode is on process-wide; bare Python literals would become
    # f64/i64 and 64-bit vectors crash the TPU vector-layout pass.  Keep
    # every in-kernel constant explicitly 32-bit.
    zero_f = jnp.float32(0.0)
    overlap = overlap_ref[0, 0]
    hits = hits_ref[0, :]                     # f32[BLK]
    active = active_ref[0, :]                 # f32[BLK]
    col_iota = jax.lax.broadcasted_iota(jnp.int32, (blk, width), 1)

    est = jnp.full((blk,), 3.0e38, dtype=jnp.float32)
    for d in range(depth):
        cols_d = cols_ref[d, :]               # i32[BLK]
        onehot = (
            (col_iota == cols_d[:, None]) & (active[:, None] > zero_f)
        ).astype(jnp.float32)                 # [BLK, W]
        eff_d = (
            cur_ref[d, :].astype(jnp.float32)
            + prev_ref[d, :].astype(jnp.float32) * overlap
        )                                     # [W]
        # Read-gather: MXU matvec [BLK,W] @ [W,1].
        reads = jnp.dot(
            onehot, eff_d[:, None], preferred_element_type=jnp.float32
        )[:, 0]
        est = jnp.minimum(est, reads)
        # Add-scatter: MXU matvec [1,BLK] @ [BLK,W].
        upd = jnp.dot(
            hits[None, :], onehot, preferred_element_type=jnp.float32
        )[0]                                  # [W]
        out_cur_ref[d, :] = out_cur_ref[d, :] + upd.astype(jnp.int32)

    est = jnp.where(active > zero_f, est, zero_f)
    over = (
        (active > zero_f)
        & (hits > zero_f)
        & (est + hits > limit_ref[0, :])
    ).astype(jnp.float32)
    over_ref[0, :] = over
    est_ref[0, :] = est


def cms_step_pallas_impl(
    state: SketchState,
    key_hash: jax.Array,
    hits: jax.Array,
    limit: jax.Array,
    now: jax.Array,
    block: int = DEFAULT_BLOCK,
    interpret: bool = False,
) -> Tuple[SketchState, jax.Array, jax.Array]:
    depth, width = state.cur.shape
    B = key_hash.shape[0]
    if B % block:
        raise ValueError(f"batch ({B}) must be a multiple of block ({block})")
    state, overlap = _rotate(state, now)
    active = key_hash != 0
    cols = row_columns(key_hash, depth, width)           # [D, B]

    grid = (B // block,)
    new_cur, over_f, est_f = pl.pallas_call(
        _cms_kernel,
        grid=grid,
        # Index-map constants must be explicit i32: under x64 a bare Python
        # 0 traces as i64 inside the Mosaic grid loop and fails to legalize
        # ("func.return ... (i32, i64)").
        in_specs=[
            pl.BlockSpec((1, 1), lambda b: (_I0, _I0)),
            pl.BlockSpec((depth, width), lambda b: (_I0, _I0)),
            pl.BlockSpec((depth, width), lambda b: (_I0, _I0)),
            pl.BlockSpec((depth, block), lambda b: (_I0, b)),
            pl.BlockSpec((1, block), lambda b: (_I0, b)),
            pl.BlockSpec((1, block), lambda b: (_I0, b)),
            pl.BlockSpec((1, block), lambda b: (_I0, b)),
        ],
        out_specs=[
            pl.BlockSpec((depth, width), lambda b: (_I0, _I0)),
            pl.BlockSpec((1, block), lambda b: (_I0, b)),
            pl.BlockSpec((1, block), lambda b: (_I0, b)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((depth, width), jnp.int32),
            jax.ShapeDtypeStruct((1, B), jnp.float32),
            jax.ShapeDtypeStruct((1, B), jnp.float32),
        ],
        # The sketch output is revisited by every grid step (accumulation),
        # so the grid must be sequential, not parallel.
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)
        ),
        interpret=interpret,
    )(
        overlap.astype(jnp.float32)[None, None],
        state.cur,
        state.prev,
        cols,
        hits.astype(jnp.float32)[None, :],
        limit.astype(jnp.float32)[None, :],
        active.astype(jnp.float32)[None, :],
    )
    return (
        SketchState(new_cur, state.prev, state.window_start, state.window_ms),
        over_f[0] > 0.0,
        est_f[0].astype(jnp.int32),
    )


cms_step_pallas = jax.jit(
    cms_step_pallas_impl, static_argnames=("block", "interpret"),
    donate_argnums=(0,),
)
