"""Logging setup: level/format parity with the reference logging config
(log.go:10-34, logging/logging.go:27-53, config.go:269-293).

`setup_logging(level, fmt)` configures the root gubernator_tpu logger with
either text or JSON lines; `parse_log_level` accepts the reference's
level names.  Library users who configure logging themselves can ignore
this module entirely — all framework code logs through stdlib loggers
under the "gubernator_tpu" namespace.
"""
from __future__ import annotations

import json
import logging
import sys
import time
from typing import Optional

LEVELS = {
    "panic": logging.CRITICAL,
    "fatal": logging.CRITICAL,
    "error": logging.ERROR,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "info": logging.INFO,
    "debug": logging.DEBUG,
    "trace": logging.DEBUG,
}


def parse_log_level(name: str) -> int:
    """Level name -> stdlib level (LogLevelJSON, logging/logging.go:27-53);
    unknown names raise like the reference's unmarshal error."""
    try:
        return LEVELS[name.strip().lower()]
    except KeyError:
        raise ValueError(
            f"unknown log level '{name}'; one of {sorted(set(LEVELS))}"
        ) from None


class JsonFormatter(logging.Formatter):
    """One JSON object per line (GUBER_LOG_FORMAT=json)."""

    def format(self, record: logging.LogRecord) -> str:
        out = {
            "time": time.strftime(
                "%Y-%m-%dT%H:%M:%S", time.localtime(record.created)
            ),
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out)


def setup_logging(
    level: str = "info",
    fmt: str = "text",
    stream=None,
) -> None:
    """Configure root logging (text|json) once, idempotently."""
    handler = logging.StreamHandler(stream or sys.stderr)
    if fmt == "json":
        handler.setFormatter(JsonFormatter())
    else:
        handler.setFormatter(
            logging.Formatter(
                "%(asctime)s %(name)s %(levelname)s %(message)s"
            )
        )
    root = logging.getLogger()
    root.handlers[:] = [handler]
    root.setLevel(parse_log_level(level))
