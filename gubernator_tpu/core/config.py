"""Configuration system.

Mirrors the reference's struct + `GUBER_*` env-var config (config.go:44-459,
example.conf), extended with TPU-specific knobs (slot-table geometry, device
batch shape, mesh axes).  Library users populate the dataclasses directly;
the CLI calls `setup_daemon_config()` which reads the environment, with an
optional KEY=VALUE config file loaded into the environment first
(config.go:583-611).
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Tuple

# Defaults from reference config.go:115-131, 300-301, lrucache.go:63.
DEFAULT_BATCH_TIMEOUT_S = 0.5
DEFAULT_BATCH_WAIT_S = 500e-6
DEFAULT_BATCH_LIMIT = 1000
DEFAULT_CACHE_SIZE = 50_000
MAX_BATCH_SIZE = 1000  # gubernator.go:41


@dataclass
class BehaviorConfig:
    """Batch / GLOBAL / multi-region timing knobs (config.go:44-65,115-127)."""

    batch_timeout_s: float = DEFAULT_BATCH_TIMEOUT_S
    batch_wait_s: float = DEFAULT_BATCH_WAIT_S
    batch_limit: int = DEFAULT_BATCH_LIMIT

    global_timeout_s: float = DEFAULT_BATCH_TIMEOUT_S
    global_sync_wait_s: float = DEFAULT_BATCH_WAIT_S
    global_batch_limit: int = DEFAULT_BATCH_LIMIT

    multi_region_timeout_s: float = DEFAULT_BATCH_TIMEOUT_S
    multi_region_sync_wait_s: float = DEFAULT_BATCH_WAIT_S
    multi_region_batch_limit: int = DEFAULT_BATCH_LIMIT


@dataclass
class CircuitConfig:
    """Per-peer circuit breaker (net/breaker.py; no reference analog —
    the Go daemon spends the full RPC deadline against a dead peer on
    every forwarded check).

    Fed by the same failures that populate the 5-minute HealthCheck
    error window: `failure_threshold` CONSECUTIVE failures trip the
    breaker open; while open, every enqueue sheds immediately with
    PeerNotReadyError (counted in `gubernator_peer_shed_total`) instead
    of burning `batch_timeout_s` against a dead channel.  After a
    jittered exponential backoff (`base_backoff_s * 2^(streak-1)`,
    capped at `max_backoff_s`, ±`jitter`) the breaker goes half-open
    and admits `half_open_probes` probe RPCs: one success re-closes it,
    one failure re-opens with a doubled backoff.  A probe whose gated
    RPC never reports an outcome (e.g. cancelled in flight) is treated
    as failed `probe_timeout_s` after it was issued, so the breaker
    cannot wedge half-open shedding forever."""

    enabled: bool = True
    failure_threshold: int = 5
    base_backoff_s: float = 0.5
    max_backoff_s: float = 30.0
    jitter: float = 0.2  # fraction of the backoff, uniform ±
    half_open_probes: int = 1
    probe_timeout_s: float = 10.0

    def __post_init__(self) -> None:
        if self.failure_threshold < 1:
            raise ValueError(
                f"circuit failure_threshold must be >= 1, "
                f"got {self.failure_threshold}"
            )
        if not 0.0 <= self.jitter <= 1.0:
            raise ValueError(
                f"circuit jitter must be in [0, 1], got {self.jitter}"
            )
        if self.probe_timeout_s <= 0.0:
            raise ValueError(
                f"circuit probe_timeout_s must be > 0, "
                f"got {self.probe_timeout_s}"
            )


# Degraded-mode ownership fallback (runtime/service.py): what a node
# answers when the owner of a forwarded key is unreachable (breaker
# open or the ownership-retry loop exhausted).  "error" is the legacy
# strict mode (the reference behavior: an error response, client
# decides); the rest are the degraded-operation policies.
DEGRADED_MODES = ("error", "fail_closed", "fail_open", "local_shadow")


def normalize_degraded_mode(value: str) -> str:
    """Canonicalize a degraded-mode policy; raise on anything unknown —
    a typo must not silently fail open."""
    v = (value or "").strip().lower() or "error"
    if v not in DEGRADED_MODES:
        raise ValueError(
            f"unknown degraded mode {value!r}; expected one of "
            + ", ".join(repr(m) for m in DEGRADED_MODES)
        )
    return v


@dataclass
class HotKeyConfig:
    """Hot-key survival plane (runtime/hotkey.py; docs/hotkeys.md; no
    reference analog — the Go daemon funnels a zipfian workload's
    hottest keys onto single owners until they melt).

    Three coupled mechanisms, all gated on MEASURED owner pressure (the
    flight recorder's rolling p99 vs GUBER_SLO_P99_MS) so that none of
    them activates on a healthy cluster — naive always-on duplication
    makes tails worse under load (arXiv:1909.08969):

    * detection — every node tracks the per-key rate of the traffic it
      routes in a host-side count-min sketch; a key whose pressure
      score (estimated hits/s x owner SLO-pressure ratio) stays past
      `threshold` for `promote_windows` consecutive windows joins a
      small exact hot-set, leaving it after `demote_windows` windows
      below (hysteresis: the set cannot flap at the threshold);
    * mirroring — a hot key's owner-set widens to the next `mirrors`
      distinct arcs of the existing ring (deterministic on every
      peer); each mirror serves from a LOCAL allowance of
      `fraction x limit` and reconciles its hits to the owner through
      the GLOBAL async-hit machinery, bounding cluster-wide
      over-admission to `limit x (1 + mirrors x fraction)` — the
      local_shadow algebra with pressure (not death) as the gate;
    * shedding — when this node's own p99 breach persists past
      `shed_cooldown_s`, requests matching `shed_priorities` globs are
      dropped with OVER_LIMIT + retry-after metadata, lowest priority
      class first, escalating one class per further cooldown.
    """

    enabled: bool = True
    # Promotion threshold on the pressure score: estimated hits/s for
    # the key (this node's local view) x the owner's SLO-pressure
    # ratio (p99 / target; 0 while the owner is healthy — so with no
    # measured pressure NOTHING ever promotes).
    threshold: float = 500.0
    # Extra next-arc ring replicas a hot key's owner-set widens to
    # while the owner is pressured.  0 disables widening entirely.
    mirrors: int = 1
    # Fraction of the limit each mirror may admit from its local slot.
    fraction: float = 0.25
    # Detection window length (seconds) — rates are estimated per
    # window; promote/demote hysteresis counts these windows.
    window_s: float = 1.0
    promote_windows: int = 2
    demote_windows: int = 3
    # Hot-set capacity (exact entries; the sketch stays O(1) per key).
    max_hot: int = 64
    # How long an owner's advertised pressure (RPC trailing metadata)
    # stays live on this node before decaying to 0.
    pressure_ttl_s: float = 5.0
    # p99 breach must persist this long before shedding arms; each
    # further cooldown escalates one priority class.
    shed_cooldown_s: float = 5.0
    # fnmatch globs over limit NAMES, lowest-priority (shed first)
    # first.  A name matching no glob is never shed.  Empty list =
    # shedding disabled.
    shed_priorities: List[str] = field(default_factory=list)

    def __post_init__(self) -> None:
        if self.threshold <= 0:
            raise ValueError(
                f"hotkey threshold must be > 0, got {self.threshold}"
            )
        if self.mirrors < 0:
            raise ValueError(
                f"hotkey mirrors must be >= 0, got {self.mirrors}"
            )
        if not 0.0 < self.fraction <= 1.0:
            raise ValueError(
                f"hotkey fraction must be in (0, 1], got {self.fraction}"
            )
        if self.window_s <= 0:
            raise ValueError(
                f"hotkey window_s must be > 0, got {self.window_s}"
            )
        for n, v in (
            ("promote_windows", self.promote_windows),
            ("demote_windows", self.demote_windows),
            ("max_hot", self.max_hot),
        ):
            if v < 1:
                raise ValueError(f"hotkey {n} must be >= 1, got {v}")
        if self.pressure_ttl_s <= 0:
            raise ValueError(
                f"hotkey pressure_ttl_s must be > 0, "
                f"got {self.pressure_ttl_s}"
            )
        if self.shed_cooldown_s <= 0:
            raise ValueError(
                f"hotkey shed_cooldown_s must be > 0, "
                f"got {self.shed_cooldown_s}"
            )


def hotkey_config_from_env() -> HotKeyConfig:
    """The hot-key plane's env parse, shared by the daemon and harnesses
    (same contract as pipeline_depth_from_env): validation errors name
    the env var at startup instead of crashing a constructor later."""
    prios = [
        p.strip()
        for p in _env("GUBER_HOTKEY_SHED_PRIORITIES").split(",")
        if p.strip()
    ]
    try:
        return HotKeyConfig(
            enabled=_env("GUBER_HOTKEY_ENABLED", "true").lower()
            not in ("0", "false", "no"),
            threshold=float(_env("GUBER_HOTKEY_THRESHOLD", "500")),
            mirrors=_env_int("GUBER_HOTKEY_MIRRORS", 1),
            fraction=float(_env("GUBER_HOTKEY_FRACTION", "0.25")),
            window_s=_env_float_s("GUBER_HOTKEY_WINDOW", 1.0),
            promote_windows=_env_int("GUBER_HOTKEY_PROMOTE_WINDOWS", 2),
            demote_windows=_env_int("GUBER_HOTKEY_DEMOTE_WINDOWS", 3),
            max_hot=_env_int("GUBER_HOTKEY_MAX", 64),
            pressure_ttl_s=_env_float_s("GUBER_HOTKEY_PRESSURE_TTL", 5.0),
            shed_cooldown_s=_env_float_s(
                "GUBER_HOTKEY_SHED_COOLDOWN", 5.0
            ),
            shed_priorities=prios,
        )
    except ValueError as e:
        raise ValueError(f"hot-key env config: {e}") from None


@dataclass
class LeaseConfig:
    """Client-side admission leases (runtime/lease.py; docs/leases.md;
    no reference analog — the cheapest RPC is the one never sent,
    arXiv:2510.04516).

    A key's owner grants a holder (a LeasedClient or an edge daemon) a
    bounded LOCAL allowance of `fraction x limit` hits it may burn with
    zero RPCs, valid for `ttl_ms`.  Allowances are carved from a
    `<unique_key>.lease-grant` shadow slot sized
    `max_holders x fraction x limit` per window — the hot-mirror
    algebra — so cluster-wide admission for a leased key is bounded by
    `limit x (1 + max_holders x fraction)` even if every holder
    partitions away with a full grant.  Burned hits reconcile
    asynchronously (at-most-once); grants are refused while the owner
    is shedding under SLO pressure.  `low_water` and `reconcile_ms`
    are CLIENT cadence knobs (grant refresh threshold, reconcile
    interval) parsed here so the SDK and the daemon read one surface.
    """

    enabled: bool = True
    # Fraction of the limit one holder's allowance covers.
    fraction: float = 0.25
    # Grant lifetime in milliseconds; an expired grant burns nothing.
    ttl_ms: int = 2000
    # Concurrent holders per key; the over-admission bound multiplier.
    max_holders: int = 4
    # Client-side: refresh the grant in the background once remaining
    # allowance drops below low_water x allowance.
    low_water: float = 0.25
    # Client-side: burned-hit reconcile cadence in milliseconds.  Must
    # not exceed ttl_ms (a grant would expire between reconciles and
    # the owner would re-collect allowances still in active use).
    reconcile_ms: int = 500

    def __post_init__(self) -> None:
        if not 0.0 < self.fraction <= 1.0:
            raise ValueError(
                f"lease fraction must be in (0, 1], got {self.fraction}"
            )
        if self.ttl_ms < 1:
            raise ValueError(
                f"lease ttl_ms must be >= 1, got {self.ttl_ms}"
            )
        if self.max_holders < 1:
            raise ValueError(
                f"lease max_holders must be >= 1, got {self.max_holders}"
            )
        if not 0.0 <= self.low_water < 1.0:
            raise ValueError(
                f"lease low_water must be in [0, 1), got {self.low_water}"
            )
        if self.reconcile_ms < 1:
            raise ValueError(
                f"lease reconcile_ms must be >= 1, got {self.reconcile_ms}"
            )
        if self.ttl_ms < self.reconcile_ms:
            raise ValueError(
                "lease ttl_ms must be >= reconcile_ms (a grant must "
                f"outlive the reconcile cadence), got ttl_ms="
                f"{self.ttl_ms} < reconcile_ms={self.reconcile_ms}"
            )


def lease_config_from_env() -> LeaseConfig:
    """The lease plane's env parse, shared by the daemon and the client
    SDK (same contract as hotkey_config_from_env): validation errors
    name the env surface at startup instead of crashing a constructor
    later."""
    try:
        return LeaseConfig(
            enabled=_env("GUBER_LEASE_ENABLED", "true").lower()
            not in ("0", "false", "no"),
            fraction=float(_env("GUBER_LEASE_FRACTION", "0.25")),
            ttl_ms=int(_env_float_s("GUBER_LEASE_TTL", 2.0) * 1000),
            max_holders=_env_int("GUBER_LEASE_MAX_HOLDERS", 4),
            low_water=float(_env("GUBER_LEASE_LOW_WATER", "0.25")),
            reconcile_ms=int(
                _env_float_s("GUBER_LEASE_RECONCILE", 0.5) * 1000
            ),
        )
    except ValueError as e:
        raise ValueError(
            "lease env config (GUBER_LEASE_FRACTION, GUBER_LEASE_TTL, "
            "GUBER_LEASE_MAX_HOLDERS, GUBER_LEASE_LOW_WATER, "
            f"GUBER_LEASE_RECONCILE): {e}"
        ) from None


@dataclass
class ReshardConfig:
    """Elastic membership / live slot migration (runtime/reshard.py;
    docs/resharding.md; no reference analog — the Go daemon's peer
    remap silently orphans every moved key's counters, so at scale
    every autoscaling event is a mass limit reset).

    When `service.set_peers` computes a hash remap, the OLD owner of
    every moved arc drives a per-destination handoff
    (PREPARE -> DRAIN -> TRANSFER -> CUTOVER -> RELEASE): packed table
    rows stream to the new owner on the peers wire (Migrate RPCs) and
    the moved slots are cleared atomically with their extraction.
    During the window the new owner forwards covered checks back to
    the still-authoritative old owner; once TRANSFER is announced it
    serves them from a bounded `<key>.handoff-shadow` carve at
    `handoff_fraction x limit` instead, so cluster-wide admission for
    a moved key is bounded by `limit x (1 + handoff_fraction)` — the
    local_shadow/mirror/lease algebra with a remap (not death or
    pressure) as the gate.  Shadow burns are applied to the
    authoritative row at cutover (counters conserved, never inflated).
    """

    enabled: bool = True
    # Fraction of the limit the NEW owner may admit from the local
    # handoff shadow while a covered key's row is in flight.
    handoff_fraction: float = 0.25
    # Rows per Migrate RPC chunk (bounded by the 4MB message cap).
    chunk_rows: int = 1024
    # New-owner watchdog: if the old owner goes silent mid-handoff for
    # this long, self-cutover (missing rows conservatively reset).
    timeout_s: float = 10.0
    # How long the old owner keeps forwarding stale-routed checks for
    # released arcs after cutover (covers discovery convergence).
    release_linger_s: float = 10.0

    def __post_init__(self) -> None:
        if not 0.0 < self.handoff_fraction <= 1.0:
            raise ValueError(
                "reshard handoff_fraction must be in (0, 1], got "
                f"{self.handoff_fraction}"
            )
        if self.chunk_rows < 1:
            raise ValueError(
                f"reshard chunk_rows must be >= 1, got {self.chunk_rows}"
            )
        if self.timeout_s <= 0:
            raise ValueError(
                f"reshard timeout_s must be > 0, got {self.timeout_s}"
            )
        if self.release_linger_s < 0:
            raise ValueError(
                "reshard release_linger_s must be >= 0, got "
                f"{self.release_linger_s}"
            )


def reshard_config_from_env() -> ReshardConfig:
    """The reshard plane's env parse (same contract as
    hotkey_config_from_env): validation errors name the env surface at
    startup instead of crashing a constructor later."""
    try:
        return ReshardConfig(
            enabled=_env("GUBER_RESHARD_ENABLED", "true").lower()
            not in ("0", "false", "no"),
            handoff_fraction=float(
                _env("GUBER_RESHARD_FRACTION", "0.25")
            ),
            chunk_rows=_env_int("GUBER_RESHARD_CHUNK", 1024),
            timeout_s=_env_float_s("GUBER_RESHARD_TIMEOUT", 10.0),
            release_linger_s=_env_float_s(
                "GUBER_RESHARD_RELEASE_LINGER", 10.0
            ),
        )
    except ValueError as e:
        raise ValueError(
            "reshard env config (GUBER_RESHARD_FRACTION, "
            "GUBER_RESHARD_CHUNK, GUBER_RESHARD_TIMEOUT, "
            f"GUBER_RESHARD_RELEASE_LINGER): {e}"
        ) from None


@dataclass
class RegionConfig:
    """Planet-scale active-active regions (runtime/multiregion.py;
    docs/multiregion.md; the reference ships only a stub sender,
    multiregion.go:23-102 — this is the follow-the-sun layer it never
    grew).

    Each region runs its own mesh + peer ring.  A key's HOME region
    (a deterministic rendezvous pick over the configured region set,
    using the region-picker hash) owns truth; every other region
    serves the key from a bounded `<key>.region-carve` shadow slot at
    `fraction x limit` per window, so cluster-wide admission is
    bounded by `limit x (1 + remote_regions x fraction)` — the
    lease/mirror/shadow carve algebra with geography (not death,
    pressure, or a remap) as the gate.  Burned carve hits reconcile
    to the home owner asynchronously over the WAN peer arcs every
    `reconcile_ms`, with the GLOBAL lane's at-most-once discipline
    (provably-unsent failures re-queue and survive a region
    partition; ambiguous failures drop — arXiv 1909.08969's caution
    against retry inflation).  `drift_max` bounds the un-reconciled
    burn backlog: past it the carve refuses new admissions, so a
    long partition's divergence stays finite.  On region heal the
    carve re-homes through REGION_PREPARE -> TRANSFER -> CUTOVER
    (late burns compensated at cutover; a carve slot still homed
    remotely keeps its consumed state, so each window's fraction is
    spent at most once — only slots whose home MOVED are dropped)."""

    enabled: bool = False
    # This daemon's region name.  Empty + enabled defers to
    # GUBER_DATA_CENTER at daemon assembly (the region name IS the
    # data-center tag peers advertise on the wire).
    name: str = ""
    # region -> WAN seed addresses (grpc host:port).  Remote entries
    # are dialed as cross-region peers; the key set (plus `name`)
    # is the configured region universe the home rendezvous runs
    # over.  Empty = derive the universe from live peer discovery.
    peers: Dict[str, List[str]] = field(default_factory=dict)
    # Fraction of the limit a remote region may admit from its local
    # carve slot per window.
    fraction: float = 0.25
    # Burned-hit WAN reconcile cadence in milliseconds.
    reconcile_ms: int = 500
    # Max un-reconciled burned hits (per node, across keys) before
    # the carve refuses new admissions — the bounded-divergence
    # valve for a long partition.
    drift_max: int = 100_000

    def __post_init__(self) -> None:
        if not 0.0 < self.fraction <= 1.0:
            raise ValueError(
                f"region fraction must be in (0, 1], got {self.fraction}"
            )
        if self.reconcile_ms < 1:
            raise ValueError(
                f"region reconcile_ms must be >= 1, "
                f"got {self.reconcile_ms}"
            )
        if self.drift_max < 1:
            raise ValueError(
                f"region drift_max must be >= 1, got {self.drift_max}"
            )
        if self.peers and self.name and self.name not in self.peers:
            raise ValueError(
                f"self region {self.name!r} missing from the region "
                "peer map — a daemon must appear in its own universe "
                f"(regions: {', '.join(sorted(self.peers))})"
            )


def _parse_region_peers(raw: str) -> Dict[str, List[str]]:
    """Parse GUBER_REGION_PEERS: `region=addr|addr,region2=addr`.
    A region with no addresses (`region=`) is legal — it names the
    region in the universe without seeding WAN dials (discovery
    supplies the peers)."""
    out: Dict[str, List[str]] = {}
    for entry in raw.split(","):
        entry = entry.strip()
        if not entry:
            continue
        if "=" not in entry:
            raise ValueError(
                f"region peer entry {entry!r} is not region=addr|addr"
            )
        region, _, addrs = entry.partition("=")
        region = region.strip()
        if not region:
            raise ValueError(
                f"region peer entry {entry!r} has an empty region name"
            )
        out[region] = [
            a.strip() for a in addrs.split("|") if a.strip()
        ]
    return out


def region_config_from_env() -> RegionConfig:
    """The region plane's env parse (same contract as
    hotkey_config_from_env): validation errors name the env surface
    at startup — fraction outside (0, 1] and a self region absent
    from the peer map are rejected here, not deep in RegionManager."""
    try:
        return RegionConfig(
            enabled=_env("GUBER_REGION_ENABLED", "false").lower()
            in ("1", "true", "yes"),
            name=_env("GUBER_REGION_NAME", "").strip(),
            peers=_parse_region_peers(_env("GUBER_REGION_PEERS", "")),
            fraction=float(_env("GUBER_REGION_FRACTION", "0.25")),
            reconcile_ms=_env_int("GUBER_REGION_RECONCILE_MS", 500),
            drift_max=_env_int("GUBER_REGION_DRIFT_MAX", 100_000),
        )
    except ValueError as e:
        raise ValueError(
            "region env config (GUBER_REGION_ENABLED, "
            "GUBER_REGION_NAME, GUBER_REGION_PEERS, "
            "GUBER_REGION_FRACTION, GUBER_REGION_RECONCILE_MS, "
            f"GUBER_REGION_DRIFT_MAX): {e}"
        ) from None


@dataclass
class StatsConfig:
    """Gubstat — state-plane introspection (runtime/gubstat.py;
    docs/observability.md; no reference analog — the Go daemon's cache
    is host memory an operator can inspect ad hoc, the device table is
    not).

    The sampler dispatches the read-only ops/state.table_stats census
    every `interval_s` as a ring host job (or an executor call outside
    ring mode), so the request path never blocks on it.  `top_k`
    bounds the per-tenant accounting surface (names tracked exactly;
    hit totals ride the existing HostCMS sketch, so cardinality is
    bounded however many tenants appear).  `peek` gates the
    /debug/key inspection route (it decodes live counter state, which
    an operator may prefer to keep off an exposed debug port)."""

    enabled: bool = True
    # Census cadence in seconds.
    interval_s: float = 5.0
    # Tenants surfaced in /debug/vars, /metrics, and gubtop.
    top_k: int = 16
    # Allow the /debug/key row-inspection route.
    peek: bool = True

    def __post_init__(self) -> None:
        if self.interval_s <= 0:
            raise ValueError(
                f"stats interval_s must be > 0, got {self.interval_s}"
            )
        if self.top_k < 1:
            raise ValueError(
                f"stats top_k must be >= 1, got {self.top_k}"
            )


def stats_config_from_env() -> StatsConfig:
    """The gubstat plane's env parse (same contract as
    hotkey_config_from_env): validation errors name the env surface at
    startup instead of crashing a constructor later."""
    try:
        return StatsConfig(
            enabled=_env("GUBER_STATS_ENABLED", "true").lower()
            not in ("0", "false", "no"),
            interval_s=_env_float_s("GUBER_STATS_INTERVAL", 5.0),
            top_k=_env_int("GUBER_STATS_TOP_K", 16),
            peek=_env("GUBER_STATS_PEEK", "true").lower()
            not in ("0", "false", "no"),
        )
    except ValueError as e:
        raise ValueError(
            "stats env config (GUBER_STATS_ENABLED, "
            "GUBER_STATS_INTERVAL, GUBER_STATS_TOP_K, "
            f"GUBER_STATS_PEEK): {e}"
        ) from None


@dataclass
class LoadConfig:
    """Gubload — the open-loop scenario harness (loadgen/;
    docs/loadgen.md; no reference analog — the Go repo benchmarks
    closed-loop).  Parsed by the gubload CLI and scripts/load_smoke.py,
    never by the daemon: the knobs shape the LOAD, not the server.

    `seed` drives every arrival timestamp and key draw (identical
    seeds reproduce identical schedules across runs and worker
    counts).  `duration_s` stretches the named scenario's phases to
    this total; `clients` bounds the connection fan-out; `target_rps`
    is the peak arrival rate the schedules are planned at."""

    seed: int = 1337
    scenario: str = "steady"
    duration_s: float = 6.0
    clients: int = 8
    target_rps: float = 400.0

    def __post_init__(self) -> None:
        if not self.scenario:
            raise ValueError("load scenario must be non-empty")
        if self.duration_s <= 0:
            raise ValueError(
                f"load duration_s must be > 0, got {self.duration_s}"
            )
        _require_min("load clients", self.clients, 1)
        if self.target_rps <= 0:
            raise ValueError(
                f"load target_rps must be > 0, got {self.target_rps}"
            )


def load_config_from_env() -> LoadConfig:
    """The gubload plane's env parse (same contract as
    hotkey_config_from_env): validation errors name the env surface at
    startup instead of crashing a constructor later."""
    try:
        return LoadConfig(
            seed=_env_int("GUBER_LOAD_SEED", 1337),
            scenario=_env("GUBER_LOAD_SCENARIO", "steady"),
            duration_s=_env_float_s("GUBER_LOAD_DURATION", 6.0),
            clients=_env_int("GUBER_LOAD_CLIENTS", 8),
            target_rps=float(_env("GUBER_LOAD_TARGET_RPS", "400")),
        )
    except ValueError as e:
        raise ValueError(
            "load env config (GUBER_LOAD_SEED, GUBER_LOAD_SCENARIO, "
            "GUBER_LOAD_DURATION, GUBER_LOAD_CLIENTS, "
            f"GUBER_LOAD_TARGET_RPS): {e}"
        ) from None


@dataclass
class TierConfig:
    """Guberberg — the two-tier key table (runtime/coldtier.py;
    docs/tiering.md; no reference analog — the Go daemon's cache IS
    host memory, so it never needed a second tier).

    Off by default: the cold tier allocates `cold_capacity` rows of
    host RAM up front, a budget the operator should size, not inherit.
    When enabled, the TierManager demotes the coldest HBM rows once
    occupancy crosses `high_water` (fraction of slots), draining to
    `low_water` (hysteresis — the gap is the breathing room between
    demote ticks); `demote_batch` bounds one demote_extract dispatch
    (per shard on a mesh)."""

    enabled: bool = False
    # Cold-tier row budget (host RAM; rows beyond it are dropped).
    cold_capacity: int = 1_000_000
    # Occupancy fraction that starts demotion pressure.
    high_water: float = 0.85
    # Occupancy fraction demotion drains down to.
    low_water: float = 0.70
    # Rows per demote_extract dispatch (per shard on a mesh).
    demote_batch: int = 256
    # Watermark evaluation cadence in seconds.
    interval_s: float = 1.0

    def __post_init__(self) -> None:
        if self.cold_capacity < 1:
            raise ValueError(
                f"tier cold_capacity must be >= 1, "
                f"got {self.cold_capacity}"
            )
        if not 0.0 < self.high_water <= 1.0:
            raise ValueError(
                f"tier high_water must be in (0, 1], "
                f"got {self.high_water}"
            )
        if not 0.0 < self.low_water <= 1.0:
            raise ValueError(
                f"tier low_water must be in (0, 1], "
                f"got {self.low_water}"
            )
        if self.low_water >= self.high_water:
            raise ValueError(
                f"tier low_water ({self.low_water}) must be below "
                f"high_water ({self.high_water}) — the gap is the "
                f"demotion hysteresis"
            )
        if self.demote_batch < 1:
            raise ValueError(
                f"tier demote_batch must be >= 1, "
                f"got {self.demote_batch}"
            )
        if self.interval_s <= 0:
            raise ValueError(
                f"tier interval_s must be > 0, got {self.interval_s}"
            )


def tier_config_from_env() -> TierConfig:
    """The tier plane's env parse: validation errors name the env
    surface at startup (reject low >= high, capacity < 1) instead of
    crashing a constructor later."""
    try:
        return TierConfig(
            enabled=_env("GUBER_TIER_ENABLED", "false").lower()
            in ("1", "true", "yes"),
            cold_capacity=_env_int(
                "GUBER_TIER_COLD_CAPACITY", 1_000_000
            ),
            high_water=float(_env("GUBER_TIER_HIGH_WATER", "0.85")),
            low_water=float(_env("GUBER_TIER_LOW_WATER", "0.70")),
            demote_batch=_env_int("GUBER_TIER_DEMOTE_BATCH", 256),
            interval_s=_env_float_s("GUBER_TIER_INTERVAL", 1.0),
        )
    except ValueError as e:
        raise ValueError(
            "tier env config (GUBER_TIER_ENABLED, "
            "GUBER_TIER_COLD_CAPACITY, GUBER_TIER_HIGH_WATER, "
            "GUBER_TIER_LOW_WATER, GUBER_TIER_DEMOTE_BATCH, "
            f"GUBER_TIER_INTERVAL): {e}"
        ) from None


def peer_debounce_ms_from_env() -> int:
    """Discovery-update coalescing window (GUBER_PEER_DEBOUNCE_MS): an
    etcd/k8s watch storm delivering N membership events within the
    window triggers ONE remap (latest peer set wins), not N
    interleaved rebuilds.  0 disables coalescing (every event applies,
    still serialized latest-wins)."""
    return _require_min(
        "GUBER_PEER_DEBOUNCE_MS",
        _env_int("GUBER_PEER_DEBOUNCE_MS", 100), 0,
    )


# Fast-lane drain disciplines (runtime/fastpath.py; docs/ring.md):
#   classic    — strict depth-1: every merge's dispatch AND fetch
#                serialize end to end (the pre-PR5 discipline);
#   pipelined  — dispatch serialized, device->host fetches overlapped at
#                GUBER_PIPELINE_DEPTH (PR 5);
#   ring       — the device-resident serving loop (runtime/ring.py):
#                merges enter a request ring, ONE runner thread drives
#                bounded jitted multi-round scans and publishes
#                responses, and the request path never blocks on a
#                device->host fetch.  Served natively by BOTH the
#                single-table backend and the mesh (the shard_map ring
#                step, parallel/sharded.make_mesh_ring_step) — ring on
#                a mesh no longer silently falls back; only a backend
#                without ring support degrades to pipelined.
#   megaround  — ring plus the adaptive round accumulator: the ring
#                capacity multiplies to GUBER_RING_SLOTS x
#                GUBER_RING_ROUNDS and a backlog past the base tier
#                dispatches as ONE mega scan (ops/ring.mega_ring_step)
#                — the XLA entry amortized across the whole block,
#                with add-latency bounded by GUBER_RING_MAX_LINGER_US.
#                A shallow queue dispatches immediately at base tiers.
#   persistent — the ring protocol served by the persistent Pallas
#                decision kernel (ops/pallas/serve_kernel.py): one
#                kernel LAUNCH drains the whole block with the table
#                resident across rounds.  TPU-only; capability is
#                PROBED at arm time and the daemon degrades to
#                megaround with the reason in /debug/vars where the
#                kernel cannot compile (docs/ring.md's matrix).
SERVE_MODES = ("classic", "pipelined", "ring", "megaround", "persistent")


def normalize_serve_mode(value: str) -> str:
    """Canonicalize a serve mode; raise on anything unknown — a typo
    must not silently drop the daemon to a slower discipline."""
    v = (value or "").strip().lower() or "pipelined"
    if v not in SERVE_MODES:
        raise ValueError(
            f"unknown serve mode {value!r}; expected one of "
            + ", ".join(repr(m) for m in SERVE_MODES)
        )
    return v


@dataclass
class DeviceConfig:
    """TPU-specific geometry (no reference analog — replaces the Go worker
    pool's NumCPU/cache-per-worker arithmetic, workers.go:127-146).

    The slot table holds `num_slots` entries arranged as
    `num_slots // ways` buckets of `ways` slots.  `batch_size` is the fixed
    device batch shape (requests are padded up to it — XLA recompiles on new
    shapes, so it never varies at runtime).
    """

    num_slots: int = 65_536
    ways: int = 8
    batch_size: int = 1024
    num_shards: int = 1  # mesh axis size for the sharded table
    platform: Optional[str] = None  # None = jax default
    # Compiled batch-shape tiers: a round whose active lanes fit a smaller
    # tier ships that shape instead of the full batch_size array, so
    # host<->device transfer (and small-batch latency) scales with traffic.
    # None = (128, batch_size).  Each tier costs one XLA compile at warmup.
    batch_tiers: Optional[Tuple[int, ...]] = None
    # GLOBAL replicated-serving cache table size (mesh GlobalEngine only).
    # None = num_slots, i.e. the engine DOUBLES the table HBM footprint;
    # size it to the expected GLOBAL working set (usually a small fraction
    # of the exact tier) to reclaim that memory.  Same divisibility /
    # power-of-two-buckets-per-shard rules as num_slots.
    global_cache_slots: Optional[int] = None

    def __post_init__(self) -> None:
        if self.num_slots % (self.ways * max(self.num_shards, 1)) != 0:
            raise ValueError(
                "num_slots must be divisible by ways*num_shards "
                f"(got {self.num_slots}, {self.ways}, {self.num_shards})"
            )
        if self.global_cache_slots is not None:
            if self.global_cache_slots % (
                self.ways * max(self.num_shards, 1)
            ) != 0:
                raise ValueError(
                    "global_cache_slots must be divisible by "
                    "ways*num_shards (got "
                    f"{self.global_cache_slots}, {self.ways}, "
                    f"{self.num_shards})"
                )


@dataclass
class SketchTierConfig:
    """Approximate (count-min sketch) tier: limit names whose key
    cardinality outgrows exact slots (no reference analog — the reference
    silently over-admits under cache pressure, lrucache.go:147-158).

    SEMANTICS CAVEAT: the sketch counts over tier-level tumbling windows of
    `window_ms` — a request's own `duration` field is IGNORED for names
    routed here (a shared sketch cannot keep per-key windows).  Configure
    `window_ms` to the duration your sketch-tier limits expect; a request
    whose duration differs silently gets window_ms semantics
    (runtime/sketch_backend.py documents the mechanics)."""

    names: List[str] = field(default_factory=list)
    depth: int = 4
    width: int = 8192  # power of two; error ~ window volume / width
    window_ms: int = 1000
    batch_size: int = 1024
    use_pallas: bool = False  # fused TPU kernel (ops/pallas/cms_kernel.py)
    # Dynamic spillover (SURVEY §5 key-space scaling): when set, a name
    # whose EXACT-tier pressure crosses a threshold is routed to this
    # sketch tier from then on (approximate answers, metadata
    # tier=sketch), so a cardinality bomb on one name degrades that name
    # instead of squeezing every name's slot-table residency.  Either
    # knob arms the mode; pressure is observed on the compiled fast
    # lane:
    #   spill_inserts    — estimated DISTINCT keys for the name (a
    #                      per-name HyperLogLog over insert-lane key
    #                      fingerprints, ~±13%; expiry/re-insert churn
    #                      of a small healthy key set does NOT
    #                      accumulate)
    #   spill_transients — cumulative lanes denied a slot under
    #                      full-bucket pressure (zero for a healthy
    #                      table; the unexpired_evictions signal)
    spill_inserts: Optional[int] = None
    spill_transients: Optional[int] = None


@dataclass
class Config:
    """Service-instance config (reference config.go:44-113)."""

    behaviors: BehaviorConfig = field(default_factory=BehaviorConfig)
    device: DeviceConfig = field(default_factory=DeviceConfig)
    cache_size: int = DEFAULT_CACHE_SIZE
    data_center: str = ""
    # "xx" (default; see net/replicated_hash.py on FNV clustering) or
    # "fnv1"/"fnv1a" for placement interop with reference peers
    # (config.go:403-425).
    local_picker_hash: str = "xx"
    region_picker_hash: str = "xx"
    loader: Optional[object] = None  # runtime.store.Loader
    store: Optional[object] = None  # runtime.store.Store
    sketch: Optional[SketchTierConfig] = None  # approximate tier
    # Resilience plane (net/breaker.py + the degraded-mode ownership
    # fallback in runtime/service.py).
    circuit: CircuitConfig = field(default_factory=CircuitConfig)
    degraded_mode: str = "error"  # see DEGRADED_MODES
    # local_shadow: fraction of the limit a non-owner may admit from its
    # shadow slot while the owner is gone (cluster-wide over-admission
    # is bounded by peers * shadow_fraction * limit).
    shadow_fraction: float = 0.5
    # Hot-key survival plane (runtime/hotkey.py; docs/hotkeys.md).
    hotkey: HotKeyConfig = field(default_factory=HotKeyConfig)
    # Client-side admission leases (runtime/lease.py; docs/leases.md).
    lease: LeaseConfig = field(default_factory=LeaseConfig)
    # Elastic membership / live slot migration (runtime/reshard.py;
    # docs/resharding.md).
    reshard: ReshardConfig = field(default_factory=ReshardConfig)
    # Gubstat state-plane introspection (runtime/gubstat.py;
    # docs/observability.md).
    stats: StatsConfig = field(default_factory=StatsConfig)
    # Guberberg two-tier key table (runtime/coldtier.py;
    # docs/tiering.md).
    tier: TierConfig = field(default_factory=TierConfig)
    # Planet-scale active-active regions (runtime/multiregion.py;
    # docs/multiregion.md).
    region: RegionConfig = field(default_factory=RegionConfig)


@dataclass
class DaemonConfig:
    """Daemon assembly config (reference config.go:171-235)."""

    grpc_listen_address: str = "localhost:1051"
    http_listen_address: str = "localhost:1050"
    advertise_address: str = ""
    cache_size: int = DEFAULT_CACHE_SIZE
    data_center: str = ""
    behaviors: BehaviorConfig = field(default_factory=BehaviorConfig)
    device: DeviceConfig = field(default_factory=DeviceConfig)
    peer_discovery_type: str = "none"  # none|static|dns|gossip|k8s|etcd
    # Ring hash for key placement: "xx" (default), or "fnv1"/"fnv1a" for
    # placement interop with reference peers (config.go:403-425); the
    # columnar fast-lane router serves all three (gub_fnv_hashkey_batch).
    local_picker_hash: str = "xx"
    region_picker_hash: str = "xx"
    static_peers: List[str] = field(default_factory=list)
    dns_fqdn: str = ""
    dns_poll_interval_s: float = 10.0
    gossip_bind_address: str = ""  # host:port UDP; default grpc_port+1000
    gossip_seeds: List[str] = field(default_factory=list)
    etcd_endpoints: str = "localhost:2379"
    # Kubernetes discovery (reference kubernetes.go:36-110 /
    # config.go:467-504): which Endpoints/Pods to watch and how to map
    # them to peer addresses.  pod_ip marks ourselves in the peer list.
    k8s_namespace: str = "default"
    k8s_endpoints_selector: str = ""
    k8s_pod_ip: str = ""
    k8s_pod_port: int = 81
    k8s_watch_mechanism: str = "endpoints"  # endpoints | pods
    log_level: str = "info"
    # TLS (reference tls.go / config.go:338-368)
    tls: Optional["TLSConfig"] = None
    metric_flags: int = 0
    # Persistence SPI (runtime.store.Loader / Store)
    loader: Optional[object] = None
    store: Optional[object] = None
    # Approximate (count-min sketch) tier for selected limit names.
    sketch: Optional[SketchTierConfig] = None
    # Compiled fast lane pipeline depth: how many coalesced device
    # merges may be in flight at once.  Depth 1 means every drain takes
    # the WHOLE queue as one maximal merge — measured 2x faster than
    # depth 3 on a high-latency device link (fewer response syncs beats
    # overlapping them: 51k vs 24k checks/s through a ~65ms-RTT tunnel,
    # monotone across depths 1>2>3>4>6).  Raise only if profiling shows
    # host-side gather/serialize starving the device between merges.
    fastpath_inflight: int = 1
    # Sparse-overlap threshold (requests): a fast-lane drain at most this
    # big may dispatch on one of 3 overlap slots instead of waiting out
    # the in-flight merge's response sync.  Re-A/B'd interleaved on the
    # r5 rig: small-batch p50 156 -> 86ms in both reps (~1 fetch cycle),
    # token-config throughput within run-to-run noise (big drains exceed
    # the limit and keep the strict depth-1 maximal-merge discipline).
    # 0 disables.
    fastpath_sparse: int = 64
    # Pipelined-drain depth (docs/pipeline.md): how many coalesced
    # merges may be OUTSTANDING (dispatched, response not yet fetched)
    # per fast-lane lane.  The dispatch stage stays serialized — this
    # never splits a maximal merge — but merge N+1's device dispatch
    # overlaps merge N's device->host readback, moving steady-state
    # throughput from B/(dispatch+fetch) toward B/max(dispatch, fetch).
    # 1 restores the strict pre-pipeline discipline (dispatch and fetch
    # serialized end to end); raise past 2 only if pipeline-occupancy
    # telemetry shows the depth saturated AND bubble time is nonzero.
    pipeline_depth: int = 2
    # Fast-lane drain discipline (SERVE_MODES; docs/ring.md).  "ring"
    # takes host fetches off the request path entirely: enqueue ->
    # poll response slot, with the device loop fed by a request ring.
    serve_mode: str = "pipelined"
    # Request-ring capacity in ROUNDS (GUBER_RING_SLOTS): how many
    # packed [12, B] rounds one ring iteration may consume (the bounded
    # jitted scan's slot budget) and how many may queue before
    # producers block (backpressure, measured as ring slot-wait).
    # Each power-of-two tier up to this costs one XLA compile at
    # warmup.
    ring_slots: int = 8
    # Megaround multiplier (GUBER_RING_ROUNDS; serve_mode=megaround or
    # persistent): ring capacity widens to ring_slots x ring_rounds and
    # a backlog past the base tier dispatches as ONE mega scan — the
    # XLA entry amortized across the block (docs/ring.md).  1 disables.
    ring_rounds: int = 4
    # Adaptive accumulator's bounded add-latency in MICROSECONDS
    # (GUBER_RING_MAX_LINGER_US): how long the runner may wait for a
    # mega block to fill once the queue is already past the base tier.
    # A shallow queue never waits.  0 disables lingering.
    ring_max_linger_us: float = 200.0
    # Flight recorder / SLO telemetry (runtime/flightrec.py).  Off by
    # default: the ring + sampler are cheap, but dumps write to disk and
    # operators should choose the directory.
    flightrec: bool = False
    flightrec_dir: str = "flightrec-dumps"
    flightrec_ring: int = 512
    # Rolling-p99 target in MILLISECONDS (BASELINE.json: p99 < 2ms); a
    # trailing-window p99 over it increments slo_breach_total and dumps.
    slo_p99_ms: float = 2.0
    # > 0: on breach, also start a time-boxed jax.profiler trace of this
    # many seconds under <flightrec_dir>/profile.
    flightrec_profile_s: float = 0.0
    # Resilience plane: per-peer circuit breakers (net/breaker.py) and
    # the degraded-mode ownership fallback (docs/resilience.md).
    circuit: CircuitConfig = field(default_factory=CircuitConfig)
    degraded_mode: str = "error"  # see DEGRADED_MODES
    shadow_fraction: float = 0.5
    # Hot-key survival plane (runtime/hotkey.py; docs/hotkeys.md):
    # owner-pressure detection, bounded mirroring, SLO-driven shedding.
    hotkey: HotKeyConfig = field(default_factory=HotKeyConfig)
    # Client-side admission leases (runtime/lease.py; docs/leases.md):
    # bounded local allowances on the peers wire.
    lease: LeaseConfig = field(default_factory=LeaseConfig)
    # Elastic membership / live slot migration (runtime/reshard.py;
    # docs/resharding.md): a remap streams moved rows old owner -> new
    # owner instead of orphaning them.
    reshard: ReshardConfig = field(default_factory=ReshardConfig)
    # Gubstat state-plane introspection (runtime/gubstat.py;
    # docs/observability.md): census cadence, tenant top-K, /debug/key.
    stats: StatsConfig = field(default_factory=StatsConfig)
    # Guberberg two-tier key table (runtime/coldtier.py;
    # docs/tiering.md): HBM hot slots over a host-RAM cold tier.
    tier: TierConfig = field(default_factory=TierConfig)
    # Planet-scale active-active regions (runtime/multiregion.py;
    # docs/multiregion.md): home-region truth, bounded remote carves,
    # at-most-once WAN reconcile.
    region: RegionConfig = field(default_factory=RegionConfig)
    # Discovery-update coalescing window in ms (GUBER_PEER_DEBOUNCE_MS):
    # rapid watch events within the window apply as ONE latest-wins
    # remap.  0 = apply every event (still serialized).
    peer_debounce_ms: int = 100
    # Graceful scale-down: on daemon close, migrate every owned row to
    # its next owner (the ring without this node) BEFORE stopping the
    # listeners — the autoscaler's preStop/SIGTERM drain.  Off by
    # default: a crash-stop must stay cheap, and tests tear clusters
    # down constantly.
    reshard_drain_on_close: bool = False
    # Chaos plane (testing/chaos.py): a seeded fault plan injected at
    # the peer-client and daemon RPC boundaries.  `chaos_plan` is a JSON
    # plan file (empty = no chaos — the production default); `chaos`
    # accepts a pre-built ChaosInjector programmatically (the in-process
    # cluster fixture).  `chaos_seed` > 0 overrides the plan's seed.
    chaos_plan: str = ""
    chaos_seed: int = 0
    chaos: Optional[object] = None  # testing.chaos.ChaosInjector


@dataclass
class TLSConfig:
    """Subset of reference TLSConfig (tls.go:46-138).

    AutoTLS tiers (tls.go:59-62): with no files at all, a private CA and
    server cert are generated — single-node only, since each daemon would
    mint its own CA.  With `ca_file` + `ca_key_file` but no server cert,
    a per-daemon cert is generated from the SHARED CA — the multi-node
    AutoTLS mode.
    """

    ca_file: str = ""
    ca_key_file: str = ""
    cert_file: str = ""
    key_file: str = ""
    # ""|request|verify-if-given|require-any|require-and-verify
    # (legacy "require"/"verify" == require-and-verify); see net/tls.py
    # for the exact python mapping of the four Go modes.  The reference's
    # spellings (config.go:351-354) are accepted as aliases by
    # normalize_tls_client_auth.
    client_auth: str = ""
    insecure_skip_verify: bool = False


# The reference daemon's GUBER_TLS_CLIENT_AUTH spellings
# (config.go:351-354) -> this repo's canonical modes (net/tls.py).
TLS_CLIENT_AUTH_ALIASES = {
    "request-cert": "request",
    "verify-cert": "verify-if-given",
    "require-any-cert": "require-any",
}
TLS_CLIENT_AUTH_MODES = (
    "",
    "request",
    "verify-if-given",
    "require-any",
    "require-and-verify",
    # Legacy spellings of require-and-verify.
    "require",
    "verify",
)


def normalize_tls_client_auth(value: str) -> str:
    """Canonicalize a client-auth mode, accepting the reference
    spellings as aliases; raise on anything unknown (the reference
    errors too, config.go:357-359) — a typo must not silently disable
    client auth."""
    v = (value or "").strip().lower()
    v = TLS_CLIENT_AUTH_ALIASES.get(v, v)
    if v not in TLS_CLIENT_AUTH_MODES:
        raise ValueError(
            f"unknown TLS client-auth mode {value!r}; expected one of "
            + ", ".join(repr(m) for m in TLS_CLIENT_AUTH_MODES if m)
            + " or a reference spelling "
            + ", ".join(repr(m) for m in TLS_CLIENT_AUTH_ALIASES)
        )
    return v


def _env(name: str, default: str = "") -> str:
    return os.environ.get(name, default)


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    return int(v) if v not in (None, "") else default


def _env_float_s(name: str, default: float) -> float:
    """Duration env var in Go-style suffix notation or plain seconds."""
    v = os.environ.get(name)
    if v in (None, ""):
        return default
    return parse_duration_s(v)


def _require_min(name: str, value: int, lo: int) -> int:
    """Fail at config parse with the env-var name instead of letting an
    out-of-range value crash deep inside a constructor."""
    if value < lo:
        raise ValueError(f"{name} must be >= {lo}, got {value}")
    return value


def parse_duration_s(v: str) -> float:
    """Parse '500us' / '500ms' / '2s' / '1m' / plain float seconds."""
    v = v.strip()
    for suffix, mult in (("us", 1e-6), ("µs", 1e-6), ("ms", 1e-3),
                         ("s", 1.0), ("m", 60.0), ("h", 3600.0)):
        if v.endswith(suffix) and v[: -len(suffix)].replace(".", "").isdigit():
            return float(v[: -len(suffix)]) * mult
    return float(v)


def load_config_file(path: str) -> None:
    """Load KEY=VALUE lines into the environment (config.go:583-611)."""
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if "=" not in line:
                continue
            k, _, val = line.partition("=")
            os.environ[k.strip()] = val.strip()


def gubtrace_dump_dir_from_env() -> str:
    """Where `python -m tools.gubtrace` writes failing kernels' jaxpr
    dumps (CI uploads the directory as the failure artifact).  Parsed
    here so the GUBTRACE_* env surface rides the same
    config->example.conf->envparity discipline as GUBER_*."""
    return _env("GUBTRACE_DUMP_DIR", "gubtrace-dumps")


def gubproof_dump_dir_from_env() -> str:
    """Where `python -m tools.gubproof` writes counterexample chaos
    plans (GUBER_CHAOS_PLAN JSON, replayable by testing/chaos.py; CI
    uploads the directory as the failure artifact).  Same discipline
    as gubtrace_dump_dir_from_env."""
    return _env("GUBPROOF_DUMP_DIR", "gubproof-dumps")


def gubproof_depth_from_env() -> Optional[int]:
    """BFS depth cap for the gubproof explorer; 0 / unset = unbounded.
    The pinned small scopes close unaided, so a cap only exists to
    bound runaway exploration when a model is edited — an insufficient
    cap is itself reported as an error, never a silent pass."""
    d = _env_int("GUBPROOF_DEPTH", 0)
    return None if d <= 0 else d


def gubrange_dump_dir_from_env() -> str:
    """Where `python -m tools.gubrange` writes failing kernels'
    interval-analysis dumps (seeded bounds, issues, witness — CI
    uploads the directory as the failure artifact).  Same discipline
    as gubtrace_dump_dir_from_env."""
    return _env("GUBRANGE_DUMP_DIR", "gubrange-dumps")


def gubrange_strict_from_env() -> bool:
    """Whether gubrange treats warnings (unknown primitives, slack
    budgets) as errors without the --strict flag — CI sets it so a
    transfer-function gap can never silently widen the analysis."""
    return _env("GUBRANGE_STRICT", "false").lower() in ("1", "true", "yes")


def fastpath_sparse_from_env() -> int:
    """The sparse-overlap drain knob, parsed/validated exactly as the
    daemon does — the public entry for harnesses (bench_e2e) that build
    DaemonConfig directly but must honor the same env override."""
    return _require_min(
        "GUBER_FASTPATH_SPARSE",
        _env_int("GUBER_FASTPATH_SPARSE", 64), 0,
    )


def pipeline_depth_from_env() -> int:
    """The pipelined-drain depth knob, parsed/validated exactly as the
    daemon does (same harness contract as fastpath_sparse_from_env)."""
    return _require_min(
        "GUBER_PIPELINE_DEPTH",
        _env_int("GUBER_PIPELINE_DEPTH", 2), 1,
    )


def serve_mode_from_env() -> str:
    """The fast-lane drain-discipline knob (GUBER_SERVE_MODE), parsed/
    validated exactly as the daemon does — rejects unknown modes at
    startup (same harness contract as pipeline_depth_from_env)."""
    return normalize_serve_mode(_env("GUBER_SERVE_MODE", "pipelined"))


def ring_slots_from_env() -> int:
    """The request-ring capacity knob (GUBER_RING_SLOTS), validated at
    daemon startup: fewer than 1 slot cannot hold a round, and past
    1024 the per-tier XLA compiles + the padded scan's wasted work
    outgrow any coalescing win — both are config mistakes, not
    tunings."""
    v = _require_min(
        "GUBER_RING_SLOTS", _env_int("GUBER_RING_SLOTS", 8), 1
    )
    if v > 1024:
        raise ValueError(f"GUBER_RING_SLOTS must be <= 1024, got {v}")
    return v


def ring_rounds_from_env() -> int:
    """The megaround multiplier (GUBER_RING_ROUNDS): how many base-tier
    ring rounds one mega dispatch may amortize — capacity becomes
    GUBER_RING_SLOTS x GUBER_RING_ROUNDS rounds (docs/ring.md).  1
    disables megaround (the plain ring ladder); past 64 the mega-tier
    compiles and the scan's padded work outgrow the amortization win —
    a config mistake, rejected at startup.  The combined
    slots x rounds capacity is bounded in setup_daemon_config (the two
    knobs compose)."""
    v = _require_min(
        "GUBER_RING_ROUNDS", _env_int("GUBER_RING_ROUNDS", 4), 1
    )
    if v > 64:
        raise ValueError(f"GUBER_RING_ROUNDS must be <= 64, got {v}")
    return v


def ring_linger_us_from_env() -> float:
    """The megaround accumulator's add-latency bound
    (GUBER_RING_MAX_LINGER_US, microseconds): how long the runner may
    wait for a mega block to fill once the queue is already past the
    base tier.  0 disables lingering (backlog still widens blocks to
    whatever has queued); past 1s it stops being a linger and starts
    being an outage — rejected at startup."""
    raw = _env("GUBER_RING_MAX_LINGER_US", "200")
    try:
        v = float(raw)
    except ValueError:
        raise ValueError(
            f"GUBER_RING_MAX_LINGER_US must be a number of "
            f"microseconds, got {raw!r}"
        ) from None
    if v < 0:
        raise ValueError(
            f"GUBER_RING_MAX_LINGER_US must be >= 0, got {raw!r}"
        )
    if v > 1_000_000:
        raise ValueError(
            "GUBER_RING_MAX_LINGER_US must be <= 1000000 (1s), got "
            f"{raw!r}"
        )
    return v


def mesh_ways_from_env() -> int:
    """The mesh axis size (GUBER_MESH_WAYS — the deployment-mode
    spelling for "shards mapped onto mesh axes"; GUBER_TPU_NUM_SHARDS
    stays as the geometry-level alias).  Returns 0 when unset so the
    caller can defer to the alias; a SET value must be >= 1 — a zero or
    negative mesh is a config mistake rejected at startup, and a count
    past the attached device set is rejected when the mesh is built
    (parallel/mesh.make_mesh names the shortfall)."""
    raw = _env("GUBER_MESH_WAYS")
    if not raw:
        return 0
    v = _env_int("GUBER_MESH_WAYS", 0)
    if v < 1:
        raise ValueError(f"GUBER_MESH_WAYS must be >= 1, got {raw!r}")
    return v


def setup_daemon_config(config_file: Optional[str] = None) -> DaemonConfig:
    """Build a DaemonConfig from GUBER_* env vars (config.go:253-459)."""
    if config_file:
        load_config_file(config_file)

    behaviors = BehaviorConfig(
        batch_timeout_s=_env_float_s("GUBER_BATCH_TIMEOUT", DEFAULT_BATCH_TIMEOUT_S),
        batch_wait_s=_env_float_s("GUBER_BATCH_WAIT", DEFAULT_BATCH_WAIT_S),
        batch_limit=_env_int("GUBER_BATCH_LIMIT", DEFAULT_BATCH_LIMIT),
        global_timeout_s=_env_float_s("GUBER_GLOBAL_TIMEOUT", DEFAULT_BATCH_TIMEOUT_S),
        global_sync_wait_s=_env_float_s("GUBER_GLOBAL_SYNC_WAIT", DEFAULT_BATCH_WAIT_S),
        global_batch_limit=_env_int("GUBER_GLOBAL_BATCH_LIMIT", DEFAULT_BATCH_LIMIT),
    )
    num_shards = mesh_ways_from_env() or _require_min(
        "GUBER_TPU_NUM_SHARDS", _env_int("GUBER_TPU_NUM_SHARDS", 1), 1
    )
    try:
        device = DeviceConfig(
            num_slots=_env_int("GUBER_TPU_NUM_SLOTS", 65_536),
            ways=_env_int("GUBER_TPU_WAYS", 8),
            batch_size=_env_int("GUBER_TPU_BATCH_SIZE", 1024),
            num_shards=num_shards,
            platform=os.environ.get("GUBER_TPU_PLATFORM") or None,
        )
    except ValueError as e:
        # Name the env surface in the startup rejection: an invalid
        # shard count (slots not divisible by ways*shards) must fail
        # here, not deep inside MeshBackend construction.
        raise ValueError(
            "mesh/device geometry invalid (GUBER_MESH_WAYS, "
            f"GUBER_TPU_NUM_SLOTS, GUBER_TPU_WAYS): {e}"
        ) from None
    tls: Optional[TLSConfig] = None
    if _env("GUBER_TLS_CERT") or _env("GUBER_TLS_CA"):
        tls = TLSConfig(
            ca_file=_env("GUBER_TLS_CA"),
            ca_key_file=_env("GUBER_TLS_CA_KEY"),
            cert_file=_env("GUBER_TLS_CERT"),
            key_file=_env("GUBER_TLS_KEY"),
            client_auth=normalize_tls_client_auth(
                _env("GUBER_TLS_CLIENT_AUTH")
            ),
            insecure_skip_verify=_env("GUBER_TLS_INSECURE_SKIP_VERIFY") == "true",
        )
    static_peers = [
        p.strip() for p in _env("GUBER_PEERS").split(",") if p.strip()
    ]
    sketch: Optional[SketchTierConfig] = None
    sketch_names = [
        n.strip() for n in _env("GUBER_SKETCH_NAMES").split(",") if n.strip()
    ]
    if sketch_names:
        window_ms = int(_env_float_s("GUBER_SKETCH_WINDOW", 1.0) * 1000)
        if window_ms < 1:
            # Fail at parse: a zero/negative window reaches the rotation
            # arithmetic as a modulo-by-zero and serves garbage silently.
            raise ValueError(
                "GUBER_SKETCH_WINDOW must be >= 1ms, got "
                f"{_env('GUBER_SKETCH_WINDOW')!r}"
            )
        sketch = SketchTierConfig(
            names=sketch_names,
            depth=_env_int("GUBER_SKETCH_DEPTH", 4),
            width=_env_int("GUBER_SKETCH_WIDTH", 8192),
            window_ms=window_ms,
            batch_size=_env_int("GUBER_SKETCH_BATCH_SIZE", 1024),
            use_pallas=_env("GUBER_SKETCH_USE_PALLAS") == "true",
        )
    circuit = CircuitConfig(
        enabled=_env("GUBER_CIRCUIT_ENABLED", "true").lower()
        not in ("0", "false", "no"),
        failure_threshold=_require_min(
            "GUBER_CIRCUIT_FAILURE_THRESHOLD",
            _env_int("GUBER_CIRCUIT_FAILURE_THRESHOLD", 5), 1,
        ),
        base_backoff_s=_env_float_s("GUBER_CIRCUIT_BASE_BACKOFF", 0.5),
        max_backoff_s=_env_float_s("GUBER_CIRCUIT_MAX_BACKOFF", 30.0),
        jitter=float(_env("GUBER_CIRCUIT_JITTER", "0.2")),
        half_open_probes=_require_min(
            "GUBER_CIRCUIT_HALF_OPEN_PROBES",
            _env_int("GUBER_CIRCUIT_HALF_OPEN_PROBES", 1), 1,
        ),
        probe_timeout_s=_env_float_s("GUBER_CIRCUIT_PROBE_TIMEOUT", 10.0),
    )
    shadow_fraction = float(_env("GUBER_DEGRADED_SHADOW_FRACTION", "0.5"))
    if not 0.0 < shadow_fraction <= 1.0:
        raise ValueError(
            "GUBER_DEGRADED_SHADOW_FRACTION must be in (0, 1], got "
            f"{shadow_fraction}"
        )
    ring_rounds = ring_rounds_from_env()
    if ring_slots_from_env() * ring_rounds > 4096:
        # The knobs compose: capacity = slots x rounds bounds both the
        # mega-tier compile ladder and the padded scan's worst case.
        raise ValueError(
            "GUBER_RING_SLOTS x GUBER_RING_ROUNDS must be <= 4096, got "
            f"{ring_slots_from_env()} x {ring_rounds}"
        )
    return DaemonConfig(
        grpc_listen_address=_env("GUBER_GRPC_ADDRESS", "localhost:1051"),
        http_listen_address=_env("GUBER_HTTP_ADDRESS", "localhost:1050"),
        advertise_address=_env("GUBER_ADVERTISE_ADDRESS", ""),
        cache_size=_env_int("GUBER_CACHE_SIZE", DEFAULT_CACHE_SIZE),
        data_center=_env("GUBER_DATA_CENTER", ""),
        behaviors=behaviors,
        device=device,
        peer_discovery_type=_env(
            "GUBER_PEER_DISCOVERY_TYPE", "static" if static_peers else "none"
        ),
        local_picker_hash=_env("GUBER_PEER_PICKER_HASH", "xx"),
        region_picker_hash=_env("GUBER_REGION_PICKER_HASH", "xx"),
        static_peers=static_peers,
        dns_fqdn=_env("GUBER_DNS_FQDN", ""),
        dns_poll_interval_s=_env_float_s("GUBER_DNS_POLL_INTERVAL", 10.0),
        gossip_bind_address=_env("GUBER_GOSSIP_ADDRESS", ""),
        gossip_seeds=[
            s.strip()
            for s in _env("GUBER_GOSSIP_SEEDS").split(",")
            if s.strip()
        ],
        etcd_endpoints=_env("GUBER_ETCD_ENDPOINTS", "localhost:2379"),
        k8s_namespace=_env("GUBER_K8S_NAMESPACE", "default"),
        k8s_endpoints_selector=_env("GUBER_K8S_ENDPOINTS_SELECTOR", ""),
        k8s_pod_ip=_env("GUBER_K8S_POD_IP", ""),
        k8s_pod_port=_env_int("GUBER_K8S_POD_PORT", 81),
        k8s_watch_mechanism=_env("GUBER_K8S_WATCH_MECHANISM", "endpoints"),
        log_level=_env("GUBER_LOG_LEVEL", "info"),
        tls=tls,
        sketch=sketch,
        # Bit 1 = process/platform/GC collectors (the GUBER_METRIC_FLAGS
        # golang/process flags, daemon.go:255-266, flags.go:19-56).
        metric_flags=_env_int("GUBER_METRIC_FLAGS", 0),
        fastpath_inflight=_require_min(
            "GUBER_FASTPATH_INFLIGHT",
            _env_int("GUBER_FASTPATH_INFLIGHT", 1), 1,
        ),
        fastpath_sparse=fastpath_sparse_from_env(),
        pipeline_depth=pipeline_depth_from_env(),
        serve_mode=serve_mode_from_env(),
        ring_slots=ring_slots_from_env(),
        ring_rounds=ring_rounds,
        ring_max_linger_us=ring_linger_us_from_env(),
        flightrec=_env("GUBER_FLIGHTREC") in ("1", "true"),
        flightrec_dir=_env("GUBER_FLIGHTREC_DIR", "flightrec-dumps"),
        flightrec_ring=_require_min(
            "GUBER_FLIGHTREC_RING",
            _env_int("GUBER_FLIGHTREC_RING", 512), 1,
        ),
        slo_p99_ms=float(_env("GUBER_SLO_P99_MS", "2.0")),
        flightrec_profile_s=_env_float_s("GUBER_FLIGHTREC_PROFILE", 0.0),
        circuit=circuit,
        degraded_mode=normalize_degraded_mode(
            _env("GUBER_DEGRADED_MODE", "error")
        ),
        shadow_fraction=shadow_fraction,
        hotkey=hotkey_config_from_env(),
        lease=lease_config_from_env(),
        reshard=reshard_config_from_env(),
        stats=stats_config_from_env(),
        tier=tier_config_from_env(),
        region=region_config_from_env(),
        peer_debounce_ms=peer_debounce_ms_from_env(),
        reshard_drain_on_close=_env(
            "GUBER_RESHARD_DRAIN_ON_CLOSE", "false"
        ).lower() in ("1", "true", "yes"),
        chaos_plan=_env("GUBER_CHAOS_PLAN", ""),
        chaos_seed=_env_int("GUBER_CHAOS_SEED", 0),
    )


def fast_test_behaviors() -> BehaviorConfig:
    """Short windows for tests (reference cluster/cluster.go:119-125)."""
    return BehaviorConfig(
        batch_timeout_s=2.0,
        batch_wait_s=0.01,
        batch_limit=DEFAULT_BATCH_LIMIT,
        global_timeout_s=2.0,
        global_sync_wait_s=0.05,
        global_batch_limit=DEFAULT_BATCH_LIMIT,
        multi_region_timeout_s=2.0,
        multi_region_sync_wait_s=0.05,
    )
