"""Gregorian calendar intervals (reference interval.go:84-148).

The reference's one-shot ticker (interval.go:29-72) has no class here: its
role is played by the asyncio window_flush_loop heartbeat
(runtime/service.py).  Duration values 0-5 select a calendar
interval; expiry is the END of the current interval (e.g. for Minutes, the
last millisecond of the current minute).
"""
from __future__ import annotations

import calendar
from datetime import datetime, timedelta
GREGORIAN_MINUTES = 0
GREGORIAN_HOURS = 1
GREGORIAN_DAYS = 2
GREGORIAN_WEEKS = 3
GREGORIAN_MONTHS = 4
GREGORIAN_YEARS = 5


class GregorianError(ValueError):
    pass


def _to_ms(dt: datetime) -> int:
    return int(dt.timestamp() * 1000)


def gregorian_duration(now: datetime, d: int) -> int:
    """Entire duration of the Gregorian interval containing `now`, in ms
    (reference interval.go:84-109).

    Deviation from the reference: interval.go:99 has an operator-precedence
    bug for Months (`end.UnixNano() - begin.UnixNano()/1000000`); we return
    the intended (end - begin) in milliseconds.
    """
    if d == GREGORIAN_MINUTES:
        return 60_000
    if d == GREGORIAN_HOURS:
        return 3_600_000
    if d == GREGORIAN_DAYS:
        return 86_400_000
    if d == GREGORIAN_WEEKS:
        raise GregorianError("`Duration = GregorianWeeks` not yet supported")
    if d == GREGORIAN_MONTHS:
        days = calendar.monthrange(now.year, now.month)[1]
        return days * 86_400_000
    if d == GREGORIAN_YEARS:
        days = 366 if calendar.isleap(now.year) else 365
        return days * 86_400_000
    raise GregorianError(
        "behavior DURATION_IS_GREGORIAN is set; but `Duration` is not a valid "
        "gregorian interval"
    )


def gregorian_expiration(now: datetime, d: int) -> int:
    """End of the current Gregorian interval as unix ms
    (reference interval.go:117-148).  E.g. Minutes → last ms of this minute.
    """
    if d == GREGORIAN_MINUTES:
        start = now.replace(second=0, microsecond=0)
        return _to_ms(start) + 60_000 - 1
    if d == GREGORIAN_HOURS:
        start = now.replace(minute=0, second=0, microsecond=0)
        return _to_ms(start) + 3_600_000 - 1
    if d == GREGORIAN_DAYS:
        start = now.replace(hour=0, minute=0, second=0, microsecond=0)
        return _to_ms(start) + 86_400_000 - 1
    if d == GREGORIAN_WEEKS:
        raise GregorianError("`Duration = GregorianWeeks` not yet supported")
    if d == GREGORIAN_MONTHS:
        start = now.replace(day=1, hour=0, minute=0, second=0, microsecond=0)
        days = calendar.monthrange(now.year, now.month)[1]
        return _to_ms(start + timedelta(days=days)) - 1
    if d == GREGORIAN_YEARS:
        start = now.replace(
            month=1, day=1, hour=0, minute=0, second=0, microsecond=0
        )
        return _to_ms(start.replace(year=start.year + 1)) - 1
    raise GregorianError(
        "behavior DURATION_IS_GREGORIAN is set; but `Duration` is not a valid "
        "gregorian interval"
    )
