"""Gregorian calendar intervals + the one-shot interval ticker.

Mirrors reference interval.go:29-148.  Duration values 0-5 select a calendar
interval; expiry is the END of the current interval (e.g. for Minutes, the
last millisecond of the current minute).
"""
from __future__ import annotations

import asyncio
import calendar
from datetime import datetime, timedelta
from typing import Optional

GREGORIAN_MINUTES = 0
GREGORIAN_HOURS = 1
GREGORIAN_DAYS = 2
GREGORIAN_WEEKS = 3
GREGORIAN_MONTHS = 4
GREGORIAN_YEARS = 5


class GregorianError(ValueError):
    pass


def _to_ms(dt: datetime) -> int:
    return int(dt.timestamp() * 1000)


def gregorian_duration(now: datetime, d: int) -> int:
    """Entire duration of the Gregorian interval containing `now`, in ms
    (reference interval.go:84-109).

    Deviation from the reference: interval.go:99 has an operator-precedence
    bug for Months (`end.UnixNano() - begin.UnixNano()/1000000`); we return
    the intended (end - begin) in milliseconds.
    """
    if d == GREGORIAN_MINUTES:
        return 60_000
    if d == GREGORIAN_HOURS:
        return 3_600_000
    if d == GREGORIAN_DAYS:
        return 86_400_000
    if d == GREGORIAN_WEEKS:
        raise GregorianError("`Duration = GregorianWeeks` not yet supported")
    if d == GREGORIAN_MONTHS:
        days = calendar.monthrange(now.year, now.month)[1]
        return days * 86_400_000
    if d == GREGORIAN_YEARS:
        days = 366 if calendar.isleap(now.year) else 365
        return days * 86_400_000
    raise GregorianError(
        "behavior DURATION_IS_GREGORIAN is set; but `Duration` is not a valid "
        "gregorian interval"
    )


def gregorian_expiration(now: datetime, d: int) -> int:
    """End of the current Gregorian interval as unix ms
    (reference interval.go:117-148).  E.g. Minutes → last ms of this minute.
    """
    if d == GREGORIAN_MINUTES:
        start = now.replace(second=0, microsecond=0)
        return _to_ms(start) + 60_000 - 1
    if d == GREGORIAN_HOURS:
        start = now.replace(minute=0, second=0, microsecond=0)
        return _to_ms(start) + 3_600_000 - 1
    if d == GREGORIAN_DAYS:
        start = now.replace(hour=0, minute=0, second=0, microsecond=0)
        return _to_ms(start) + 86_400_000 - 1
    if d == GREGORIAN_WEEKS:
        raise GregorianError("`Duration = GregorianWeeks` not yet supported")
    if d == GREGORIAN_MONTHS:
        start = now.replace(day=1, hour=0, minute=0, second=0, microsecond=0)
        days = calendar.monthrange(now.year, now.month)[1]
        return _to_ms(start + timedelta(days=days)) - 1
    if d == GREGORIAN_YEARS:
        start = now.replace(
            month=1, day=1, hour=0, minute=0, second=0, microsecond=0
        )
        return _to_ms(start.replace(year=start.year + 1)) - 1
    raise GregorianError(
        "behavior DURATION_IS_GREGORIAN is set; but `Duration` is not a valid "
        "gregorian interval"
    )


class Interval:
    """One-shot async ticker (reference interval.go:29-72).

    `next()` arms the timer; `wait()` resolves one interval later.  Multiple
    `next()` calls before the tick fires are coalesced.  This is the batching
    heartbeat used by the peer batcher and the GLOBAL manager.
    """

    def __init__(self, delay_s: float) -> None:
        self._delay = delay_s
        self._armed = False
        self._event = asyncio.Event()
        self._task: Optional[asyncio.Task] = None

    def next(self) -> None:
        if self._armed:
            return
        self._armed = True
        self._task = asyncio.get_running_loop().create_task(self._fire())

    async def _fire(self) -> None:
        await asyncio.sleep(self._delay)
        self._event.set()

    async def wait(self) -> None:
        await self._event.wait()
        self._event.clear()
        self._armed = False

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        self._armed = False
