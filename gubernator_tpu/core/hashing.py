"""Key hashing.

Two consumers:

1. The device slot table needs a 64-bit fingerprint per key string; we use
   xxhash64 (the reference uses xxhash for its worker hash ring,
   workers.go:47,154).  Hash value 0 is reserved as the empty-slot sentinel,
   remapped to 1.

2. The consistent-hash peer ring needs fnv1/fnv1a 64-bit string hashes
   (reference replicated_hash.go:26,33 via segmentio/fasthash).
"""
from __future__ import annotations

from typing import Iterable, List

import numpy as np
import xxhash

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_MASK64 = 0xFFFFFFFFFFFFFFFF


def fnv1_64(data: bytes) -> int:
    """FNV-1 64-bit (multiply then xor) — fasthash/fnv1.HashString64."""
    h = _FNV_OFFSET
    for b in data:
        h = (h * _FNV_PRIME) & _MASK64
        h ^= b
    return h


def fnv1a_64(data: bytes) -> int:
    """FNV-1a 64-bit (xor then multiply) — fasthash/fnv1a.HashString64."""
    h = _FNV_OFFSET
    for b in data:
        h ^= b
        h = (h * _FNV_PRIME) & _MASK64
    return h


def key_hash64(key: str) -> int:
    """64-bit device fingerprint of a hash key; never 0 (empty sentinel)."""
    h = xxhash.xxh64_intdigest(key)
    return h if h != 0 else 1


def bulk_key_hash64(keys: Iterable[str]) -> np.ndarray:
    """Vector of int64 fingerprints (two's-complement view of the uint64)."""
    out: List[int] = []
    for k in keys:
        h = xxhash.xxh64_intdigest(k)
        if h == 0:
            h = 1
        out.append(h)
    return np.array(out, dtype=np.uint64).view(np.int64)
