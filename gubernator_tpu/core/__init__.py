"""Core pure-Python layer: types, clock, calendar intervals, config, hashing."""
