"""Exact sequential rate-limit model (host fallback + differential oracle).

This is a faithful re-derivation of the reference algorithm semantics
(algorithms.go:31-492) over a plain dict cache.  It exists for three reasons:

1. Differential testing: the vectorized device kernels
   (gubernator_tpu.ops.step) must produce byte-identical decisions; tests
   drive random op streams through both and compare.
2. Host fallback backend when no accelerator is configured.
3. The Loader/Store persistence SPI operates on these CacheItem records.

Every special case is labeled with its reference file:line.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from gubernator_tpu.core import clock as clock_mod
from gubernator_tpu.core.interval import (
    gregorian_duration,
    gregorian_expiration,
)
from gubernator_tpu.core.types import (
    Algorithm,
    Behavior,
    CacheItem,
    RateLimitReq,
    RateLimitResp,
    Status,
    has_behavior,
)


_I64_MAX = 2**63 - 1
_I64_MIN = -(2**63)


def _trunc(x: float) -> int:
    """Go's int64(float64) — truncation toward zero — pinned to the
    device kernel's exact edge semantics (ops/step.py _trunc_i64):
    out-of-range values SATURATE at the int64 bounds and NaN maps to 0
    (XLA convert behavior; Go itself is implementation-dependent here —
    amd64 collapses all three cases to INT64_MIN).  A bare
    int(math.trunc(x)) would diverge beyond ±2^63 (python ints are
    unbounded) and raise on NaN/inf; the differential suite
    (tests/test_differential.py::test_go_trunc_differential) holds the
    two implementations bit-identical across the full edge matrix."""
    if math.isnan(x):
        return 0
    if x >= _I64_MAX:
        return _I64_MAX
    if x <= _I64_MIN:
        return _I64_MIN
    return int(math.trunc(x))


def _clamp_i64(x: int) -> int:
    if x > _I64_MAX:
        return _I64_MAX
    if x < _I64_MIN:
        return _I64_MIN
    return x


def _sat_add(a: int, b: int) -> int:
    """Saturating int64 add — the oracle half of the device's
    _sat_add_i64 (ops/step.py).  The device clamps the addend into the
    room the augend leaves, which equals clamping the exact
    unbounded-int sum; composed saturating ops must still clamp STEP BY
    STEP in the same order as the device, not clamp one exact total."""
    return _clamp_i64(a + b)


def _sat_sub(a: int, b: int) -> int:
    """Saturating int64 subtract (see _sat_add)."""
    return _clamp_i64(a - b)


class PyRateLimiter:
    """Sequential, exact rate limiter over a dict cache."""

    def __init__(self, clock: Optional[clock_mod.Clock] = None) -> None:
        self.cache: Dict[str, CacheItem] = {}
        self.clock = clock or clock_mod.default_clock()

    # -- public ----------------------------------------------------------
    def get_rate_limit(self, r: RateLimitReq) -> RateLimitResp:
        if r.algorithm == Algorithm.TOKEN_BUCKET:
            return self._token_bucket(r)
        return self._leaky_bucket(r)

    # -- token bucket (algorithms.go:31-258) -----------------------------
    def _token_bucket(self, r: RateLimitReq) -> RateLimitResp:
        now = self.clock.millisecond_now()
        key = r.hash_key()
        item = self.cache.get(key)
        # Expiry is handled by the cache in the reference (lrucache.go:115-127
        # returns miss for expired items); emulate here.
        if item is not None and item.is_expired(now):
            del self.cache[key]
            item = None

        if item is not None:
            if has_behavior(r.behavior, Behavior.RESET_REMAINING):
                # algorithms.go:78-90: remove and answer fresh.
                del self.cache[key]
                return RateLimitResp(
                    status=Status.UNDER_LIMIT,
                    limit=r.limit,
                    remaining=r.limit,
                    reset_time=0,
                )
            if item.algorithm != Algorithm.TOKEN_BUCKET or item.cached_resp is not None:
                # Algorithm switch (algorithms.go:97-109): drop + recreate.
                del self.cache[key]
                return self._token_bucket_new(r, now)

            # Limit change (algorithms.go:112-119).  Saturating like the
            # device (step-by-step: add, then sub).
            if item.limit != r.limit:
                item.remaining = max(
                    _sat_sub(
                        _sat_add(int(item.remaining), r.limit), item.limit
                    ),
                    0,
                )
                item.limit = r.limit

            rl = RateLimitResp(
                status=item.status,
                limit=r.limit,
                remaining=int(item.remaining),
                reset_time=item.expire_at,
            )

            # Duration change (algorithms.go:129-152).
            if item.duration != r.duration:
                if has_behavior(r.behavior, Behavior.DURATION_IS_GREGORIAN):
                    expire = gregorian_expiration(self.clock.now(), r.duration)
                else:
                    expire = _sat_add(item.created_at, r.duration)
                if expire <= now:
                    # Renew (algorithms.go:141-147).
                    expire = _sat_add(now, r.duration)
                    item.created_at = now
                    item.remaining = item.limit
                item.expire_at = expire
                item.duration = r.duration
                rl.reset_time = expire

            # Hits==0 status read (algorithms.go:162-164).
            if r.hits == 0:
                return rl

            # Already at the limit (algorithms.go:167-173) — tests the
            # RESPONSE remaining (pre-duration-renew), not item.remaining.
            if rl.remaining == 0 and r.hits > 0:
                rl.status = Status.OVER_LIMIT
                item.status = Status.OVER_LIMIT
                return rl

            # Exact take (algorithms.go:176-181) — tests ITEM remaining.
            if int(item.remaining) == r.hits:
                item.remaining = 0
                rl.remaining = 0
                return rl

            # Over without mutation (algorithms.go:185-190).
            if r.hits > int(item.remaining):
                rl.status = Status.OVER_LIMIT
                return rl

            # Under (algorithms.go:192-195).
            item.remaining = int(item.remaining) - r.hits
            rl.remaining = int(item.remaining)
            return rl

        return self._token_bucket_new(r, now)

    def _token_bucket_new(self, r: RateLimitReq, now: int) -> RateLimitResp:
        """algorithms.go:203-258."""
        if has_behavior(r.behavior, Behavior.DURATION_IS_GREGORIAN):
            expire = gregorian_expiration(self.clock.now(), r.duration)
        else:
            expire = _sat_add(now, r.duration)
        remaining = r.limit - r.hits
        rl = RateLimitResp(
            status=Status.UNDER_LIMIT,
            limit=r.limit,
            remaining=remaining,
            reset_time=expire,
        )
        if r.hits > r.limit:
            # algorithms.go:243-249: over on first hit; stored status stays
            # UNDER (only rl.Status flips).
            rl.status = Status.OVER_LIMIT
            rl.remaining = r.limit
            remaining = r.limit
        self.cache[r.hash_key()] = CacheItem(
            key=r.hash_key(),
            algorithm=Algorithm.TOKEN_BUCKET,
            expire_at=expire,
            limit=r.limit,
            duration=r.duration,
            remaining=remaining,
            created_at=now,
            status=Status.UNDER_LIMIT,
        )
        return rl

    # -- leaky bucket (algorithms.go:261-492) ----------------------------
    def _leaky_bucket(self, r: RateLimitReq) -> RateLimitResp:
        burst = r.burst if r.burst != 0 else r.limit  # algorithms.go:271-272
        now = self.clock.millisecond_now()
        key = r.hash_key()
        item = self.cache.get(key)
        if item is not None and item.is_expired(now):
            del self.cache[key]
            item = None

        if item is None:
            return self._leaky_bucket_new(r, burst, now)

        if item.algorithm != Algorithm.LEAKY_BUCKET or item.cached_resp is not None:
            # Algorithm switch (algorithms.go:315-325).
            del self.cache[key]
            return self._leaky_bucket_new(r, burst, now)

        rem = float(item.remaining)

        # RESET_REMAINING (algorithms.go:327-329): remaining := burst.
        if has_behavior(r.behavior, Behavior.RESET_REMAINING):
            rem = float(burst)

        # Burst change (algorithms.go:332-337).
        if item.burst != burst:
            if burst > _trunc(rem):
                rem = float(burst)
            item.burst = burst

        item.limit = r.limit
        item.duration = r.duration  # stored as the RAW duration here
        duration = r.duration
        rate = duration / r.limit if r.limit != 0 else 0.0

        if has_behavior(r.behavior, Behavior.DURATION_IS_GREGORIAN):
            # algorithms.go:345-361: rate from the FULL interval duration;
            # duration = remaining time until interval end.
            d = gregorian_duration(self.clock.now(), r.duration)
            rate = d / r.limit if r.limit != 0 else 0.0
            duration = gregorian_expiration(self.clock.now(), r.duration) - now

        if r.hits != 0:
            item.expire_at = _sat_add(now, duration)  # algorithms.go:363-365

        # Leak (algorithms.go:367-378).
        elapsed = now - item.created_at
        leak = elapsed / rate if rate != 0 else 0.0
        if _trunc(leak) > 0:
            rem += leak
            item.created_at = now
        if _trunc(rem) > burst:
            rem = float(burst)

        rem_i = _trunc(rem)
        rate_i = _trunc(rate)
        # ResetTime in float64 + saturating truncation, mirroring the
        # device's evaluation order exactly (ops/step.py le_resp_reset):
        # exact below 2^53, saturates instead of wrapping beyond int64.
        rl = RateLimitResp(
            status=Status.UNDER_LIMIT,
            limit=item.limit,
            remaining=rem_i,
            reset_time=_trunc(
                float(now) + (float(item.limit) - float(rem_i))
                * float(rate_i)
            ),
        )

        if rem_i == 0 and r.hits > 0:
            # algorithms.go:396-400.
            rl.status = Status.OVER_LIMIT
            item.remaining = rem
            return rl

        if rem_i == r.hits:
            # algorithms.go:403-408: exact take.
            rem -= float(r.hits)
            item.remaining = rem
            rl.remaining = 0
            rl.reset_time = _trunc(
                float(now) + (float(rl.limit) - 0.0) * float(rate_i)
            )
            return rl

        if r.hits > rem_i:
            # algorithms.go:412-416.
            rl.status = Status.OVER_LIMIT
            item.remaining = rem
            return rl

        if r.hits == 0:
            # algorithms.go:419-421.
            item.remaining = rem
            return rl

        # Under (algorithms.go:423-426).
        rem -= float(r.hits)
        item.remaining = rem
        rl.remaining = _trunc(rem)
        rl.reset_time = _trunc(
            float(now) + (float(rl.limit) - float(rl.remaining))
            * float(rate_i)
        )
        return rl

    def _leaky_bucket_new(
        self, r: RateLimitReq, burst: int, now: int
    ) -> RateLimitResp:
        """algorithms.go:433-492."""
        duration = r.duration
        # Quirk preserved: rate uses the RAW r.duration even under
        # DURATION_IS_GREGORIAN (algorithms.go:440-451 computes rate before
        # the gregorian adjustment and never recomputes it).
        rate = duration / r.limit if r.limit != 0 else 0.0
        if has_behavior(r.behavior, Behavior.DURATION_IS_GREGORIAN):
            duration = gregorian_expiration(self.clock.now(), r.duration) - now

        rem = float(burst - r.hits)
        rate_i = _trunc(rate)
        rl = RateLimitResp(
            status=Status.UNDER_LIMIT,
            limit=r.limit,
            remaining=burst - r.hits,
            reset_time=_trunc(
                float(now) + (float(r.limit) - float(burst - r.hits))
                * float(rate_i)
            ),
        )
        if r.hits > burst:
            # algorithms.go:470-476.
            rl.status = Status.OVER_LIMIT
            rl.remaining = 0
            rl.reset_time = _trunc(
                float(now) + (float(rl.limit) - 0.0) * float(rate_i)
            )
            rem = 0.0
        self.cache[r.hash_key()] = CacheItem(
            key=r.hash_key(),
            algorithm=Algorithm.LEAKY_BUCKET,
            expire_at=_sat_add(now, duration),
            limit=r.limit,
            duration=duration,  # stored as the COMPUTED duration here
            remaining=rem,
            created_at=now,
            burst=burst,
        )
        return rl
