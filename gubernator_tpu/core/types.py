"""Core request/response types and enums.

Mirrors the reference wire contract (proto/gubernator.proto:57-182,
proto/peers.proto:36-57) as plain Python dataclasses.  These are the host-side
currency of the framework; the device layer consumes them as packed arrays
(see gubernator_tpu.ops.batch).
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional


class Algorithm(enum.IntEnum):
    """Rate-limit algorithm (gubernator.proto:57-62)."""

    TOKEN_BUCKET = 0
    LEAKY_BUCKET = 1


class Behavior(enum.IntFlag):
    """Behavior flag bits (gubernator.proto:65-131).

    BATCHING is the zero value (default); the rest are single bits that can be
    OR-ed together.
    """

    BATCHING = 0
    NO_BATCHING = 1
    GLOBAL = 2
    DURATION_IS_GREGORIAN = 4
    RESET_REMAINING = 8
    MULTI_REGION = 16


class Status(enum.IntEnum):
    """Rate-limit decision (gubernator.proto:164-167)."""

    UNDER_LIMIT = 0
    OVER_LIMIT = 1


def has_behavior(b: int, flag: Behavior) -> bool:
    """Bit test, reference gubernator.go:782-785."""
    return bool(int(b) & int(flag))


# Duration convenience constants (reference client.go:31-35).
MILLISECOND = 1
SECOND = 1000
MINUTE = 60 * SECOND
HOUR = 60 * MINUTE


@dataclass
class RateLimitReq:
    """One rate-limit check (gubernator.proto:133-162)."""

    name: str = ""
    unique_key: str = ""
    hits: int = 0
    limit: int = 0
    duration: int = 0  # milliseconds (or Gregorian interval id 0-5)
    algorithm: Algorithm = Algorithm.TOKEN_BUCKET
    behavior: Behavior = Behavior.BATCHING
    burst: int = 0

    def hash_key(self) -> str:
        """Canonical cache key: Name + "_" + UniqueKey (client.go:37-39)."""
        return self.name + "_" + self.unique_key


@dataclass
class RateLimitResp:
    """One rate-limit answer (gubernator.proto:169-182)."""

    status: Status = Status.UNDER_LIMIT
    limit: int = 0
    remaining: int = 0
    reset_time: int = 0  # unix ms
    error: str = ""
    metadata: Dict[str, str] = field(default_factory=dict)


@dataclass
class GetRateLimitsReq:
    requests: List[RateLimitReq] = field(default_factory=list)


@dataclass
class GetRateLimitsResp:
    responses: List[RateLimitResp] = field(default_factory=list)


@dataclass
class HealthCheckResp:
    """gubernator.proto:185-192."""

    status: str = "healthy"
    message: str = ""
    peer_count: int = 0


@dataclass
class UpdatePeerGlobal:
    """peers.proto:52-56 — owner-authoritative status pushed to peers."""

    key: str = ""
    status: Optional[RateLimitResp] = None
    algorithm: Algorithm = Algorithm.TOKEN_BUCKET


@dataclass
class LeaseGrant:
    """One granted (or refused) client-side admission lease
    (peers.proto Lease/Reconcile; docs/leases.md).

    `allowance` hits may be burned locally with zero RPCs until
    `expires_at` (unix ms); a non-empty `refusal` means no allowance was
    granted (allowance == 0) and the holder must degrade to per-call
    checks.  `reset_time` is the carve slot's window reset — the
    holder's local remaining/reset view between reconciles."""

    key: str = ""  # hash key (name + "_" + unique_key)
    allowance: int = 0
    expires_at: int = 0  # unix ms
    reset_time: int = 0  # unix ms
    limit: int = 0
    refusal: str = ""  # empty = granted

    @property
    def granted(self) -> bool:
        return self.allowance > 0 and not self.refusal


@dataclass
class ReconcileItem:
    """One holder->owner reconcile entry: `request.hits` carries the
    hits burned locally since the last reconcile (0 = nothing new);
    `release` drops the holder's grant outright; `renew` piggybacks a
    grant refresh on the reconcile RPC (the low-water refresh without a
    second round trip)."""

    request: RateLimitReq = field(default_factory=RateLimitReq)
    release: bool = False
    renew: bool = False


@dataclass(frozen=True)
class PeerInfo:
    """Cluster-membership record (reference config.go peer info struct)."""

    grpc_address: str = ""
    http_address: str = ""
    data_center: str = ""
    is_owner: bool = False  # true only for the local instance


@dataclass
class CacheItem:
    """Host-side representation of one cached entry, used by the Store/Loader
    persistence SPI (reference cache.go:30-42).  On device the same record is
    a row across the SlotTable arrays; this form is the DMA'd host view.
    """

    key: str = ""
    algorithm: Algorithm = Algorithm.TOKEN_BUCKET
    expire_at: int = 0
    invalid_at: int = 0
    # Algorithm payload (TokenBucketItem store.go:37-43 / LeakyBucketItem
    # store.go:29-35), flattened:
    limit: int = 0
    duration: int = 0
    remaining: float = 0.0  # int-valued for token bucket, float for leaky
    created_at: int = 0  # token CreatedAt / leaky UpdatedAt
    status: Status = Status.UNDER_LIMIT
    burst: int = 0
    # When a GLOBAL broadcast response is cached on a non-owner the stored
    # value is a whole RateLimitResp (gubernator.go:464-479):
    cached_resp: Optional[RateLimitResp] = None

    def is_expired(self, now_ms: int) -> bool:
        if self.invalid_at and self.invalid_at <= now_ms:
            return True
        return self.expire_at <= now_ms
