"""Injectable, freezable clock.

The reference routes all algorithm time through holster's clock so tests can
freeze and advance it deterministically (functional_test.go:160, 215;
MillisecondNow lrucache.go:106-108).  On TPU there is no wall clock on device,
so `now` is always a host-computed batch input — which makes this seam even
more central: every device step takes `millisecond_now()` as an argument.
"""
from __future__ import annotations

import threading
import time
from datetime import datetime, timezone
from typing import Optional


class Clock:
    """Monotonic-ish wall clock that can be frozen and manually advanced."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._frozen_ns: Optional[int] = None

    def now_ns(self) -> int:
        with self._lock:
            if self._frozen_ns is not None:
                return self._frozen_ns
        return time.time_ns()

    def now(self) -> datetime:
        return datetime.fromtimestamp(self.now_ns() / 1e9, tz=timezone.utc)

    def millisecond_now(self) -> int:
        """Unix epoch milliseconds — the timestamp unit of the whole protocol
        (reference MillisecondNow, lrucache.go:106-108)."""
        return self.now_ns() // 1_000_000

    def freeze(self, at_ns: Optional[int] = None) -> None:
        with self._lock:
            self._frozen_ns = time.time_ns() if at_ns is None else at_ns

    def advance(self, ms: int) -> None:
        with self._lock:
            if self._frozen_ns is None:
                raise RuntimeError("clock is not frozen")
            self._frozen_ns += ms * 1_000_000

    def unfreeze(self) -> None:
        with self._lock:
            self._frozen_ns = None

    @property
    def frozen(self) -> bool:
        with self._lock:
            return self._frozen_ns is not None


# Module-level default clock, mirroring holster's global clock.
_default = Clock()


def default_clock() -> Clock:
    return _default


def now() -> datetime:
    return _default.now()


def millisecond_now() -> int:
    return _default.millisecond_now()


def freeze(at_ns: Optional[int] = None) -> None:
    _default.freeze(at_ns)


def advance(ms: int) -> None:
    _default.advance(ms)


def unfreeze() -> None:
    _default.unfreeze()


class frozen_time:
    """Context manager for tests::

        with frozen_time() as clk:
            ...
            clk.advance(1000)
    """

    def __init__(self, at_ns: Optional[int] = None) -> None:
        self._at_ns = at_ns

    def __enter__(self) -> Clock:
        _default.freeze(self._at_ns)
        return _default

    def __exit__(self, *exc) -> None:
        _default.unfreeze()
