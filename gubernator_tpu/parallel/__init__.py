from gubernator_tpu.parallel.mesh import make_mesh, shard_of_hash  # noqa: F401
from gubernator_tpu.parallel.sharded import MeshBackend  # noqa: F401
