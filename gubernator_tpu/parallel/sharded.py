"""Mesh-sharded slot table: the multi-chip engine.

The slot table's slot axis is sharded over the mesh `shard` axis
(`NamedSharding(mesh, P("shard"))`); each device owns `num_slots/n` slots and
is the single writer for the keys that hash to it — the same
single-writer-by-placement discipline as the reference worker pool
(workers.go:19-37) and peer ring (architecture.md:13-17), enforced here by
data placement instead of goroutine ownership.

One jitted `shard_map` step applies a [n_shards, batch_size] request block:
each device runs the same branchless kernel (ops/step.py) on its local shard.
The hot path needs NO collectives — routing already placed every request on
its owner — which is exactly why the table is sharded on hash bits rather
than consistent-hashed: placement is static, so the "network hop" of the
reference (peer_client.go) compiles away to local work on the right device.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

try:  # jax >= 0.4.35
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

import gubernator_tpu.ops  # noqa: F401  (enables x64)
from gubernator_tpu.core import clock as clock_mod
from gubernator_tpu.core.config import DeviceConfig
from gubernator_tpu.core.hashing import key_hash64
from gubernator_tpu.core.types import CacheItem, RateLimitReq, RateLimitResp
from gubernator_tpu.ops.batch import PackedGrid, pack_requests_grid
from gubernator_tpu.ops.state import SlotTable, init_table, table_to_host
from gubernator_tpu.ops.step import DeviceBatchJ, apply_batch_packed_impl
from gubernator_tpu.parallel.mesh import SHARD_AXIS, make_mesh, shard_of_hash
from gubernator_tpu.runtime.backend import (
    PersistenceHost,
    _row_to_item,
    probe_bucket,
    resolve_tiers,
    tier_of,
    unmarshal_responses,
)


def pack_requests_sharded(
    reqs: Sequence[RateLimitReq],
    batch_size: int,
    n_shards: int,
    clock: Optional[clock_mod.Clock] = None,
    use_cached: Optional[Sequence[bool]] = None,
) -> PackedGrid:
    """Route each request to its owning shard and pack per-shard lanes.

    Same contract as ops.batch.pack_requests (validation, duplicate-key
    rounds) with one more coordinate: the shard.  A key's occurrences are
    serialized across rounds; capacity is batch_size lanes per (round, shard).
    """
    return pack_requests_grid(
        reqs,
        batch_size,
        n_shards,
        lambda key: int(shard_of_hash(key_hash64(key), n_shards)),
        clock,
        use_cached,
    )


# -- packed single-transfer hot path ------------------------------------
# A per-field path would cost 12 sharded host->device puts and 6
# device->host reads per round; with a per-transfer host link latency
# (remote-device tunnels) transfers dominate E2E, which is why the
# single-device backend got apply_batch_packed (ops/step.py:542-568).
# Here the whole DeviceBatch travels as ONE int64[12, n, B] array and the
# response returns as ONE int64[n, 6, B] array.


def pack_grid_batch(db) -> np.ndarray:
    """Stack a [n, B] DeviceBatch into one int64[12, n, B] host array."""
    arrs = [np.asarray(a) for a in db]
    out = np.empty((len(arrs),) + arrs[0].shape, dtype=np.int64)
    for i, a in enumerate(arrs):
        out[i] = a
    return out


def unpack_grid_batch(q) -> DeviceBatchJ:
    """Device-side inverse of pack_grid_batch for one shard block [12, B]."""
    import jax.numpy as jnp

    return DeviceBatchJ(
        key_hash=q[0], hits=q[1], limit=q[2], duration=q[3],
        algo=q[4].astype(jnp.int32), burst=q[5],
        reset_remaining=q[6].astype(bool), is_greg=q[7].astype(bool),
        greg_expire=q[8], greg_duration=q[9],
        active=q[10].astype(bool), use_cached=q[11].astype(bool),
    )


def make_sharded_step_packed(mesh, ways: int):
    """Jitted multi-device step over packed transfers:
    table'[n·S], resp[n, 9, B] = step(table[n·S], batch[12, n, B], now).

    Response row order is apply_batch_packed's: status, limit, remaining,
    reset_time, persisted, found, stored, cached, stored_status (one
    shared packer, ops/step.py).
    """

    def _local(table: SlotTable, packed, now):
        b = unpack_grid_batch(packed[:, 0])
        t2, resp = apply_batch_packed_impl(table, b, now, ways=ways)
        return t2, resp[None]

    sharded = _shard_map(
        _local,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(None, SHARD_AXIS), P()),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
    )
    return jax.jit(sharded, donate_argnums=(0,))


def packed_grid_rounds_to_host(round_resps) -> List[Dict[str, np.ndarray]]:
    """Host view of packed [n, 9, B] responses — ONE transfer for all
    rounds (fetch_ravel).  Field arrays are [n, B], so (shard, lane)
    positions index directly."""
    from gubernator_tpu.runtime.backend import (
        _packed_resp_dict,
        fetch_ravel,
    )

    return [
        _packed_resp_dict(a) for a in fetch_ravel(list(round_resps))
    ]


def make_mesh_ring_step(mesh, ways: int):
    """The ring drain's bounded multi-round scan, lifted to the sharded
    grid table (docs/ring.md):

        table'[n·S], resps[k, n, 9, B], seq'[n] =
            mesh_ring_step(table[n·S], qs[k, 12, n, B], nows[k], seq[n])

    Each shard runs ops/ring.ring_step_impl — the EXACT single-table
    scan body — on its local [k, 12, B] request block, so mesh-ring ≡
    one ring per shard by construction.  The table is donated (the loop
    updates each shard's HBM block in place); the per-shard sequence
    words are NOT (the double-buffered response protocol must still
    fetch iteration N's words after iteration N+1 dispatched with them
    as input — the same keep rule as the single-device seq).  The hot
    path needs NO collectives: routing already placed every lane on its
    owner shard, so the scan compiles to independent per-device loops
    over ICI-free local work."""
    from gubernator_tpu.ops.ring import ring_step_impl

    def _local(table: SlotTable, qs, nows, seq):
        t2, resps, s2 = ring_step_impl(
            table, qs[:, :, 0, :], nows, seq[0], ways=ways
        )
        return t2, resps[:, None], s2[None]

    sharded = _shard_map(
        _local,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(None, None, SHARD_AXIS), P(),
                  P(SHARD_AXIS)),
        out_specs=(P(SHARD_AXIS), P(None, SHARD_AXIS), P(SHARD_AXIS)),
    )
    return jax.jit(sharded, donate_argnums=(0,))


def make_mesh_mega_ring_step(mesh, ways: int):
    """Megaround serving on the mesh (docs/ring.md):

        table'[n·S], resps[r, s, n, 9, B], seq'[n] =
            mesh_mega_ring_step(table[n·S], qs[r, s, 12, n, B],
                                nows[r, s], seq[n])

    The same composition rule as the base mesh ring: each shard runs
    ops/ring.mega_ring_step_impl — the EXACT single-table megaround
    scan-of-scans — on its local [r, s, 12, B] block, so
    mesh-megaround ≡ one megaround loop per shard by construction.
    Donation/keep rules are unchanged (table donated, per-shard seq
    words kept for the double-buffered response protocol), and the hot
    path still needs NO collectives."""
    from gubernator_tpu.ops.ring import mega_ring_step_impl

    def _local(table: SlotTable, qs, nows, seq):
        t2, resps, s2 = mega_ring_step_impl(
            table, qs[:, :, :, 0, :], nows, seq[0], ways=ways
        )
        return t2, resps[:, :, None], s2[None]

    sharded = _shard_map(
        _local,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(None, None, None, SHARD_AXIS), P(),
                  P(SHARD_AXIS)),
        out_specs=(P(SHARD_AXIS), P(None, None, SHARD_AXIS),
                   P(SHARD_AXIS)),
    )
    return jax.jit(sharded, donate_argnums=(0,))


def make_sharded_row_op(mesh, ways: int, impl, row_type):
    """Shared factory for row-upsert collectiveless steps: each shard
    applies `impl` to its routed [B] block of `row_type` rows.  Instances:
    - load_rows_impl/BucketRows — Loader restore / Store.get seeding
      (workers.go:340-426 over the mesh);
    - store_cached_rows_impl/CachedRows — GLOBAL broadcast receive
      (gubernator.go:464-479 over the mesh)."""

    def _local(table: SlotTable, rows, now):
        r = row_type(*[a[0] for a in rows])
        return impl(table, r, now, ways=ways)

    sharded = _shard_map(
        _local,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P()),
        out_specs=P(SHARD_AXIS),
    )
    return jax.jit(sharded, donate_argnums=(0,))


def make_sharded_probe(mesh, ways: int):
    """Sharded read-only lookup: (found[n,B], local_slot[n,B]) for a
    shard-routed hash grid — one jitted call per chunk instead of per-key
    host probes (the mesh analog of ops/step.probe_batch)."""
    from gubernator_tpu.ops.step import probe_batch_impl

    def _local(table: SlotTable, h, now):
        f, s = probe_batch_impl(table, h[0], now, ways=ways)
        return f[None], s[None]

    sharded = _shard_map(
        _local,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P()),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
    )
    return jax.jit(sharded)


def make_sharded_gather(mesh, ways: int):
    """Sharded columnar row read-back: (int64[n, 10, B] packed CacheItem
    fields in ops/step.GATHER_ROW_FIELDS order, float64[n, B]
    remaining_f) for a shard-routed hash grid — one sync where per-field
    fancy-index reads would cost a transfer each (the mesh analog of
    ops/step.gather_rows; the fast lane's Store.on_change capture)."""
    from gubernator_tpu.ops.step import gather_rows_impl

    def _local(table: SlotTable, h, now):
        packed, rf = gather_rows_impl(table, h[0], now, ways=ways)
        return packed[None], rf[None]

    sharded = _shard_map(
        _local,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P()),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
    )
    return jax.jit(sharded)


def make_sharded_demote_extract(mesh, ways: int, batch: int):
    """Sharded tier demotion (docs/tiering.md): every shard runs
    ops/state.demote_extract_impl on its slice in the same donated
    dispatch — each picks its own `batch` coldest eligible residents
    (victim choice is slice-local, exactly like bucket-local pseudo-LRU
    is bucket-local), gathers and clears them atomically.  The protect
    fingerprint grid is replicated (P()): a shadow key only matches on
    its home shard, so protection is exact.  Output carries the leading
    [n] shard axis: packed int64[n, 10, batch] (DEMOTE_ROW_FIELDS
    order), remaining_f float64[n, batch]."""
    from gubernator_tpu.ops.state import demote_extract_impl

    def _local(table: SlotTable, protect, now):
        t2, packed, rf = demote_extract_impl(
            table, protect, now, ways=ways, batch=batch
        )
        return t2, packed[None], rf[None]

    sharded = _shard_map(
        _local,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(), P()),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS)),
    )
    return jax.jit(sharded, donate_argnums=(0,))


def make_sharded_table_stats(mesh, ways: int):
    """Sharded state census (docs/observability.md): every shard runs
    ops/state.table_stats_impl on its slice in one read-only pass and
    keeps its own row — the output carries a leading [n] shard axis on
    every TableStats leaf, so the host gets per-shard occupancy/fill
    for free and sums for cluster totals.  The shadow fingerprint grid
    is replicated (P()): a derived key only matches on its home shard
    (inserts used the same bucket math), so per-class census sums
    across shards are exact, never double counted."""
    from gubernator_tpu.ops.state import TableStats, table_stats_impl

    def _local(table: SlotTable, shadow_fps, now):
        st = table_stats_impl(table, shadow_fps, now, ways=ways)
        return TableStats(*[a[None] for a in st])

    sharded = _shard_map(
        _local,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(), P()),
        out_specs=P(SHARD_AXIS),
    )
    return jax.jit(sharded)


def drain_to_grids(per_shard: List[list], B: int, make_grid, fill_lane):
    """Drain per-shard row lists into consecutive [n, B] grids (overflow
    chunks into extra grids).  `fill_lane(grid, shard, lane, row)` writes
    one row; yields each full grid."""
    while any(per_shard):
        grid = make_grid()
        for s in range(len(per_shard)):
            take, per_shard[s] = per_shard[s][:B], per_shard[s][B:]
            for lane, row in enumerate(take):
                fill_lane(grid, s, lane, row)
        yield grid


class MeshBackend(PersistenceHost):
    """Drop-in peer of runtime.backend.DeviceBackend over a device mesh."""

    def __init__(
        self,
        cfg: DeviceConfig,
        clock: Optional[clock_mod.Clock] = None,
        devices: Optional[Sequence[jax.Device]] = None,
        metrics=None,
        store=None,
        track_keys: bool = False,
    ) -> None:
        self.metrics = metrics
        self.store = store
        self._keymap: Optional[Dict[int, str]] = (
            {} if (store is not None or track_keys) else None
        )
        if cfg.num_shards < 1:
            raise ValueError("num_shards must be >= 1")
        self.cfg = cfg
        self.clock = clock or clock_mod.default_clock()
        self._lock = threading.Lock()
        self._init_write_through()
        self.mesh = make_mesh(cfg.num_shards, devices)
        self.local_slots = cfg.num_slots // cfg.num_shards
        nb_local = self.local_slots // cfg.ways
        if nb_local & (nb_local - 1):
            raise ValueError(
                f"buckets per shard ({nb_local}) must be a power of two"
            )
        self._tsharding = NamedSharding(self.mesh, P(SHARD_AXIS))
        self._bsharding = NamedSharding(self.mesh, P(SHARD_AXIS))
        self.table: SlotTable = jax.device_put(
            init_table(cfg.num_slots), self._tsharding
        )
        from gubernator_tpu.ops.step import (
            BucketRows,
            CachedRows,
            load_rows_impl,
            store_cached_rows_impl,
        )

        self._step_packed = make_sharded_step_packed(self.mesh, cfg.ways)
        # Batch-shape tiers (see DeviceConfig.batch_tiers): sparse rounds
        # ship a sliced [12, n, t] block instead of the full batch shape.
        self._tiers = resolve_tiers(cfg)
        # Batch input sharding: [12, n, B] split on the shard axis (dim 1).
        self._psharding = NamedSharding(self.mesh, P(None, SHARD_AXIS))
        # Ring request-block sharding: [k, 12, n, B] split on dim 2.
        self._qsharding = NamedSharding(
            self.mesh, P(None, None, SHARD_AXIS)
        )
        self._ring_step = make_mesh_ring_step(self.mesh, cfg.ways)
        # Megaround request-block sharding: [r, s, 12, n, B] on dim 3.
        self._mega_qsharding = NamedSharding(
            self.mesh, P(None, None, None, SHARD_AXIS)
        )
        self._mega_ring_step = make_mesh_mega_ring_step(
            self.mesh, cfg.ways
        )
        self._cached_store = make_sharded_row_op(
            self.mesh, cfg.ways, store_cached_rows_impl, CachedRows
        )
        self._load_rows_sharded = make_sharded_row_op(
            self.mesh, cfg.ways, load_rows_impl, BucketRows
        )
        self._probe_sharded = make_sharded_probe(self.mesh, cfg.ways)
        self._gather_sharded = make_sharded_gather(self.mesh, cfg.ways)
        self._table_stats = make_sharded_table_stats(self.mesh, cfg.ways)
        self.checks = 0
        self.over_limit = 0
        self.not_persisted = 0

    # -- ring drain discipline (runtime/ring.py; docs/ring.md) -----------
    def ring_supported(self) -> bool:
        """The mesh serves ring mode natively: make_mesh_ring_step is the
        shard_map lift of the single-table scan, so GUBER_SERVE_MODE=ring
        on a mesh service arms a real device loop instead of falling back
        to the pipelined discipline (the pre-mesh-ring fallback rule is
        retired; docs/ring.md)."""
        return True

    def ring_q_shape(self, tb: int) -> tuple:
        """Per-round request-slot shape at batch tier `tb` — the grid
        form [12, n_shards, tb] (the ring runner builds blocks of
        (slot_tier,) + this shape)."""
        return (12, self.cfg.num_shards, tb)

    def ring_pack_round(self, db, tb: int) -> np.ndarray:
        """One [n, B] grid DeviceBatch -> its ring slot [12, n, tb]."""
        return pack_grid_batch(db)[:, :, :tb]

    def ring_seq_init(self):
        """Fresh per-shard sequence words (int64[n], sharded)."""
        return jax.device_put(
            np.zeros(self.cfg.num_shards, dtype=np.int64),
            self._bsharding,
        )

    def ring_step_dispatch(self, qs: np.ndarray, nows: np.ndarray, seq):
        """Dispatch one bounded mesh ring iteration — `qs`
        int64[k, 12, n, B] stacked grid rounds — under the lock (the
        same single-writer section as every other table mutation).
        Returns the un-synced device (responses[k, n, 9, B], per-shard
        seq words); the ring runner fetches them off the request path."""
        import time as time_mod

        t_start = time_mod.monotonic()
        with self._lock:
            batch = jax.device_put(qs, self._qsharding)
            self.table, resps, seq = self._ring_step(
                self.table, batch, np.asarray(nows, dtype=np.int64), seq
            )
        if self.metrics is not None:
            self.metrics.device_step_duration.observe(
                time_mod.monotonic() - t_start
            )
        return resps, seq

    def ring_mega_dispatch(self, qs: np.ndarray, nows: np.ndarray, seq):
        """Dispatch one MEGAROUND mesh iteration — `qs`
        int64[r, s, 12, n, B] stacked ring rounds applied in order by
        the shard_map megaround scan (make_mesh_mega_ring_step) — under
        the lock.  Returns the un-synced device
        (responses[r, s, n, 9, B], per-shard seq words); the ring
        runner flattens the (r, s) round axes back on the host."""
        import time as time_mod

        t_start = time_mod.monotonic()
        with self._lock:
            batch = jax.device_put(qs, self._mega_qsharding)
            self.table, resps, seq = self._mega_ring_step(
                self.table, batch, np.asarray(nows, dtype=np.int64), seq
            )
        if self.metrics is not None:
            self.metrics.device_step_duration.observe(
                time_mod.monotonic() - t_start
            )
        return resps, seq

    def persistent_serve_supported(self):
        """The persistent Pallas decision kernel owns ONE table block;
        the sharded grid table has no shard_map lift for it yet —
        honest capability reporting per docs/ring.md: megaround is the
        mesh's dispatch-amortization tier."""
        return False, (
            "persistent serve kernel is single-table only; mesh "
            "backends serve megaround (the shard_map mega ring step)"
        )

    def _add_tally(self, tally) -> None:
        with self._lock:
            self.checks += tally.checks
            self.over_limit += tally.over_limit
            self.not_persisted += tally.not_persisted
        m = self.metrics
        if m is not None:
            m.check_counter.inc(tally.checks)
            if tally.over_limit:
                m.over_limit_counter.inc(tally.over_limit)
            if tally.not_persisted:
                m.unexpired_evictions.inc(tally.not_persisted)
            m.cache_access_count.labels(type="hit").inc(tally.cache_hits)
            m.cache_access_count.labels(type="miss").inc(
                tally.checks - tally.cache_hits
            )

    # -- hot path --------------------------------------------------------
    def check(
        self,
        reqs: Sequence[RateLimitReq],
        use_cached: Optional[Sequence[bool]] = None,
    ) -> List[RateLimitResp]:
        packed = pack_requests_sharded(
            reqs, self.cfg.batch_size, self.cfg.num_shards, self.clock,
            use_cached,
        )
        now_ms = self.clock.millisecond_now()
        now = np.int64(now_ms)
        if self._keymap is not None:
            with self._keymap_lock:
                for i, r in enumerate(reqs):
                    if i not in packed.errors:
                        k = r.hash_key()
                        self._keymap[key_hash64(k)] = k
            self._maybe_prune_keymap()

        import time as time_mod

        round_resps = []
        captured = None
        t_start = time_mod.monotonic()
        with self._lock:
            if self.store is not None:
                self._seed_from_store(reqs, packed, now_ms)
            for db in packed.rounds:
                # ONE sharded put for the whole batch, ONE packed readback.
                t = tier_of(db.active, self._tiers)
                batch = jax.device_put(
                    pack_grid_batch(db)[:, :, :t], self._psharding
                )
                self.table, resp = self._step_packed(self.table, batch, now)
                round_resps.append(resp)
            if self.store is not None:
                # Read-back inside the lock: a concurrent batch must not
                # mutate a key between this batch's step and on_change.
                captured = self._capture_write_through(
                    reqs, packed, use_cached
                )
                wt_seq = self._wt_ticket()
        try:
            step_s = time_mod.monotonic() - t_start
            if self.metrics is not None:
                self.metrics.device_step_duration.observe(step_s)
            out, tally = unmarshal_responses(
                len(reqs), packed.errors, packed.positions,
                packed_grid_rounds_to_host(round_resps),
            )
            self._add_tally(tally)
            fr = getattr(self.metrics, "flightrec", None)
            if fr is not None:
                fr.record_batch(
                    len(reqs), step_s * 1e3,
                    over_limit=tally.over_limit,
                    errors=len(packed.errors),
                )
        finally:
            # Redeem the ticket even if unmarshal fails (see
            # DeviceBackend.check) — unredeemed tickets wedge delivery.
            if captured is not None:
                self._deliver_write_through(captured, wt_seq)
        return out

    def step_rounds(
        self, rounds: Sequence, add_tally: bool = True
    ) -> List[Dict[str, np.ndarray]]:
        """Columnar hot path over the mesh: apply pre-packed [n, B] grid
        DeviceBatch rounds (the compiled fast lane, runtime/fastpath.py).
        No persistence hooks — the fast lane requires no attached Store.
        Returns [n, B]-shaped host response dicts per round."""
        return self.step_rounds_begin(rounds, add_tally)()

    def step_rounds_begin(self, rounds: Sequence, add_tally: bool = True):
        """Pipelined step_rounds (see DeviceBackend.step_rounds_begin):
        dispatch under the lock, return the host-fetch closure — the
        sharded responses are pinned to this table version, so the fetch
        may run while the next merge dispatches."""
        from gubernator_tpu.runtime.backend import tally_from_rounds

        with self._lock:
            round_resps = self._dispatch_rounds_locked(rounds)

        def fetch() -> List[Dict[str, np.ndarray]]:
            host = packed_grid_rounds_to_host(round_resps)
            if add_tally:
                self._add_tally(tally_from_rounds(rounds, host))
            return host

        return fetch

    def _dispatch_rounds_locked(self, rounds) -> list:
        """Dispatch grid rounds; caller holds `_lock` (see
        DeviceBackend._dispatch_rounds_locked)."""
        import time as time_mod

        now = np.int64(self.clock.millisecond_now())
        t_start = time_mod.monotonic()
        round_resps = []
        for db in rounds:
            t = tier_of(db.active, self._tiers)
            batch = jax.device_put(
                pack_grid_batch(db)[:, :, :t], self._psharding
            )
            self.table, resp = self._step_packed(self.table, batch, now)
            round_resps.append(resp)
        if self.metrics is not None:
            self.metrics.device_step_duration.observe(
                time_mod.monotonic() - t_start
            )
        return round_resps

    def warmup(self) -> None:
        """Compile the sharded executables with a synthetic batch that
        BYPASSES the Store/keymap hooks and the tallies — a check() here
        would leak '__warmup__' keys into an attached store (the same
        bypass DeviceBackend.warmup applies)."""
        reqs = [
            RateLimitReq(name="__warmup__", unique_key=f"w{s}", hits=0,
                         limit=1, duration=1)
            for s in range(self.cfg.num_shards)
        ]
        packed = pack_requests_sharded(
            reqs, self.cfg.batch_size, self.cfg.num_shards, self.clock
        )
        now = np.int64(self.clock.millisecond_now())
        with self._lock:
            # Compile the sharded step at EVERY batch tier.
            for t in self._tiers:
                batch = jax.device_put(
                    np.zeros(
                        (12, self.cfg.num_shards, t), dtype=np.int64
                    ),
                    self._psharding,
                )
                self.table, resp = self._step_packed(self.table, batch, now)
            for db in packed.rounds:
                batch = jax.device_put(pack_grid_batch(db), self._psharding)
                self.table, resp = self._step_packed(self.table, batch, now)
            # Probe + broadcast-receive executables (store seeding,
            # UpdatePeerGlobals paths) — zero grids, no side effects.
            from gubernator_tpu.ops.step import CachedRows

            zeros = jax.device_put(
                np.zeros(
                    (self.cfg.num_shards, self.cfg.batch_size),
                    dtype=np.int64,
                ),
                self._bsharding,
            )
            self._probe_sharded(self.table, zeros, now)
            self._gather_sharded(self.table, zeros, now)
            # Gubstat census executable at the sampler's minimum shadow
            # pad tier (runtime/gubstat.py pads to powers of two from 8).
            self._table_stats(
                self.table, np.zeros((4, 8), dtype=np.int64), now
            )
            self.table = self._cached_store(
                self.table,
                CachedRows(*[
                    jax.device_put(a, self._bsharding)
                    for a in self._zero_cached_grid()
                ]),
                now,
            )
        jax.block_until_ready(resp)

    # -- GLOBAL broadcast receive ----------------------------------------
    def _zero_cached_grid(self):
        from gubernator_tpu.ops.step import CachedRows

        n, B = self.cfg.num_shards, self.cfg.batch_size
        return CachedRows(
            key_hash=np.zeros((n, B), dtype=np.int64),
            algo=np.zeros((n, B), dtype=np.int32),
            limit=np.zeros((n, B), dtype=np.int64),
            remaining=np.zeros((n, B), dtype=np.int64),
            status=np.zeros((n, B), dtype=np.int32),
            reset_time=np.zeros((n, B), dtype=np.int64),
        )

    def apply_cached_rows(self, rows: Sequence[tuple]) -> None:
        """Upsert owner-broadcast statuses, routed to their shards: rows of
        (hash_key_str, algorithm, limit, remaining, status, reset_time)."""
        n, B = self.cfg.num_shards, self.cfg.batch_size
        now = np.int64(self.clock.millisecond_now())
        if self._keymap is not None:
            with self._keymap_lock:
                for key, *_ in rows:
                    self._keymap[key_hash64(key)] = key
        per_shard: List[list] = [[] for _ in range(n)]
        for row in rows:
            h = key_hash64(row[0])
            per_shard[int(shard_of_hash(h, n))].append(row)

        def fill(grid, s, lane, row):
            key, algo, limit, rem, status, reset = row
            grid.key_hash[s, lane] = np.int64(
                np.uint64(key_hash64(key)).view(np.int64)
            )
            grid.algo[s, lane] = algo
            grid.limit[s, lane] = limit
            grid.remaining[s, lane] = rem
            grid.status[s, lane] = status
            grid.reset_time[s, lane] = reset

        for grid in drain_to_grids(per_shard, B, self._zero_cached_grid,
                                   fill):
            with self._lock:
                self.table = self._cached_store(
                    self.table,
                    type(grid)(*[
                        jax.device_put(a, self._bsharding) for a in grid
                    ]),
                    now,
                )

    # -- point reads / persistence ---------------------------------------
    def bucket_offset(self, key: str, shard: int) -> int:
        """Global row index of `key`'s bucket within `shard`'s table block."""
        nb_local = self.local_slots // self.cfg.ways
        bucket = key_hash64(key) & (nb_local - 1)
        return shard * self.local_slots + bucket * self.cfg.ways

    def get_cache_item(self, key: str) -> Optional[CacheItem]:
        shard = int(shard_of_hash(key_hash64(key), self.cfg.num_shards))
        lo = self.bucket_offset(key, shard)
        now = self.clock.millisecond_now()
        with self._lock:
            return probe_bucket(self.table, lo, self.cfg.ways, key, now)

    def _probe_nolock(
        self, key: str, now: int, include_cached: bool
    ) -> Optional[CacheItem]:
        shard = int(shard_of_hash(key_hash64(key), self.cfg.num_shards))
        lo = self.bucket_offset(key, shard)
        return probe_bucket(
            self.table, lo, self.cfg.ways, key, now,
            include_cached=include_cached,
        )

    # -- persistence device hooks (PersistenceHost) ----------------------
    def _probe_grid(
        self, keys: Sequence[str], hashes, now: int,
        table: Optional[SlotTable] = None, route=None,
    ):
        """Shard-routed batched probes: (found, global_slot) per key, in
        key order, one jitted probe per chunk (lock held).

        `table`/`route` default to the auth table with owner routing; the
        GlobalEngine passes its replicated cache table with arrival-device
        routing."""
        if table is None:
            table = self.table
        # Table geometry may differ from the auth table's (the GlobalEngine
        # cache can be smaller via global_cache_slots).
        local_slots = table.key.shape[0] // self.cfg.num_shards
        n, B = self.cfg.num_shards, self.cfg.batch_size
        if route is None:
            route = lambda h: int(shard_of_hash(h, n))  # noqa: E731
        per_shard: List[list] = [[] for _ in range(n)]
        for j, h in enumerate(hashes):
            per_shard[route(h)].append((j, h))

        found = np.zeros(len(keys), dtype=bool)
        gslot = np.zeros(len(keys), dtype=np.int64)

        def make_grid():
            return [
                np.zeros((n, B), dtype=np.int64),  # hashes
                np.full((n, B), -1, dtype=np.int64),  # original index
            ]

        def fill(grid, s, lane, row):
            j, h = row
            grid[0][s, lane] = np.int64(np.uint64(h).view(np.int64))
            grid[1][s, lane] = j

        for hv, jv in drain_to_grids(per_shard, B, make_grid, fill):
            f, slot = self._probe_sharded(
                table,
                jax.device_put(hv, self._bsharding),
                np.int64(now),
            )
            f, slot = np.asarray(f), np.asarray(slot)
            for s in range(n):
                sel = jv[s] >= 0
                js = jv[s][sel]
                found[js] = f[s][sel]
                gslot[js] = s * local_slots + slot[s][sel]
        return found, gslot

    def _found_mask(self, keys, hashes, now: int) -> np.ndarray:
        found, _ = self._probe_grid(keys, hashes, now)
        return found

    def _gather_rows_dispatch(self, h64: np.ndarray, now: int):
        """Dispatch shard-routed columnar row gathers for int64
        fingerprints (lock held).  Returns an opaque token for
        `_gather_rows_finish`: the dispatched reads are pinned to this
        table version (jax arrays are immutable), so the caller may
        release the lock before fetching."""
        n, B = self.cfg.num_shards, self.cfg.batch_size
        sh = shard_of_hash(h64, n)
        per_shard: List[list] = [[] for _ in range(n)]
        for j, h in enumerate(h64):
            per_shard[int(sh[j])].append((j, int(h)))

        def make_grid():
            return [
                np.zeros((n, B), dtype=np.int64),
                np.full((n, B), -1, dtype=np.int64),
            ]

        def fill(grid, s, lane, row):
            j, h = row
            grid[0][s, lane] = h
            grid[1][s, lane] = j

        token = []
        for hv, jv in drain_to_grids(per_shard, B, make_grid, fill):
            token.append((
                self._gather_sharded(
                    self.table,
                    jax.device_put(hv, self._bsharding),
                    np.int64(now),
                ),
                jv,
            ))
        return token

    def _gather_rows_int_arrays(self, token) -> list:
        """The token's int64 device buffers — exposed so a caller can fold
        them into ONE fetch_ravel round-trip with its response buffers."""
        return [d for (d, _rf), _jv in token]

    def _gather_rows_rf_arrays(self, token) -> list:
        return [rf for (_d, rf), _jv in token]

    def _gather_rows_build(self, token, m: int, int_hosts,
                           rf_hosts=None):
        """Assemble (int64[10, m] GATHER_ROW_FIELDS columns, float64[m]
        remaining_f) from pre-fetched host chunks via each chunk's
        shard/lane placement grid.  rf_hosts=None -> zeros (no leaky row
        captured)."""
        from gubernator_tpu.ops.step import GATHER_ROW_FIELDS

        out = np.zeros((len(GATHER_ROW_FIELDS), m), dtype=np.int64)
        rf = np.zeros(m, dtype=np.float64)
        for i, (_devs, jv) in enumerate(token):
            a = int_hosts[i]     # [n_shards, 10, B]
            f = rf_hosts[i] if rf_hosts is not None else None
            for s in range(a.shape[0]):
                sel = jv[s] >= 0
                if sel.any():
                    out[:, jv[s][sel]] = a[s][:, sel]
                    if f is not None:
                        rf[jv[s][sel]] = f[s][sel]
        return out, rf

    def _gather_rows_finish(self, token, m: int):
        """Fetch + assemble in two packed round-trips (ints, rf)."""
        from gubernator_tpu.runtime.backend import fetch_ravel

        return self._gather_rows_build(
            token, m,
            fetch_ravel(self._gather_rows_int_arrays(token)),
            fetch_ravel(self._gather_rows_rf_arrays(token)),
        )

    def _bulk_upsert(
        self, rows: List[dict], hashes: List[int], now: int
    ) -> None:
        """Route row dicts to their shards and upsert via the sharded
        load_rows step (lock held)."""
        self.table = self._bulk_upsert_into(self.table, rows, hashes, now)

    def _bulk_upsert_into(
        self, table: SlotTable, rows: List[dict], hashes: List[int],
        now: int, route=None,
    ) -> SlotTable:
        """Upsert row dicts into `table` with `route` (defaults to owner
        routing); returns the new table.  The GlobalEngine seeds its cache
        table through this with arrival-device routing (lock held)."""
        from gubernator_tpu.ops.step import BucketRows

        n, B = self.cfg.num_shards, self.cfg.batch_size
        if route is None:
            route = lambda h: int(shard_of_hash(h, n))  # noqa: E731
        per_shard: List[list] = [[] for _ in range(n)]
        for row, h in zip(rows, hashes):
            per_shard[route(h)].append((h, row))
        fields = (
            "algo", "limit", "duration", "remaining", "remaining_f",
            "t0", "status", "burst", "expire_at",
        )

        def make_grid():
            return BucketRows(
                key_hash=np.zeros((n, B), dtype=np.int64),
                **{
                    f: np.zeros(
                        (n, B),
                        dtype=np.float64 if f == "remaining_f" else (
                            np.int32 if f in ("algo", "status") else np.int64
                        ),
                    )
                    for f in fields
                },
            )

        def fill(grid, s, lane, row):
            h, rd = row
            grid.key_hash[s, lane] = np.int64(np.uint64(h).view(np.int64))
            for f in fields:
                getattr(grid, f)[s, lane] = rd[f]

        for grid in drain_to_grids(per_shard, B, make_grid, fill):
            table = self._load_rows_sharded(
                table,
                type(grid)(*[
                    jax.device_put(a, self._bsharding) for a in grid
                ]),
                np.int64(now),
            )
        return table

    def read_items_bulk(
        self, keys: Sequence[str], include_cached: bool = False
    ) -> Dict[str, CacheItem]:
        """Batched point-reads (write-through readback): one sharded probe
        per chunk + one fancy-index gather per table field."""
        with self._lock:
            return self._read_items_locked(keys, include_cached)

    def _read_items_locked(
        self, keys: Sequence[str], include_cached: bool = False
    ) -> Dict[str, CacheItem]:
        """read_items_bulk body; caller holds `_lock` (write-through capture
        reads back rows within the same critical section as the step)."""
        from gubernator_tpu.ops.state import KIND_CACHED_RESP

        now = self.clock.millisecond_now()
        hashes = [key_hash64(k) for k in keys]
        out: Dict[str, CacheItem] = {}
        found, gslot = self._probe_grid(keys, hashes, now)
        if not found.any():
            return out
        sel = np.flatnonzero(found)
        rows = {
            f: np.asarray(getattr(self.table, f)[gslot[sel]])
            for f in self.table._fields
        }
        for r_i, j in enumerate(sel):
            if rows["kind"][r_i] == KIND_CACHED_RESP and not include_cached:
                continue
            out[keys[j]] = _row_to_item(rows, r_i, keys[j])
        return out

    def snapshot(self) -> Dict[str, np.ndarray]:
        with self._lock:
            return table_to_host(self.table)

    def _install_table(self, arrays: Dict[str, np.ndarray]) -> None:
        """Replace the sharded table from host arrays (checkpoint restore):
        orbax round-trips the host copy; placement re-shards over the mesh.
        """
        from gubernator_tpu.ops.state import table_from_host

        if arrays["key"].shape[0] != self.cfg.num_slots:
            raise ValueError(
                f"checkpoint has {arrays['key'].shape[0]} slots, backend "
                f"expects {self.cfg.num_slots}"
            )
        with self._lock:
            self.table = jax.device_put(
                table_from_host(arrays), self._tsharding
            )

    def occupancy(self) -> int:
        with self._lock:
            return int(np.asarray(self.table.occupancy()))

    def shard_occupancy(self) -> List[int]:
        """Live rows PER SHARD (one device reduce + one [n] fetch) — the
        skew view the aggregate occupancy() hides: hash routing spreads
        keys uniformly in expectation, but a production key set can pile
        onto one shard, and only the per-shard counts show it
        (/debug/vars `shard_occupancy`, gubernator_shard_occupancy)."""
        import jax.numpy as jnp

        with self._lock:
            counts = jnp.sum(
                self.table.key.reshape(
                    self.cfg.num_shards, self.local_slots
                ) != 0,
                axis=1,
            )
        return [int(c) for c in np.asarray(counts)]

    def table_stats_dispatch(self, shadow_fps: np.ndarray):
        """Dispatch the sharded gubstat census under the lock and return
        a zero-arg fetch closure (DeviceBackend.table_stats_dispatch's
        contract: every fetched TableStats leaf carries a leading shard
        axis — here one row per mesh shard, so the sampler gets the
        per-shard occupancy skew for free and sums for totals)."""
        from gubernator_tpu.ops.state import TableStats

        now = np.int64(self.clock.millisecond_now())
        fps = np.asarray(shadow_fps, dtype=np.int64)
        with self._lock:
            st = self._table_stats(self.table, fps, now)

        def fetch() -> "TableStats":
            return TableStats(*[np.asarray(a) for a in st])

        return fetch

    # -- tiered table (runtime/coldtier.py; docs/tiering.md) -------------
    def occupancy_dispatch(self):
        """Dispatch the cluster resident count under the lock; the
        returned zero-arg fetch closure pulls the scalar off the runner
        (DeviceBackend.occupancy_dispatch's contract)."""
        import jax.numpy as jnp

        with self._lock:
            occ = jnp.sum(self.table.key != 0)

        def fetch() -> int:
            return int(np.asarray(occ))

        return fetch

    def demote_extract_dispatch(self, protect_fps: np.ndarray,
                                batch: int):
        """Sharded demote: each shard picks its own `batch` coldest
        unprotected rows (victim choice is slice-local, like the
        bucket-local pseudo-LRU), so one dispatch yields n_shards*batch
        candidates.  Fetch flattens the per-shard planes back to the
        DeviceBackend contract: (int64[10, n*batch], float64[n*batch]).
        """
        if not hasattr(self, "_demote_cache"):
            self._demote_cache = {}
        fn = self._demote_cache.get(batch)
        if fn is None:
            fn = make_sharded_demote_extract(
                self.mesh, self.cfg.ways, batch
            )
            self._demote_cache[batch] = fn

        now = np.int64(self.clock.millisecond_now())
        fps = np.asarray(protect_fps, dtype=np.int64)
        with self._lock:
            self.table, packed, rf = fn(self.table, fps, now)

        def fetch():
            p = np.asarray(packed)  # [n, 10, batch]
            r = np.asarray(rf)  # [n, batch]
            return (
                np.concatenate([p[s] for s in range(p.shape[0])],
                               axis=1),
                r.reshape(-1),
            )

        return fetch

    def migrate_inject_dispatch(self, cols: Dict[str, np.ndarray]):
        """Promote-path inject for the mesh: the generic
        PersistenceHost.migrate_inject_rows path already serializes on
        self._lock, so the whole probe+upsert+merge runs inside the
        fetch closure on the tier manager's executor — off the ring
        runner, same lock discipline, same (injected, merged) result."""
        def fetch():
            return self.migrate_inject_rows(cols)

        return fetch
