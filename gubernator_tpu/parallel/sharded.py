"""Mesh-sharded slot table: the multi-chip engine.

The slot table's slot axis is sharded over the mesh `shard` axis
(`NamedSharding(mesh, P("shard"))`); each device owns `num_slots/n` slots and
is the single writer for the keys that hash to it — the same
single-writer-by-placement discipline as the reference worker pool
(workers.go:19-37) and peer ring (architecture.md:13-17), enforced here by
data placement instead of goroutine ownership.

One jitted `shard_map` step applies a [n_shards, batch_size] request block:
each device runs the same branchless kernel (ops/step.py) on its local shard.
The hot path needs NO collectives — routing already placed every request on
its owner — which is exactly why the table is sharded on hash bits rather
than consistent-hashed: placement is static, so the "network hop" of the
reference (peer_client.go) compiles away to local work on the right device.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

try:  # jax >= 0.4.35
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

import gubernator_tpu.ops  # noqa: F401  (enables x64)
from gubernator_tpu.core import clock as clock_mod
from gubernator_tpu.core.config import DeviceConfig
from gubernator_tpu.core.hashing import key_hash64
from gubernator_tpu.core.types import CacheItem, RateLimitReq, RateLimitResp
from gubernator_tpu.ops.batch import PackedGrid, pack_requests_grid
from gubernator_tpu.ops.state import SlotTable, init_table, table_to_host
from gubernator_tpu.ops.step import DeviceBatchJ, apply_batch_packed_impl
from gubernator_tpu.parallel.mesh import SHARD_AXIS, make_mesh, shard_of_hash
from gubernator_tpu.runtime.backend import (
    probe_bucket,
    unmarshal_responses,
)


def pack_requests_sharded(
    reqs: Sequence[RateLimitReq],
    batch_size: int,
    n_shards: int,
    clock: Optional[clock_mod.Clock] = None,
    use_cached: Optional[Sequence[bool]] = None,
) -> PackedGrid:
    """Route each request to its owning shard and pack per-shard lanes.

    Same contract as ops.batch.pack_requests (validation, duplicate-key
    rounds) with one more coordinate: the shard.  A key's occurrences are
    serialized across rounds; capacity is batch_size lanes per (round, shard).
    """
    return pack_requests_grid(
        reqs,
        batch_size,
        n_shards,
        lambda key: int(shard_of_hash(key_hash64(key), n_shards)),
        clock,
        use_cached,
    )


# -- packed single-transfer hot path ------------------------------------
# A per-field path would cost 12 sharded host->device puts and 6
# device->host reads per round; with a per-transfer host link latency
# (remote-device tunnels) transfers dominate E2E, which is why the
# single-device backend got apply_batch_packed (ops/step.py:542-568).
# Here the whole DeviceBatch travels as ONE int64[12, n, B] array and the
# response returns as ONE int64[n, 6, B] array.


def pack_grid_batch(db) -> np.ndarray:
    """Stack a [n, B] DeviceBatch into one int64[12, n, B] host array."""
    arrs = [np.asarray(a) for a in db]
    out = np.empty((len(arrs),) + arrs[0].shape, dtype=np.int64)
    for i, a in enumerate(arrs):
        out[i] = a
    return out


def unpack_grid_batch(q) -> DeviceBatchJ:
    """Device-side inverse of pack_grid_batch for one shard block [12, B]."""
    import jax.numpy as jnp

    return DeviceBatchJ(
        key_hash=q[0], hits=q[1], limit=q[2], duration=q[3],
        algo=q[4].astype(jnp.int32), burst=q[5],
        reset_remaining=q[6].astype(bool), is_greg=q[7].astype(bool),
        greg_expire=q[8], greg_duration=q[9],
        active=q[10].astype(bool), use_cached=q[11].astype(bool),
    )


def make_sharded_step_packed(mesh, ways: int):
    """Jitted multi-device step over packed transfers:
    table'[n·S], resp[n, 6, B] = step(table[n·S], batch[12, n, B], now).

    Response row order is apply_batch_packed's: status, limit, remaining,
    reset_time, persisted, found (one shared packer, ops/step.py:542-568).
    """

    def _local(table: SlotTable, packed, now):
        b = unpack_grid_batch(packed[:, 0])
        t2, resp = apply_batch_packed_impl(table, b, now, ways=ways)
        return t2, resp[None]

    sharded = _shard_map(
        _local,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(None, SHARD_AXIS), P()),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
    )
    return jax.jit(sharded, donate_argnums=(0,))


def packed_grid_rounds_to_host(round_resps) -> List[Dict[str, np.ndarray]]:
    """Host view of packed [n, 6, B] responses — one transfer per round.
    Field arrays are [n, B], so (shard, lane) positions index directly."""
    out = []
    for p in round_resps:
        a = np.asarray(p)
        out.append({
            "status": a[:, 0],
            "limit": a[:, 1],
            "remaining": a[:, 2],
            "reset_time": a[:, 3],
            "persisted": a[:, 4],
            "found": a[:, 5],
        })
    return out


def make_sharded_cached_store(mesh, ways: int):
    """Sharded GLOBAL broadcast receive: each shard upserts its routed
    KIND_CACHED_RESP rows (gubernator.go:464-479 over the mesh)."""
    from gubernator_tpu.ops.step import CachedRows, store_cached_rows_impl

    def _local(table: SlotTable, rows: CachedRows, now):
        r = CachedRows(*[a[0] for a in rows])
        return store_cached_rows_impl(table, r, now, ways=ways)

    sharded = _shard_map(
        _local,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P()),
        out_specs=P(SHARD_AXIS),
    )
    return jax.jit(sharded, donate_argnums=(0,))


class MeshBackend:
    """Drop-in peer of runtime.backend.DeviceBackend over a device mesh."""

    def __init__(
        self,
        cfg: DeviceConfig,
        clock: Optional[clock_mod.Clock] = None,
        devices: Optional[Sequence[jax.Device]] = None,
        metrics=None,
        store=None,
        track_keys: bool = False,
    ) -> None:
        if store is not None or track_keys:
            raise NotImplementedError(
                "the Store/Loader SPI is single-device for now; use "
                "TableCheckpointer for mesh persistence"
            )
        self.metrics = metrics
        self.store = None
        if cfg.num_shards < 1:
            raise ValueError("num_shards must be >= 1")
        self.cfg = cfg
        self.clock = clock or clock_mod.default_clock()
        self._lock = threading.Lock()
        self.mesh = make_mesh(cfg.num_shards, devices)
        self.local_slots = cfg.num_slots // cfg.num_shards
        nb_local = self.local_slots // cfg.ways
        if nb_local & (nb_local - 1):
            raise ValueError(
                f"buckets per shard ({nb_local}) must be a power of two"
            )
        self._tsharding = NamedSharding(self.mesh, P(SHARD_AXIS))
        self._bsharding = NamedSharding(self.mesh, P(SHARD_AXIS))
        self.table: SlotTable = jax.device_put(
            init_table(cfg.num_slots), self._tsharding
        )
        self._step_packed = make_sharded_step_packed(self.mesh, cfg.ways)
        # Batch input sharding: [12, n, B] split on the shard axis (dim 1).
        self._psharding = NamedSharding(self.mesh, P(None, SHARD_AXIS))
        self._cached_store = make_sharded_cached_store(self.mesh, cfg.ways)
        self.checks = 0
        self.over_limit = 0
        self.not_persisted = 0

    def _add_tally(self, tally) -> None:
        with self._lock:
            self.checks += tally.checks
            self.over_limit += tally.over_limit
            self.not_persisted += tally.not_persisted
        m = self.metrics
        if m is not None:
            m.check_counter.inc(tally.checks)
            if tally.over_limit:
                m.over_limit_counter.inc(tally.over_limit)
            if tally.not_persisted:
                m.unexpired_evictions.inc(tally.not_persisted)
            m.cache_access_count.labels(type="hit").inc(tally.cache_hits)
            m.cache_access_count.labels(type="miss").inc(
                tally.checks - tally.cache_hits
            )

    # -- hot path --------------------------------------------------------
    def check(
        self,
        reqs: Sequence[RateLimitReq],
        use_cached: Optional[Sequence[bool]] = None,
    ) -> List[RateLimitResp]:
        packed = pack_requests_sharded(
            reqs, self.cfg.batch_size, self.cfg.num_shards, self.clock,
            use_cached,
        )
        now = np.int64(self.clock.millisecond_now())

        round_resps = []
        with self._lock:
            for db in packed.rounds:
                # ONE sharded put for the whole batch, ONE packed readback.
                batch = jax.device_put(pack_grid_batch(db), self._psharding)
                self.table, resp = self._step_packed(self.table, batch, now)
                round_resps.append(resp)
        out, tally = unmarshal_responses(
            len(reqs), packed.errors, packed.positions,
            packed_grid_rounds_to_host(round_resps),
        )
        self._add_tally(tally)
        return out

    def warmup(self) -> None:
        """Compile the sharded step executables before serving."""
        reqs = [
            RateLimitReq(name="__warmup__", unique_key=f"w{s}", hits=0,
                         limit=1, duration=1)
            for s in range(self.cfg.num_shards)
        ]
        r = self.check(reqs)
        del r
        self.apply_cached_rows([])

    # -- GLOBAL broadcast receive ----------------------------------------
    def apply_cached_rows(self, rows: Sequence[tuple]) -> None:
        """Upsert owner-broadcast statuses, routed to their shards: rows of
        (hash_key_str, algorithm, limit, remaining, status, reset_time)."""
        from gubernator_tpu.ops.step import CachedRows

        n, B = self.cfg.num_shards, self.cfg.batch_size
        now = np.int64(self.clock.millisecond_now())
        # Route rows to shards; chunk any shard overflow into extra passes.
        per_shard: List[List[tuple]] = [[] for _ in range(n)]
        for row in rows:
            h = key_hash64(row[0])
            per_shard[int(shard_of_hash(h, n))].append(row)
        while True:
            grid = CachedRows(
                key_hash=np.zeros((n, B), dtype=np.int64),
                algo=np.zeros((n, B), dtype=np.int32),
                limit=np.zeros((n, B), dtype=np.int64),
                remaining=np.zeros((n, B), dtype=np.int64),
                status=np.zeros((n, B), dtype=np.int32),
                reset_time=np.zeros((n, B), dtype=np.int64),
            )
            any_filled = False
            for s in range(n):
                take, per_shard[s] = per_shard[s][:B], per_shard[s][B:]
                for lane, (key, algo, limit, rem, status, reset) in (
                    enumerate(take)
                ):
                    grid.key_hash[s, lane] = np.int64(
                        np.uint64(key_hash64(key)).view(np.int64)
                    )
                    grid.algo[s, lane] = algo
                    grid.limit[s, lane] = limit
                    grid.remaining[s, lane] = rem
                    grid.status[s, lane] = status
                    grid.reset_time[s, lane] = reset
                    any_filled = True
            with self._lock:
                self.table = self._cached_store(
                    self.table,
                    CachedRows(
                        *[
                            jax.device_put(a, self._bsharding)
                            for a in grid
                        ]
                    ),
                    now,
                )
            if not any_filled or not any(per_shard):
                break

    # -- point reads / persistence ---------------------------------------
    def bucket_offset(self, key: str, shard: int) -> int:
        """Global row index of `key`'s bucket within `shard`'s table block."""
        nb_local = self.local_slots // self.cfg.ways
        bucket = key_hash64(key) & (nb_local - 1)
        return shard * self.local_slots + bucket * self.cfg.ways

    def get_cache_item(self, key: str) -> Optional[CacheItem]:
        shard = int(shard_of_hash(key_hash64(key), self.cfg.num_shards))
        lo = self.bucket_offset(key, shard)
        now = self.clock.millisecond_now()
        with self._lock:
            return probe_bucket(self.table, lo, self.cfg.ways, key, now)

    def snapshot(self) -> Dict[str, np.ndarray]:
        with self._lock:
            return table_to_host(self.table)

    def occupancy(self) -> int:
        with self._lock:
            return int(np.asarray(self.table.occupancy()))
