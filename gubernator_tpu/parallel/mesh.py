"""Device-mesh construction and key->shard routing.

The reference shards its key space twice: across worker goroutines inside a
node (workers.go:127-186, 63-bit xxhash ranges) and across peers with a
consistent hash ring (replicated_hash.go:29-118).  On TPU the intra-pod
analog of both is ONE mesh axis: the slot table is sharded along its slot
dimension over the `shard` axis, and a request's 64-bit key fingerprint
selects the owning shard.

Routing uses hash bits 32.. (disjoint from the bucket-index bits, which come
from the LOW bits — ops/step.py bucket = h & (nb_local-1)), so the same
fingerprint drives both levels without correlation.  Shard routing happens on
host, so any shard count works (modulo); only the per-shard bucket count must
stay a power of two for the device-side mask.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

SHARD_AXIS = "shard"
_SHARD_SHIFT = 32


def make_mesh(
    num_shards: int, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """1-D mesh over the first `num_shards` devices, axis name "shard".

    The rate-limit table is pure data-parallel over the key space, so one
    axis is the natural topology (the reference's peer ring is also 1-D).
    """
    devs = list(devices) if devices is not None else jax.devices()
    if len(devs) < num_shards:
        raise ValueError(
            f"need {num_shards} devices, have {len(devs)}"
        )
    return Mesh(np.asarray(devs[:num_shards]), (SHARD_AXIS,))


def shard_of_hash(h, num_shards: int):
    """Owning shard for a 64-bit key fingerprint (works on np or jnp arrays).

    Replaces the worker-pool hash-range interpolation (workers.go:182-186) and
    intra-pod consistent-hash lookup (replicated_hash.go:104-118) with a mask
    over high hash bits.
    """
    u = np.uint64(h) if np.isscalar(h) else h.astype(np.uint64)
    return (u >> np.uint64(_SHARD_SHIFT)) % np.uint64(num_shards)
