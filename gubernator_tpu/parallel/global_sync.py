"""GLOBAL behavior on the mesh: hot-key replication with collective sync.

Reference semantics (global.go:33-254, gubernator.go:420-479): a GLOBAL key
is served from the local cache on ANY peer — a live owner-broadcast status
answers verbatim; a miss is processed locally "like we own it" — while every
hit is queued, aggregated by key, flushed to the owning peer, applied there,
and the authoritative status broadcast back to all peers.  Stale-but-fast
reads; owner-authoritative eventual consistency.

TPU re-expression: devices are the peers.  Every device keeps a local CACHE
table (replicated serving state — any device can answer any GLOBAL key, which
is what lets a hot key scale past its owner's lanes); the authoritative state
lives in the owner's shard of the AUTH table (the same sharded table as the
non-GLOBAL path).  One jitted collective step replaces the reference's two
RPC loops (sendHits + broadcastPeers):

    all_to_all   hit deltas  ->  owner      (sendHits,  global.go:124-164)
    apply        merged hits ->  auth shard (GetPeerRateLimits server side)
    hits=0 read  broadcast rows              (broadcastPeers re-read :214-217)
    all_gather   rows -> every cache shard  (UpdatePeerGlobals, :464-479)

The DEFAULT sync collective (make_global_sync_step_psum) collapses the
first step further: because the host pending dict already merged
duplicate keys and the chunk builder gives each key a globally unique
(owner, lane) slot, hit aggregation is ONE `psum` over the shard axis —
no all_to_all, no device-side sort/segment merge.  Intra-mesh "peers"
never touch the network: UpdatePeerGlobals between shards IS the
all_gather, and the RPC plane (PeerClient) is engaged only for
cross-daemon peers (service._engine_synced) — the hybrid ring topology
where daemon-level arcs of the consistent-hash ring map to meshes and
mesh-level arcs map to shards.

One deliberate deviation from the reference: the owner device also serves
GLOBAL reads from its replicated cache rather than answering authoritatively
(reference gubernator.go:272-283 answers authoritatively on the owner node).
Routing GLOBAL traffic by owner would re-concentrate exactly the hot keys
GLOBAL exists to spread; the eventual-consistency contract is unchanged.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from gubernator_tpu.core.types import RateLimitReq, RateLimitResp
from gubernator_tpu.ops.batch import pack_requests_grid
from gubernator_tpu.ops.state import SlotTable, init_table
from gubernator_tpu.ops.step import (
    CachedRows,
    DeviceBatchJ,
    apply_batch_impl,
    store_cached_rows_impl,
)
from gubernator_tpu.parallel.mesh import SHARD_AXIS, shard_of_hash
from gubernator_tpu.parallel.sharded import (
    MeshBackend,
    _shard_map,
    pack_grid_batch,
    packed_grid_rounds_to_host,
)
from gubernator_tpu.runtime.backend import tier_of, unmarshal_responses


class DeltaGrid(NamedTuple):
    """Per-(source, owner) aggregated hit deltas: arrays [n_src, n_dst, D].

    The device form of globalManager's `hits map[string]*RateLimitReq`
    (global.go:87-95), already partitioned by owning shard.
    """

    key_hash: np.ndarray   # int64
    hits: np.ndarray       # int64 (summed per key)
    limit: np.ndarray      # int64
    duration: np.ndarray   # int64
    algo: np.ndarray       # int32
    burst: np.ndarray      # int64
    is_greg: np.ndarray    # bool
    greg_expire: np.ndarray   # int64
    greg_duration: np.ndarray  # int64


def make_global_sync_step(mesh, ways: int):
    """Build the jitted collective sync:
    (auth, cache, delta, now) -> (auth', cache')."""

    def _local(auth: SlotTable, cache: SlotTable, delta: DeltaGrid, now):
        d = DeltaGrid(*[a[0] for a in delta])  # local [n_dst, D]
        # sendHits: deltas travel to their owning shard over ICI.
        recv = DeltaGrid(
            *[
                jax.lax.all_to_all(a, SHARD_AXIS, split_axis=0, concat_axis=0)
                for a in d
            ]
        )  # [n_src, D] — this device's keys, from every source
        key = recv.key_hash.reshape(-1)
        b2 = key.shape[0]

        # Merge duplicates across sources (same key hit on several devices):
        # sort by key, segment-sum hits into the first occurrence.
        order = jnp.argsort(key)
        ks = key[order]
        first = jnp.concatenate(
            [jnp.ones((1,), dtype=bool), ks[1:] != ks[:-1]]
        )
        seg = jnp.cumsum(first) - 1
        hsum = jax.ops.segment_sum(
            recv.hits.reshape(-1)[order], seg, num_segments=b2
        )
        act = first & (ks != 0)

        def pick(a):
            return a.reshape(-1)[order]

        batch = DeviceBatchJ(
            key_hash=ks,
            hits=hsum[seg],
            limit=pick(recv.limit),
            duration=pick(recv.duration),
            algo=pick(recv.algo),
            burst=pick(recv.burst),
            reset_remaining=jnp.zeros((b2,), dtype=bool),
            is_greg=pick(recv.is_greg),
            greg_expire=pick(recv.greg_expire),
            greg_duration=pick(recv.greg_duration),
            active=act,
            use_cached=jnp.zeros((b2,), dtype=bool),
        )
        # Owner applies the aggregated hits (server side of sendHits).
        auth, _ = apply_batch_impl(auth, batch, now, ways=ways)
        # Broadcast status is a hits=0 re-read (broadcastPeers clears GLOBAL
        # and zeroes Hits before getRateLimit, global.go:211-217).
        auth, resp0 = apply_batch_impl(
            auth, batch._replace(hits=jnp.zeros((b2,), dtype=jnp.int64)),
            now, ways=ways,
        )
        rows = CachedRows(
            key_hash=jnp.where(act, ks, 0),
            algo=batch.algo,
            limit=resp0.limit,
            remaining=resp0.remaining,
            status=resp0.status,
            reset_time=resp0.reset_time,
        )
        # UpdatePeerGlobals to every peer: all_gather the authoritative rows
        # and upsert them into this device's cache shard.
        gathered = CachedRows(
            *[
                jax.lax.all_gather(a, SHARD_AXIS).reshape(-1)
                for a in rows
            ]
        )
        cache = store_cached_rows_impl(cache, gathered, now, ways=ways)
        return auth, cache

    sharded = _shard_map(
        _local,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P()),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
    )
    return jax.jit(sharded, donate_argnums=(0, 1))


def make_global_sync_step_psum(mesh, ways: int):
    """The single-collective form of the sync step: hit aggregation is
    ONE `psum` over the shard axis instead of an all_to_all followed by
    an O(B log B) sort + segment-sum merge (arXiv 2602.11741's framing:
    on a mesh, GLOBAL coordination should cost one collective, not a
    routing exchange plus a device-side merge).

    It leans on a host invariant the a2a step doesn't need: the engine's
    pending dict already merged duplicate keys (global.go:87-95 applied
    at queue time), and `_build_chunks` allocates each key ONE
    (dst, lane) slot globally — so a key occupies exactly one source
    shard's grid and every other source holds zeros there.  The psum of
    the per-source [n_dst, D] grids is then the full merged delta on
    every shard with no duplicate handling at all; each shard slices its
    own row (`axis_index`), applies it to its auth shard, and the
    broadcast rows all_gather into the replicated cache exactly as in
    the a2a step.  Differentially pinned bit-identical to the a2a step
    (tests/test_differential.py)."""

    def _local(auth: SlotTable, cache: SlotTable, delta: DeltaGrid, now):
        d = DeltaGrid(*[a[0] for a in delta])  # local [n_dst, D]

        # sendHits, as ONE collective: per-source grids are disjoint by
        # host construction, so the sum IS the merge (bool fields ride
        # as int32 — psum is an add reduction).  int64 lanes reduce in
        # uint64: the fingerprint lane spans the full int64 range, and
        # if the disjointness invariant is ever violated its sum must
        # wrap modularly (a bogus key that matches nothing) rather than
        # hit signed overflow — two's-complement addition is
        # bit-identical either way, so behavior under the invariant is
        # unchanged (still pinned against the a2a step).
        def _psum_lane(a):
            if a.dtype == jnp.bool_:
                a = a.astype(jnp.int32)
            if a.dtype == jnp.int64:
                return jax.lax.psum(
                    a.astype(jnp.uint64), SHARD_AXIS
                ).astype(jnp.int64)
            return jax.lax.psum(a, SHARD_AXIS)

        merged = DeltaGrid(*[_psum_lane(a) for a in d])
        me = jax.lax.axis_index(SHARD_AXIS)
        mine = DeltaGrid(*[a[me] for a in merged])  # this shard's [D] row
        key = mine.key_hash
        b2 = key.shape[0]
        act = key != 0
        batch = DeviceBatchJ(
            key_hash=key,
            hits=mine.hits,
            limit=mine.limit,
            duration=mine.duration,
            algo=mine.algo,
            burst=mine.burst,
            reset_remaining=jnp.zeros((b2,), dtype=bool),
            is_greg=mine.is_greg != 0,
            greg_expire=mine.greg_expire,
            greg_duration=mine.greg_duration,
            active=act,
            use_cached=jnp.zeros((b2,), dtype=bool),
        )
        # Owner applies the aggregated hits (server side of sendHits).
        auth, _ = apply_batch_impl(auth, batch, now, ways=ways)
        # Broadcast status is a hits=0 re-read (global.go:211-217).
        auth, resp0 = apply_batch_impl(
            auth, batch._replace(hits=jnp.zeros((b2,), dtype=jnp.int64)),
            now, ways=ways,
        )
        rows = CachedRows(
            key_hash=jnp.where(act, key, 0),
            algo=batch.algo,
            limit=resp0.limit,
            remaining=resp0.remaining,
            status=resp0.status,
            reset_time=resp0.reset_time,
        )
        # UpdatePeerGlobals to every shard: all_gather the authoritative
        # rows and upsert them into this device's cache shard.
        gathered = CachedRows(
            *[
                jax.lax.all_gather(a, SHARD_AXIS).reshape(-1)
                for a in rows
            ]
        )
        cache = store_cached_rows_impl(cache, gathered, now, ways=ways)
        return auth, cache

    sharded = _shard_map(
        _local,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P()),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
    )
    return jax.jit(sharded, donate_argnums=(0, 1))


@dataclass
class _Pending:
    """One key's queued hits since the last sync (global.go:87-95)."""

    req: RateLimitReq
    hits: int
    src_dev: int


def zero_delta_grid(n: int, D: int) -> DeltaGrid:
    """All-zero [n, n, D] delta grid (key_hash=0 rows are inactive)."""
    z64 = lambda: np.zeros((n, n, D), dtype=np.int64)  # noqa: E731
    return DeltaGrid(
        key_hash=z64(), hits=z64(), limit=z64(), duration=z64(),
        algo=np.zeros((n, n, D), dtype=np.int32), burst=z64(),
        is_greg=np.zeros((n, n, D), dtype=bool),
        greg_expire=z64(), greg_duration=z64(),
    )


_ARRIVAL_SHIFT = 44  # disjoint from owner-routing bits (32..) and bucket bits


def arrival_dev(h64: int, n: int) -> int:
    """Serving device for a GLOBAL key: deterministic hash spread, using
    bits disjoint from both the owner shard and the bucket index.  Stateless
    (no per-key host memory) — a key's serving device never changes, but all
    broadcast rows exist on every device, so any assignment is correct."""
    return int((np.uint64(h64) >> np.uint64(_ARRIVAL_SHIFT)) % np.uint64(n))


class GlobalEngine:
    """Host-side globalManager: replicated serving + periodic collective sync.

    Owns the per-device cache tables (one sharded SlotTable) and the pending
    hit-delta aggregation; applies authoritative updates to the MeshBackend's
    sharded auth table inside the sync step.
    """

    def __init__(
        self,
        backend: MeshBackend,
        delta_slots: int = 256,
        batch_limit: int = 1000,
        collective: str = "psum",
    ) -> None:
        if collective not in ("psum", "a2a"):
            raise ValueError(
                f"unknown sync collective {collective!r}; expected "
                "'psum' or 'a2a'"
            )
        self.b = backend
        self.n = backend.cfg.num_shards
        self.delta_slots = delta_slots
        self.batch_limit = batch_limit
        self.collective = collective
        self.clock = backend.clock
        # Replicated serving table: its OWN slot budget
        # (DeviceConfig.global_cache_slots; default = num_slots, which
        # doubles the table HBM footprint — size it to the GLOBAL working
        # set to reclaim memory).
        self.cache_slots = (
            backend.cfg.global_cache_slots
            if backend.cfg.global_cache_slots is not None
            else backend.cfg.num_slots
        )
        self.cache_local = self.cache_slots // self.n
        nb_local = self.cache_local // backend.cfg.ways
        if nb_local & (nb_local - 1):
            raise ValueError(
                f"global cache buckets per shard ({nb_local}) must be a "
                "power of two"
            )
        self.cache_table: SlotTable = jax.device_put(
            init_table(self.cache_slots), backend._tsharding
        )
        # Same packed sharded step as the backend hot path, run on the
        # cache table (single-transfer in and out).
        self._ingest = backend._step_packed
        # Default sync collective: ONE psum over the shard axis (the
        # mesh's whole point — hit aggregation over ICI, no device-side
        # merge).  "a2a" keeps the all_to_all + sort/segment form as the
        # differential reference (tests pin the two bit-identical).
        self._sync_step = (
            make_global_sync_step_psum(backend.mesh, backend.cfg.ways)
            if collective == "psum"
            else make_global_sync_step(backend.mesh, backend.cfg.ways)
        )
        self._lock = threading.Lock()  # cache_table + pending + metrics
        self.pending: Dict[str, _Pending] = {}
        # Metrics (global.go:48-57 async/broadcast durations + counts).
        self.syncs = 0
        self.sync_keys = 0
        self.dropped = 0
        # Post-sync hook: called with the synced pending dict (may run on a
        # device-executor thread).  The service uses it to bridge collective
        # syncs to the RPC tier — broadcasting owner-authoritative statuses
        # to cross-NODE peers (global.go:167-250's second loop).
        self.on_synced = None

    # -- serving path ----------------------------------------------------
    def check(self, reqs: Sequence[RateLimitReq]) -> List[RateLimitResp]:
        """Serve GLOBAL checks from the replicated cache tables
        (getGlobalRateLimit, gubernator.go:420-460) and queue the hits.

        Duplicate keys within one call are pre-aggregated (hits summed, the
        reference's own global.go:87-95 aggregation applied at ingest), so a
        hot key costs one lane per batch; the duplicates share one response.
        This deviates from per-hit interim decrements in the pre-broadcast
        window but keeps the same eventual-consistency contract.
        """
        from gubernator_tpu.core.hashing import key_hash64

        agg_idx: Dict[str, int] = {}
        agg_reqs: List[RateLimitReq] = []
        idx_map: List[int] = []
        for r in reqs:
            if r.name and r.unique_key:
                key = r.hash_key()
                j = agg_idx.get(key)
                if j is not None:
                    a = agg_reqs[j]
                    agg_reqs[j] = RateLimitReq(
                        **{**a.__dict__, "hits": a.hits + r.hits}
                    )
                    idx_map.append(j)
                    continue
                agg_idx[key] = len(agg_reqs)
            idx_map.append(len(agg_reqs))
            agg_reqs.append(r)

        packed = pack_requests_grid(
            agg_reqs, self.b.cfg.batch_size, self.n,
            lambda key: arrival_dev(key_hash64(key), self.n),
            self.clock,
        )
        for db in packed.rounds:
            np.copyto(db.use_cached, db.active)
        now_ms = self.clock.millisecond_now()
        now = np.int64(now_ms)

        # Persistence hooks, same contract as the backend hot path: record
        # key strings for Loader save, and seed never-seen keys from the
        # Store (a persisted GLOBAL bucket must survive a restart instead of
        # resetting to full remaining until the first broadcast read-back).
        if self.b._keymap is not None:
            with self.b._keymap_lock:
                for j, r in enumerate(agg_reqs):
                    if j not in packed.errors:
                        k = r.hash_key()
                        self.b._keymap[key_hash64(k)] = k
            self.b._maybe_prune_keymap()
        if self.b.store is not None:
            # Lock order everywhere: auth (backend) before cache (self).
            with self.b._lock, self._lock:
                self._seed_from_store_engine(agg_reqs, packed, now_ms)

        round_resps = []
        with self._lock:
            for db in packed.rounds:
                t = tier_of(db.active, self.b._tiers)
                batch = jax.device_put(
                    pack_grid_batch(db)[:, :, :t], self.b._psharding
                )
                self.cache_table, resp = self._ingest(
                    self.cache_table, batch, now
                )
                round_resps.append(resp)
            # Queue hits AFTER preparing the response (the deferred QueueHit,
            # gubernator.go:429-432).
            for j, r in enumerate(agg_reqs):
                if j in packed.errors:
                    continue
                key = r.hash_key()
                p = self.pending.get(key)
                if p is None:
                    self.pending[key] = _Pending(
                        req=r, hits=r.hits,
                        src_dev=arrival_dev(key_hash64(key), self.n),
                    )
                else:
                    p.hits += r.hits
                    p.req = r
            want_sync = len(self.pending) >= self.batch_limit

        agg_out, tally = unmarshal_responses(
            len(agg_reqs), packed.errors, packed.positions,
            packed_grid_rounds_to_host(round_resps),
        )
        self.b._add_tally(tally)
        if want_sync:
            self.sync()
        return [agg_out[j] for j in idx_map]

    def serve_packed(self, rounds, pend_items):
        """The compiled fast lane's entry: ingest pre-packed use_cached
        rounds into the replicated cache table and queue pending hits,
        under ONE lock hold with check()'s ordering (serve, then queue).
        `pend_items` is [(req, summed_hits, src_dev)] — one per unique
        key, decoded by the caller.  Returns (round_resps_device,
        want_sync); the caller fetches responses to host OUTSIDE the
        lock (merges pipeline) and calls sync() itself when want_sync —
        matching check()'s after-lock sync call.

        Persistence hooks run like check()'s: keymap registration and
        Store.get seeding for never-seen keys (write-through itself
        happens at sync(), the engine's store tier)."""
        from gubernator_tpu.core.hashing import key_hash64

        now_ms = self.clock.millisecond_now()
        if self.b._keymap is not None:
            with self.b._keymap_lock:
                for req, _h, _s in pend_items:
                    k = req.hash_key()
                    self.b._keymap[key_hash64(k)] = k
            self.b._maybe_prune_keymap()
        if self.b.store is not None and pend_items:
            uniq: Dict[str, RateLimitReq] = {}
            for req, _h, _s in pend_items:
                uniq.setdefault(req.hash_key(), req)
            # Lock order everywhere: auth (backend) before cache (self).
            with self.b._lock, self._lock:
                self._seed_uniq_from_store(uniq, now_ms)
        now = np.int64(now_ms)
        with self._lock:
            resps = []
            for db in rounds:
                t = tier_of(db.active, self.b._tiers)
                batch = jax.device_put(
                    pack_grid_batch(db)[:, :, :t], self.b._psharding
                )
                self.cache_table, r = self._ingest(
                    self.cache_table, batch, now
                )
                resps.append(r)
            for req, hits, src_dev in pend_items:
                key = req.hash_key()
                p = self.pending.get(key)
                if p is None:
                    self.pending[key] = _Pending(
                        req=req, hits=hits, src_dev=src_dev
                    )
                else:
                    p.hits += hits
                    p.req = req
            want_sync = len(self.pending) >= self.batch_limit
        return resps, want_sync

    # -- sync path -------------------------------------------------------
    def _seed_from_store_engine(self, agg_reqs, packed, now_ms: int) -> None:
        """Store.get for batch keys with no live row in the replicated
        cache; hits upsert into BOTH tables — the auth table (owner-routed,
        where sync applies hits, the s.Get of algorithms.go:45-51) and the
        cache table (arrival-routed, so pre-sync serving reflects persisted
        state, not a fresh bucket).  Caller holds b._lock then self._lock."""
        uniq: Dict[str, RateLimitReq] = {}
        for j, r in enumerate(agg_reqs):
            if j not in packed.errors:
                uniq.setdefault(r.hash_key(), r)
        if uniq:
            self._seed_uniq_from_store(uniq, now_ms)

    def _seed_uniq_from_store(
        self, uniq: Dict[str, "RateLimitReq"], now_ms: int
    ) -> None:
        """_seed_from_store_engine body over a per-unique-key request dict
        (shared by check() and the fast lane's serve_packed).  Caller
        holds b._lock then self._lock."""
        from gubernator_tpu.core.hashing import key_hash64
        from gubernator_tpu.runtime.store import item_to_row_fields

        keys = list(uniq)
        hashes = [key_hash64(k) for k in keys]
        route = lambda h: arrival_dev(h, self.n)  # noqa: E731
        found, _ = self.b._probe_grid(
            keys, hashes, now_ms, table=self.cache_table, route=route
        )
        rows: List[dict] = []
        row_hashes: List[int] = []
        for k, h, f in zip(keys, hashes, found):
            if f:
                continue
            item = self.b.store.get(uniq[k])
            if item is None or item.is_expired(now_ms):
                continue
            rows.append(item_to_row_fields(item))
            row_hashes.append(h)
        if rows:
            self.b._bulk_upsert(rows, row_hashes, now_ms)
            self.cache_table = self.b._bulk_upsert_into(
                self.cache_table, rows, row_hashes, now_ms, route
            )

    def sync(self) -> int:
        """Run the collective hits->owner->broadcast step; returns #keys."""
        with self._lock:
            pending, self.pending = self.pending, {}
        if not pending:
            return 0
        now_dt = self.clock.now()
        chunks = self._build_chunks(pending, now_dt)
        now = np.int64(self.clock.millisecond_now())
        # Transfers don't read table state — stage them BEFORE taking the
        # locks so concurrent checks only block for the sync steps, not
        # the host->device puts.
        staged = [
            DeltaGrid(*[jax.device_put(a, self.b._bsharding) for a in grid])
            for grid in chunks
        ]
        cap_keys = cap_token = wt_seq = None
        # Lock order: auth (backend) before cache (self).
        with self.b._lock, self._lock:
            for sharded in staged:
                self.b.table, self.cache_table = self._sync_step(
                    self.b.table, self.cache_table, sharded, now
                )
            if self.b.store is not None:
                # Post-sync auth rows -> Store.on_change (the write-through
                # of algorithms.go:154-158, batch-granular at the sync
                # tier).  The row gathers are DISPATCHED inside the lock —
                # pinned to the post-sync table version (jax arrays are
                # immutable) — and FETCHED outside it, so concurrent
                # checks block only for the sync steps, never the
                # device->host readback (the pipelined-drain split,
                # docs/pipeline.md).
                from gubernator_tpu.core.hashing import key_hash64

                cap_keys = list(pending.keys())
                h64 = np.array(
                    [np.uint64(key_hash64(k)) for k in cap_keys],
                    dtype=np.uint64,
                ).view(np.int64)
                cap_token = self.b._gather_rows_dispatch(h64, int(now))
                wt_seq = self.b._wt_ticket()
            self.syncs += 1
            self.sync_keys += len(pending)
        if cap_keys is not None:
            captured: list = []
            try:
                a, rf = self.b._gather_rows_finish(
                    cap_token, len(cap_keys)
                )
                captured = self._captured_items(cap_keys, pending, a, rf)
            finally:
                # Redeem the ticket even if a fetch fails — an
                # unredeemed ticket wedges every later delivery
                # (PersistenceHost._deliver_write_through).
                self.b._deliver_write_through(captured, wt_seq)
        if self.on_synced is not None:
            self.on_synced(pending)
        return len(pending)

    def _captured_items(self, keys, pending, a, rf) -> list:
        """(req, CacheItem) pairs from packed GATHER_ROW_FIELDS columns —
        misses and KIND_CACHED_RESP rows are skipped exactly like
        MeshBackend._read_items_locked."""
        from gubernator_tpu.core.types import Algorithm, CacheItem, Status
        from gubernator_tpu.ops.state import KIND_CACHED_RESP

        out: list = []
        for j, key in enumerate(keys):
            if not a[0, j] or a[1, j] == KIND_CACHED_RESP:
                continue
            algo = Algorithm(int(a[2, j]))
            remaining = (
                float(rf[j]) if algo == Algorithm.LEAKY_BUCKET
                else int(a[5, j])
            )
            out.append((pending[key].req, CacheItem(
                key=key,
                algorithm=algo,
                expire_at=int(a[9, j]),
                limit=int(a[3, j]),
                duration=int(a[4, j]),
                remaining=remaining,
                created_at=int(a[6, j]),
                status=Status(int(a[7, j])),
                burst=int(a[8, j]),
            )))
        return out

    def _build_chunks(self, pending: Dict[str, _Pending], now_dt):
        """Pack pending deltas into [n, n, D] grids (chunked on overflow)."""
        from gubernator_tpu.core.hashing import key_hash64
        from gubernator_tpu.core.interval import (
            GregorianError,
            gregorian_duration,
            gregorian_expiration,
        )
        from gubernator_tpu.core.types import Behavior, has_behavior

        n, D = self.n, self.delta_slots
        chunks: List[DeltaGrid] = []
        # Lane counters are per (chunk, DST) — shared across sources —
        # so every key gets a GLOBALLY unique (dst, lane) slot within a
        # chunk.  The psum step's whole premise is that the per-source
        # grids are disjoint (the sum IS the merge); the a2a step
        # handles this layout too (its sort/segment merge degenerates to
        # a permutation), so one builder serves both collectives.
        fill: List[np.ndarray] = []  # [n_dst] lane counters per chunk

        def new_chunk() -> DeltaGrid:
            g = zero_delta_grid(n, D)
            chunks.append(g)
            fill.append(np.zeros(n, dtype=np.int64))
            return g

        def fill_lane(ci: int, lane: int, h64, p: _Pending, is_greg, ge, gd):
            g, r = chunks[ci], p.req
            src, dst = p.src_dev, int(shard_of_hash(h64, n))
            g.key_hash[src, dst, lane] = np.int64(np.uint64(h64).view(np.int64))
            g.hits[src, dst, lane] = p.hits
            g.limit[src, dst, lane] = r.limit
            g.duration[src, dst, lane] = r.duration
            g.algo[src, dst, lane] = int(r.algorithm)
            g.burst[src, dst, lane] = r.burst if r.burst != 0 else r.limit
            g.is_greg[src, dst, lane] = is_greg
            g.greg_expire[src, dst, lane] = ge
            g.greg_duration[src, dst, lane] = gd
            fill[ci][dst] = lane + 1

        for key, p in pending.items():
            r = p.req
            h64 = key_hash64(key)
            dst = int(shard_of_hash(h64, n))
            is_greg = has_behavior(r.behavior, Behavior.DURATION_IS_GREGORIAN)
            ge = gd = 0
            if is_greg:
                try:
                    ge = gregorian_expiration(now_dt, r.duration)
                    gd = gregorian_duration(now_dt, r.duration)
                except GregorianError:
                    with self._lock:
                        self.dropped += 1
                    continue
            while True:
                for ci in range(len(chunks)):
                    lane = int(fill[ci][dst])
                    if lane < D:
                        fill_lane(ci, lane, h64, p, is_greg, ge, gd)
                        break
                else:
                    new_chunk()
                    continue
                break
        if not chunks:
            new_chunk()
        return chunks

    def warmup(self) -> None:
        """Compile the collective sync executable with an all-zero delta
        grid (key_hash=0 rows are inactive, so the tables are unchanged) —
        a first compile inside the serving cadence would stall every lane.
        """
        grid = zero_delta_grid(self.n, self.delta_slots)
        sharded = DeltaGrid(
            *[jax.device_put(a, self.b._bsharding) for a in grid]
        )
        now = np.int64(self.clock.millisecond_now())
        with self.b._lock, self._lock:
            self.b.table, self.cache_table = self._sync_step(
                self.b.table, self.cache_table, sharded, now
            )
            # Ingest executables for the CACHE table geometry (the jit
            # cache keys on table size, so the auth-table warmup doesn't
            # cover a global_cache_slots-sized table) at every tier.
            for t in self.b._tiers:
                batch = jax.device_put(
                    np.zeros((12, self.n, t), dtype=np.int64),
                    self.b._psharding,
                )
                self.cache_table, _ = self._ingest(
                    self.cache_table, batch, now
                )

    # -- point reads (tests / HealthCheck) -------------------------------
    def _cache_bucket_offset(self, key: str, shard: int) -> int:
        """Global row index of `key`'s bucket within the CACHE table (its
        geometry may differ from the auth table's via global_cache_slots).
        """
        from gubernator_tpu.core.hashing import key_hash64

        nb_local = self.cache_local // self.b.cfg.ways
        bucket = key_hash64(key) & (nb_local - 1)
        return shard * self.cache_local + bucket * self.b.cfg.ways

    def get_cached(self, key: str):
        """Read this key's row from its serving device's cache table."""
        from gubernator_tpu.core.hashing import key_hash64
        from gubernator_tpu.runtime.backend import probe_bucket

        dev = arrival_dev(key_hash64(key), self.n)
        lo = self._cache_bucket_offset(key, dev)
        now = self.clock.millisecond_now()
        with self._lock:
            return probe_bucket(
                self.cache_table, lo, self.b.cfg.ways, key, now
            )

    def cache_occupancy(self) -> int:
        """Live rows in the replicated serving table (HBM observability for
        the 2x-table cost; exported as gubernator_global_cache_size)."""
        with self._lock:
            return int(np.asarray(self.cache_table.occupancy()))
