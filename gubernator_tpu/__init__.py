"""gubernator-tpu: a TPU-native distributed rate-limiting framework.

A from-scratch rebuild of the capabilities of Gubernator (reference:
/root/reference, mailgun/gubernator v2 — a stateless distributed
rate-limiting microservice) designed TPU-first:

- Per-key counter state lives on device as fixed-size struct-of-arrays
  (a set-associative slot table), not a host LRU dict.
- The token-bucket / leaky-bucket algorithms are branchless vectorized
  lane arithmetic inside ONE jitted step function, not per-request
  control flow (reference: algorithms.go).
- Intra-node key sharding (reference: workers.go worker pool) becomes
  mesh sharding of the slot table over TPU cores via shard_map.
- GLOBAL async hit aggregation (reference: global.go) becomes psum /
  all_gather collectives over the ICI mesh.
- The host side (gRPC frontend, batching, consistent-hash peer routing,
  discovery, TLS, metrics) mirrors the reference's daemon surface.
"""

__version__ = "0.1.0"

from gubernator_tpu.core.types import (  # noqa: F401
    Algorithm,
    Behavior,
    Status,
    RateLimitReq,
    RateLimitResp,
    HealthCheckResp,
    has_behavior,
)


def __getattr__(name: str):
    """Lazy top-level client SDK (keeps `import gubernator_tpu` free of
    grpc; the reference's Go package exposes its client the same
    flat way, client.go:42-63)."""
    if name in ("V1Client", "AsyncV1Client"):
        from gubernator_tpu import client

        return getattr(client, name)
    raise AttributeError(name)


def __dir__():
    return sorted(list(globals()) + ["V1Client", "AsyncV1Client"])
