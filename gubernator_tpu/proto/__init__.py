"""Generated protobuf modules for the V1 / PeersV1 wire contract.

Regenerate with scripts/protogen.sh.  The wire format is compatible with the
reference service (reference proto/gubernator.proto, proto/peers.proto) so
existing clients interoperate unchanged.
"""
from . import gubernator_pb2, peers_pb2  # noqa: F401
