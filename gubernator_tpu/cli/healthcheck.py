"""Container health probe (reference cmd/healthcheck/main.go:29-50).

GETs /v1/HealthCheck on the local daemon; exits 0 when healthy, 2 when
unhealthy or unreachable — the contract container runtimes expect.
"""
from __future__ import annotations

import argparse
import json
import sys
import urllib.request


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument(
        "--url", default="http://localhost:1050/v1/HealthCheck"
    )
    args = p.parse_args()
    try:
        with urllib.request.urlopen(args.url, timeout=5) as resp:
            payload = json.loads(resp.read())
    except Exception as e:  # noqa: BLE001
        print(f"unreachable: {e}", file=sys.stderr)
        sys.exit(2)
    if payload.get("status") != "healthy":
        print(payload.get("message", "unhealthy"), file=sys.stderr)
        sys.exit(2)
    print("healthy")


if __name__ == "__main__":
    main()
