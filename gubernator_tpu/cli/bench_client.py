"""Load generator CLI (reference cmd/gubernator-cli/main.go:52-224).

Generates N random rate limits and replays them endlessly against a daemon
with a concurrency fan-out, optional client-side rate limiting and batch
size, reporting throughput and over-limit counts.
"""
from __future__ import annotations

import argparse
import asyncio
import random
import time

from gubernator_tpu.client import AsyncV1Client, random_string
from gubernator_tpu.core.types import Algorithm, RateLimitReq


def make_rate_limits(n: int) -> list:
    """2000 random limits by default (main.go:117-129)."""
    out = []
    for _ in range(n):
        out.append(
            RateLimitReq(
                name=random_string("ID-", 6),
                unique_key=random_string("", 10),
                hits=1,
                limit=random.randint(1, 100),
                duration=random.randint(1, 60) * 1000,
                algorithm=random.choice(list(Algorithm)),
            )
        )
    return out


async def run(args) -> None:
    limits = make_rate_limits(args.limits)
    client = AsyncV1Client(args.address)
    stats = {"checks": 0, "over": 0, "errors": 0}
    t0 = time.monotonic()

    async def worker() -> None:
        while time.monotonic() - t0 < args.seconds:
            batch = random.sample(limits, min(args.checks, len(limits)))
            try:
                resps = await client.get_rate_limits(batch, timeout=5.0)
            except Exception:  # noqa: BLE001
                stats["errors"] += len(batch)
                continue
            stats["checks"] += len(resps)
            stats["over"] += sum(1 for r in resps if int(r.status) == 1)
            if args.rate > 0:
                await asyncio.sleep(len(batch) / args.rate)

    await asyncio.gather(*(worker() for _ in range(args.concurrency)))
    dt = time.monotonic() - t0
    print(
        f"checks={stats['checks']} over_limit={stats['over']} "
        f"errors={stats['errors']} elapsed={dt:.1f}s "
        f"rate={stats['checks'] / dt:,.0f}/s"
    )
    await client.close()


def main() -> None:
    p = argparse.ArgumentParser(description="gubernator-tpu load generator")
    p.add_argument("--address", default="localhost:1051")
    p.add_argument("--limits", type=int, default=2000,
                   help="distinct random rate limits")
    p.add_argument("--checks", type=int, default=10,
                   help="checks per request batch")
    p.add_argument("--concurrency", type=int, default=32)
    p.add_argument("--rate", type=float, default=0,
                   help="client-side checks/sec cap per worker (0=off)")
    p.add_argument("--seconds", type=float, default=10.0)
    asyncio.run(run(p.parse_args()))


if __name__ == "__main__":
    main()
