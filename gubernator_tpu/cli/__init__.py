"""Command-line entry points (reference cmd/ parity):

- python -m gubernator_tpu.cli.server       — the daemon
  (cmd/gubernator/main.go)
- python -m gubernator_tpu.cli.bench_client — load generator
  (cmd/gubernator-cli/main.go)
- python -m gubernator_tpu.cli.cluster      — local dev cluster
  (cmd/gubernator-cluster/main.go)
- python -m gubernator_tpu.cli.healthcheck  — container health probe
  (cmd/healthcheck/main.go)
"""
