"""Pretty-printer for flight-recorder dumps (runtime/flightrec.py).

Usage:
    python -m gubernator_tpu.cli.flightrec DUMP.json [...]
    python -m gubernator_tpu.cli.flightrec --ring DUMP.json   # full ring
    gubernator-tpu-flightrec flightrec-dumps/                 # newest first

Reads the JSON snapshots the daemon writes on SLO breach / error storm /
SIGUSR2 and renders the headline (trigger, rolling percentiles vs the
target, loop lag) plus a per-kind ring digest, so an operator can read a
black box without jq."""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List


def _fmt_ts(ts: float) -> str:
    return time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(ts))


def _digest_ring(ring: List[Dict]) -> List[str]:
    """Per-kind summary: count, size/latency spread, worst offenders."""
    by_kind: Dict[str, List[Dict]] = {}
    for rec in ring:
        by_kind.setdefault(rec.get("kind", "?"), []).append(rec)
    lines = []
    for kind in sorted(by_kind):
        recs = by_kind[kind]
        line = f"  {kind:<18} x{len(recs)}"
        ms = [r["step_ms"] for r in recs if "step_ms" in r]
        if ms:
            line += "  step_ms min/max %.3f/%.3f" % (min(ms), max(ms))
        sizes = [r["size"] for r in recs if "size" in r]
        if sizes:
            line += "  size min/max %d/%d" % (min(sizes), max(sizes))
        lags = [r["lag_ms"] for r in recs if "lag_ms" in r]
        if lags:
            line += "  lag_ms max %.1f" % max(lags)
        lines.append(line)
    return lines


def render(path: str, show_ring: bool = False) -> str:
    with open(path, encoding="utf-8") as f:
        snap = json.load(f)
    roll = snap.get("rolling", {})
    lag = snap.get("loop_lag_ms", {})
    out = [
        f"== {path}",
        "  reason=%s  pid=%s  at %s" % (
            snap.get("reason", "live"), snap.get("pid"),
            _fmt_ts(snap.get("now", 0)),
        ),
        "  rolling p50=%.3fms p99=%.3fms over %s sample(s) "
        "(target p99 < %sms)" % (
            roll.get("p50_ms", 0.0), roll.get("p99_ms", 0.0),
            roll.get("samples", 0), snap.get("slo_p99_ms"),
        ),
        "  errors_in_window=%s  breaches=%s  dumps=%s  "
        "loop_lag last=%.2fms max=%.2fms" % (
            roll.get("errors_in_window", 0), snap.get("breaches", 0),
            snap.get("dumps", 0), lag.get("last", 0.0),
            lag.get("max", 0.0),
        ),
    ]
    ring = snap.get("ring", [])
    out.append(f"  ring: {len(ring)} record(s)")
    out.extend(_digest_ring(ring))
    if show_ring:
        for rec in ring:
            fields = {
                k: v for k, v in rec.items() if k not in ("ts", "kind")
            }
            out.append(
                "    %s %-16s %s" % (
                    _fmt_ts(rec.get("ts", 0)), rec.get("kind", "?"),
                    json.dumps(fields, sort_keys=True),
                )
            )
    return "\n".join(out)


def _expand(paths: List[str]) -> List[str]:
    """Directories expand to their dumps, newest first."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            dumps = [
                os.path.join(p, n) for n in os.listdir(p)
                if n.startswith("flightrec-") and n.endswith(".json")
            ]
            out.extend(
                sorted(dumps, key=os.path.getmtime, reverse=True)
            )
        else:
            out.append(p)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="gubernator-tpu-flightrec",
        description="Pretty-print flight-recorder dumps.",
    )
    ap.add_argument(
        "paths", nargs="+",
        help="dump files or directories of dumps (newest first)",
    )
    ap.add_argument(
        "--ring", action="store_true",
        help="print every ring record, not just the per-kind digest",
    )
    args = ap.parse_args(argv)
    files = _expand(args.paths)
    if not files:
        print("no flight-recorder dumps found", file=sys.stderr)
        return 1
    rc = 0
    for path in files:
        try:
            print(render(path, show_ring=args.ring))
        except (OSError, ValueError) as e:
            print(f"== {path}\n  unreadable: {e}", file=sys.stderr)
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
