"""gubtop: the cluster-wide gubstat console (docs/observability.md).

Usage:
    python -m gubernator_tpu.cli.gubtop HOST:PORT [HOST:PORT ...]
    gubernator-tpu-gubtop --watch 2 10.0.0.1:1050 10.0.0.2:1050
    gubernator-tpu-gubtop --json localhost:1050

Scrapes every peer's /debug/vars (and derives SLO pressure from its
flightrec block) over plain HTTP — stdlib urllib only, so it runs from
any operator box without the package's server dependencies.  One-shot
by default; `--watch N` refreshes every N seconds; `--json` emits the
raw merged scrape for scripting.

Per node: table occupancy (live/expired split and per-shard skew),
rounds-per-dispatch (the megaround amortization factor), rolling
p50/p99 vs the SLO target with the pressure flag, breaker/degraded/
reshard state, and the shadow-plane census.  Cluster-wide: the merged
top-K tenants by hits with per-plane over-admission.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, List, Optional


def scrape(addr: str, timeout: float = 3.0) -> Dict:
    """One node's /debug/vars, or {"error": ...} when unreachable."""
    url = f"http://{addr}/debug/vars"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return json.loads(resp.read().decode("utf-8"))
    except (urllib.error.URLError, OSError, ValueError) as e:
        return {"error": str(e)}


def _node_lines(addr: str, v: Dict) -> List[str]:
    if "error" in v:
        return [f"{addr:<22} UNREACHABLE: {v['error']}"]
    be = v.get("backend", {})
    table = v.get("table", {})
    fp = v.get("fastpath", {})
    fr = v.get("flightrec", {})
    occ = table.get("occupancy", be.get("occupancy", 0))
    live = table.get("live")
    expired = table.get("expired_resident")
    occ_s = f"occ={occ}"
    if live is not None:
        occ_s += f" (live={live} expired={expired})"
    shards = table.get("per_shard_occupancy") or be.get("shard_occupancy")
    if shards and len(shards) > 1:
        occ_s += " shards=" + "/".join(str(s) for s in shards)
    ring = fp.get("ring") or {}
    rpd = ring.get("rounds_per_dispatch", v.get("rounds_per_dispatch"))
    rpd_s = f" r/d={rpd:.2f}" if isinstance(rpd, (int, float)) else ""
    slo = ""
    if fr:
        slo = " p50=%.2fms p99=%.2fms" % (
            fr.get("last_p50_ms", 0.0), fr.get("last_p99_ms", 0.0),
        )
        if fr.get("breaches"):
            slo += " breaches=%d" % fr["breaches"]
    open_circuits = [
        a for a, c in (v.get("circuits") or {}).items()
        if c.get("state") not in (0, "closed", None)
    ]
    flags = []
    if open_circuits:
        flags.append("CIRCUIT[%s]" % ",".join(open_circuits))
    deg = v.get("degraded", {})
    if deg.get("served"):
        flags.append("degraded=%d" % deg["served"])
    rs = v.get("reshard", {})
    active = rs.get("outbound") or rs.get("inbound")
    if active:
        flags.append("RESHARD")
    hk = v.get("hotkeys", {})
    if hk.get("shed", {}).get("served"):
        flags.append("shed=%d" % hk["shed"]["served"])
    lines = [
        "%-22s checks=%-10s %s%s%s %s" % (
            addr, be.get("checks", 0), occ_s, rpd_s, slo,
            " ".join(flags),
        )
    ]
    shadow = table.get("shadow_slots")
    if shadow and any(shadow.values()):
        lines.append(
            "    shadow: " + "  ".join(
                f"{k}={n}" for k, n in shadow.items() if n
            )
        )
    tier = v.get("tier")
    if tier:
        lat = tier.get("promote_latency") or {}
        p99 = lat.get("p99_s")
        p99_s = (
            " promote_p99=%.2fms" % (p99 * 1e3)
            if isinstance(p99, (int, float)) and p99 > 0 else ""
        )
        drops = tier.get("capacity_drops", 0)
        lines.append(
            "    tier: cold=%d/%d hits=%d promotes=%d demotes=%d%s%s"
            % (
                tier.get("cold_residents", 0),
                tier.get("cold_capacity", 0),
                tier.get("cold_hits", 0),
                tier.get("promotes", 0),
                tier.get("demotes", 0),
                p99_s,
                f" DROPS={drops}" if drops else "",
            )
        )
    region = v.get("region")
    if region:
        # The region carve plane (docs/multiregion.md): drift is the
        # un-reconciled burn backlog toward every home region; any
        # non-remote link is a WAN incident in progress.
        links = region.get("links") or {}
        bad = [
            f"{rg}:{lk.get('state')}" for rg, lk in sorted(links.items())
            if lk.get("state") != "remote"
        ]
        dropped = region.get("reconcile_dropped", 0)
        lines.append(
            "    region: %s drift=%d carves=%d rehomes=%d%s%s" % (
                region.get("name", "?"),
                region.get("drift", 0),
                region.get("carve_served", 0),
                region.get("rehomes", 0),
                f" dropped={dropped}" if dropped else "",
                " DEGRADED[%s]" % ",".join(bad) if bad else "",
            )
        )
    load = v.get("load")
    if load:
        # A gubload scenario phase is driving this node right now —
        # the operator can tie any latency blip to its phase.
        since = load.get("since")
        age_s = (
            " t+%.1fs" % (time.time() - since)
            if isinstance(since, (int, float)) else ""
        )
        lines.append(
            "    load: scenario=%s phase=%s seq=%s%s" % (
                load.get("scenario", "?"), load.get("phase", "?"),
                load.get("seq", "?"), age_s,
            )
        )
    return lines


def _merge_tenants(scrapes: Dict[str, Dict], k: int) -> List[Dict]:
    """Cluster-wide tenant view: sum each node's local ledger (local
    serves only per node, so the sum is exact — no double counting)."""
    merged: Dict[str, Dict] = {}
    for v in scrapes.values():
        for t in (v.get("tenants") or {}).get("top", []):
            m = merged.setdefault(
                t["name"],
                {"name": t["name"], "allowed": 0, "denied": 0,
                 "shed": 0, "over_admitted": {}},
            )
            for f in ("allowed", "denied", "shed"):
                m[f] += t.get(f, 0)
            for plane, n in (t.get("over_admitted") or {}).items():
                m["over_admitted"][plane] = (
                    m["over_admitted"].get(plane, 0) + n
                )
    ranked = sorted(
        merged.values(),
        key=lambda t: t["allowed"] + t["denied"] + t["shed"],
        reverse=True,
    )
    return ranked[:k]


def render(addrs: List[str], top_k: int = 10) -> str:
    scrapes = {a: scrape(a) for a in addrs}
    out = [
        "gubtop — %d node(s) @ %s" % (
            len(addrs), time.strftime("%H:%M:%S"),
        )
    ]
    for a in addrs:
        out.extend(_node_lines(a, scrapes[a]))
    tenants = _merge_tenants(scrapes, top_k)
    if tenants:
        out.append("top tenants (cluster-wide hits):")
        out.append(
            "    %-28s %10s %10s %8s  %s" % (
                "name", "allowed", "denied", "shed", "over-admitted"
            )
        )
        for t in tenants:
            over = " ".join(
                f"{p}={n}" for p, n in sorted(t["over_admitted"].items())
            )
            out.append(
                "    %-28s %10d %10d %8d  %s" % (
                    t["name"][:28], t["allowed"], t["denied"],
                    t["shed"], over,
                )
            )
    return "\n".join(out)


def peek_key(addr: str, name: str, key: str) -> Dict:
    """One /debug/key round-trip (owner-routed by the serving node)."""
    qs = urllib.parse.urlencode({"name": name, "key": key})
    url = f"http://{addr}/debug/key?{qs}"
    with urllib.request.urlopen(url, timeout=5) as resp:
        return json.loads(resp.read().decode("utf-8"))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="gubernator-tpu-gubtop",
        description="Cluster-wide gubstat console over /debug/vars.",
    )
    ap.add_argument(
        "addrs", nargs="+", metavar="HOST:PORT",
        help="HTTP listener address of each node",
    )
    ap.add_argument(
        "--watch", type=float, default=0.0, metavar="SECS",
        help="refresh every SECS seconds (default: one shot)",
    )
    ap.add_argument(
        "--json", action="store_true",
        help="emit the raw merged scrape as JSON",
    )
    ap.add_argument(
        "--top", type=int, default=10,
        help="tenants to show in the cluster view (default 10)",
    )
    ap.add_argument(
        "--key", default="", metavar="NAME/KEY",
        help="inspect one key instead: NAME/UNIQUE_KEY via /debug/key",
    )
    args = ap.parse_args(argv)
    if args.key:
        name, _, key = args.key.partition("/")
        try:
            print(json.dumps(
                peek_key(args.addrs[0], name, key), indent=2,
            ))
        except (urllib.error.URLError, OSError, ValueError) as e:
            print(f"peek failed: {e}", file=sys.stderr)
            return 1
        return 0
    if args.json:
        print(json.dumps(
            {a: scrape(a) for a in args.addrs}, indent=2,
        ))
        return 0
    if args.watch <= 0:
        print(render(args.addrs, args.top))
        return 0
    try:
        while True:
            # ANSI clear + home, like top(1).
            sys.stdout.write("\x1b[2J\x1b[H")
            print(render(args.addrs, args.top))
            sys.stdout.flush()
            time.sleep(args.watch)
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
