"""Service-level micro-benchmark suite (reference benchmark_test.go:29-148).

Scenarios, each against an in-process daemon pair over real gRPC:
  peer_rpc       — direct GetPeerRateLimits, NO_BATCHING analog
  get_ratelimits — client GetRateLimits, owner-local keys
  global         — GLOBAL behavior reads on a non-owner
  sketch         — approximate-tier (CMS) checks on a sketch-named limit
  healthcheck    — HealthCheck RPC
  herd           — 100-way concurrent fan-out on one key (thundering herd)

Reports throughput and p50/p99 latency per scenario as JSON lines.
Run on CPU for the host-path numbers (JAX_PLATFORMS=cpu) or on the real
chip for end-to-end device numbers.

Reading the numbers: client + both daemons share ONE python process here,
so per-RPC latency is dominated by the grpc/asyncio floor (compare the
healthcheck scenario, which does no device work at all).  Device-path
throughput comes from batched calls — a single daemon sustains
~500 RPC/s x 1000-check batches through this frontend (vs the reference's
~2k single-check requests/s per node, README.md:94-100), and bench.py
measures the raw device ceiling.
"""
from __future__ import annotations

import argparse
import asyncio
import json
import time
from typing import Callable, List

import numpy as np

from gubernator_tpu.client import AsyncV1Client
from gubernator_tpu.core.config import (
    DaemonConfig,
    DeviceConfig,
    SketchTierConfig,
    fast_test_behaviors,
)
from gubernator_tpu.core.types import Behavior, PeerInfo, RateLimitReq
from gubernator_tpu.daemon import Daemon, wait_for_connect
from gubernator_tpu.net.grpc_api import PeersV1Stub, req_to_pb
from gubernator_tpu.proto import peers_pb2


async def timed(fn: Callable, seconds: float, concurrency: int):
    lat: List[float] = []
    stop = time.monotonic() + seconds

    async def worker():
        while time.monotonic() < stop:
            t0 = time.monotonic()
            await fn()
            lat.append(time.monotonic() - t0)

    await asyncio.gather(*(worker() for _ in range(concurrency)))
    arr = np.array(lat)
    return {
        "ops": len(lat),
        "ops_per_sec": round(len(lat) / seconds, 1),
        "p50_ms": round(float(np.percentile(arr, 50)) * 1e3, 3),
        "p99_ms": round(float(np.percentile(arr, 99)) * 1e3, 3),
    }


async def run(args) -> None:
    daemons = []
    for _ in range(2):
        d = Daemon(
            DaemonConfig(
                grpc_listen_address="127.0.0.1:0",
                http_listen_address="127.0.0.1:0",
                behaviors=fast_test_behaviors(),
                device=DeviceConfig(
                    num_slots=args.slots, batch_size=args.batch
                ),
                sketch=SketchTierConfig(
                    names=["bench_sketch"], width=1 << 16,
                    window_ms=60_000, batch_size=args.batch,
                ),
            )
        )
        await d.start()
        d.conf.advertise_address = d.grpc_address
        daemons.append(d)
    peers = [PeerInfo(grpc_address=d.grpc_address) for d in daemons]
    for d in daemons:
        await d.set_peers(peers)
    await wait_for_connect([d.grpc_address for d in daemons])

    import grpc.aio

    client = AsyncV1Client(daemons[0].grpc_address)
    ch = grpc.aio.insecure_channel(daemons[0].grpc_address)
    peers_stub = PeersV1Stub(ch)

    # A key owned by daemon 0 (so "local") and one owned by daemon 1.
    # Ownership depends on the FULL hash key, so each scenario's name
    # needs its own lookup (a key local under "bench" may be remote
    # under "bench_sketch").
    def owned_by(d, name):
        i = 0
        while True:
            key = f"bench_k{i}"
            peer = daemons[0].service.get_peer(f"{name}_{key}")
            if peer.info().grpc_address == d.grpc_address:
                return key
            i += 1

    local_key = owned_by(daemons[0], "bench")
    remote_key = owned_by(daemons[1], "bench")
    sketch_key = owned_by(daemons[0], "bench_sketch")

    async def peer_rpc():
        await peers_stub.GetPeerRateLimits(
            peers_pb2.GetPeerRateLimitsReq(requests=[
                req_to_pb(RateLimitReq(
                    name="bench", unique_key=local_key, hits=1,
                    limit=1_000_000_000, duration=60_000,
                ))
            ])
        )

    async def get_ratelimits():
        await client.get_rate_limits([
            RateLimitReq(name="bench", unique_key=local_key, hits=1,
                         limit=1_000_000_000, duration=60_000)
        ])

    async def global_read():
        await client.get_rate_limits([
            RateLimitReq(name="bench", unique_key=remote_key, hits=1,
                         limit=1_000_000_000, duration=60_000,
                         behavior=Behavior.GLOBAL)
        ])

    async def sketch():
        await client.get_rate_limits([
            RateLimitReq(name="bench_sketch", unique_key=sketch_key, hits=1,
                         limit=1_000_000_000, duration=60_000)
        ])

    async def healthcheck():
        await client.health_check()

    async def herd():
        await asyncio.gather(*(
            client.get_rate_limits([
                RateLimitReq(name="bench", unique_key=local_key, hits=1,
                             limit=1_000_000_000, duration=60_000)
            ])
            for _ in range(100)
        ))

    scenarios = {
        "peer_rpc": (peer_rpc, args.concurrency),
        "get_ratelimits": (get_ratelimits, args.concurrency),
        "global": (global_read, args.concurrency),
        "sketch": (sketch, args.concurrency),
        "healthcheck": (healthcheck, args.concurrency),
        "herd_100way": (herd, 1),
    }
    for name, (fn, conc) in scenarios.items():
        stats = await timed(fn, args.seconds, conc)
        print(json.dumps({"scenario": name, **stats}))

    if args.recompile_audit:
        # Runtime counterpart of gubtrace's static recompile audit
        # (tools/gubtrace): after the canonical workload above, report
        # the live jit-cache entry count per registered module-level
        # kernel.  Counts beyond the warmed tier/shape set mean
        # recompiles landed inside the serving window — the storm the
        # static audit exists to prevent.
        try:
            from tools.gubtrace.recompile import runtime_cache_report
        except ImportError:
            print(json.dumps({
                "scenario": "recompile_audit",
                "error": "tools.gubtrace not importable (run from a "
                         "repo checkout)",
            }))
        else:
            print(json.dumps({
                "scenario": "recompile_audit",
                "jit_caches": runtime_cache_report(),
            }))

    await client.close()
    await ch.close()
    for d in daemons:
        await d.close()


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--seconds", type=float, default=3.0)
    p.add_argument("--concurrency", type=int, default=16)
    p.add_argument("--slots", type=int, default=65_536)
    p.add_argument("--batch", type=int, default=1024)
    p.add_argument(
        "--recompile-audit", action="store_true",
        help="after the scenarios, report per-kernel jit cache "
             "hits/misses via the gubtrace registry (runtime "
             "counterpart of `python -m tools.gubtrace`)",
    )
    asyncio.run(run(p.parse_args()))


if __name__ == "__main__":
    main()
