"""The server CLI (reference cmd/gubernator/main.go:40-106).

Reads GUBER_* environment variables (optionally seeded from a --config
KEY=VALUE file), spawns the daemon, and serves until SIGINT/SIGTERM.
"""
from __future__ import annotations

import argparse
import asyncio
import logging
import os
import signal

from gubernator_tpu.core.config import setup_daemon_config
from gubernator_tpu.daemon import Daemon


def main() -> None:
    parser = argparse.ArgumentParser(description="gubernator-tpu daemon")
    parser.add_argument(
        "--config", default="", help="KEY=VALUE environment file"
    )
    args = parser.parse_args()

    conf = setup_daemon_config(args.config or None)
    from gubernator_tpu.core.logging import setup_logging

    setup_logging(
        level=conf.log_level,
        fmt=os.environ.get("GUBER_LOG_FORMAT", "text"),
    )
    # Tracing from standard OTEL_* env vars (cmd/gubernator/main.go
    # initializes its tracer the same way, main.go:56-69).  The status
    # is logged HONESTLY: a configured OTLP endpoint whose exporter
    # packages are missing says so instead of pretending spans export
    # (the old bool return hid exactly that failure).
    from gubernator_tpu.runtime.tracing import init_tracing

    trace_log = logging.getLogger("gubernator_tpu.tracing")
    status = init_tracing()
    if status.enabled:
        if status.exporter_error:
            trace_log.warning(
                "tracing armed (sampler=%s) but NOT exporting: %s — "
                "spans stay in-process (breach dumps, /debug/vars)",
                status.sampler, status.exporter_error,
            )
        else:
            trace_log.info(
                "tracing armed: sampler=%s exporter=%s",
                status.sampler, status.exporter,
            )
    else:
        trace_log.info("tracing disabled: %s", status.reason)

    async def run() -> None:
        daemon = Daemon(conf)
        await daemon.start()
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)

        def dump_flightrec() -> None:
            # SIGUSR2: operator-initiated flight-recorder dump (the Go
            # expvar/pprof-on-signal idiom).  Fire-and-forget on the loop;
            # a disarmed recorder just logs where to turn it on.  The
            # dump carries the gubstat `table` census block when the
            # sampler is armed (flightrec extras, runtime/gubstat.py).
            if daemon.flightrec is None:
                logging.getLogger("gubernator_tpu").warning(
                    "SIGUSR2: flight recorder disabled "
                    "(set GUBER_FLIGHTREC=1)"
                )
                return
            asyncio.ensure_future(daemon.flightrec.dump("signal"))

        loop.add_signal_handler(signal.SIGUSR2, dump_flightrec)
        await stop.wait()
        logging.getLogger("gubernator_tpu").info("shutting down")
        await daemon.close()

    asyncio.run(run())


if __name__ == "__main__":
    main()
