"""gubernator-tpu-gubload — the open-loop scenario harness CLI
(docs/loadgen.md).

Runs one scenario from the library (loadgen/scenarios.py) against an
in-process cluster (default; fault scenarios require it) or an
external address list, prints each BENCH-compatible artifact row as a
JSON line, and writes the full artifact for scripts/bench_gate.py.

Knobs come from the gubload env surface (deploy/example.conf) with
flags overriding; the run is deterministic from GUBER_LOAD_SEED.

Exit status: 0 when the scenario's merged-ledger verdict passed,
1 when an assertion failed (the run is a proof artifact — latency is
only reported alongside its proven admission bound).
"""
from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from ..core.config import load_config_from_env
    from ..loadgen import SCENARIOS, run_scenario

    env = load_config_from_env()
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--scenario", default=env.scenario,
                    help=f"one of {sorted(SCENARIOS)} "
                    "(GUBER_LOAD_SCENARIO)")
    ap.add_argument("--seed", type=int, default=env.seed,
                    help="schedule seed (GUBER_LOAD_SEED)")
    ap.add_argument("--duration", type=float, default=env.duration_s,
                    help="total run seconds (GUBER_LOAD_DURATION)")
    ap.add_argument("--clients", type=int, default=env.clients,
                    help="client connection fan-out "
                    "(GUBER_LOAD_CLIENTS)")
    ap.add_argument("--target-rps", type=float, default=env.target_rps,
                    help="peak arrival rate (GUBER_LOAD_TARGET_RPS)")
    ap.add_argument("--addresses", default="",
                    help="comma-separated external daemon addresses "
                    "(default: boot an in-process cluster)")
    ap.add_argument("--daemons", type=int, default=2,
                    help="in-process cluster size (ignored with "
                    "--addresses)")
    ap.add_argument("--out", default="",
                    help="artifact path (default "
                    "BENCH_LOAD_<scenario>.json)")
    ap.add_argument("--profile-dir", default="",
                    help="time-boxed jax.profiler captures at marked "
                    "phase boundaries land here (off when empty)")
    ap.add_argument("--list", action="store_true",
                    help="list scenarios and exit")
    args = ap.parse_args(argv)

    if args.list:
        for name in sorted(SCENARIOS):
            print(f"{name:<18} {SCENARIOS[name].description}")
        return 0

    from ..core.config import LoadConfig

    cfg = LoadConfig(
        seed=args.seed, scenario=args.scenario,
        duration_s=args.duration, clients=args.clients,
        target_rps=args.target_rps,
    )
    addresses = [a for a in args.addresses.split(",") if a]
    try:
        result = run_scenario(
            cfg.scenario, cfg,
            addresses=addresses or None,
            profile_dir=args.profile_dir or None,
            num_daemons=args.daemons,
        )
    except AssertionError as e:
        print(f"gubload: VERDICT FAILED: {e}", file=sys.stderr)
        return 1

    artifact = result["artifact"]
    for row in artifact["results"]:
        print(json.dumps(row), flush=True)
    out = args.out or f"BENCH_LOAD_{cfg.scenario}.json"
    with open(out, "w") as f:
        json.dump(artifact, f, indent=1)
    print(
        f"gubload: {cfg.scenario} OK (seed={cfg.seed}): verdict "
        f"proven, artifact -> {out}",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
