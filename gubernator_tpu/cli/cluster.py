"""Local dev cluster CLI (reference cmd/gubernator-cluster/main.go:29-56).

Spawns a 6-node in-process cluster on fixed localhost ports for client
development, and serves until interrupted.
"""
from __future__ import annotations

import argparse
import asyncio
import logging
import signal

from gubernator_tpu.core.config import (
    DaemonConfig,
    DeviceConfig,
    fast_test_behaviors,
)
from gubernator_tpu.core.types import PeerInfo
from gubernator_tpu.daemon import Daemon

BASE_GRPC = 9990
BASE_HTTP = 9980


async def run(n: int) -> None:
    daemons = []
    for i in range(n):
        conf = DaemonConfig(
            grpc_listen_address=f"127.0.0.1:{BASE_GRPC + i}",
            http_listen_address=f"127.0.0.1:{BASE_HTTP + i}",
            behaviors=fast_test_behaviors(),
            device=DeviceConfig(num_slots=65_536, batch_size=1024),
        )
        d = Daemon(conf)
        await d.start()
        d.conf.advertise_address = d.grpc_address
        daemons.append(d)
    peers = [
        PeerInfo(grpc_address=d.grpc_address, http_address=d.http_address)
        for d in daemons
    ]
    for d in daemons:
        await d.set_peers(peers)
    print("cluster ready:")
    for d in daemons:
        print(f"  grpc={d.grpc_address}  http={d.http_address}")

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    for d in daemons:
        await d.close()


def main() -> None:
    p = argparse.ArgumentParser(description="local gubernator-tpu cluster")
    p.add_argument("--nodes", type=int, default=6)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    asyncio.run(run(args.nodes))


if __name__ == "__main__":
    main()
