"""In-process multi-daemon cluster fixture.

The analog of the reference's cluster package (cluster/cluster.go:31-155):
N real daemons in one process on localhost ephemeral ports, every daemon told
about all peers, real gRPC between them — "multi-node without a cluster".

All daemons share ONE asyncio loop running on a background thread; the
fixture exposes a synchronous facade (run/stop/restart) so plain pytest
tests can drive it.  Sharing a loop also shares the process's single JAX
backend — each daemon gets its own slot table on the same device, like the
reference daemons each owning a private cache in one test process.
"""
from __future__ import annotations

import asyncio
import random
import threading
from dataclasses import replace
from typing import Awaitable, List, Optional, Sequence, TypeVar

from gubernator_tpu.core.config import (
    DaemonConfig,
    DeviceConfig,
    fast_test_behaviors,
)
from gubernator_tpu.core.types import PeerInfo
from gubernator_tpu.daemon import Daemon, wait_for_connect

T = TypeVar("T")

# Small tables keep per-daemon XLA compiles fast in tests.
TEST_DEVICE = DeviceConfig(num_slots=4096, ways=8, batch_size=128)


class Cluster:
    """A running in-process cluster."""

    def __init__(self) -> None:
        self.daemons: List[Daemon] = []
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop.run_forever, name="cluster-loop", daemon=True
        )
        self._thread.start()

    # -- sync facade -----------------------------------------------------
    def run(self, coro: Awaitable[T], timeout: float = 60.0) -> T:
        """Run a coroutine on the cluster loop from test code."""
        return asyncio.run_coroutine_threadsafe(
            coro, self._loop
        ).result(timeout)

    # -- lifecycle (cluster.go:83-155) ------------------------------------
    @classmethod
    def start(cls, num_instances: int, **kwargs) -> "Cluster":
        """Start N daemons in the default datacenter (cluster.Start)."""
        return cls.start_with([""] * num_instances, **kwargs)

    @classmethod
    def start_with(
        cls,
        datacenters: Sequence[str],
        device: Optional[DeviceConfig] = None,
        conf_template: Optional[DaemonConfig] = None,
    ) -> "Cluster":
        """Start one daemon per entry of `datacenters`
        (cluster.StartWith, cluster/cluster.go:111-146)."""
        c = cls()

        async def boot() -> None:
            for dc in datacenters:
                base = conf_template or DaemonConfig()
                conf = replace(
                    base,
                    grpc_listen_address="127.0.0.1:0",
                    http_listen_address="127.0.0.1:0",
                    data_center=dc,
                    behaviors=fast_test_behaviors(),
                    device=device or TEST_DEVICE,
                )
                d = Daemon(conf)
                await d.start()
                d.conf.advertise_address = d.grpc_address
                c.daemons.append(d)
            await c._push_peers()
            await wait_for_connect([d.grpc_address for d in c.daemons])

        c.run(boot(), timeout=300.0)
        return c

    async def _push_peers(self) -> None:
        peers = [
            PeerInfo(
                grpc_address=d.grpc_address,
                http_address=d.http_address,
                data_center=d.conf.data_center,
            )
            for d in self.daemons
        ]
        for d in self.daemons:
            await d.set_peers(peers)

    def stop(self) -> None:
        async def shutdown() -> None:
            for d in self.daemons:
                await d.close()
            # Cancel anything a daemon left behind (a coalescer or
            # batcher task parked on queue.get) BEFORE the loop closes —
            # a pending queue getter GC'd after close raises an
            # unraisable "Event loop is closed" from its callback.
            rest = [
                t for t in asyncio.all_tasks()
                if t is not asyncio.current_task()
            ]
            for t in rest:
                t.cancel()
            await asyncio.gather(*rest, return_exceptions=True)

        self.run(shutdown(), timeout=120.0)
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=10.0)
        self._loop.close()

    # -- accessors (cluster.go:41-108) ------------------------------------
    def addresses(self) -> List[str]:
        return [d.grpc_address for d in self.daemons]

    def daemon_at(self, idx: int) -> Daemon:
        return self.daemons[idx]

    def peer_at(self, idx: int) -> PeerInfo:
        d = self.daemons[idx]
        return PeerInfo(
            grpc_address=d.grpc_address,
            http_address=d.http_address,
            data_center=d.conf.data_center,
        )

    def get_random_peer(self, data_center: str = "") -> PeerInfo:
        cands = [
            self.peer_at(i)
            for i, d in enumerate(self.daemons)
            if d.conf.data_center == data_center
        ]
        return random.choice(cands)

    def owner_daemon_of(self, key: str) -> Daemon:
        """The daemon owning `key` (per daemon 0's picker — all agree)."""
        peer = self.daemons[0].service.get_peer(key)
        addr = peer.info().grpc_address
        for d in self.daemons:
            if d.grpc_address == addr:
                return d
        raise KeyError(addr)

    def breaker_states(self) -> dict:
        """{daemon addr: {peer addr: circuit state name}} — the chaos
        tests' "every opened breaker re-closed after heal" probe."""
        out: dict = {}
        for d in self.daemons:
            if d.service is None:
                continue
            out[d.grpc_address] = {
                p.info().grpc_address: p.circuit_state_name()
                for p in d.service.peer_list()
                if not p.info().is_owner
            }
        return out

    def kill(self, idx: int) -> None:
        """Hard-stop one daemon, keeping its slot in the list
        (functional_test.go:1063-1071 kills daemons for health tests)."""
        d = self.daemons[idx]
        self.run(d.close(), timeout=60.0)

    def restart(self, idx: int) -> Daemon:
        """Restart daemon `idx` on its old address
        (cluster.Restart, cluster/cluster.go:99-108)."""
        old = self.daemons[idx]

        async def boot() -> Daemon:
            try:
                await old.close()
            except Exception:  # noqa: BLE001 — may already be dead
                pass
            conf = replace(
                old.conf,
                grpc_listen_address=old.grpc_address,
                http_listen_address=old.http_address,
            )
            d = Daemon(conf)
            await d.start()
            d.conf.advertise_address = d.grpc_address
            self.daemons[idx] = d
            await self._push_peers()
            return d

        return self.run(boot(), timeout=300.0)
