"""Chaos plane: deterministic, seedable fault injection for the cluster.

Nothing in the repo could previously *inject* a peer failure, so the
retry-safety logic in net/peer_client.py (`provably_unsent`, the
ownership-retry loop, the GLOBAL requeue-vs-drop split) was exercised
only by whatever faults the OS happened to produce.  This module makes
fault sequences a first-class, reproducible test input:

* **Client boundary** — `PeerClient` awaits `chaos.on_client(dst,
  method)` immediately before issuing each outbound RPC.  A firing rule
  delays the call, or raises a REAL `grpc.aio.AioRpcError` with a
  chosen status code and detail text, so every existing error-handling
  path (status-code conversion, marker-string classification, breaker
  feed) runs exactly as it would on a production failure.  Faults
  raised here are genuinely *unsent* — the RPC was never issued — which
  is what makes `provably_unsent`-gated retries assertable: a plan of
  client-side faults must produce ZERO double counts.

* **Daemon boundary** — `ChaosServerInterceptor` wraps every unary
  handler.  `phase="before"` rules abort the RPC before the handler
  runs (the request was delivered but never applied); `phase="after"`
  rules run the handler — hits ARE applied — then fail the RPC anyway:
  the delivered-but-unanswered window that makes blind retries double
  count.

* **Partition** — `injector.partition(group_a, group_b, ...)` makes
  every cross-group client call fail with UNAVAILABLE and a
  connect-phase marker ("failed to connect"), honestly: the fault fires
  before the RPC is issued, so classifying it retry-safe is correct.
  `injector.heal()` lifts the partition and deactivates all rules.

* **Kill/restart** — daemon lifecycle faults ride the existing
  `Cluster.kill` / `Cluster.restart` (testing/cluster.py).

Determinism: every probabilistic decision draws from a PRNG seeded with
`(plan.seed, rule index, src, dst, per-pair call counter)` — the
decision SEQUENCE for each (rule, src, dst) pair is a pure function of
the plan seed, independent of event-loop interleaving across runs.

Wiring: `DaemonConfig.chaos` takes a pre-built injector (the in-process
cluster fixture path); `GUBER_CHAOS_PLAN` points a real daemon at a
JSON plan file (`GUBER_CHAOS_SEED` > 0 overrides the plan's seed) —
see docs/resilience.md for the plan format.
"""
from __future__ import annotations

import asyncio
import collections
import fnmatch
import json
import random
from dataclasses import dataclass, field
from typing import Callable, Dict, FrozenSet, List, Optional, Tuple

import grpc
import grpc.aio


def zipf_keys(seed: int, s: float, n: int, universe: int):
    """Seeded zipfian key indices for storm scenarios: `n` draws over
    `[0, universe)` with exponent `s` (rank-frequency skew; s ~ 1.1-1.5
    models production key popularity).  Deterministic from the seed —
    the same discipline as the fault plans, so a hot-key overload
    scenario reproduces from (seed, s) alone.  Used by
    scripts/chaos_smoke.py and the bench_e2e --workload zipf:<s>
    config."""
    import numpy as np

    rng = np.random.default_rng(seed)
    return (rng.zipf(s, size=n) - 1) % universe


def injected_rpc_error(
    status: str, message: str, debug: Optional[str] = None
) -> grpc.aio.AioRpcError:
    """A real AioRpcError (not a stand-in): it must flow through the
    same isinstance checks, status-code conversions and marker-string
    classification as an organic failure."""
    return grpc.aio.AioRpcError(
        getattr(grpc.StatusCode, status),
        None,  # initial_metadata
        None,  # trailing_metadata
        details=message,
        debug_error_string=debug if debug is not None else message,
    )


@dataclass
class Rule:
    """One fault rule.  Patterns are fnmatch globs over peer addresses
    (`target` = RPC destination, `source` = calling daemon — client
    side only) and the short method name (e.g. "GetPeerRateLimits")."""

    op: str  # "error" | "delay" | "drop"
    where: str = "client"  # "client" | "server"
    phase: str = "before"  # server side: "before" | "after" the handler
    method: str = "*"
    target: str = "*"
    source: str = "*"
    probability: float = 1.0
    status: str = "UNAVAILABLE"  # grpc.StatusCode name
    message: str = "injected fault"
    delay_s: float = 0.05  # delay op; also the hang before a drop fails
    max_count: int = 0  # 0 = unlimited firings

    def __post_init__(self) -> None:
        if self.op not in ("error", "delay", "drop"):
            raise ValueError(f"unknown chaos op {self.op!r}")
        if self.where not in ("client", "server"):
            raise ValueError(f"unknown chaos where {self.where!r}")
        if self.phase not in ("before", "after"):
            raise ValueError(f"unknown chaos phase {self.phase!r}")
        getattr(grpc.StatusCode, self.status)  # fail fast on a typo


@dataclass
class ChaosPlan:
    """A seed plus an ordered rule list — the whole fault schedule."""

    seed: int = 0
    rules: List[Rule] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: dict) -> "ChaosPlan":
        return cls(
            seed=int(d.get("seed", 0)),
            rules=[Rule(**r) for r in d.get("rules", [])],
        )

    @classmethod
    def from_file(cls, path: str) -> "ChaosPlan":
        with open(path, encoding="utf-8") as f:
            return cls.from_dict(json.load(f))


def load_plan(path: str, seed_override: Optional[int] = None) -> ChaosPlan:
    """GUBER_CHAOS_PLAN entry point (GUBER_CHAOS_SEED overrides)."""
    plan = ChaosPlan.from_file(path)
    if seed_override is not None:
        plan.seed = seed_override
    return plan


class ChaosInjector:
    """Shared across every daemon of a cluster (one fault schedule, one
    partition view).  All state is touched from the cluster's single
    event loop — no locks, nothing for the gubguard ranking to order."""

    def __init__(self, plan: Optional[ChaosPlan] = None) -> None:
        self.plan = plan or ChaosPlan()
        self.active = True
        self._groups: List[FrozenSet[str]] = []
        # (rule idx, src, dst) -> decisions drawn so far: the counter
        # that makes per-pair decision sequences deterministic.
        self._draws: Dict[Tuple[int, str, str], int] = {}
        self._fired: Dict[int, int] = collections.defaultdict(int)
        self.injected: Dict[str, int] = collections.defaultdict(int)
        self.attempts: Dict[str, int] = collections.defaultdict(int)

    # -- control ---------------------------------------------------------
    def partition(self, *groups) -> None:
        """Partition the cluster into address groups; cross-group client
        calls fail as never-connected (retry-safe by construction)."""
        self._groups = [frozenset(g) for g in groups]

    def heal(self) -> None:
        """Lift the partition and deactivate every rule — the cluster is
        whole again; breakers may now re-close."""
        self._groups = []
        self.active = False

    def set_active(self, active: bool) -> None:
        self.active = active

    def reset(self, plan: Optional[ChaosPlan] = None) -> None:
        """Fresh schedule (tests reuse one injector across scenarios):
        install `plan` (activating it) or just clear partition, draw
        counters and accounting."""
        if plan is not None:
            self.plan = plan
            self.active = True
        self._groups = []
        self._draws.clear()
        self._fired.clear()
        self.injected.clear()
        self.attempts.clear()

    def bind(self, src: str) -> "BoundChaos":
        """Per-daemon handle carrying the caller's address (PeerClient
        doesn't know which daemon owns it)."""
        return BoundChaos(self, src)

    # -- accounting ------------------------------------------------------
    def failure_fraction(self) -> float:
        """Injected hard failures / outbound RPC attempts observed."""
        att = self.attempts.get("client", 0)
        if att == 0:
            return 0.0
        fails = (
            self.injected.get("client_error", 0)
            + self.injected.get("client_drop", 0)
            + self.injected.get("partition", 0)
            + self.injected.get("server_before", 0)
            + self.injected.get("server_after", 0)
        )
        return fails / att

    # -- decisions -------------------------------------------------------
    def _partitioned(self, src: str, dst: str) -> bool:
        if not self._groups or src == dst:
            return False
        for g in self._groups:
            if src in g:
                return dst not in g
        return False  # src outside every group: unaffected

    def _fires(self, idx: int, rule: Rule, src: str, dst: str) -> bool:
        if rule.max_count and self._fired[idx] >= rule.max_count:
            return False
        key = (idx, src, dst)
        n = self._draws.get(key, 0)
        self._draws[key] = n + 1
        if rule.probability >= 1.0:
            fired = True
        else:
            # Seeding with a string hashes via sha512 — stable across
            # processes (unlike hash(), which is salted per run).
            r = random.Random(
                f"{self.plan.seed}/{idx}/{src}/{dst}/{n}"
            )
            fired = r.random() < rule.probability
        if fired:
            self._fired[idx] += 1
        return fired

    def _match_client(
        self, rule: Rule, src: str, dst: str, method: str
    ) -> bool:
        return (
            rule.where == "client"
            and fnmatch.fnmatch(src, rule.source)
            and fnmatch.fnmatch(dst, rule.target)
            and fnmatch.fnmatch(method, rule.method)
        )

    # -- client boundary -------------------------------------------------
    async def on_client(self, src: str, dst: str, method: str) -> None:
        """Awaited by PeerClient immediately before each outbound RPC.
        May sleep (delay) or raise an AioRpcError (error/drop/partition).
        Faults raised here are genuinely unsent."""
        self.attempts["client"] += 1
        if not self.active and not self._groups:
            return
        if self._partitioned(src, dst):
            self.injected["partition"] += 1
            raise injected_rpc_error(
                "UNAVAILABLE",
                f"injected partition: failed to connect to {dst}",
            )
        if not self.active:
            return
        for idx, rule in enumerate(self.plan.rules):
            if not self._match_client(rule, src, dst, method):
                continue
            if not self._fires(idx, rule, src, dst):
                continue
            if rule.op == "delay":
                self.injected["client_delay"] += 1
                await asyncio.sleep(rule.delay_s)
                continue  # later rules may still fire
            if rule.op == "drop":
                self.injected["client_drop"] += 1
                await asyncio.sleep(rule.delay_s)
                raise injected_rpc_error(
                    "DEADLINE_EXCEEDED",
                    f"injected drop: Deadline Exceeded ({method})",
                )
            self.injected["client_error"] += 1
            raise injected_rpc_error(rule.status, rule.message)

    # -- server boundary -------------------------------------------------
    def server_rule(
        self, dst: str, method: str, phase: str
    ) -> Optional[Rule]:
        """First firing server-side rule for this RPC, or None.  Split
        by phase so the interceptor checks "before" ahead of the handler
        and "after" behind it."""
        if not self.active:
            return None
        for idx, rule in enumerate(self.plan.rules):
            if rule.where != "server" or rule.phase != phase:
                continue
            if not fnmatch.fnmatch(dst, rule.target):
                continue
            if not fnmatch.fnmatch(method, rule.method):
                continue
            if self._fires(idx, rule, "server", dst):
                self.injected[f"server_{phase}"] += 1
                return rule
        return None


class BoundChaos:
    """A daemon-local handle: (injector, this daemon's address)."""

    def __init__(self, injector: ChaosInjector, src: str) -> None:
        self.injector = injector
        self.src = src

    async def on_client(self, dst: str, method: str) -> None:
        await self.injector.on_client(self.src, dst, method)


class ChaosServerInterceptor(grpc.aio.ServerInterceptor):
    """The daemon-boundary injection point.  `addr_fn` resolves this
    daemon's address lazily — interceptors are built before the
    ephemeral port is bound."""

    def __init__(
        self, injector: ChaosInjector, addr_fn: Callable[[], str]
    ) -> None:
        self.injector = injector
        self.addr_fn = addr_fn

    async def intercept_service(self, continuation, handler_call_details):
        handler = await continuation(handler_call_details)
        if handler is None or handler.unary_unary is None:
            return handler
        method = handler_call_details.method.rsplit("/", 1)[-1]
        inner = handler.unary_unary
        inj = self.injector
        addr_fn = self.addr_fn

        async def wrapped(request, context):
            inj.attempts["server"] += 1
            rule = inj.server_rule(addr_fn(), method, "before")
            if rule is not None:
                if rule.op == "delay":
                    await asyncio.sleep(rule.delay_s)
                else:
                    # Rejected BEFORE the handler: nothing was applied.
                    await context.abort(
                        getattr(grpc.StatusCode, rule.status),
                        f"{rule.message} (before {method})",
                    )
            out = await inner(request, context)
            rule = inj.server_rule(addr_fn(), method, "after")
            if rule is not None and rule.op != "delay":
                # The handler RAN — hits were applied — and the caller
                # sees a failure anyway: the delivered-but-unanswered
                # window.  A client that blind-retries this double
                # counts; provably_unsent must classify it unsafe.
                await context.abort(
                    getattr(grpc.StatusCode, rule.status),
                    f"{rule.message} (after {method})",
                )
            return out

        return grpc.unary_unary_rpc_method_handler(
            wrapped,
            request_deserializer=handler.request_deserializer,
            response_serializer=handler.response_serializer,
        )
