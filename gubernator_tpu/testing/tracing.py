"""In-memory span exporter + install helper for tracing tests.

The attribution plane (runtime/tracing.py) needs no collector to be
assertable: `MemorySpanExporter` receives every finished sampled span,
and `memory_tracing()` arms the plane around a test body and disarms it
after — span-TREE shape (parents, links, attributes like the ring's
sequence word) is then plain-python assertable.

Because the in-process cluster fixture (testing/cluster.py) runs every
daemon in one process, a single exporter observes the spans of ALL
daemons — which is exactly what a "one trace spans the cluster"
assertion needs (scripts/trace_smoke.py, tests/test_tracing.py).
"""
from __future__ import annotations

import contextlib
import threading
from typing import Dict, Iterator, List, Optional

from gubernator_tpu.runtime.tracing import (
    Span,
    init_tracing,
    shutdown_tracing,
)


class MemorySpanExporter:
    """Collects finished spans; thread-safe (spans finish on the event
    loop, pool workers, and the ring runner alike)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._spans: List[Span] = []

    # -- exporter interface ----------------------------------------------
    def export(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)

    # -- assertions ------------------------------------------------------
    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def dicts(self) -> List[Dict]:
        return [sp.to_dict() for sp in self.spans()]

    def by_name(self, name: str) -> List[Span]:
        return [sp for sp in self.spans() if sp.name == name]

    def trace_ids(self) -> List[str]:
        """Distinct trace ids, in first-finish order."""
        seen: List[str] = []
        for sp in self.spans():
            tid = sp.context.trace_id_hex()
            if tid not in seen:
                seen.append(tid)
        return seen

    def spans_for_trace(self, trace_id_hex: str) -> List[Span]:
        return [
            sp for sp in self.spans()
            if sp.context.trace_id_hex() == trace_id_hex
        ]

    def find(self, span_id: int) -> Optional[Span]:
        for sp in self.spans():
            if sp.context.span_id == span_id:
                return sp
        return None

    def children_of(self, span: Span) -> List[Span]:
        return [
            sp for sp in self.spans()
            if sp.parent_id == span.context.span_id
        ]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)


@contextlib.contextmanager
def memory_tracing(
    sampler: str = "always_on",
    service_name: str = "gubernator-tpu-test",
    sampler_arg=None,
) -> Iterator[MemorySpanExporter]:
    """Arm tracing with a fresh MemorySpanExporter for the with-body,
    then disarm — the disabled default is restored even on failure, so
    one test's tracing never leaks into the next."""
    exporter = MemorySpanExporter()
    init_tracing(
        service_name=service_name,
        exporter=exporter,
        sampler=sampler,
        sampler_arg=sampler_arg,
    )
    try:
        yield exporter
    finally:
        shutdown_tracing()
