"""raceguard: runtime lock-order + event-loop-stall detection for tests.

The static half (tools/gubguard) proves the LEXICAL lock nesting is
consistent; this pytest plugin catches what static analysis cannot — a
callee taking a lock while its caller holds another, across await
points, on the real asyncio locks under the real test workloads (the
functional cluster tests drive every serving path).

Two detectors, armed for the whole pytest session:

* **lock order** — `asyncio.Lock.acquire` is wrapped to maintain a
  per-task held-set and a global acquisition graph over lock
  *instances*.  An edge A->B is recorded when B is acquired while A is
  held; a new edge that closes a cycle is an inversion — two tasks
  interleaving those paths can deadlock — and FAILS the test that
  produced it.  Lock identity includes its creation site
  (`Lock.__init__` is wrapped too), so reports point at code, not ids.

* **event-loop stalls** — `asyncio.events.Handle._run` is timed; any
  single callback over ``GUBGUARD_STALL_MS`` (default 50) is recorded.
  One stray host fetch on the loop costs 70-300ms through the device
  tunnel, so stalls are the runtime shadow of the host-sync checker.
  Stalls are reported in the terminal summary (not failed: CI timing
  jitter would flap) — treat a growing stall list as a regression.

Arming: the plugin registers via ``pytest_plugins`` in tests/conftest.py
and is on by default; set ``GUBGUARD_RACE=0`` to disarm.
"""
from __future__ import annotations

import asyncio
import itertools
import os
import time
import traceback
from typing import Dict, List, Optional, Set, Tuple

import pytest

_STALL_MS_ENV = "GUBGUARD_STALL_MS"
_DISARM_ENV = "GUBGUARD_RACE"


class LockOrderGraph:
    """Acquisition-order graph over lock instances with incremental
    cycle detection.  Pure data structure — unit-testable without
    patching anything."""

    def __init__(self) -> None:
        self.edges: Dict[int, Set[int]] = {}
        self.labels: Dict[int, str] = {}
        self.inversions: List[str] = []

    def label(self, lock_id: int, label: str) -> None:
        self.labels[lock_id] = label

    def _name(self, lock_id: int) -> str:
        return self.labels.get(lock_id, f"<lock {lock_id:#x}>")

    def record(self, held_id: int, acquired_id: int, context: str = "") -> bool:
        """Record edge held->acquired; returns True (and logs an
        inversion) if the edge closes a cycle."""
        if held_id == acquired_id:
            return False
        succ = self.edges.setdefault(held_id, set())
        if acquired_id in succ:
            return False
        if self._reaches(acquired_id, held_id):
            path = self._path(acquired_id, held_id) or [
                acquired_id, held_id
            ]
            cycle = " -> ".join(self._name(n) for n in path + [acquired_id])
            self.inversions.append(
                f"lock-order inversion: acquiring {self._name(acquired_id)} "
                f"while holding {self._name(held_id)}, but the reverse "
                f"order exists: {cycle}"
                + (f"\n  at: {context}" if context else "")
            )
            succ.add(acquired_id)  # record anyway; report once
            return True
        succ.add(acquired_id)
        return False

    def _reaches(self, src: int, dst: int) -> bool:
        seen: Set[int] = set()
        stack = [src]
        while stack:
            n = stack.pop()
            if n == dst:
                return True
            if n in seen:
                continue
            seen.add(n)
            stack.extend(self.edges.get(n, ()))
        return False

    def _path(self, src: int, dst: int) -> Optional[List[int]]:
        stack: List[Tuple[int, List[int]]] = [(src, [src])]
        seen: Set[int] = set()
        while stack:
            n, path = stack.pop()
            if n == dst:
                return path
            if n in seen:
                continue
            seen.add(n)
            for m in self.edges.get(n, ()):
                stack.append((m, path + [m]))
        return None


class RaceGuard:
    """The armed detector: asyncio.Lock + Handle patches and their
    recorded evidence."""

    def __init__(self, stall_ms: float = 50.0) -> None:
        self.graph = LockOrderGraph()
        self.stall_ms = stall_ms
        self.stalls: List[str] = []
        self.max_stall_ms = 0.0
        # task id -> stack of held lock tokens (a task dies with its
        # locks released through our release wrapper, so no weakrefs
        # needed).
        self._held: Dict[int, List[int]] = {}
        # Lock identity: a monotonic token stamped at creation.  id()
        # would be recycled after gc and chain edges across unrelated
        # locks — a false-inversion source.
        self._tokens = itertools.count(1)
        self._armed = False
        self._saved: Dict[str, object] = {}

    def _token(self, lock) -> int:
        tok = getattr(lock, "_raceguard_token", None)
        if tok is None:
            # Lock created before arming: stamp lazily (the object is
            # alive right now, so the token is unique from here on).
            tok = next(self._tokens)
            try:
                lock._raceguard_token = tok
            except AttributeError:
                return id(lock)
        return tok

    # -- arming ----------------------------------------------------------
    def arm(self) -> None:
        if self._armed:
            return
        self._armed = True
        guard = self

        self._saved["lock_init"] = asyncio.Lock.__init__
        self._saved["lock_acquire"] = asyncio.Lock.acquire
        self._saved["lock_release"] = asyncio.Lock.release
        self._saved["handle_run"] = asyncio.events.Handle._run

        lock_init = asyncio.Lock.__init__
        lock_acquire = asyncio.Lock.acquire
        lock_release = asyncio.Lock.release
        handle_run = asyncio.events.Handle._run

        def init(self, *a, **kw):
            lock_init(self, *a, **kw)
            guard.graph.label(guard._token(self), _creation_site())

        async def acquire(self):
            task = asyncio.current_task()
            tid = id(task)
            tok = guard._token(self)
            held = guard._held.get(tid)
            if held:
                ctx = _call_site()
                for h in held:
                    guard.graph.record(h, tok, ctx)
            ok = await lock_acquire(self)
            guard._held.setdefault(tid, []).append(tok)
            return ok

        def release(self):
            task = asyncio.current_task()
            tok = guard._token(self)
            held = guard._held.get(id(task))
            if held and tok in held:
                held.remove(tok)
                if not held:
                    guard._held.pop(id(task), None)
            return lock_release(self)

        def timed_run(self):
            t0 = time.perf_counter()
            try:
                return handle_run(self)
            finally:
                dt_ms = (time.perf_counter() - t0) * 1e3
                if dt_ms > guard.stall_ms:
                    guard.max_stall_ms = max(guard.max_stall_ms, dt_ms)
                    if len(guard.stalls) < 50:
                        guard.stalls.append(
                            f"{dt_ms:.1f}ms in {self!r}"
                        )

        asyncio.Lock.__init__ = init  # type: ignore[method-assign]
        asyncio.Lock.acquire = acquire  # type: ignore[method-assign]
        asyncio.Lock.release = release  # type: ignore[method-assign]
        asyncio.events.Handle._run = timed_run  # type: ignore[method-assign]

    def disarm(self) -> None:
        if not self._armed:
            return
        asyncio.Lock.__init__ = self._saved["lock_init"]  # type: ignore
        asyncio.Lock.acquire = self._saved["lock_acquire"]  # type: ignore
        asyncio.Lock.release = self._saved["lock_release"]  # type: ignore
        asyncio.events.Handle._run = self._saved["handle_run"]  # type: ignore
        self._armed = False


def _creation_site() -> str:
    for frame in reversed(traceback.extract_stack(limit=8)[:-2]):
        if "raceguard" not in frame.filename and "asyncio" not in (
            frame.filename
        ):
            return f"Lock({frame.filename}:{frame.lineno})"
    return "Lock(?)"


def _call_site() -> str:
    for frame in reversed(traceback.extract_stack(limit=8)[:-2]):
        if "raceguard" not in frame.filename and "asyncio" not in (
            frame.filename
        ):
            return f"{frame.filename}:{frame.lineno}"
    return "?"


_guard: Optional[RaceGuard] = None


def active_guard() -> Optional[RaceGuard]:
    return _guard


# -- pytest hooks --------------------------------------------------------
def pytest_configure(config) -> None:
    global _guard
    if os.environ.get(_DISARM_ENV, "1") == "0":
        return
    _guard = RaceGuard(
        stall_ms=float(os.environ.get(_STALL_MS_ENV, "50"))
    )
    _guard.arm()


def pytest_unconfigure(config) -> None:
    global _guard
    if _guard is not None:
        _guard.disarm()
        _guard = None


@pytest.hookimpl(wrapper=True)
def pytest_runtest_makereport(item, call):
    report = yield
    if _guard is None or call.when != "call":
        return report
    count = getattr(item, "_raceguard_seen", 0)
    new = _guard.graph.inversions[count:]
    item._raceguard_seen = len(_guard.graph.inversions)
    if new:
        report.outcome = "failed"
        report.longrepr = (
            "raceguard detected lock-order inversion(s) during this "
            "test:\n" + "\n".join(new)
        )
    return report


def pytest_runtest_setup(item) -> None:
    # Snapshot BEFORE the test body so fixture-time inversions count too.
    if _guard is not None and not hasattr(item, "_raceguard_seen"):
        item._raceguard_seen = len(_guard.graph.inversions)


def pytest_terminal_summary(terminalreporter) -> None:
    if _guard is None:
        return
    tr = terminalreporter
    n_edges = sum(len(v) for v in _guard.graph.edges.values())
    tr.write_sep("-", "raceguard")
    tr.write_line(
        f"raceguard: {n_edges} lock-order edge(s) observed, "
        f"{len(_guard.graph.inversions)} inversion(s), "
        f"{len(_guard.stalls)} event-loop stall(s) "
        f"> {_guard.stall_ms:.0f}ms"
        + (
            f" (max {_guard.max_stall_ms:.0f}ms)"
            if _guard.stalls else ""
        )
    )
    for s in _guard.stalls[:10]:
        tr.write_line(f"  stall: {s}")
    for inv in _guard.graph.inversions:
        tr.write_line(f"  {inv}")
