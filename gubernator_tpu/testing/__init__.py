"""Test infrastructure: the in-process multi-daemon cluster fixture and
the chaos plane (deterministic fault injection, testing/chaos.py)."""
from gubernator_tpu.testing.chaos import (  # noqa: F401
    ChaosInjector,
    ChaosPlan,
    Rule,
    zipf_keys,
)
from gubernator_tpu.testing.cluster import Cluster  # noqa: F401
