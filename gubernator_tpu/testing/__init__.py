"""Test infrastructure: the in-process multi-daemon cluster fixture."""
from gubernator_tpu.testing.cluster import Cluster  # noqa: F401
