"""Mesh-ring CPU smoke: mesh ring vs mesh classic equivalence + zero
request-path fetches over an 8-virtual-device mesh.

The mesh edition of scripts/ring_smoke.py (PR 9 acceptance): ~10k mixed
checks (token/leaky, bursts, RESET_REMAINING, valid Gregorian,
zero/negative hits, duplicate keys, a GLOBAL slice with per-key-constant
params served by the collective GlobalEngine) through the compiled fast
lane twice on an 8-shard MeshBackend — once at GUBER_SERVE_MODE=classic
and once in ring mode — under a frozen clock with a quiesced collective
sync cadence (a mid-run sync makes GLOBAL reads stale BY CONTRACT,
which would inject schedule noise into the comparison; sync equivalence
is pinned by the psum-vs-broadcast differential).  Pass criteria:

  1. responses and final table rows bit-identical across modes;
  2. the ring run performed ZERO blocking device->host fetches on the
     request path — machinery, sketch, AND engine lanes (the mesh
     GLOBAL readback rides the ring runner as a host job);
  3. the mesh ring actually iterated, every shard's sequence word
     agreed with the host mirror (0 mismatches), and per-shard
     occupancy is reported and consistent with the aggregate.

On failure the armed flight recorder's ring is dumped to
mesh-smoke-dumps/ for the CI artifact.  Runs in the CI matrix
(JAX_PLATFORMS=cpu + 8 virtual devices); exit 0 = pass.
"""
from __future__ import annotations

import asyncio
import json
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

N_SHARDS = 8
N_WORKERS = 6
BATCHES_PER_WORKER = 24
KEYS_PER_WORKER = 8  # k0..k5 exact mix, k6..k7 GLOBAL constant-param


def build_schedules():
    from gubernator_tpu.proto import gubernator_pb2 as pb

    rng = random.Random(4321)
    schedules = []
    total = 0
    for w in range(N_WORKERS):
        payloads = []
        for _ in range(BATCHES_PER_WORKER):
            reqs = []
            glob_used = set()
            for _ in range(rng.randrange(40, 90)):
                if rng.random() < 0.15 and len(glob_used) < 2:
                    # GLOBAL slice: constant params, at most ONE
                    # occurrence per key per payload — the collective
                    # engine aggregates intra-batch duplicates by
                    # design (parallel/global_sync.GlobalEngine.check).
                    k = 6 + rng.randrange(2)
                    if k in glob_used:
                        continue
                    glob_used.add(k)
                    reqs.append(pb.RateLimitReq(
                        name=f"msmoke{w}",
                        unique_key=f"k{k}",
                        hits=rng.choice([0, 1, 1, 2]),
                        limit=200 + 100 * (k % 2),
                        duration=60_000,
                        algorithm=k % 2,
                        behavior=2,  # GLOBAL
                        burst=250 if k % 2 == 0 else 0,
                    ))
                    continue
                behavior = 0
                duration = rng.choice([60_000, 60_000, 1_000])
                if rng.random() < 0.06:
                    behavior |= 8  # RESET_REMAINING
                if rng.random() < 0.04:
                    behavior |= 4  # DURATION_IS_GREGORIAN
                    duration = rng.choice([1, 4])
                reqs.append(pb.RateLimitReq(
                    name=f"msmoke{w}",
                    unique_key=f"k{rng.randrange(6)}",
                    hits=rng.choice([0, 1, 1, 1, 2, 5, -1]),
                    limit=rng.choice([50, 200, 1000]),
                    duration=duration,
                    algorithm=rng.choice([0, 1]),
                    behavior=behavior,
                    burst=rng.choice([0, 0, 60]),
                ))
            total += len(reqs)
            payloads.append(
                pb.GetRateLimitsReq(requests=reqs).SerializeToString()
            )
        schedules.append(payloads)
    return schedules, total


def run_mode(mode: str, schedules, clock):
    from gubernator_tpu.core.config import (
        BehaviorConfig,
        Config,
        DeviceConfig,
    )
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from gubernator_tpu.runtime.fastpath import FastPath
    from gubernator_tpu.runtime.flightrec import FlightRecorder
    from gubernator_tpu.runtime.metrics import Metrics
    from gubernator_tpu.runtime.service import Service

    dev = DeviceConfig(
        num_slots=N_SHARDS * 8 * 256, ways=8, batch_size=256,
        num_shards=N_SHARDS,
    )

    async def scenario():
        metrics = Metrics()
        fr = FlightRecorder(metrics=metrics, dump_dir="mesh-smoke-dumps")
        metrics.flightrec = fr
        fr.start()
        svc = Service(
            Config(
                device=dev,
                behaviors=BehaviorConfig(global_sync_wait_s=3600.0),
            ),
            clock=clock, metrics=metrics,
        )
        await svc.start()
        fp = FastPath(svc, serve_mode=mode, ring_slots=8)
        results: dict = {}

        async def worker(w: int):
            await asyncio.sleep(w * 0.002)
            got = []
            for payload in schedules[w]:
                raw = await fp.check_raw(payload, peer_rpc=False)
                assert raw is not None, "fast lane fell back"
                got.append([
                    (r.status, r.limit, r.remaining, r.reset_time, r.error)
                    for r in pb.GetRateLimitsResp.FromString(raw).responses
                ])
            results[w] = got

        await asyncio.gather(*(worker(w) for w in range(N_WORKERS)))
        rows = {}
        for w in range(N_WORKERS):
            for k in range(KEYS_PER_WORKER):
                key = f"msmoke{w}_k{k}"
                item = svc.backend.get_cache_item(key)
                rows[key] = (
                    (item.remaining, item.expire_at, int(item.status),
                     item.limit, item.duration, int(item.algorithm))
                    if item is not None else None
                )
        dv = fp.debug_vars()
        shard_occ = svc.backend.shard_occupancy()
        agg_occ = svc.backend.occupancy()
        snap = fr.snapshot()
        await fp.close()
        await svc.close()
        await fr.close()
        return results, rows, dv, shard_occ, agg_occ, snap

    return asyncio.run(scenario())


def main() -> int:
    from gubernator_tpu import native
    from gubernator_tpu.core import clock as clock_mod

    if not native.available():
        print("mesh_smoke: SKIP (native library unavailable)")
        return 0

    schedules, total = build_schedules()
    print(f"mesh_smoke: {total} checks x 2 serve modes on a "
          f"{N_SHARDS}-shard mesh")
    clock_mod.freeze()
    try:
        (base_results, base_rows, base_dv, base_shards, base_occ,
         base_snap) = run_mode(
            "classic", schedules, clock_mod.default_clock()
        )
        (ring_results, ring_rows, ring_dv, ring_shards, ring_occ,
         ring_snap) = run_mode(
            "ring", schedules, clock_mod.default_clock()
        )
    finally:
        clock_mod.unfreeze()

    ok = True
    if ring_results != base_results:
        for w in base_results:
            for i, (a, b) in enumerate(
                zip(base_results[w], ring_results[w])
            ):
                if a != b:
                    print(
                        f"FAIL: worker {w} batch {i} diverged:\n"
                        f"  classic: {a[:3]}...\n  ring: {b[:3]}..."
                    )
                    break
        ok = False
    if ring_rows != base_rows:
        diff = {
            k for k in base_rows if base_rows[k] != ring_rows.get(k)
        }
        print(f"FAIL: {len(diff)} table rows diverged: {sorted(diff)[:5]}")
        ok = False
    ring_stats = ring_dv.get("ring", {})
    blocking = ring_dv["blocking_fetches"]
    if ring_dv["effective_serve_mode"] != "ring":
        print(
            "FAIL: mesh service fell back to "
            f"{ring_dv['effective_serve_mode']!r} — the mesh must serve "
            "ring natively (docs/ring.md)"
        )
        ok = False
    if sum(blocking.values()) != 0:
        per_check = sum(blocking.values()) / float(total) if total else 0.0
        print(
            "FAIL: mesh ring mode performed blocking request-path "
            f"fetches: {blocking} ({per_check:.4f} per check; must be 0)"
        )
        ok = False
    if base_dv["blocking_fetches"]["mach"] == 0:
        print("FAIL: classic run counted no machinery fetches — the "
              "smoke's counter is broken/vacuous")
        ok = False
    if ring_stats.get("iterations", 0) < 1:
        print(f"FAIL: the mesh ring never iterated: {ring_stats}")
        ok = False
    if ring_stats.get("seq_mismatches", 0) != 0:
        print(f"FAIL: per-shard sequence-word mismatches: {ring_stats}")
        ok = False
    seq_shards = ring_stats.get("seq_shards", [])
    if len(seq_shards) != N_SHARDS or len(set(seq_shards)) != 1:
        print(f"FAIL: inconsistent per-shard seq words: {seq_shards}")
        ok = False
    if len(ring_shards) != N_SHARDS or sum(ring_shards) != ring_occ:
        print(
            f"FAIL: per-shard occupancy {ring_shards} does not sum to "
            f"the aggregate {ring_occ}"
        )
        ok = False
    print("mesh_smoke: classic stats "
          + json.dumps(base_dv["blocking_fetches"]))
    print("mesh_smoke: ring stats " + json.dumps(ring_stats))
    print("mesh_smoke: per-shard occupancy " + json.dumps(ring_shards))
    if ok:
        print(
            f"mesh_smoke: OK — {total} checks bit-identical across serve "
            f"modes on the {N_SHARDS}-shard mesh; ring ran "
            f"{ring_stats.get('iterations')} iterations + "
            f"{ring_stats.get('host_jobs')} host jobs with 0 blocking "
            "request-path fetches; per-shard seq consistent at "
            f"{seq_shards[:1] and seq_shards[0]}"
        )
    else:
        # Dump both runs' flight-recorder rings for the CI artifact.
        os.makedirs("mesh-smoke-dumps", exist_ok=True)
        with open("mesh-smoke-dumps/mesh_smoke_failure.json", "w") as f:
            json.dump({
                "classic": {"debug_vars": base_dv, "flightrec": base_snap,
                            "shard_occupancy": base_shards},
                "ring": {"debug_vars": ring_dv, "flightrec": ring_snap,
                         "shard_occupancy": ring_shards},
            }, f, indent=1, default=str)
        print("mesh_smoke: FAILED (see mesh-smoke-dumps/)")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
