#!/usr/bin/env bash
# Regenerate the protobuf modules (analog of reference scripts/proto.sh).
#
# grpc_python_plugin is not available in this image, so only message modules
# (*_pb2.py) are generated; the service/stub wiring is hand-written in
# gubernator_tpu/net/grpc_api.py against grpc generic handlers.
set -euo pipefail
cd "$(dirname "$0")/../gubernator_tpu/proto"
protoc --python_out=. -I. gubernator.proto peers.proto
# protoc emits a flat sibling import; make it package-relative.
sed -i 's/^import gubernator_pb2 as gubernator__pb2$/from . import gubernator_pb2 as gubernator__pb2/' peers_pb2.py
echo "regenerated gubernator_pb2.py peers_pb2.py"
