#!/usr/bin/env python
"""Regenerate gubernator_tpu/proto/peers_pb2.py WITHOUT protoc.

protoc is unavailable in some build images, but its --python_out output
is fully determined by the FileDescriptorProto: the generated module is
a fixed template around `AddSerializedFile(<serialized descriptor>)`
plus byte offsets of each descriptor within that blob.  This script
constructs the descriptor programmatically (the declaration below IS
proto/peers.proto, message for message, in file order) and emits the
module in protoc's exact format, so the CI protogen-drift job — which
DOES run protoc and diffs — stays green.

Self-check: building only the pre-existing messages must reproduce the
committed file byte-for-byte before any new message is trusted (run
with --verify-base to see that check alone).
"""
from __future__ import annotations

import argparse
import os

from google.protobuf import descriptor_pb2 as dp

OUT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "gubernator_tpu", "proto", "peers_pb2.py",
)

L_OPT = dp.FieldDescriptorProto.LABEL_OPTIONAL
L_REP = dp.FieldDescriptorProto.LABEL_REPEATED
T_MSG = dp.FieldDescriptorProto.TYPE_MESSAGE
T_STR = dp.FieldDescriptorProto.TYPE_STRING
T_I64 = dp.FieldDescriptorProto.TYPE_INT64
T_I32 = dp.FieldDescriptorProto.TYPE_INT32
T_DBL = dp.FieldDescriptorProto.TYPE_DOUBLE
T_BOOL = dp.FieldDescriptorProto.TYPE_BOOL
T_ENUM = dp.FieldDescriptorProto.TYPE_ENUM


def field(name, number, type_, label=L_OPT, type_name=""):
    f = dp.FieldDescriptorProto(
        name=name, number=number, label=label, type=type_
    )
    if type_name:
        f.type_name = type_name
    return f


def message(name, *fields):
    m = dp.DescriptorProto(name=name)
    m.field.extend(fields)
    return m


def method(name, inp, out):
    m = dp.MethodDescriptorProto(
        name=name,
        input_type=f".pb.gubernator.{inp}",
        output_type=f".pb.gubernator.{out}",
    )
    m.options.SetInParent()  # protoc emits empty options for `{}` bodies
    return m


def build(with_reshard: bool = True) -> dp.FileDescriptorProto:
    fd = dp.FileDescriptorProto(
        name="peers.proto", package="pb.gubernator", syntax="proto3"
    )
    fd.dependency.append("gubernator.proto")
    fd.options.cc_generic_services = True

    P = ".pb.gubernator."
    fd.message_type.extend([
        message(
            "GetPeerRateLimitsReq",
            field("requests", 1, T_MSG, L_REP, P + "RateLimitReq"),
        ),
        message(
            "GetPeerRateLimitsResp",
            field("rate_limits", 1, T_MSG, L_REP, P + "RateLimitResp"),
        ),
        message(
            "UpdatePeerGlobalsReq",
            field("globals", 1, T_MSG, L_REP, P + "UpdatePeerGlobal"),
        ),
        message(
            "UpdatePeerGlobal",
            field("key", 1, T_STR),
            field("status", 2, T_MSG, L_OPT, P + "RateLimitResp"),
            field("algorithm", 3, T_ENUM, L_OPT, P + "Algorithm"),
        ),
        message("UpdatePeerGlobalsResp"),
        message(
            "LeaseReq",
            field("client_id", 1, T_STR),
            field("requests", 2, T_MSG, L_REP, P + "RateLimitReq"),
        ),
        message(
            "LeaseGrant",
            field("key", 1, T_STR),
            field("allowance", 2, T_I64),
            field("expires_at", 3, T_I64),
            field("reset_time", 4, T_I64),
            field("limit", 5, T_I64),
            field("refusal", 6, T_STR),
        ),
        message(
            "LeaseResp",
            field("grants", 1, T_MSG, L_REP, P + "LeaseGrant"),
        ),
        message(
            "ReconcileItem",
            field("request", 1, T_MSG, L_OPT, P + "RateLimitReq"),
            field("release", 2, T_BOOL),
            field("renew", 3, T_BOOL),
        ),
        message(
            "ReconcileReq",
            field("client_id", 1, T_STR),
            field("items", 2, T_MSG, L_REP, P + "ReconcileItem"),
        ),
        message(
            "ReconcileResp",
            field("grants", 1, T_MSG, L_REP, P + "LeaseGrant"),
        ),
    ])
    if with_reshard:
        fd.message_type.extend([
            message(
                "HandoffReq",
                field("from_address", 1, T_STR),
                field("epoch", 2, T_I64),
                field("phase", 3, T_STR),
                field("total_rows", 4, T_I64),
            ),
            message(
                "HandoffResp",
                field("accepted", 1, T_BOOL),
                field("state", 2, T_STR),
            ),
            message(
                "MigratedRows",
                field("key_hash", 1, T_I64, L_REP),
                field("algo", 2, T_I32, L_REP),
                field("limit", 3, T_I64, L_REP),
                field("duration", 4, T_I64, L_REP),
                field("remaining", 5, T_I64, L_REP),
                field("remaining_f", 6, T_DBL, L_REP),
                field("t0", 7, T_I64, L_REP),
                field("status", 8, T_I32, L_REP),
                field("burst", 9, T_I64, L_REP),
                field("expire_at", 10, T_I64, L_REP),
                field("keys", 11, T_STR, L_REP),
            ),
            message(
                "MigrateReq",
                field("from_address", 1, T_STR),
                field("epoch", 2, T_I64),
                field("rows", 3, T_MSG, L_OPT, P + "MigratedRows"),
                field("final", 4, T_BOOL),
            ),
            message(
                "MigrateResp",
                field("injected", 1, T_I64),
                field("skipped", 2, T_I64),
            ),
        ])

    svc = dp.ServiceDescriptorProto(name="PeersV1")
    svc.method.extend([
        method("GetPeerRateLimits", "GetPeerRateLimitsReq",
               "GetPeerRateLimitsResp"),
        method("UpdatePeerGlobals", "UpdatePeerGlobalsReq",
               "UpdatePeerGlobalsResp"),
        method("Lease", "LeaseReq", "LeaseResp"),
        method("Reconcile", "ReconcileReq", "ReconcileResp"),
    ])
    if with_reshard:
        svc.method.extend([
            method("Handoff", "HandoffReq", "HandoffResp"),
            method("Migrate", "MigrateReq", "MigrateResp"),
        ])
    fd.service.append(svc)
    return fd


def protoc_bytes_repr(blob: bytes) -> str:
    """protoc's C-style escaping of the serialized descriptor: `\"` is
    always escaped, and a printable hex-digit character immediately
    following a `\\xNN` escape is itself hex-escaped (C literal
    ambiguity protoc avoids; python's repr() would not)."""
    out = []
    prev_hex = False
    for b in blob:
        c = chr(b)
        if c == "\n":
            out.append("\\n"); prev_hex = False
        elif c == "\t":
            out.append("\\t"); prev_hex = False
        elif c == "\r":
            out.append("\\r"); prev_hex = False
        elif c == "'":
            out.append("\\'"); prev_hex = False
        elif c == '"':
            out.append('\\"'); prev_hex = False
        elif c == "\\":
            out.append("\\\\"); prev_hex = False
        elif 32 <= b < 127:
            if prev_hex and c in "0123456789abcdefABCDEF":
                out.append("\\x%02x" % b); prev_hex = True
            else:
                out.append(c); prev_hex = False
        else:
            out.append("\\x%02x" % b); prev_hex = True
    return "b'%s'" % "".join(out)


def emit(fd: dp.FileDescriptorProto) -> str:
    blob = fd.SerializeToString(deterministic=True)
    lines = [
        "# -*- coding: utf-8 -*-",
        "# Generated by the protocol buffer compiler.  DO NOT EDIT!",
        "# source: peers.proto",
        '"""Generated protocol buffer code."""',
        "from google.protobuf.internal import builder as _builder",
        "from google.protobuf import descriptor as _descriptor",
        "from google.protobuf import descriptor_pool as _descriptor_pool",
        "from google.protobuf import symbol_database as _symbol_database",
        "# @@protoc_insertion_point(imports)",
        "",
        "_sym_db = _symbol_database.Default()",
        "",
        "",
        "from . import gubernator_pb2 as gubernator__pb2",
        "",
        "",
        "DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile(%s)"
        % protoc_bytes_repr(blob),
        "",
        "_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())",
        "_builder.BuildTopDescriptorsAndMessages(DESCRIPTOR, 'peers_pb2',"
        " globals())",
        "if _descriptor._USE_C_DESCRIPTORS == False:",
        "",
        "  DESCRIPTOR._options = None",
        "  DESCRIPTOR._serialized_options = b'\\200\\001\\001'",
    ]
    for m in fd.message_type:
        content = m.SerializeToString(deterministic=True)
        start = blob.find(content)
        assert start > 0, m.name
        lines.append(
            "  _%s._serialized_start=%d" % (m.name.upper(), start)
        )
        lines.append(
            "  _%s._serialized_end=%d"
            % (m.name.upper(), start + len(content))
        )
    for s in fd.service:
        content = s.SerializeToString(deterministic=True)
        start = blob.find(content)
        assert start > 0, s.name
        lines.append(
            "  _%s._serialized_start=%d" % (s.name.upper(), start)
        )
        lines.append(
            "  _%s._serialized_end=%d"
            % (s.name.upper(), start + len(content))
        )
    lines.append("# @@protoc_insertion_point(module_scope)")
    return "\n".join(lines) + "\n"


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--verify-base", action="store_true",
                    help="only check the pre-reshard reproduction")
    args = ap.parse_args()

    base = emit(build(with_reshard=False))
    with open(OUT) as f:
        current = f.read()
    if args.verify_base:
        if base == current:
            print("base reproduction OK (byte-identical to protoc)")
        else:
            import difflib
            import sys

            sys.stdout.writelines(difflib.unified_diff(
                current.splitlines(True), base.splitlines(True),
                "committed", "generated",
            ))
            raise SystemExit(1)
        return

    with open(OUT, "w") as f:
        f.write(emit(build(with_reshard=True)))
    print(f"wrote {OUT}")


if __name__ == "__main__":
    main()
