"""Pipelined-drain CPU smoke: depth-2 vs depth-1 result equivalence.

Drives ~10k mixed checks (token/leaky, bursts, RESET_REMAINING, valid
Gregorian, zero/negative hits, duplicate keys) through the compiled fast
lane twice — once at GUBER_PIPELINE_DEPTH=1 (the strict pre-pipeline
discipline) and once at depth 2 — under a frozen clock, with concurrent
workers owning disjoint key spaces so every key's history is
deterministic regardless of merge composition.  Responses and the final
table rows must match bit-for-bit; the depth-2 run must actually have
pipelined (>= 2 merges observed in flight) or the smoke is vacuous.

Runs in the CI matrix (JAX_PLATFORMS=cpu); exit 0 = pass.
"""
from __future__ import annotations

import asyncio
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

N_WORKERS = 6
BATCHES_PER_WORKER = 24
KEYS_PER_WORKER = 8


def build_schedules():
    from gubernator_tpu.proto import gubernator_pb2 as pb

    rng = random.Random(1234)
    schedules = []
    total = 0
    for w in range(N_WORKERS):
        payloads = []
        for _ in range(BATCHES_PER_WORKER):
            reqs = []
            for _ in range(rng.randrange(40, 90)):
                behavior = 0
                duration = rng.choice([60_000, 60_000, 1_000])
                if rng.random() < 0.06:
                    behavior |= 8  # RESET_REMAINING
                if rng.random() < 0.04:
                    behavior |= 4  # DURATION_IS_GREGORIAN
                    duration = rng.choice([1, 4])
                reqs.append(pb.RateLimitReq(
                    name=f"smoke{w}",
                    unique_key=f"k{rng.randrange(KEYS_PER_WORKER)}",
                    hits=rng.choice([0, 1, 1, 1, 2, 5, -1]),
                    limit=rng.choice([50, 200, 1000]),
                    duration=duration,
                    algorithm=rng.choice([0, 1]),
                    behavior=behavior,
                    burst=rng.choice([0, 0, 60]),
                ))
            total += len(reqs)
            payloads.append(
                pb.GetRateLimitsReq(requests=reqs).SerializeToString()
            )
        schedules.append(payloads)
    return schedules, total


def run_at_depth(depth: int, schedules, clock):
    from gubernator_tpu.core.config import Config, DeviceConfig
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from gubernator_tpu.runtime.fastpath import FastPath
    from gubernator_tpu.runtime.service import Service

    dev = DeviceConfig(num_slots=1 << 14, ways=8, batch_size=512)

    async def scenario():
        svc = Service(Config(device=dev), clock=clock)
        await svc.start()
        fp = FastPath(svc, pipeline_depth=depth)
        results: dict = {}

        async def worker(w: int):
            await asyncio.sleep(w * 0.002)
            got = []
            for payload in schedules[w]:
                raw = await fp.check_raw(payload, peer_rpc=False)
                assert raw is not None, "fast lane fell back"
                got.append([
                    (r.status, r.limit, r.remaining, r.reset_time, r.error)
                    for r in pb.GetRateLimitsResp.FromString(raw).responses
                ])
            results[w] = got

        await asyncio.gather(*(worker(w) for w in range(N_WORKERS)))
        rows = {}
        for w in range(N_WORKERS):
            for k in range(KEYS_PER_WORKER):
                key = f"smoke{w}_k{k}"
                item = svc.backend.get_cache_item(key)
                rows[key] = (
                    (item.remaining, item.expire_at, int(item.status),
                     item.limit, item.duration, int(item.algorithm))
                    if item is not None else None
                )
        stats = fp._mach.debug_vars()
        await fp.close()
        await svc.close()
        return results, rows, stats

    return asyncio.run(scenario())


def main() -> int:
    from gubernator_tpu import native
    from gubernator_tpu.core import clock as clock_mod

    if not native.available():
        print("pipeline_smoke: SKIP (native library unavailable)")
        return 0

    schedules, total = build_schedules()
    print(f"pipeline_smoke: {total} checks x 2 depths")
    clock_mod.freeze()
    try:
        base_results, base_rows, base_stats = run_at_depth(
            1, schedules, clock_mod.default_clock()
        )
        deep_results, deep_rows, deep_stats = run_at_depth(
            2, schedules, clock_mod.default_clock()
        )
    finally:
        clock_mod.unfreeze()

    ok = True
    if deep_results != base_results:
        for w in base_results:
            for i, (a, b) in enumerate(
                zip(base_results[w], deep_results[w])
            ):
                if a != b:
                    print(
                        f"FAIL: worker {w} batch {i} diverged:\n"
                        f"  depth1: {a[:3]}...\n  depth2: {b[:3]}..."
                    )
                    break
        ok = False
    if deep_rows != base_rows:
        diff = {
            k for k in base_rows if base_rows[k] != deep_rows.get(k)
        }
        print(f"FAIL: {len(diff)} table rows diverged: {sorted(diff)[:5]}")
        ok = False
    if deep_stats["max_inflight_seen"] < 2:
        print(
            "FAIL: depth-2 run never pipelined "
            f"(max_inflight_seen={deep_stats['max_inflight_seen']})"
        )
        ok = False
    print(f"pipeline_smoke: depth1 stats {base_stats}")
    print(f"pipeline_smoke: depth2 stats {deep_stats}")
    if ok:
        print(
            f"pipeline_smoke: OK — {total} checks bit-identical across "
            "depths; depth-2 overlapped "
            f"{deep_stats['max_inflight_seen']} merges"
        )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
