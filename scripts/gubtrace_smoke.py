"""CI smoke: run the gubtrace verifier end-to-end the way an operator
does — the CLI over the real registry must scan clean, a seeded
violation must fail with a diff, and the golden snapshots must cover
every registered kernel.

Run from the repo root:  python scripts/gubtrace_smoke.py
Exits non-zero with a labeled assertion on any missing piece.
(Mirrors scripts/flightrec_smoke.py.)
"""
from __future__ import annotations

import json
import os
import subprocess
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Runnable from a checkout without an installed package.
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> None:
    # 1. The CLI over the real registry scans clean (exit 0).
    proc = subprocess.run(
        [sys.executable, "-m", "tools.gubtrace", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, (
        f"gubtrace CLI failed (rc={proc.returncode}):\n"
        f"{proc.stdout}\n{proc.stderr}"
    )
    findings = json.loads(proc.stdout)
    errors = [f for f in findings if f["severity"] == "error"]
    assert errors == [], f"tree not clean: {errors}"

    # 2. Golden snapshots exist for every registered kernel.
    from tools.gubtrace import GOLDEN_DIR
    from tools.gubtrace.registry import registered_names

    names = registered_names()
    assert len(names) >= 15, f"registry shrank: {names}"
    missing = [
        n for n in names if not (GOLDEN_DIR / f"{n}.json").is_file()
    ]
    assert not missing, f"kernels without golden snapshots: {missing}"

    # 3. A seeded violation demonstrably fails (the checker suite is
    #    alive, not vacuously green).
    from pathlib import Path

    from tests.gubtrace_fixtures.kernels import FIXTURE_SPECS
    from tools.gubtrace import run

    seeded = run(
        select=["dtype-taint"],
        specs=[s for s in FIXTURE_SPECS if s.name == "viol_dtype_narrow"],
        root=Path(REPO),
    )
    assert any(
        f.severity == "error" and f.checker == "dtype-taint"
        for f in seeded
    ), f"seeded dtype violation not caught: {seeded}"

    print(
        "gubtrace smoke OK:"
        f" {len(names)} kernels clean, seeded violation caught"
    )


if __name__ == "__main__":
    main()
