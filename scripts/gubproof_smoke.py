"""CI smoke: run the gubproof verifier end-to-end the way an operator
does — the CLI over the real specs must pass clean, every seeded
fixture must fail its phase, the explorer must close every pinned
small scope reproducing the documented maxima exactly, and a
counterexample from the replay-guard-removed reshard variant must
lower to a chaos plan the real loader parses.

Run from the repo root:  python scripts/gubproof_smoke.py
Exits non-zero with a labeled assertion on any missing piece.
(Mirrors scripts/gubtrace_smoke.py.)
"""
from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# Runnable from a checkout without an installed package.
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> None:
    # 1. The CLI over the real specs passes clean (exit 0, no errors),
    #    strict so even warnings would fail here.
    proc = subprocess.run(
        [sys.executable, "-m", "tools.gubproof", "--json", "--strict"],
        cwd=REPO, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, (
        f"gubproof CLI failed (rc={proc.returncode}):\n"
        f"{proc.stdout}\n{proc.stderr}"
    )
    assert json.loads(proc.stdout) == [], (
        f"tree not clean: {proc.stdout}"
    )

    # 2. Every seeded fixture fails its phase with the expected class.
    from tools.gubproof.conformance import lint_spec
    from tools.gubproof.spec import load_spec
    from pathlib import Path

    fixtures = Path(REPO) / "tests" / "gubproof_fixtures"
    expect = {
        "spec_undeclared.json": "undeclared transition",
        "spec_unguarded.json": "missing guard",
        "spec_missing_edge.json": "no implementation site",
    }
    for name, needle in expect.items():
        spec = load_spec(fixtures / name)
        errs = [
            f for f in lint_spec(spec, Path(REPO))
            if f.severity == "error"
        ]
        assert errs, f"fixture {name} did not fail"
        assert any(needle in f.message for f in errs), (
            f"fixture {name}: expected {needle!r} in {errs}"
        )

    # 3. The explorer closes every pinned scope and reproduces the
    #    documented over-admission algebra EXACTLY.
    from tools.gubproof import load_all_specs
    from tools.gubproof.explore import explore_model
    from tools.gubproof.models import ReshardModel, build_models

    specs = load_all_specs()
    algebra = {
        "breaker": {"half_open_probes_admitted": 1},
        "lease": {"admitted": 6},
        "reshard": {"admitted_clean": 5, "admitted_lost": 9},
        "tier": {"admitted": 12},
        "reshard_lease": {"admitted_clean": 7, "admitted_lost": 11},
    }
    for model in build_models(specs):
        res = explore_model(model)
        assert res.closed, f"{model.name}: {res.closure_note}"
        assert not res.violations, (
            f"{model.name}: {[v.message for v in res.violations]}"
        )
        assert res.max_counters == algebra[model.name], (
            f"{model.name}: explored {res.max_counters}, documented "
            f"{algebra[model.name]}"
        )
        print(
            f"gubproof smoke: {model.name:14s} {res.states:5d} states "
            f"closed, maxima {res.max_counters}"
        )

    # 4. A violated bound ships as a replayable chaos plan: the broken
    #    variant's counterexample round-trips through the real loader.
    from gubernator_tpu.testing.chaos import ChaosPlan
    from tools.gubproof.chaosplan import plan_from_trace

    res = explore_model(ReshardModel(specs, replay_guard=False))
    assert res.violations, "replay-guard removal must yield a violation"
    v = res.violations[0]
    plan = plan_from_trace("reshard-no-replay-guard", list(v.trace),
                           v.message, seed=1)
    cp = ChaosPlan.from_dict(plan)
    assert cp.rules, "counterexample lowered to an empty plan"
    assert any(
        r.method == "*Migrate*" and r.phase == "after" for r in cp.rules
    ), f"dup-delivery window missing from {plan['rules']}"

    # 5. The CLI writes the plan to the dump dir on violation paths
    #    (exercised via an insufficient depth cap + the dump flag, then
    #    a direct dump of the broken-variant plan).
    dump = os.path.join(REPO, "gubproof-smoke-dumps")
    shutil.rmtree(dump, ignore_errors=True)
    os.makedirs(dump)
    with open(os.path.join(dump, "dup-migrate.chaosplan.json"), "w") as f:
        json.dump(plan, f, indent=2)
    reloaded = ChaosPlan.from_dict(
        json.load(open(os.path.join(dump, "dup-migrate.chaosplan.json")))
    )
    assert reloaded.seed == 1 and len(reloaded.rules) == len(cp.rules)
    shutil.rmtree(dump, ignore_errors=True)

    print("gubproof smoke: PASS")


if __name__ == "__main__":
    main()
