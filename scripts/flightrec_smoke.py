"""CI smoke: boot one daemon with the flight recorder armed, drive a few
checks, scrape /metrics and /debug/flightrec, assert the telemetry plane
is actually there (histogram buckets, SLO series, ring records).

Run from the repo root:  GUBER_FLIGHTREC=1 python scripts/flightrec_smoke.py
Exits non-zero with a labeled assertion on any missing piece.
"""
from __future__ import annotations

import asyncio
import json
import os
import sys
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# Runnable from a checkout without an installed package.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


async def main() -> None:
    from gubernator_tpu.core.config import (
        DaemonConfig,
        DeviceConfig,
        setup_daemon_config,
    )
    from gubernator_tpu.core.types import RateLimitReq
    from gubernator_tpu.daemon import Daemon
    from gubernator_tpu.net.grpc_api import V1Stub, req_to_pb
    from gubernator_tpu.proto import gubernator_pb2 as pb

    env = setup_daemon_config()
    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="127.0.0.1:0",
        device=DeviceConfig(num_slots=4096, ways=8, batch_size=128),
        flightrec=True,
        flightrec_dir=env.flightrec_dir,
        slo_p99_ms=env.slo_p99_ms,
    )
    daemon = Daemon(conf)
    await daemon.start()
    try:
        import grpc.aio

        ch = grpc.aio.insecure_channel(daemon.grpc_address)
        stub = V1Stub(ch)
        req = pb.GetRateLimitsReq(requests=[
            req_to_pb(RateLimitReq(
                name="smoke", unique_key=f"k{i}", hits=1, limit=100,
                duration=60_000,
            ))
            for i in range(8)
        ])
        for _ in range(5):
            await stub.GetRateLimits(req)
        await ch.close()
        # One sampler tick so the SLO gauges refresh.
        await asyncio.sleep(0.6)

        def _get_sync(path: str) -> bytes:
            url = f"http://{daemon.http_address}{path}"
            with urllib.request.urlopen(url, timeout=10) as resp:
                return resp.read()

        loop = asyncio.get_running_loop()

        async def get(path: str) -> bytes:
            # The daemon serves on THIS loop — a sync urlopen here would
            # deadlock against our own HTTP server.
            return await loop.run_in_executor(None, _get_sync, path)

        text = (await get("/metrics")).decode()
        for needle in (
            'gubernator_grpc_request_duration_bucket{le="0.002"',
            "gubernator_tpu_device_step_duration_bucket",
            "gubernator_slo_p99_seconds",
            "gubernator_slo_breach_total",
            "gubernator_event_loop_lag_seconds",
        ):
            assert needle in text, f"/metrics missing {needle!r}"

        snap = json.loads(await get("/debug/flightrec"))
        assert snap["enabled"] is True, snap
        assert snap["rolling"]["samples"] >= 5, snap["rolling"]
        kinds = {r["kind"] for r in snap["ring"]}
        assert kinds, "flight-recorder ring is empty"

        vars_ = json.loads(await get("/debug/vars"))
        assert vars_["backend"]["checks"] >= 40, vars_["backend"]
        assert "flightrec" in vars_, vars_
        print("flightrec smoke OK:", sorted(kinds))
    finally:
        await daemon.close()


if __name__ == "__main__":
    asyncio.run(main())
