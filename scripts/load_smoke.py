"""CI smoke: one seeded gubload scenario end to end against a
2-daemon in-process cluster (docs/loadgen.md), proving the whole
open-loop harness chain in one required step:

  1. schedule determinism — the same GUBER_LOAD_SEED builds
     byte-identical arrival plans (digest equality across two builds,
     and across worker shardings: the union of shards IS the plan);
  2. the flashcrowd scenario passes its merged-ledger verdict (exact
     accounting: ledger allowed == client-observed admissions, the
     zipfian hot key saturates its limit exactly, global bound holds);
  3. phase markers landed in every daemon's flight-recorder ring
     (kind="load_phase", enter AND exit for each phase) — the
     phase-linked attribution an operator joins dumps against;
  4. every artifact row passes the BENCH schema check and
     scripts/bench_gate.py accepts the artifact against itself
     (0 regressions — the self-diff proves key compatibility).

On any failure each daemon's flight recorder dumps its ring to
GUBER_FLIGHTREC_DIR (default flightrec-dumps/) so the CI artifact
step can pick the evidence up.

Run from the repo root:  python scripts/load_smoke.py [--seed N]
The whole run is deterministic given the seed.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# Runnable from a checkout without an installed package.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SCENARIO = "flashcrowd"


def _dump_flightrec(cluster) -> None:
    for d in cluster.daemons:
        if d.flightrec is not None:
            path = cluster.run(d.flightrec.dump("load_smoke_failure"))
            print(f"flightrec dump ({d.grpc_address}): {path}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int,
                    default=int(os.environ.get("GUBER_LOAD_SEED", 424242)))
    ap.add_argument("--duration", type=float, default=4.0)
    ap.add_argument("--target-rps", type=float, default=300.0)
    args = ap.parse_args(argv)

    from gubernator_tpu.core.config import DaemonConfig, LoadConfig
    from gubernator_tpu.loadgen import (
        SCENARIOS, build_schedules, run_scenario, validate_row,
    )
    from gubernator_tpu.testing import Cluster

    cfg = LoadConfig(
        seed=args.seed, scenario=SCENARIO,
        duration_s=args.duration, clients=6,
        target_rps=args.target_rps,
    )
    spec = SCENARIOS[SCENARIO]

    # 1. Determinism before any RPC: two builds from the seed are
    # byte-identical, and sharding is a partition of the plan.
    a, b = build_schedules(spec, cfg), build_schedules(spec, cfg)
    assert [s.digest() for s in a] == [s.digest() for s in b], (
        "schedule build is not deterministic for a fixed seed"
    )
    for sched in a:
        shards = sched.shard(4)
        assert sum(len(s) for s in shards) == len(sched)
        assert sorted(
            t for s in shards for t in s.times_s.tolist()
        ) == sorted(sched.times_s.tolist()), (
            "worker shards do not partition the schedule"
        )
    print(f"load_smoke: schedules deterministic (seed={cfg.seed}, "
          f"{[len(s) for s in a]} arrivals/phase)")

    # Own cluster (NOT run_scenario's) so the flight-recorder rings are
    # still inspectable after the run.
    conf = DaemonConfig(
        flightrec=True,
        flightrec_dir=os.environ.get(
            "GUBER_FLIGHTREC_DIR", "flightrec-dumps"
        ),
        # Sized so the run's per-request records cannot evict the first
        # phase's markers before we inspect the ring.
        flightrec_ring=16384,
    )
    cluster = Cluster.start_with(["", ""], conf_template=conf)
    try:
        # 2. The scenario itself — run_scenario raises AssertionError
        # with the ledger facts when the verdict fails.
        result = run_scenario(SCENARIO, cfg, cluster=cluster)
        verdict = result["verdict"]
        print(f"load_smoke: {SCENARIO} verdict proven: "
              f"{json.dumps(verdict)}")

        # 3. Phase markers in every daemon's ring: enter AND exit per
        # phase, tagged with this scenario.
        want_phases = {p.name for p in spec.phases}
        for d in cluster.daemons:
            ring = d.flightrec.snapshot()["ring"]
            marks = [r for r in ring if r.get("kind") == "load_phase"
                     and r.get("scenario") == SCENARIO]
            for action in ("enter", "exit"):
                got = {r["phase"] for r in marks
                       if r.get("action") == action}
                assert want_phases <= got, (
                    f"{d.grpc_address}: flightrec ring missing "
                    f"load_phase {action} markers: want {want_phases}, "
                    f"got {got}"
                )
        print(f"load_smoke: phase markers present in "
              f"{len(cluster.daemons)} rings ({sorted(want_phases)})")

        # 4. Artifact schema + bench_gate self-diff (exit 0, matched
        # keys, no regressions).
        artifact = result["artifact"]
        for row in artifact["results"]:
            validate_row(row)
        from scripts import bench_gate

        rc = bench_gate.gate(
            artifact, artifact, threshold=0.25, warn_only=False
        )
        assert rc == 0, f"bench_gate self-diff failed (exit {rc})"
        print(f"load_smoke: {len(artifact['results'])} artifact rows "
              "valid; bench_gate accepts")
    except BaseException:
        _dump_flightrec(cluster)
        raise
    finally:
        cluster.stop()

    print(f"load_smoke: PASS (seed={cfg.seed})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
