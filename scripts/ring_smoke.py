"""Ring-mode CPU smoke: ring vs classic result equivalence + zero
request-path fetches.

Drives ~10k mixed checks (token/leaky, bursts, RESET_REMAINING, valid
Gregorian, zero/negative hits, duplicate keys, a GLOBAL slice with
per-key-constant params) through the compiled fast lane twice — once at
GUBER_SERVE_MODE=classic (the strict depth-1 drain) and once in ring
mode — under a frozen clock, with concurrent workers owning disjoint
key spaces so every key's history is deterministic regardless of merge
composition.  Pass criteria (ISSUE 6 acceptance):

  1. responses and final table rows bit-identical across modes;
  2. the ring run performed ZERO blocking device->host fetches on the
     request path (the machinery counter the classic run increments on
     every merge);
  3. the ring actually served (iterations > 0) and the sequence word
     never disagreed with the host mirror.

On failure the armed flight recorder's ring is dumped to
ring-smoke-dumps/ for the CI artifact.  Runs in the CI matrix
(JAX_PLATFORMS=cpu); exit 0 = pass.
"""
from __future__ import annotations

import asyncio
import json
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

N_WORKERS = 6
BATCHES_PER_WORKER = 24
KEYS_PER_WORKER = 8  # k0..k5 exact mix, k6..k7 GLOBAL constant-param


def build_schedules():
    from gubernator_tpu.proto import gubernator_pb2 as pb

    rng = random.Random(1234)
    schedules = []
    total = 0
    for w in range(N_WORKERS):
        payloads = []
        for _ in range(BATCHES_PER_WORKER):
            reqs = []
            for _ in range(rng.randrange(40, 90)):
                if rng.random() < 0.20:
                    # GLOBAL slice: per-key-constant params (a flush-
                    # time re-read of changed params would inject
                    # schedule noise — see test_ring_mode_differential).
                    k = 6 + rng.randrange(2)
                    reqs.append(pb.RateLimitReq(
                        name=f"rsmoke{w}",
                        unique_key=f"k{k}",
                        hits=rng.choice([0, 1, 1, 2]),
                        limit=200 + 100 * (k % 2),
                        duration=60_000,
                        algorithm=k % 2,
                        behavior=2,  # GLOBAL
                        burst=250 if k % 2 == 0 else 0,
                    ))
                    continue
                behavior = 0
                duration = rng.choice([60_000, 60_000, 1_000])
                if rng.random() < 0.06:
                    behavior |= 8  # RESET_REMAINING
                if rng.random() < 0.04:
                    behavior |= 4  # DURATION_IS_GREGORIAN
                    duration = rng.choice([1, 4])
                reqs.append(pb.RateLimitReq(
                    name=f"rsmoke{w}",
                    unique_key=f"k{rng.randrange(6)}",
                    hits=rng.choice([0, 1, 1, 1, 2, 5, -1]),
                    limit=rng.choice([50, 200, 1000]),
                    duration=duration,
                    algorithm=rng.choice([0, 1]),
                    behavior=behavior,
                    burst=rng.choice([0, 0, 60]),
                ))
            total += len(reqs)
            payloads.append(
                pb.GetRateLimitsReq(requests=reqs).SerializeToString()
            )
        schedules.append(payloads)
    return schedules, total


def run_mode(mode: str, schedules, clock):
    from gubernator_tpu.core.config import Config, DeviceConfig
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from gubernator_tpu.runtime.fastpath import FastPath
    from gubernator_tpu.runtime.flightrec import FlightRecorder
    from gubernator_tpu.runtime.metrics import Metrics
    from gubernator_tpu.runtime.service import Service

    dev = DeviceConfig(num_slots=1 << 14, ways=8, batch_size=512)

    async def scenario():
        metrics = Metrics()
        fr = FlightRecorder(metrics=metrics, dump_dir="ring-smoke-dumps")
        metrics.flightrec = fr
        fr.start()
        svc = Service(Config(device=dev), clock=clock, metrics=metrics)
        await svc.start()
        fp = FastPath(svc, serve_mode=mode, ring_slots=8)
        results: dict = {}

        async def worker(w: int):
            await asyncio.sleep(w * 0.002)
            got = []
            for payload in schedules[w]:
                raw = await fp.check_raw(payload, peer_rpc=False)
                assert raw is not None, "fast lane fell back"
                got.append([
                    (r.status, r.limit, r.remaining, r.reset_time, r.error)
                    for r in pb.GetRateLimitsResp.FromString(raw).responses
                ])
            results[w] = got

        await asyncio.gather(*(worker(w) for w in range(N_WORKERS)))
        rows = {}
        for w in range(N_WORKERS):
            for k in range(KEYS_PER_WORKER):
                key = f"rsmoke{w}_k{k}"
                item = svc.backend.get_cache_item(key)
                rows[key] = (
                    (item.remaining, item.expire_at, int(item.status),
                     item.limit, item.duration, int(item.algorithm))
                    if item is not None else None
                )
        dv = fp.debug_vars()
        snap = fr.snapshot()
        await fp.close()
        await svc.close()
        await fr.close()
        return results, rows, dv, snap

    return asyncio.run(scenario())


def main() -> int:
    from gubernator_tpu import native
    from gubernator_tpu.core import clock as clock_mod

    if not native.available():
        print("ring_smoke: SKIP (native library unavailable)")
        return 0

    schedules, total = build_schedules()
    print(f"ring_smoke: {total} checks x 2 serve modes")
    clock_mod.freeze()
    try:
        base_results, base_rows, base_dv, base_snap = run_mode(
            "classic", schedules, clock_mod.default_clock()
        )
        ring_results, ring_rows, ring_dv, ring_snap = run_mode(
            "ring", schedules, clock_mod.default_clock()
        )
    finally:
        clock_mod.unfreeze()

    ok = True
    if ring_results != base_results:
        for w in base_results:
            for i, (a, b) in enumerate(
                zip(base_results[w], ring_results[w])
            ):
                if a != b:
                    print(
                        f"FAIL: worker {w} batch {i} diverged:\n"
                        f"  classic: {a[:3]}...\n  ring: {b[:3]}..."
                    )
                    break
        ok = False
    if ring_rows != base_rows:
        diff = {
            k for k in base_rows if base_rows[k] != ring_rows.get(k)
        }
        print(f"FAIL: {len(diff)} table rows diverged: {sorted(diff)[:5]}")
        ok = False
    ring_stats = ring_dv.get("ring", {})
    blocking = ring_dv["blocking_fetches"]
    per_check = (
        sum(blocking.values()) / float(total) if total else 0.0
    )
    if sum(blocking.values()) != 0:
        print(
            "FAIL: ring mode performed blocking request-path fetches: "
            f"{blocking} ({per_check:.4f} per check; must be 0)"
        )
        ok = False
    if base_dv["blocking_fetches"]["mach"] == 0:
        print("FAIL: classic run counted no machinery fetches — the "
              "smoke's counter is broken/vacuous")
        ok = False
    if ring_stats.get("iterations", 0) < 1:
        print(f"FAIL: the ring never iterated: {ring_stats}")
        ok = False
    if ring_stats.get("seq_mismatches", 0) != 0:
        print(f"FAIL: sequence-word mismatches: {ring_stats}")
        ok = False
    print("ring_smoke: classic stats "
          + json.dumps(base_dv["blocking_fetches"]))
    print("ring_smoke: ring stats " + json.dumps(ring_stats))
    if ok:
        print(
            f"ring_smoke: OK — {total} checks bit-identical across serve "
            f"modes; ring ran {ring_stats.get('iterations')} iterations "
            f"+ {ring_stats.get('host_jobs')} host jobs with 0 blocking "
            "request-path fetches"
        )
    else:
        # Dump both runs' flight-recorder rings for the CI artifact.
        os.makedirs("ring-smoke-dumps", exist_ok=True)
        with open("ring-smoke-dumps/ring_smoke_failure.json", "w") as f:
            json.dump({
                "classic": {"debug_vars": base_dv, "flightrec": base_snap},
                "ring": {"debug_vars": ring_dv, "flightrec": ring_snap},
            }, f, indent=1, default=str)
        print("ring_smoke: FAILED (see ring-smoke-dumps/)")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
